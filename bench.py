"""Benchmark entry: prints ONE JSON line with the north-star metric.

Metric (BASELINE.md): item-pairs/sec = ObservedCooccurrences / Duration on a
Zipfian basket stream, device backend. ``vs_baseline`` compares against the
first recorded CPU-oracle-backend run of this same framework (the reference
publishes no numbers — BASELINE.md "Published reference numbers: None").
"""

from __future__ import annotations

import json
import os
import sys
import time


def run(backend: str, users, items, ts, num_items: int, window_ms: int):
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.metrics import OBSERVED_COOCCURRENCES

    cfg = Config(window_size=window_ms, seed=0xC0FFEE, item_cut=500,
                 user_cut=500, backend=Backend(backend), num_items=num_items)
    job = CooccurrenceJob(cfg)
    start = time.monotonic()
    job.add_batch(users, items, ts)
    job.finish()
    elapsed = time.monotonic() - start
    pairs = job.counters.get(OBSERVED_COOCCURRENCES)
    return pairs, elapsed


def _accelerator_reachable(timeout_s: float = 240.0) -> bool:
    """Probe whether a JAX accelerator actually executes, in a subprocess.

    The tunneled TPU plugin can hang indefinitely at backend init when its
    pool has no capacity; probing in a child with a hard timeout keeps the
    bench from hanging with it. Generous timeout: a live tunnel's first
    contact legitimately takes minutes (grant + first compile). A success
    marker (1h TTL) skips the probe on healthy repeat runs so they don't
    pay a throwaway duplicate first-contact every time.
    """
    import subprocess

    marker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".accel_probe_ok")
    try:
        if time.time() - os.path.getmtime(marker) < 3600:
            return True
    except OSError:
        pass

    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.zeros((8,), jnp.int32); x.block_until_ready(); "
            "print('ACCEL-' + jax.default_backend())")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, timeout=timeout_s, text=True)
        ok = "ACCEL-" in r.stdout and "ACCEL-cpu" not in r.stdout
        if ok:
            with open(marker, "w"):
                pass
        return ok
    except subprocess.TimeoutExpired:
        return False


_HISTORY = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_history.jsonl")


def _record_onchip(value: float, vs_baseline: float, backend: str) -> None:
    """Append a successful on-chip measurement to the bench history."""
    entry = {"ts": time.strftime("%Y-%m-%d %H:%M:%S"),
             "pairs_per_sec": value, "vs_baseline": vs_baseline,
             "backend": backend}
    with open(_HISTORY, "a") as f:
        f.write(json.dumps(entry) + "\n")


def _last_onchip():
    """Most recent recorded on-chip measurement, or None. Skips corrupt
    lines (e.g. a truncated append from a crashed run) — a bad history
    must not take down the fallback path it exists to serve."""
    try:
        last = None
        with open(_HISTORY) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    last = json.loads(line)
                except ValueError:
                    continue
        return last
    except OSError:
        return None


def main() -> None:
    # Default to CPU JAX when no real accelerator platform is reachable; the
    # driver's TPU environment leaves JAX_PLATFORMS as configured.
    platform = "accelerator"
    if os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu") \
            and not _accelerator_reachable():
        # Configured accelerator is unreachable (dead tunnel): fall back to
        # CPU so the run records a (clearly labeled) number instead of
        # hanging forever. The env var alone is not enough when the
        # environment pre-imports jax (sitecustomize); override the live
        # config too (see tests/conftest.py for the same dance).
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        import jax

        jax.config.update("jax_platforms", "cpu")
        platform = "cpu-fallback"

    from tpu_cooccurrence.io.synthetic import zipfian_interactions

    n_events = int(os.environ.get("BENCH_EVENTS", 400_000))
    n_items = int(os.environ.get("BENCH_ITEMS", 20_000))
    users, items, ts = zipfian_interactions(
        n_events, n_items=n_items, n_users=5_000, alpha=1.1, seed=3,
        events_per_ms=200)

    # Untimed warmup on the full stream: populates the jit caches for every
    # pad bucket the measured run will hit, so the metric is steady-state
    # throughput rather than one-time XLA compile latency.
    run("device", users, items, ts, num_items=n_items, window_ms=100)

    # Median of three measured runs: the benched chip can be reached over a
    # shared tunnel, where single-run wall-clock swings by 2x under
    # contention.
    samples = []
    for _ in range(3):
        pairs, elapsed = run("device", users, items, ts,
                             num_items=n_items, window_ms=100)
        samples.append(pairs / max(elapsed, 1e-9))
    pairs_per_sec = sorted(samples)[1]

    # Baseline: the exact host (oracle) backend on the same stream, cached
    # in .bench_baseline.json on first run.
    baseline_path = os.path.join(os.path.dirname(__file__), ".bench_baseline.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)["pairs_per_sec"]
    else:
        b_pairs, b_elapsed = run("oracle", users, items, ts,
                                 num_items=n_items, window_ms=100)
        baseline = b_pairs / max(b_elapsed, 1e-9)
        with open(baseline_path, "w") as f:
            json.dump({"pairs_per_sec": baseline}, f)

    import jax

    backend = jax.default_backend()  # what the measured runs actually used
    out = {
        "metric": "item-pairs/sec (Zipfian basket stream, device backend)",
        "value": round(pairs_per_sec, 1),
        "unit": "pairs/s",
        "vs_baseline": round(pairs_per_sec / max(baseline, 1e-9), 3),
    }
    if platform == "cpu-fallback" or backend == "cpu":
        out["platform"] = platform if platform == "cpu-fallback" else backend
        # A dead tunnel must not read as a ~20x perf regression: carry the
        # most recent real on-chip measurement alongside the fallback
        # number, clearly dated and marked stale (VERDICT r2, Missing #3).
        prior = _last_onchip()
        if prior is not None:
            out["last_onchip"] = {
                "value": prior["pairs_per_sec"],
                "vs_baseline": prior["vs_baseline"],
                "ts": prior["ts"],
                "stale": True,
            }
    else:
        _record_onchip(out["value"], out["vs_baseline"], backend)
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
