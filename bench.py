"""Benchmark entry: prints ONE JSON line with the north-star metric.

Metric (BASELINE.md): item-pairs/sec = ObservedCooccurrences / Duration on a
Zipfian basket stream, device backend. ``vs_baseline`` compares against the
first recorded CPU-oracle-backend run of this same framework (the reference
publishes no numbers — BASELINE.md "Published reference numbers: None").

Structure (VERDICT r3, Weak #2 / Next #5): the orchestrating parent never
imports jax and runs every chip-touching step in a subprocess with a hard
deadline — a tunnel that dies at ANY point during the run (including
mid-measurement, which the old probe-marker trust window could not catch)
costs at most the deadline, after which the run falls back to a clearly
labeled cpu-fallback number carrying the last real on-chip measurement.
``bench.py --measure`` is the child mode that actually measures on
whatever platform the environment provides.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
_HISTORY = os.path.join(REPO, "bench_history.jsonl")

# Child deadlines live in grant_watch (single owner: its watch-stage
# backstop is derived from the same values, so the two can never drift
# apart). Accel: generous — first tunnel contact + compiles legitimately
# take minutes. CPU: no tunnel involved, but the run must terminate.
from tpu_cooccurrence.bench.grant_watch import (
    BENCH_ACCEL_DEADLINE_S as ACCEL_DEADLINE_S,
    BENCH_CPU_DEADLINE_S as CPU_DEADLINE_S)


def run(backend: str, users, items, ts, num_items: int, window_ms: int,
        pipeline_depth: int = 0, journal: str = None,
        fused_window: str = "off", wire_format: str = "auto",
        cell_dtype: str = "auto", spill_threshold_windows: int = 0,
        spill_target_hbm_frac: float = 0.5):
    import hashlib

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.metrics import OBSERVED_COOCCURRENCES
    from tpu_cooccurrence.observability import LEDGER
    from tpu_cooccurrence.observability.registry import REGISTRY

    # Per-run metrics scope: the registry and ledger are process-global,
    # so clear them here and the summaries below describe exactly this
    # run's windows.
    REGISTRY.reset()
    LEDGER.reset()
    cfg = Config(window_size=window_ms, seed=0xC0FFEE, item_cut=500,
                 user_cut=500, backend=Backend(backend), num_items=num_items,
                 pipeline_depth=pipeline_depth, journal=journal,
                 fused_window=fused_window, wire_format=wire_format,
                 cell_dtype=cell_dtype,
                 spill_threshold_windows=spill_threshold_windows,
                 spill_target_hbm_frac=spill_target_hbm_frac)
    job = CooccurrenceJob(cfg)
    start = time.monotonic()
    job.add_batch(users, items, ts)
    job.finish()
    elapsed = time.monotonic() - start
    pairs = job.counters.get(OBSERVED_COOCCURRENCES)
    # Per-stage busy fractions (observability.StepTimer.occupancy): the
    # pipeline-overlap diagnostic — a serial run's host+score sums to
    # <= ~100%, an overlapped run exceeds it. Latency: per-window
    # p50/p95/p99 from the fixed-log-bucket histograms — BENCH_* carries
    # tails, not just means (a 2x p99 regression is invisible in a mean).
    # Degradation counters ride along (robustness/degrade.py): a bench
    # number earned by shedding load is not the same bench number — zero
    # here is the claim that nothing was shed or quarantined.
    degradation = {
        "level": int(REGISTRY.gauge("cooc_degradation_level").get()),
        "shed_events_total": int(
            REGISTRY.gauge("cooc_shed_events_total").get()),
        "quarantined_total": int(
            REGISTRY.gauge("cooc_quarantined_lines_total").get()),
    }
    # Dispatch-path counters (--fused-window): how many windows took the
    # fused one-dispatch program vs the chained scatter+score path.
    dispatches = {
        "fused_dispatches": int(
            REGISTRY.gauge("cooc_fused_dispatches_total").get()),
        "chained_dispatches": int(
            REGISTRY.gauge("cooc_chained_dispatches_total").get()),
        # Fused-sparse shape specialization: distinct fused-program
        # shapes compiled (per-bucket churn; 0 on the chained path).
        "fused_bucket_compilations": int(
            REGISTRY.gauge("cooc_fused_bucket_compilations_total").get()),
    }
    # Compressed-state accounting (sparse backend; zeros elsewhere): the
    # raw-vs-encoded uplink pair from the ledger, plus the host index /
    # device slab footprint gauges the scorer refreshes per window.
    snap = LEDGER.snapshot()
    windows = max(int(REGISTRY.gauge("cooc_windows_fired").get()), 1)
    wire = {
        "windows": windows,
        "uplink_bytes_raw": snap["uplink_raw_bytes"],
        "uplink_bytes_encoded": snap["uplink_enc_bytes"],
        "h2d_bytes": snap["h2d_bytes"],
        "host_index_rss_bytes": int(
            REGISTRY.gauge("cooc_host_index_rss_bytes").get()),
        "slab_device_bytes": int(
            REGISTRY.gauge("cooc_slab_device_bytes").get()),
        "slab_live_cells": int(
            REGISTRY.gauge("cooc_slab_live_cells").get()),
    }
    # Tiered-state accounting (PR 9): spill/promote counters, the rows
    # the run MANAGED (device-resident + spilled to the host arena —
    # identical across arms on the same stream), and a digest of the
    # final top-K so the spill A/B arm can assert bit-identity without
    # holding both result tables.
    scorer = job.scorer
    rows_managed = 0
    if hasattr(scorer, "index"):
        rows_managed = len(scorer.index.rows.occupied())
        if getattr(scorer, "index_w", None) is not None:
            rows_managed += len(scorer.index_w.rows.occupied())
        store = getattr(scorer, "store", None)
        if getattr(store, "tiered", False):
            rows_managed += len(store.arena)
    digest = hashlib.sha256()
    snap = job.latest.snapshot()
    for item in sorted(snap):
        digest.update(repr((item, snap[item])).encode())
    spill = {
        "evictions_total": int(
            REGISTRY.gauge("cooc_spill_evictions_total").get()),
        "promotions_total": int(
            REGISTRY.gauge("cooc_spill_promotions_total").get()),
        "touches_total": int(
            REGISTRY.gauge("cooc_spill_row_touches_total").get()),
        "resident_rows": int(
            REGISTRY.gauge("cooc_spill_resident_rows").get()),
        "arena_bytes": int(
            REGISTRY.gauge("cooc_spill_arena_bytes").get()),
        "rows_managed": rows_managed,
        "results_digest": digest.hexdigest(),
    }
    return pairs, elapsed, job.step_timer.occupancy(elapsed), \
        REGISTRY.summaries(), degradation, dispatches, wire, spill


def query_storm(seconds: float = None, threads: int = None,
                user_space: int = 1_000_000) -> dict:
    """Closed-loop query storm: a keep-alive HTTP client pool hammers
    ``/recommend`` on a live ingesting job (PR-8 serving plane).

    The job ingests a Zipfian stream on its own thread (oracle backend:
    steady host-side window cadence with no compile pauses, so the storm
    measures the *query plane*, not XLA warm-up) while ``threads``
    keep-alive clients draw uniform user ids from a million-user space —
    mostly cold users (the popularity-fallback path, the realistic storm
    shape) with the Zipf-head users exercising the blend. Client-side
    latencies give qps + p50/p95/p99; the server-side
    ``cooc_query_seconds`` histogram rides along for cross-checking, and
    the snapshot generation span proves the storm overlapped live window
    swaps.
    """
    import http.client

    import numpy as np

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.io.synthetic import zipfian_interactions
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.observability import LEDGER
    from tpu_cooccurrence.observability.http import MetricsServer
    from tpu_cooccurrence.observability.registry import REGISTRY

    seconds = seconds if seconds is not None else float(
        os.environ.get("BENCH_STORM_SECONDS", 3.0))
    threads = threads if threads is not None else int(
        os.environ.get("BENCH_STORM_THREADS", 8))
    n_events = int(os.environ.get("BENCH_STORM_EVENTS", 200_000))
    REGISTRY.reset()
    LEDGER.reset()
    users, items, ts = zipfian_interactions(
        n_events, n_items=20_000, n_users=user_space, alpha=1.1, seed=9,
        events_per_ms=200)
    cfg = Config(window_size=100, seed=0xC0FFEE, item_cut=500,
                 user_cut=500, backend=Backend.ORACLE, serve_port=0)
    job = CooccurrenceJob(cfg)
    srv = MetricsServer(REGISTRY, counters=job.counters, ledger=LEDGER,
                        port=0, serving=job.serving).start()
    stop = threading.Event()
    latencies = [[] for _ in range(threads)]
    # Per-thread error tallies (summed at the end): a shared += would be
    # a read-modify-write raced across the pool and could undercount.
    errors = [0] * threads

    def client(tid: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        rng = np.random.default_rng(tid)
        lat = latencies[tid]
        while not stop.is_set():
            u = int(rng.integers(0, user_space))
            t0 = time.perf_counter()
            try:
                conn.request("GET", f"/recommend?user={u}&n=10")
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    errors[tid] += 1
                    continue
            except Exception:
                errors[tid] += 1
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                                  timeout=10)
                continue
            lat.append(time.perf_counter() - t0)
        conn.close()

    def ingest() -> None:
        chunk = 4000
        i = 0
        while not stop.is_set() and i < n_events:
            j = min(i + chunk, n_events)
            job.add_batch(users[i:j], items[i:j], ts[i:j])
            i = j

    gen0 = job.serving.generation
    feeder = threading.Thread(target=ingest, daemon=True)
    pool = [threading.Thread(target=client, args=(t,), daemon=True)
            for t in range(threads)]
    feeder.start()
    for t in pool:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in pool:
        t.join(timeout=30)
    feeder.join(timeout=120)
    job.finish()
    server_hist = REGISTRY.histogram("cooc_query_seconds").summary()
    srv.stop()
    flat = [x for lat in latencies for x in lat]
    total = len(flat)
    arr = np.asarray(flat) if flat else np.zeros(1)
    return {
        # Explicit status flag (ISSUE 13 satellite): a degraded arm
        # records {"ok": false, "error": ...} in bench_history.jsonl
        # instead of a silently absent block.
        "ok": True,
        "users": user_space,
        "threads": threads,
        "seconds": round(seconds, 3),
        "queries": total,
        "errors": sum(errors),
        "qps": round(total / max(seconds, 1e-9), 1),
        "query_p50_s": round(float(np.percentile(arr, 50)), 6),
        "query_p95_s": round(float(np.percentile(arr, 95)), 6),
        "query_p99_s": round(float(np.percentile(arr, 99)), 6),
        "generations": [gen0, job.serving.generation],
        "snapshot_swaps": job.serving.builder.swaps,
        "server_query_seconds": server_hist,
    }


def storm_client(url: str, seconds: float, threads: int,
                 fallback: str = None) -> dict:
    """Closed-loop keep-alive client pool against ONE replica (the
    ``--storm-client`` child mode of the fleet arm — client CPU must
    live outside the replicas' processes AND outside the orchestrating
    parent's GIL, or the fleet's aggregate qps would be client-bound).

    ``fallback``: a survivor's URL. On a connection failure (the chaos
    kill) the thread switches ALL remaining traffic there — the
    load-balancer drain. The failed attempt counts as a
    ``drain_failover``, not an error; errors AFTER the drain are the
    chaos case's acceptance metric (must be zero).
    """
    import http.client
    import urllib.parse

    import numpy as np

    def _conn(u):
        netloc = urllib.parse.urlparse(u).netloc
        host, _, port = netloc.partition(":")
        return http.client.HTTPConnection(host, int(port), timeout=10)

    latencies = [[] for _ in range(threads)]
    errors = [0] * threads
    failovers = [0] * threads
    stop = threading.Event()

    def client(tid: int) -> None:
        target = url
        conn = _conn(target)
        rng = np.random.default_rng(tid)
        lat = latencies[tid]
        while not stop.is_set():
            u = int(rng.integers(0, 1_000_000))
            t0 = time.perf_counter()
            try:
                conn.request("GET", f"/recommend?user={u}&n=10")
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    errors[tid] += 1
                    continue
            except Exception:
                conn.close()
                if fallback is not None and target != fallback:
                    # The drain: all remaining traffic to the survivor.
                    target = fallback
                    failovers[tid] += 1
                else:
                    errors[tid] += 1
                conn = _conn(target)
                continue
            lat.append(time.perf_counter() - t0)
        conn.close()

    pool = [threading.Thread(target=client, args=(t,), daemon=True)
            for t in range(threads)]
    for t in pool:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in pool:
        t.join(timeout=30)
    flat = [x for lat in latencies for x in lat]
    arr = (np.asarray(flat) if flat else np.zeros(1))
    return {
        "url": url,
        "threads": threads,
        "seconds": round(seconds, 3),
        "queries": len(flat),
        "errors": sum(errors),
        "drain_failovers": sum(failovers),
        "qps": round(len(flat) / max(seconds, 1e-9), 1),
        "query_p50_s": round(float(np.percentile(arr, 50)), 6),
        "query_p95_s": round(float(np.percentile(arr, 95)), 6),
        "query_p99_s": round(float(np.percentile(arr, 99)), 6),
    }


def _wait_replica(port_file: str, timeout_s: float = 90.0) -> dict:
    """Wait for a replica's port file AND a 200 /healthz; returns the
    ``{"port", "pid", "url"}`` record."""
    import urllib.request

    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        try:
            with open(port_file) as f:
                info = json.load(f)
            urllib.request.urlopen(info["url"] + "/healthz", timeout=2)
            return info
        except Exception:
            time.sleep(0.25)
    raise TimeoutError(f"replica never came up ({port_file})")


def _replica_health(url: str) -> dict:
    import urllib.request

    with urllib.request.urlopen(url + "/healthz", timeout=5) as r:
        return json.load(r)


def _fleet_storm() -> dict:
    """The replicated-serving-fleet arm (ISSUE 13).

    One live ingest job (sparse backend, ``--checkpoint-incremental``)
    commits delta generations throughout; stateless ``cooc-replica``
    subprocesses bootstrap from its checkpoints and tail the delta log.
    Three phases against the same live writer:

    * **single** — 1 replica, 1 client subprocess: the per-replica
      baseline;
    * **fleet** — N (default 3) replicas under the serving-gang
      supervisor (``cooc-replica --fleet N``), one client subprocess
      per replica: per-replica and AGGREGATE qps + tails — reads scale
      with replicas, not with the TPU job;
    * **chaos** — mid-storm, replica 0 is SIGKILLed: its client drains
      to a survivor (zero failed queries after drain), and the fleet
      supervisor's relaunched replica re-syncs from checkpoint + delta
      tail to the live generation.
    """
    import shutil
    import signal
    import tempfile

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.io.synthetic import zipfian_interactions
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.observability import LEDGER
    from tpu_cooccurrence.observability.registry import REGISTRY
    from tpu_cooccurrence.state import checkpoint as ckpt

    seconds = float(os.environ.get("BENCH_FLEET_SECONDS", 4.0))
    n_replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", 3))
    threads = int(os.environ.get("BENCH_FLEET_CLIENT_THREADS", 4))
    n_events = int(os.environ.get("BENCH_FLEET_EVENTS", 120_000))
    REGISTRY.reset()
    LEDGER.reset()
    users, items, ts = zipfian_interactions(
        n_events, n_items=20_000, n_users=1_000_000, alpha=1.1, seed=9,
        events_per_ms=200)
    state_dir = tempfile.mkdtemp(prefix="bench-fleet-")
    job = CooccurrenceJob(Config(
        window_size=50, seed=0xC0FFEE, item_cut=500, user_cut=500,
        backend=Backend.SPARSE, checkpoint_dir=state_dir,
        checkpoint_every_windows=2, checkpoint_retain=10_000,
        checkpoint_incremental=True))
    # Enough ingest for a bootstrap checkpoint, then keep the writer
    # live across both storms (generations keep committing — the
    # replicas must tail a MOVING log, not a finished one).
    warm = n_events // 3
    chunk = 4000
    for lo in range(0, warm, chunk):
        job.add_batch(users[lo:lo + chunk], items[lo:lo + chunk],
                      ts[lo:lo + chunk])
    if not ckpt.generations(state_dir, ""):
        job.checkpoint()
    stop_feed = threading.Event()
    # Pace the remaining stream across both storms (~2 storm windows),
    # so the delta log the replicas tail keeps MOVING the whole time.
    n_chunks = max((n_events - warm + chunk - 1) // chunk, 1)
    feed_sleep = max(0.02, 2.0 * seconds / n_chunks)

    def feed() -> None:
        lo = warm
        while not stop_feed.is_set() and lo < n_events:
            hi = min(lo + chunk, n_events)
            job.add_batch(users[lo:hi], items[lo:hi], ts[lo:hi])
            lo = hi
            time.sleep(feed_sleep)

    feeder = threading.Thread(target=feed, daemon=True)
    feeder.start()

    env = dict(os.environ)
    procs = []

    def spawn_replica(port_file: str, extra=()) -> "subprocess.Popen":
        p = subprocess.Popen(
            [sys.executable, "-m", "tpu_cooccurrence.serving.replica",
             "--state-dir", state_dir, "--port", "0",
             "--port-file", port_file, "--poll-interval-s", "0.2",
             "--stale-after-s", "0", *extra],
            env=env, cwd=REPO, stderr=subprocess.DEVNULL)
        procs.append(p)
        return p

    def spawn_client(url: str, fallback: str = None) -> "subprocess.Popen":
        cmd = [sys.executable, os.path.abspath(__file__),
               "--storm-client", url, str(seconds), str(threads)]
        if fallback:
            cmd.append(fallback)
        p = subprocess.Popen(cmd, env=env, cwd=REPO,
                             stdout=subprocess.PIPE, text=True)
        procs.append(p)
        return p

    def client_result(p: "subprocess.Popen") -> dict:
        out, _ = p.communicate(timeout=seconds + 120)
        for line in reversed(out.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError("storm client printed no result")

    try:
        # -- single-replica baseline ---------------------------------
        pf = os.path.join(state_dir, "single.port")
        single_proc = spawn_replica(pf)
        single = _wait_replica(pf)
        single_res = client_result(spawn_client(single["url"]))
        single_proc.terminate()

        # -- fleet storm + chaos -------------------------------------
        fleet_dir = os.path.join(state_dir, "fleet")
        fleet_proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_cooccurrence.serving.replica",
             "--state-dir", state_dir, "--fleet", str(n_replicas),
             "--fleet-dir", fleet_dir, "--poll-interval-s", "0.2",
             "--stale-after-s", "0", "--gang-stale-after-s", "0",
             "--restart-on-failure", "3"],
            env=env, cwd=REPO, stderr=subprocess.DEVNULL)
        procs.append(fleet_proc)
        infos = [_wait_replica(os.path.join(
            fleet_dir, f"replica.p{i}.port")) for i in range(n_replicas)]
        gen_start = _replica_health(infos[0]["url"])["replica"][
            "generation"]
        # Victim's client drains to replica 1; the rest have no chaos.
        clients = [spawn_client(
            infos[i]["url"],
            fallback=(infos[1]["url"] if i == 0 and n_replicas > 1
                      else None)) for i in range(n_replicas)]
        time.sleep(seconds * 0.4)
        os.kill(infos[0]["pid"], signal.SIGKILL)  # the chaos kill
        fleet_res = [client_result(c) for c in clients]

        # The supervisor relaunches slot 0; it must re-sync from
        # checkpoint + delta tail to the LIVE generation.
        stop_feed.set()
        feeder.join(timeout=120)
        job.finish()
        live_gen = ckpt.generations(state_dir, "")[0][0]
        relaunched_gen = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                info = _wait_replica(os.path.join(
                    fleet_dir, "replica.p0.port"), timeout_s=5)
                if info["pid"] != infos[0]["pid"]:
                    h = _replica_health(info["url"])
                    relaunched_gen = h["replica"]["generation"]
                    if relaunched_gen >= live_gen:
                        break
            except Exception:
                pass
            time.sleep(0.3)
        aggregate_qps = round(sum(r["qps"] for r in fleet_res), 1)
        survivors = fleet_res[1:] if n_replicas > 1 else fleet_res
        return {
            "ok": True,
            "seconds": round(seconds, 3),
            "events": n_events,
            "replicas": n_replicas,
            # Scaling context: aggregate qps scales with replicas only
            # while cores outnumber them (replica processes + client
            # processes + the live writer all need CPU) — a 2-core box
            # records ~1x honestly; the >= 2x claim needs the cores to
            # put the replicas on.
            "cpus": os.cpu_count(),
            "client_threads_per_replica": threads,
            "single": single_res,
            "fleet": {
                "per_replica_qps": [r["qps"] for r in fleet_res],
                "aggregate_qps": aggregate_qps,
                "queries": sum(r["queries"] for r in fleet_res),
                "query_p99_s_max": max(r["query_p99_s"]
                                       for r in fleet_res),
                "errors": sum(r["errors"] for r in fleet_res),
            },
            # The headline: reads scale with replicas (>= 2x at 3
            # replicas on uncontended cores; recorded honestly either
            # way — the arm runs wherever the bench runs).
            "qps_scaling": round(aggregate_qps
                                 / max(single_res["qps"], 1e-9), 3),
            "chaos": {
                "killed_pid": infos[0]["pid"],
                "drain_failovers": fleet_res[0]["drain_failovers"],
                # THE acceptance number: zero failed queries after the
                # drain (survivor errors are post-drain by definition).
                "errors_after_drain": sum(r["errors"]
                                          for r in survivors),
                "victim_errors_after_drain": fleet_res[0]["errors"],
                "relaunched": relaunched_gen is not None,
                "resynced_generation": relaunched_gen,
                "live_generation": live_gen,
            },
            "generations": [gen_start, live_gen],
        }
    finally:
        stop_feed.set()
        # SIGTERM first: the fleet supervisor's handler tears its
        # replica children down with it — a bare SIGKILL would orphan
        # them (no --run-seconds, polling a deleted dir forever).
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 15
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(deadline - time.monotonic(),
                                       0.1))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        # Belt and braces: any replica grandchild that survived its
        # supervisor is findable through the port-file pids.
        for dirpath, _dirs, files in os.walk(state_dir):
            for name in files:
                if not name.endswith(".port"):
                    continue
                try:
                    with open(os.path.join(dirpath, name)) as f:
                        os.kill(json.load(f)["pid"], signal.SIGKILL)
                except (OSError, ValueError, KeyError):
                    pass
        shutil.rmtree(state_dir, ignore_errors=True)


def _longtail_churn_stream(windows: int, users_per: int, events_per: int,
                           n_items: int, alpha: float, drift: int,
                           seed: int, window_ms: int):
    """Long-tail stream with genuinely COLD rows, for the spill arm.

    Two production shapes the plain Zipf generator cannot produce
    (reservoir expansion re-touches every history item's row on every
    event, so a persistent user base keeps nearly all rows hot):

    * **user cohorts** — each window has its own fresh user cohort;
      when a cohort leaves, its items stop being re-expanded, and
    * **catalog drift** — the Zipf head rotates ``drift`` item ids per
      window (new content replaces old), so even head rows go cold a
      few windows after the head moves past them.

    Rows touched once and never again are exactly the long-tail items
    the tiered store exists for.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_items + 1, dtype=np.float64)
    p = ranks ** -alpha
    p /= p.sum()
    us, its, tss = [], [], []
    for w in range(windows):
        u = (w * users_per
             + rng.integers(0, users_per, events_per)).astype(np.int64)
        i = (rng.choice(n_items, size=events_per, p=p)
             + w * drift) % n_items
        t = w * window_ms + np.sort(rng.integers(0, window_ms, events_per))
        us.append(u)
        its.append(i.astype(np.int64))
        tss.append(t.astype(np.int64))
    return (np.concatenate(us), np.concatenate(its),
            np.concatenate(tss))


def _rescale_arm() -> dict:
    """Autoscale-seam arm (ISSUE 15): pairs/s across the load-forced
    2→4 gang rescale on the churn stream.

    A real 2-worker CPU gang (the autoscaler is gang machinery; the arm
    must not fight the throughput bench for the chip, so it pins
    ``JAX_PLATFORMS=cpu`` like the other subprocess arms) ingests the
    churn stream with delay faults billed into three consecutive window
    walls — the same injection the chaos capstone uses — and a scale-up
    at ``--autoscale-trip-windows 2``. Scale-down is disabled (clear
    threshold beyond the stream) so the arm isolates ONE seam. From
    worker 0's journal: the rescale count, the **seam stall** (drain
    record to the first post-resume window — relaunch + jax init +
    cross-topology restore + first dispatch), **windows-to-recover**
    (post-resume windows until the wall drops back under twice the
    pre-seam median — recompile warm-up), and pre/post/overall pairs/s.
    """
    import tempfile

    windows = int(os.environ.get("BENCH_RESCALE_WINDOWS", 24))
    users_per = int(os.environ.get("BENCH_RESCALE_USERS_PER", 60))
    events_per = int(os.environ.get("BENCH_RESCALE_EVENTS_PER", 800))
    u, i, t = _longtail_churn_stream(
        windows=windows, users_per=users_per, events_per=events_per,
        n_items=4000, alpha=1.07, drift=100, seed=5, window_ms=100)
    work = tempfile.mkdtemp(prefix="bench-rescale-")
    try:
        csv = os.path.join(work, "in.csv")
        with open(csv, "w") as fh:
            for uu, ii, tt in zip(u.tolist(), i.tolist(), t.tolist()):
                fh.write(f"{uu},{ii},{tt}\n")
        jpath = os.path.join(work, "journal.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=1")
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_cooccurrence.cli",
             "-i", csv, "-ws", "100", "-s", "0xC0FFEE",
             "--backend", "sparse", "--num-shards", "2",
             "--checkpoint-dir", os.path.join(work, "ck"),
             "--checkpoint-every-windows", "1",
             "--checkpoint-retain", "100",
             "--gang-workers", "2", "--gang-heartbeat-s", "1",
             "--collective-timeout-s", "60", "--restart-delay-ms", "0",
             "--journal", jpath,
             "--degrade", "--degrade-window-wall-s", "2.0",
             "--degrade-trip-windows", "3",
             "--autoscale", "on", "--autoscale-min-workers", "2",
             "--autoscale-max-workers", "4",
             "--autoscale-trip-windows", "2",
             "--autoscale-clear-windows", "100000",
             "--autoscale-cooldown-windows", "2",
             "--inject-fault", "window_fire@0:3:delay_ms:2500",
             "--inject-fault", "window_fire@0:4:delay_ms:2500",
             "--inject-fault", "window_fire@0:5:delay_ms:2500",
             "--fault-state-dir", os.path.join(work, "faults")],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"rescale arm gang exited rc={proc.returncode}: "
                f"{proc.stderr[-500:]}")
        with open(jpath + ".p0") as f:
            recs = [json.loads(line) for line in f if line.strip()]
        wrecs = [r for r in recs if "seq" in r]
        scale = [r for r in recs if "autoscale" in r]
        if not scale or not wrecs:
            raise RuntimeError("rescale arm journal has no seam")
        drain = scale[0]
        pre = [r for r in wrecs if r["seq"] <= drain["window"]]
        post = sorted((r for r in wrecs if r["seq"] > drain["window"]),
                      key=lambda r: r["seq"])
        seam_stall = round(post[0]["wall_unix"] - drain["wall_unix"], 3)
        # Injected delays are load, not measurement: drop the delayed
        # windows (wall over the 2.0 s overload threshold the arm
        # configures) from the pre-seam baseline, or the recovery
        # cutoff would sit above every post-seam window and the metric
        # could never read anything but 0.
        pre_walls = sorted(
            w for w in (r["sample_seconds"] + r["score_seconds"]
                        for r in pre) if w < 2.0)
        baseline = (pre_walls[len(pre_walls) // 2] if pre_walls
                    else 0.05)
        recover = 0
        for r in post:
            if (r["sample_seconds"] + r["score_seconds"]
                    <= max(2 * baseline, 0.05)):
                break
            recover += 1

        def _rate(rs):
            span = rs[-1]["wall_unix"] - rs[0]["wall_unix"]
            return round(sum(r["pairs"] for r in rs) / max(span, 1e-9),
                         1)

        return {
            "ok": True,
            "events": int(len(u)),
            "windows": len(wrecs),
            "rescales": len(scale),
            "from_to": [int(drain["from"]), int(drain["to"])],
            "seam_stall_seconds": seam_stall,
            "windows_to_recover": recover,
            "pairs_per_sec": {
                "pre_seam": _rate(pre) if len(pre) > 1 else None,
                "post_seam": _rate(post) if len(post) > 1 else None,
                "overall": _rate(wrecs),
            },
        }
    finally:
        import shutil

        shutil.rmtree(work, ignore_errors=True)


def _fused_gang_arm() -> dict:
    """Fused-vs-chained gang A/B (ISSUE 16): one launch per worker.

    Three real 2-worker CPU gangs (multi-controller sharded sparse —
    the production topology, pinned to ``JAX_PLATFORMS=cpu`` like the
    other subprocess arms) ingest the same steady-keyed stream (fixed
    event population repeated per window, so the pair population
    stabilizes after window 1 and the fused path owns the steady
    state):

    * ``--fused-window off`` — the chained two-launch baseline;
    * ``--fused-window on`` — the one-launch fused window; per-worker
      dispatch splits and bucket compiles from each worker's journal;
    * ``--fused-window on`` + the ISSUE-15 load-forced 2→4 rescale —
      the **seam-recompile cost**: the first post-seam window must
      route chained (cold plans), and the fresh topology's bucket
      recompile count and seam stall ride the arm.
    """
    import tempfile

    import numpy as np

    windows = int(os.environ.get("BENCH_FUSED_GANG_WINDOWS", 14))
    events_per = int(os.environ.get("BENCH_FUSED_GANG_EVENTS_PER", 500))
    rng = np.random.default_rng(16)
    base_u = rng.integers(0, 8, events_per)
    base_i = rng.integers(0, 64, events_per)
    work = tempfile.mkdtemp(prefix="bench-fused-gang-")
    try:
        csv = os.path.join(work, "in.csv")
        with open(csv, "w") as fh:
            for w in range(windows):
                for uu, ii in zip(base_u.tolist(), base_i.tolist()):
                    fh.write(f"{uu},{ii},{w * 100 + 50}\n")
            fh.write(f"0,9999,{windows * 100 + 50}\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=1")

        def gang_run(tag, fused, seam):
            jpath = os.path.join(work, f"journal-{tag}.jsonl")
            argv = [sys.executable, "-m", "tpu_cooccurrence.cli",
                    "-i", csv, "-ws", "100", "-s", "0xC0FFEE",
                    "--backend", "sparse", "--num-shards", "2",
                    "--gang-workers", "2", "--gang-heartbeat-s", "1",
                    "--collective-timeout-s", "60",
                    "--restart-delay-ms", "0",
                    "--fused-window", fused, "--journal", jpath]
            if seam:
                argv += ["--checkpoint-dir", os.path.join(work, "ck"),
                         "--checkpoint-every-windows", "1",
                         "--checkpoint-retain", "100",
                         "--degrade", "--degrade-window-wall-s", "2.0",
                         "--degrade-trip-windows", "3",
                         "--autoscale", "on",
                         "--autoscale-min-workers", "2",
                         "--autoscale-max-workers", "4",
                         "--autoscale-trip-windows", "2",
                         "--autoscale-clear-windows", "100000",
                         "--autoscale-cooldown-windows", "2",
                         "--inject-fault", "window_fire@0:3:delay_ms:2500",
                         "--inject-fault", "window_fire@0:4:delay_ms:2500",
                         "--inject-fault", "window_fire@0:5:delay_ms:2500",
                         "--fault-state-dir", os.path.join(work, "faults")]
            proc = subprocess.run(argv, env=env, cwd=REPO,
                                  capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"fused-gang arm ({tag}) exited "
                    f"rc={proc.returncode}: {proc.stderr[-500:]}")
            out = {}
            for p in ("p0", "p1"):
                with open(f"{jpath}.{p}") as f:
                    out[p] = [json.loads(line) for line in f
                              if line.strip()]
            return out

        def _rate(recs):
            wrecs = sorted((r for r in recs if "seq" in r),
                           key=lambda r: r["seq"])
            span = wrecs[-1]["wall_unix"] - wrecs[0]["wall_unix"]
            return (sum(r["pairs"] for r in wrecs) / max(span, 1e-9),
                    wrecs)

        def _split(wrecs):
            flags = [r.get("fused", 0) for r in wrecs]
            return {"fused": int(sum(flags)),
                    "chained": int(len(flags) - sum(flags)),
                    "bucket_compiles": int(
                        wrecs[-1].get("fused_compiles", 0))}

        chained = gang_run("chained", "off", seam=False)
        fused = gang_run("fused", "on", seam=False)
        c_rate, _ = _rate(chained["p0"])
        f_rate, _ = _rate(fused["p0"])
        per_worker = {p: _split(_rate(fused[p])[1]) for p in fused}
        if not any(s["fused"] for s in per_worker.values()):
            raise RuntimeError(
                "fused-gang arm: no worker ever took the fused path")

        seam = gang_run("seam", "on", seam=True)
        recs0 = seam["p0"]
        scale = [r for r in recs0 if "autoscale" in r]
        if not scale:
            raise RuntimeError("fused-gang seam run never rescaled")
        drain = scale[0]
        _, wrecs = _rate(recs0)
        post = [r for r in wrecs if r["seq"] > drain["window"]]
        return {
            "ok": True,
            "windows": windows,
            "pairs_per_sec_chained": round(c_rate, 1),
            "pairs_per_sec_fused": round(f_rate, 1),
            "vs_chained": round(f_rate / max(c_rate, 1e-9), 3),
            "per_worker_dispatches": per_worker,
            "seam": {
                "from_to": [int(drain["from"]), int(drain["to"])],
                "stall_seconds": round(
                    post[0]["wall_unix"] - drain["wall_unix"], 3),
                # Cold plans: the window after the seam must not fuse.
                "first_post_seam_fused": int(post[0].get("fused", 0)),
                # What the fresh topology paid to re-specialize.
                "recompiles_post_seam": int(
                    post[-1].get("fused_compiles", 0)),
            },
        }
    finally:
        import shutil

        shutil.rmtree(work, ignore_errors=True)


def _checkpoint_arm(sp_u, sp_i, sp_t, window_ms: int = 100) -> dict:
    """Full-vs-incremental checkpoint A/B on the churn stream (PR 12).

    Three ingest runs feed window-aligned slices and poll
    ``state/checkpoint.LAST_COMMIT`` after each, so every generation's
    committed bytes/seconds land in the arm (not just the last):

    * ``full@fine`` vs ``incr@fine`` — same cadence, so the
      commit-bytes ratio is apples-to-apples (the acceptance headline:
      median incremental generation ≪ the full rewrite);
    * ``full@coarse`` — the cadence expensive full commits force in
      practice; its crash-replay tail is what the incremental run's
      fine cadence eliminates.

    Restore-to-first-window is measured for real: restore from the
    newest generation, replay the events ingested after that commit,
    stop at the first fired window.
    """
    import statistics
    import tempfile

    import numpy as np

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.observability import LEDGER
    from tpu_cooccurrence.observability.registry import REGISTRY
    from tpu_cooccurrence.state import checkpoint as ckpt

    # The coarse cadence models what expensive full rewrites force in
    # practice: a rational interval scales with commit cost, and the
    # measured full-vs-delta gap is ~10x bytes / ~2x seconds (plus
    # whatever the durable-storage link multiplies it by).
    fine = int(os.environ.get("BENCH_CKPT_EVERY_FINE", 2))
    coarse = int(os.environ.get("BENCH_CKPT_EVERY_COARSE", 16))
    bounds = np.searchsorted(
        sp_t, np.arange(window_ms, int(sp_t[-1]) + 2 * window_ms,
                        window_ms))

    def cfg_kw(d, incremental, every):
        return dict(window_size=window_ms, seed=0xC0FFEE, item_cut=500,
                    user_cut=500, backend=Backend.SPARSE,
                    checkpoint_dir=d, checkpoint_every_windows=every,
                    checkpoint_retain=10_000,
                    checkpoint_incremental=incremental,
                    checkpoint_compact_ratio=0.5)

    # Both arms "crash" at the SAME mid-stream point — deliberately LATE
    # in a coarse checkpoint cycle (the expected-case crash position:
    # uniformly random arrival lands ~coarse/2 windows past the last
    # coarse commit; we pin coarse-2 for determinism): each arm restores
    # from ITS newest commit and replays the input ingested after it —
    # the replay-tail difference IS the cadence difference cheap
    # commits buy.
    crash_at = max((len(bounds) // coarse) * coarse - 2, coarse)

    def ingest(incremental, every):
        import shutil

        REGISTRY.reset()
        LEDGER.reset()
        ckpt.LAST_COMMIT = None
        d = tempfile.mkdtemp(prefix="bench-ckpt-")
        job = CooccurrenceJob(Config(**cfg_kw(d, incremental, every)))
        commits, idx_at = [], []
        crash = None
        last_gen = 0
        lo = 0
        for w, hi in enumerate(bounds):
            if hi > lo:
                job.add_batch(sp_u[lo:hi], sp_i[lo:hi], sp_t[lo:hi])
                lo = hi
            c = ckpt.LAST_COMMIT
            if c is not None and c["gen"] != last_gen:
                last_gen = c["gen"]
                commits.append(dict(c))
                idx_at.append(hi)
            if w == crash_at and crash is None:
                # Snapshot the checkpoint dir as of the crash point.
                shutil.copytree(d, d + "-crash")
                crash = (d + "-crash", idx_at[-1] if idx_at else 0,
                         job.windows_fired)
        job.finish()
        c = ckpt.LAST_COMMIT
        if c is not None and c["gen"] != last_gen:
            commits.append(dict(c))
            idx_at.append(len(sp_u))
        return d, job, commits, crash

    def restore_to_first_window(crash, incremental, every):
        """(first-window seconds, catch-up seconds, replayed windows):
        restore from the crash snapshot, replay the input ingested
        after its newest commit until (a) the first window fires and
        (b) the run is back AT the crash point — (b) is where the fine
        cadence cheap commits buy pays off (shorter replay tail)."""
        snap_dir, resume_idx, fired_at_crash = crash
        REGISTRY.reset()
        LEDGER.reset()
        t0 = time.monotonic()
        job = CooccurrenceJob(Config(**cfg_kw(snap_dir, incremental,
                                              every)))
        job.restore()
        w0 = job.windows_fired
        first_window_s = None
        replayed = 0
        lo = resume_idx
        for hi in bounds:
            if hi <= lo:
                continue
            job.add_batch(sp_u[lo:hi], sp_i[lo:hi], sp_t[lo:hi])
            replayed += 1
            lo = hi
            if first_window_s is None and job.windows_fired > w0:
                first_window_s = time.monotonic() - t0
            if job.windows_fired >= fired_at_crash:
                break
        catch_up_s = time.monotonic() - t0
        job.abort()
        return first_window_s or catch_up_s, catch_up_s, replayed

    d_full, j_full, commits_full, crash_full = ingest(False, fine)
    d_incr, _j_incr, commits_incr, crash_incr = ingest(True, fine)
    d_coarse, _j_coarse, _commits_coarse, crash_coarse = ingest(
        False, coarse)

    full_bytes = [c["bytes"] for c in commits_full]
    delta_bytes = [c["bytes"] for c in commits_incr
                   if c["kind"] == "delta"]
    coarse_restore, coarse_catch, coarse_replay = \
        restore_to_first_window(crash_coarse, False, coarse)
    incr_restore, incr_catch, incr_replay = restore_to_first_window(
        crash_incr, True, fine)
    import shutil

    for path in (d_full, d_incr, d_coarse, crash_full[0],
                 crash_incr[0], crash_coarse[0]):
        shutil.rmtree(path, ignore_errors=True)
    med = statistics.median
    return {
        "events": len(sp_u),
        "windows": j_full.windows_fired,
        "every_fine": fine,
        "every_coarse": coarse,
        "generations_full": len(commits_full),
        "generations_incremental": len(commits_incr),
        "delta_generations": len(delta_bytes),
        "compactions": sum(
            1 for i, c in enumerate(commits_incr[1:], 1)
            if c["kind"] == "full"
            and commits_incr[i - 1]["kind"] == "delta"),
        "chain_len_max": max(
            (c["chain_len"] for c in commits_incr), default=0),
        "full_commit_bytes_median": med(full_bytes) if full_bytes else 0,
        "incr_commit_bytes_median": (med(delta_bytes)
                                     if delta_bytes else 0),
        # The acceptance headline: median incremental generation vs the
        # median full rewrite at the SAME cadence.
        "commit_bytes_ratio": round(
            med(delta_bytes) / max(med(full_bytes), 1), 4)
        if delta_bytes and full_bytes else None,
        "full_commit_seconds_median": round(
            med([c["seconds"] for c in commits_full]), 4)
        if commits_full else 0,
        "incr_commit_seconds_median": round(
            med([c["seconds"] for c in commits_incr
                 if c["kind"] == "delta"]), 4) if delta_bytes else 0,
        # Crash-replay comparison: full checkpoints at the coarse
        # cadence their cost forces vs incremental at the fine one.
        "restore_to_first_window_seconds": {
            "full_coarse": round(coarse_restore, 3),
            "incremental": round(incr_restore, 3),
        },
        "restore_catch_up_seconds": {
            "full_coarse": round(coarse_catch, 3),
            "incremental": round(incr_catch, 3),
        },
        "replay_windows": {
            "full_coarse": coarse_replay,
            "incremental": incr_replay,
        },
    }


def _uplink_per_window(latency: dict) -> float:
    """Mean host->device bytes per fired window, from the run's
    ``cooc_window_uplink_bytes`` histogram summary (TransferLedger-fed:
    the fused-vs-chained uplink comparison the basket format exists
    for)."""
    h = (latency or {}).get("cooc_window_uplink_bytes") or {}
    count = h.get("count") or 0
    return round(h.get("sum", 0.0) / count, 1) if count else 0.0


# Shared execute-a-real-op probe (grant_watch imports no jax, so this
# parent stays jax-free). Probed EVERY run — the old 1h success marker
# let a grant that died mid-hour send the official capture into an
# unbounded device run (VERDICT r3, Weak #2).
from tpu_cooccurrence.bench.grant_watch import probe_backend


def _record_onchip(value: float, vs_baseline: float, backend: str,
                   pipeline_depth: int, occupancy: dict,
                   latency: dict = None, degradation: dict = None,
                   fused: dict = None, compression: dict = None,
                   serving: dict = None, spill: dict = None,
                   fused_sparse: dict = None,
                   checkpoint: dict = None,
                   fleet: dict = None,
                   rescale: dict = None,
                   fused_gang: dict = None,
                   regression: dict = None) -> None:
    """Append a successful on-chip measurement to the bench history.

    ``pipeline_depth`` and the per-stage occupancy ride along so the
    overlap win (host-busy% + score-busy% > 100) is visible in the
    trajectory, not just in a single run's stdout; ``latency`` carries
    the per-window p50/p95/p99 summaries for the same reason — tail
    regressions must be visible across PRs; ``degradation`` carries the
    shed/quarantine counters so a throughput number earned by shedding
    load is marked as such in the trajectory; ``fused`` carries the
    fused-vs-chained A/B (pairs/s ratio, dispatch counts, per-window
    uplink bytes) so the one-dispatch window's win — and the
    CPU-fallback neutrality of the chained default — are visible in
    ``bench_history.jsonl``.
    """
    entry = {"ts": time.strftime("%Y-%m-%d %H:%M:%S"),
             "pairs_per_sec": value, "vs_baseline": vs_baseline,
             "backend": backend, "pipeline_depth": pipeline_depth,
             "occupancy": occupancy}
    if latency:
        entry["latency"] = latency
    if degradation:
        entry["degradation"] = degradation
    if fused:
        entry["fused"] = fused
    if compression:
        # The PR-7 A/B: uplink_bytes_raw / uplink_bytes_encoded /
        # host_index_rss_bytes and effective-cells-per-byte per dtype,
        # trajectory-visible like the fused arm.
        entry["compression"] = compression
    if serving:
        # The PR-8 storm: qps + query p50/p95/p99 against a live
        # ingesting job — the user-facing metric every later perf PR
        # moves, trajectory-visible like the other arms.
        entry["serving"] = serving
    if spill:
        # The PR-9 tiered-state A/B: effective rows per HBM byte off/on,
        # eviction/promotion counters, hot-row hit rate and the
        # bit-identity verdict — the elastic-state headline numbers.
        entry["spill"] = spill
    if fused_sparse:
        # The PR-11 fused-SPARSE A/B: one-dispatch sparse window vs the
        # chained sparse path (pairs/s ratio, per-window uplink bytes,
        # bucket compile counts) — trajectory-visible like the dense
        # fused arm, CPU-neutrality included.
        entry["fused_sparse"] = fused_sparse
    if checkpoint:
        # The PR-12 incremental-checkpoint A/B: full-vs-delta commit
        # bytes + seconds per generation on the churn stream, and the
        # restore-to-first-window comparison — the commit-bandwidth and
        # restart-replay headline numbers.
        entry["checkpoint"] = checkpoint
    if fleet:
        # The ISSUE-13 serving-fleet storm: 1-vs-N replica qps +
        # aggregate scaling over the live delta log, and the kill-one
        # chaos verdict (errors after drain, relaunch re-sync) —
        # trajectory-visible like every other arm, ok:false when the
        # arm degraded.
        entry["fleet"] = fleet
    if rescale:
        # The ISSUE-15 autoscale seam: pairs/s across the load-forced
        # 2→4 gang rescale (seam stall seconds, windows-to-recover,
        # rescale count) — the cost of scaling must stay trajectory-
        # visible, or a "free" rescale that quietly stalls a minute
        # would never be caught.
        entry["rescale"] = rescale
    if fused_gang:
        # The ISSUE-16 fused-SHARDED A/B: one launch per worker vs the
        # chained two-launch gang on the steady-keyed stream (pairs/s
        # ratio, per-worker dispatch splits, bucket compiles, and the
        # 2→4 seam's recompile cost) — trajectory-visible like the
        # single-process fused arms.
        entry["fused_gang"] = fused_gang
    if regression:
        # The ISSUE-17 regression gate's verdict (bench.regress):
        # whether THIS run's tracked metrics sat inside the history's
        # noise bands when it landed. flatten() skips this subtree, so
        # a recorded verdict never bands future verdicts.
        entry["regression"] = regression
    with open(_HISTORY, "a") as f:
        f.write(json.dumps(entry) + "\n")


def _last_onchip():
    """Most recent recorded on-chip measurement, or None. Skips corrupt
    lines (e.g. a truncated append from a crashed run) — a bad history
    must not take down the fallback path it exists to serve."""
    try:
        last = None
        with open(_HISTORY) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    last = json.loads(line)
                except ValueError:
                    continue
        return last
    except OSError:
        return None


def measure() -> None:
    """Child mode: measure on whatever platform this process gets.

    Prints the one JSON line; exit code 0 iff the measurement completed.
    The parent enforces the wall-clock deadline from outside.
    """
    if os.environ.get("BENCH_EXPECT_ACCEL"):
        # The parent probed an accelerator; if jax silently fell back to
        # CPU between the probe and here (grant died at backend init),
        # fail so the parent re-runs the labeled cpu-fallback path —
        # a dead-tunnel number must not publish as an honest CPU run.
        import jax

        if jax.default_backend() == "cpu":
            sys.stderr.write("bench: expected an accelerator but jax "
                             "fell back to cpu\n")
            return 1

    from tpu_cooccurrence.io.synthetic import zipfian_interactions

    n_events = int(os.environ.get("BENCH_EVENTS", 400_000))
    n_items = int(os.environ.get("BENCH_ITEMS", 20_000))
    pipeline_depth = int(os.environ.get("BENCH_PIPELINE_DEPTH", 0))
    # Optional flight recorder for the measured runs (BENCH_JOURNAL=path):
    # the three measured runs append to one JSONL, and its path rides the
    # output line so the artifact is findable from the BENCH_* record.
    journal = os.environ.get("BENCH_JOURNAL") or None
    users, items, ts = zipfian_interactions(
        n_events, n_items=n_items, n_users=5_000, alpha=1.1, seed=3,
        events_per_ms=200)

    # Untimed warmup on the full stream: populates the jit caches for every
    # pad bucket the measured run will hit, so the metric is steady-state
    # throughput rather than one-time XLA compile latency.
    run("device", users, items, ts, num_items=n_items, window_ms=100,
        pipeline_depth=pipeline_depth)

    # Median of three measured runs: the benched chip can be reached over a
    # shared tunnel, where single-run wall-clock swings by 2x under
    # contention. The occupancy/latency published are the median run's.
    samples = []
    for _ in range(3):
        pairs, elapsed, occupancy, latency, degradation, _, _, _ = run(
            "device", users, items, ts, num_items=n_items, window_ms=100,
            pipeline_depth=pipeline_depth, journal=journal)
        samples.append((pairs / max(elapsed, 1e-9), occupancy, latency,
                        degradation))
    samples.sort(key=lambda s: s[0])
    pairs_per_sec, occupancy, latency, degradation = samples[1]

    # Fused-window A/B arm (--fused-window auto): on a real chip this is
    # the one-dispatch window program; on CPU auto resolves OFF and the
    # arm re-measures the chained path — which doubles as the
    # CPU-fallback neutrality check (vs_chained ~ 1.0, zero fused
    # dispatches). Same methodology as the chained arm — its own
    # untimed warmup (the main warmup ran chained, and the fused shape
    # ladder's first compiles must not bill the timed runs), the same
    # journal setting, and the median of three on the contended tunnel —
    # vs_chained is a headline number, not a smoke probe. Per-window
    # uplink bytes come from the TransferLedger via the uplink
    # histogram, so the basket-vs-COO wire cut is a measured number.
    run("device", users, items, ts, num_items=n_items, window_ms=100,
        pipeline_depth=pipeline_depth, fused_window="auto")
    f_samples = []
    for _ in range(3):
        f_pairs, f_elapsed, _, f_latency, _, f_dispatches, _, _ = run(
            "device", users, items, ts, num_items=n_items, window_ms=100,
            pipeline_depth=pipeline_depth, journal=journal,
            fused_window="auto")
        f_samples.append((f_pairs / max(f_elapsed, 1e-9), f_latency,
                          f_dispatches))
    f_samples.sort(key=lambda s: s[0])
    f_rate, f_latency, f_dispatches = f_samples[1]
    fused_info = {
        "mode": "auto",
        "pairs_per_sec": round(f_rate, 1),
        "vs_chained": round(f_rate / max(pairs_per_sec, 1e-9), 3),
        "uplink_bytes_per_window": _uplink_per_window(f_latency),
        "chained_uplink_bytes_per_window": _uplink_per_window(latency),
        **f_dispatches,
    }

    # Compression A/B arm (sparse backend): raw int32 slab + raw wire vs
    # the PR-7 compressed default (int16 cells with wide-promotion +
    # packed delta/bit-packed uplink + bitmap row index). Same
    # methodology as the fused arm — per-arm untimed warmup, median of
    # three — on a truncated stream (the sparse CPU path is slower than
    # dense and the arm measures *wire/footprint* ratios, which converge
    # long before throughput medians do). Ledger-measured: the uplink
    # cut and the effective-cells-per-slab-byte pair are the tentpole's
    # headline numbers.
    comp_events = min(len(users),
                      int(os.environ.get("BENCH_COMPRESS_EVENTS", 120_000)))
    cu, ci, ct = users[:comp_events], items[:comp_events], ts[:comp_events]

    def _comp_arm(wire, cell):
        run("sparse", cu, ci, ct, num_items=n_items, window_ms=100,
            wire_format=wire, cell_dtype=cell)  # warmup (compiles)
        arm = []
        for _ in range(3):
            c_pairs, c_elapsed, _, _, _, _, c_wire, _ = run(
                "sparse", cu, ci, ct, num_items=n_items, window_ms=100,
                wire_format=wire, cell_dtype=cell)
            arm.append((c_pairs / max(c_elapsed, 1e-9), c_wire))
        arm.sort(key=lambda s: s[0])
        return arm[1]

    raw_rate, raw_wire = _comp_arm("raw", "int32")
    pkd_rate, pkd_wire = _comp_arm("packed", "int16")

    def _cells_per_byte(w):
        return round(w["slab_live_cells"] / max(w["slab_device_bytes"], 1),
                     4)

    windows_pkd = max(pkd_wire["windows"], 1)
    compression = {
        "events": comp_events,
        "pairs_per_sec_raw": round(raw_rate, 1),
        "pairs_per_sec_packed": round(pkd_rate, 1),
        "vs_raw": round(pkd_rate / max(raw_rate, 1e-9), 3),
        # Ledger-measured per-window uplink pair: what the raw layout
        # would have shipped vs what the packed encoder actually shipped
        # (same run, so the two describe identical windows).
        "uplink_bytes_raw": round(
            pkd_wire["uplink_bytes_raw"] / windows_pkd, 1),
        "uplink_bytes_encoded": round(
            pkd_wire["uplink_bytes_encoded"] / windows_pkd, 1),
        "uplink_cut": round(
            pkd_wire["uplink_bytes_raw"]
            / max(pkd_wire["uplink_bytes_encoded"], 1), 2),
        "host_index_rss_bytes": pkd_wire["host_index_rss_bytes"],
        "host_index_rss_bytes_raw_arm": raw_wire["host_index_rss_bytes"],
        "effective_cells_per_byte": {
            "int32": _cells_per_byte(raw_wire),
            "int16": _cells_per_byte(pkd_wire),
        },
    }

    # Fused-SPARSE A/B arm (--fused-window auto on the sparse backend):
    # chained vs fused over the same truncated stream as the compression
    # arm, compressed defaults on BOTH arms (int16 cells + packed wire —
    # the fused program decodes the packed uplink in its prologue, so
    # the two levers compose under measurement). On a real chip this is
    # the one-dispatch sparse window; on CPU auto resolves OFF and the
    # arm re-measures the chained path — the CPU-neutrality check
    # (vs_chained ~ 1.0, zero fused dispatches), exactly like the dense
    # fused arm. Per-arm untimed warmup, median of three; per-window
    # uplink bytes ride the ledger-fed histogram, bucket compile counts
    # ride the shape-specialization gauge.
    def _sparse_fused_arm(fused):
        run("sparse", cu, ci, ct, num_items=n_items, window_ms=100,
            wire_format="packed", cell_dtype="int16",
            fused_window=fused)  # warmup (compiles)
        arm = []
        for _ in range(3):
            s_pairs, s_elapsed, _, s_lat, _, s_disp, s_wire, _ = run(
                "sparse", cu, ci, ct, num_items=n_items, window_ms=100,
                wire_format="packed", cell_dtype="int16",
                fused_window=fused)
            arm.append((s_pairs / max(s_elapsed, 1e-9), s_lat, s_disp,
                        s_wire))
        arm.sort(key=lambda s: s[0])
        return arm[1]

    sc_rate, sc_lat, _sc_disp, sc_wire = _sparse_fused_arm("off")
    sf_rate, sf_lat, sf_disp, sf_wire = _sparse_fused_arm("auto")
    sf_windows = max(sf_wire["windows"], 1)
    fused_sparse = {
        "mode": "auto",
        "pairs_per_sec_chained": round(sc_rate, 1),
        "pairs_per_sec_fused": round(sf_rate, 1),
        "vs_chained": round(sf_rate / max(sc_rate, 1e-9), 3),
        "uplink_bytes_per_window": _uplink_per_window(sf_lat),
        "chained_uplink_bytes_per_window": _uplink_per_window(sc_lat),
        "uplink_bytes_encoded_per_window": round(
            sf_wire["uplink_bytes_encoded"] / sf_windows, 1),
        **sf_disp,
    }

    # Tiered-state (spill) A/B arm (PR 9): the SAME long-tail churn
    # stream through the sparse backend with tiering off vs on. The
    # headline pair is deterministic footprint, not timing — effective
    # rows per HBM byte (rows managed / device slab bytes; rows managed
    # is identical across arms by construction) and the hot-row hit
    # rate — so one run per arm suffices; and the results digest pins
    # the bit-identity claim (spill/promote is exact movement). The
    # stream mixes user-cohort churn with catalog drift: the two
    # production shapes that actually create cold rows (see
    # _longtail_churn_stream).
    sp_windows = int(os.environ.get("BENCH_SPILL_WINDOWS", 60))
    sp_u, sp_i, sp_t = _longtail_churn_stream(
        windows=sp_windows, users_per=150, events_per=2500,
        n_items=60_000, alpha=1.07, drift=400, seed=11, window_ms=100)
    sp_thr = int(os.environ.get("BENCH_SPILL_THRESHOLD", 4))

    def _spill_arm(threshold, frac):
        s_pairs, s_elapsed, _, _, _, _, s_wire, s_spill = run(
            "sparse", sp_u, sp_i, sp_t, num_items=60_000, window_ms=100,
            spill_threshold_windows=threshold,
            spill_target_hbm_frac=frac)
        return s_pairs / max(s_elapsed, 1e-9), s_wire, s_spill

    off_rate, off_wire, off_spill = _spill_arm(0, 0.5)
    on_rate, on_wire, on_spill = _spill_arm(sp_thr, 0.0)

    def _rows_per_byte(sp, w):
        return sp["rows_managed"] / max(w["slab_device_bytes"], 1)

    spill_info = {
        "events": len(sp_u),
        "threshold_windows": sp_thr,
        "rows_managed": on_spill["rows_managed"],
        "slab_device_bytes_off": off_wire["slab_device_bytes"],
        "slab_device_bytes_on": on_wire["slab_device_bytes"],
        "effective_rows_per_hbm_byte": {
            "off": round(_rows_per_byte(off_spill, off_wire), 8),
            "on": round(_rows_per_byte(on_spill, on_wire), 8),
        },
        "rows_per_hbm_byte_gain": round(
            _rows_per_byte(on_spill, on_wire)
            / max(_rows_per_byte(off_spill, off_wire), 1e-12), 3),
        "spill_evictions_total": on_spill["evictions_total"],
        "promotions_total": on_spill["promotions_total"],
        "hot_row_hit_rate": round(
            1.0 - on_spill["promotions_total"]
            / max(on_spill["touches_total"], 1), 4),
        "arena_bytes": on_spill["arena_bytes"],
        "resident_rows": on_spill["resident_rows"],
        "pairs_per_sec_off": round(off_rate, 1),
        "pairs_per_sec_on": round(on_rate, 1),
        # The whole point: exact movement, never approximation.
        "identical_topk": (on_spill["results_digest"]
                           == off_spill["results_digest"]),
    }

    # Incremental-checkpoint arm (PR 12): the SAME long-tail churn
    # stream (cold rows = churn a fraction of accumulated state — the
    # regime incremental commits exist for), full-vs-incremental at the
    # same fine cadence for the commit-bytes ratio, plus the
    # restore-to-first-window comparison: a full-checkpoint run is
    # forced onto a COARSE cadence by its commit cost, so a crash
    # replays more input; the incremental run checkpoints every other
    # window and resumes almost immediately.
    try:
        ckpt_info = _checkpoint_arm(sp_u, sp_i, sp_t, window_ms=100)
    except Exception as exc:
        ckpt_info = {"error": f"{type(exc).__name__}: {exc}"}

    # Query-storm arm (PR-8 serving plane): closed-loop qps + query
    # latency tails from a keep-alive HTTP pool against a live ingesting
    # job (million-user id space). Host-side plane, so the arm runs
    # identically on-chip and on the CPU fallback; it must never kill
    # the throughput bench it rides along with.
    try:
        serving_storm = query_storm()
    except Exception as exc:
        # ok: false — the degraded arm must be RECORDED as degraded in
        # bench JSON + history, not read as a silently absent block.
        serving_storm = {"ok": False,
                         "error": f"{type(exc).__name__}: {exc}"}

    # Replicated-serving fleet arm (ISSUE 13): 1-vs-3 stateless read
    # replicas (cooc-replica subprocesses) tailing the same live
    # incremental-checkpoint delta log, client subprocesses hammering
    # each replica (client CPU out of this process's GIL so the fleet's
    # aggregate is server-bound), plus the kill-one chaos case: a
    # replica dies mid-storm, its client drains to a survivor with zero
    # failed queries after the drain, and the fleet supervisor's
    # relaunched replica re-syncs from checkpoint + delta tail to the
    # live generation.
    try:
        fleet_storm = _fleet_storm()
    except Exception as exc:
        fleet_storm = {"ok": False,
                       "error": f"{type(exc).__name__}: {exc}"}

    # Autoscale-seam arm (ISSUE 15): pairs/s across a load-forced 2→4
    # gang rescale — seam stall seconds, windows-to-recover and the
    # rescale count, from the gang's own journal.
    try:
        rescale_info = _rescale_arm()
    except Exception as exc:
        rescale_info = {"ok": False,
                        "error": f"{type(exc).__name__}: {exc}"}

    # Fused-gang arm (ISSUE 16): chained-vs-fused A/B at
    # --gang-workers 2 — one launch per worker, per-worker dispatch
    # splits, bucket compiles, and the 2→4 seam-recompile cost.
    try:
        fused_gang_info = _fused_gang_arm()
    except Exception as exc:
        fused_gang_info = {"ok": False,
                           "error": f"{type(exc).__name__}: {exc}"}

    # Baseline: the exact host (oracle) backend on the same stream, cached
    # in .bench_baseline.json on first run.
    baseline_path = os.path.join(REPO, ".bench_baseline.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)["pairs_per_sec"]
    else:
        b_pairs, b_elapsed, _, _, _, _, _, _ = run("oracle", users, items, ts,
                                             num_items=n_items,
                                             window_ms=100)
        baseline = b_pairs / max(b_elapsed, 1e-9)
        with open(baseline_path, "w") as f:
            json.dump({"pairs_per_sec": baseline}, f)

    import jax

    backend = jax.default_backend()  # what the measured runs actually used
    out = {
        "metric": "item-pairs/sec (Zipfian basket stream, device backend)",
        "value": round(pairs_per_sec, 1),
        "unit": "pairs/s",
        "vs_baseline": round(pairs_per_sec / max(baseline, 1e-9), 3),
        "pipeline_depth": pipeline_depth,
        "occupancy": occupancy,
        "latency": latency,
        "degradation": degradation,
        "fused": fused_info,
        "fused_sparse": fused_sparse,
        "compression": compression,
        "spill": spill_info,
        "checkpoint": ckpt_info,
        "serving": serving_storm,
        "fleet": fleet_storm,
        "rescale": rescale_info,
        "fused_gang": fused_gang_info,
    }
    if journal:
        out["journal"] = journal
    # Regression gate (bench.regress, ISSUE-17): band this run's
    # tracked metrics against the same-backend history BEFORE the run
    # is appended to it; the verdict rides the bench JSON and (on-chip)
    # the history entry itself. Gate failures never fail the bench —
    # the verify skill's post-bench step is where exit 1 bites.
    try:
        from tpu_cooccurrence.bench import regress as _regress

        candidate = dict(out)
        candidate["pairs_per_sec"] = out["value"]
        candidate["backend"] = backend
        out["regression"] = _regress.evaluate(
            _regress.read_history(_HISTORY), candidate)
    except Exception as exc:  # pragma: no cover - defensive
        out["regression"] = {"ok": True, "error": str(exc)}
    if backend == "cpu":
        out["platform"] = ("cpu-fallback"
                           if os.environ.get("BENCH_CPU_FALLBACK") else "cpu")
        # A dead tunnel must not read as a ~20x perf regression: carry the
        # most recent real on-chip measurement alongside the fallback
        # number, clearly dated and marked stale (VERDICT r2, Missing #3).
        prior = _last_onchip()
        if prior is not None:
            out["last_onchip"] = {
                "value": prior["pairs_per_sec"],
                "vs_baseline": prior["vs_baseline"],
                "ts": prior["ts"],
                "stale": True,
            }
    else:
        _record_onchip(out["value"], out["vs_baseline"], backend,
                       pipeline_depth, occupancy, latency, degradation,
                       fused_info, compression, serving_storm, spill_info,
                       fused_sparse, ckpt_info, fleet_storm,
                       rescale_info, fused_gang_info,
                       regression=out.get("regression"))
    print(json.dumps(out))


#: Known-benign XLA stderr noise: the CPU AOT machine-feature mismatch
#: warning ("Target machine feature +prefer-no-gather is not supported
#: ...", plus its feature-list and SIGILL-caveat lines) that every CPU
#: measurement child emits and that previously flooded the captured
#: bench tail in BENCH_r0*.json, burying the `parsed` context. A line
#: containing any of these markers is withheld from the live stderr
#: stream and surfaced instead as a count + sample in the JSON line's
#: ``stderr_noise`` debug field — suppressed from the tail, not lost.
BENIGN_STDERR_MARKERS = (
    "+prefer-no-gather",
    "Machine type used for XLA:CPU compilation",
    "This could lead to execution errors such as SIGILL",
)


def _is_benign_stderr(line: str) -> bool:
    return any(m in line for m in BENIGN_STDERR_MARKERS)


def _pump_stderr(pipe, noise: dict) -> None:
    """Forward a child's stderr line-by-line (hang diagnostics must
    stay live), withholding the known-benign XLA noise into ``noise``."""
    for line in pipe:
        if _is_benign_stderr(line):
            noise["lines"] += 1
            if noise["sample"] is None:
                noise["sample"] = line.strip()[:160]
            continue
        sys.stderr.write(line)
        sys.stderr.flush()


def _run_child(env: dict, deadline_s: float):
    """One measurement child under a hard deadline. Returns the JSON
    line it printed, or None on timeout/failure/garbage output.

    stderr streams through live (jax warnings, job logs, hang
    diagnostics — same discipline as the supervisor's), minus the
    known-benign XLA noise (``BENIGN_STDERR_MARKERS``), which is folded
    into the JSON line's ``stderr_noise`` debug field instead of
    flooding whatever captured this process's tail.
    """
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--measure"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
    except OSError:
        return None
    noise = {"lines": 0, "sample": None}
    out_buf = []
    pump = threading.Thread(target=_pump_stderr,
                            args=(proc.stderr, noise), daemon=True)
    # stdout is drained on a thread too: the deadline must bound the
    # child's WALL time (proc.wait below), and a main-thread read() on a
    # hung child would block past any deadline.
    drain = threading.Thread(target=lambda: out_buf.append(
        proc.stdout.read()), daemon=True)
    pump.start()
    drain.start()
    try:
        rc = proc.wait(timeout=deadline_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        return None
    pump.join(timeout=10)
    drain.join(timeout=10)
    out = out_buf[0] if out_buf else ""
    if rc != 0:
        return None
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if noise["lines"]:
                obj["stderr_noise"] = {"suppressed_lines": noise["lines"],
                                       "sample": noise["sample"]}
                line = json.dumps(obj)
            return line
    return None


def main() -> None:
    # --pipeline-depth N (default 0 = serial): the execution-mode knob
    # under measurement; flows to the measurement children via env so the
    # parent stays argv-compatible with the driver's bare invocation.
    argv = sys.argv[1:]
    if "--storm-client" in argv:
        # Fleet-arm client child: hammer one replica URL, fail over to
        # an optional survivor URL on connection loss, print one JSON
        # line. Kept out of the parent so client CPU never shares a GIL
        # with orchestration (or with another client).
        i = argv.index("--storm-client")
        try:
            url = argv[i + 1]
            seconds = float(argv[i + 2])
            threads = int(argv[i + 3])
            fallback = argv[i + 4] if len(argv) > i + 4 else None
        except (IndexError, ValueError):
            sys.stderr.write("bench: --storm-client URL SECONDS THREADS "
                             "[FALLBACK_URL]\n")
            return 2
        print(json.dumps(storm_client(url, seconds, threads, fallback)))
        return 0
    if "--pipeline-depth" in argv:
        i = argv.index("--pipeline-depth")
        try:
            depth = int(argv[i + 1])
        except (IndexError, ValueError):
            sys.stderr.write("bench: --pipeline-depth needs an integer\n")
            return 2
        if depth not in (0, 1, 2):
            # Fail here, not minutes later as an opaque all-children-
            # failed artifact after the backend probe has run.
            sys.stderr.write(
                f"bench: --pipeline-depth must be 0, 1 or 2, got {depth}\n")
            return 2
        os.environ["BENCH_PIPELINE_DEPTH"] = str(depth)
    if "--measure" in argv:
        return measure()

    # Parent: never imports jax; all chip contact is in deadline'd
    # children, so this process completes within a bound regardless of
    # tunnel state at any point during the run.
    cpu_forced = os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
    # The control flags are owned by THIS parent: stale exported values
    # must not leak into the children and invert the labeling logic.
    base_env = dict(os.environ)
    base_env.pop("BENCH_EXPECT_ACCEL", None)
    base_env.pop("BENCH_CPU_FALLBACK", None)
    probed = None if cpu_forced else probe_backend(240.0)
    if probed not in (None, "cpu"):
        line = _run_child(dict(base_env, BENCH_EXPECT_ACCEL="1"),
                          ACCEL_DEADLINE_S)
        if line is not None:
            print(line)
            return
        sys.stderr.write(
            "bench: accelerator child failed or exceeded the "
            f"{ACCEL_DEADLINE_S:.0f}s deadline; falling back to CPU\n")
    env = dict(base_env, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    # 'cpu-fallback' means a configured accelerator was unreachable or
    # died mid-run; a clean 'cpu' probe is an honest CPU box and must
    # not carry that label (nor the stale on-chip attachment).
    if not cpu_forced and probed != "cpu":
        env["BENCH_CPU_FALLBACK"] = "1"
    line = _run_child(env, CPU_DEADLINE_S)
    if line is not None:
        print(line)
        return
    # Even the CPU child failed: emit an explicit error object rather
    # than nothing — the driver records whatever this prints.
    prior = _last_onchip()
    out = {"metric": "item-pairs/sec (Zipfian basket stream, device backend)",
           "value": 0.0, "unit": "pairs/s", "vs_baseline": 0.0,
           "platform": "error", "error": "all measurement children failed"}
    if prior is not None:
        out["last_onchip"] = {"value": prior["pairs_per_sec"],
                              "vs_baseline": prior["vs_baseline"],
                              "ts": prior["ts"], "stale": True}
    print(json.dumps(out))
    return 1  # the error artifact must not read as a successful run


if __name__ == "__main__":
    sys.exit(main())
