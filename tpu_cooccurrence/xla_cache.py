"""Persistent XLA compilation cache.

The tunneled single-chip environment pays ~1s per executable compile and
the framework's bucketed shapes produce a bounded but non-trivial set of
programs; caching compiled executables on disk removes that cost from every
run after the first (and from every window after the first in a run).

Default location is repo-local (``.xla_cache/`` next to the package) so no
paths outside the repository are touched; override with
``TPU_COOC_COMPILE_CACHE`` (empty string disables).
"""

from __future__ import annotations

import logging
import os


from . import tuning
LOG = logging.getLogger("tpu_cooccurrence")

_enabled = False


def _host_fingerprint() -> str:
    """Short stable id of this host's CPU feature set (+ platform)."""
    import hashlib
    import platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 lists features under "flags", aarch64 under "Features".
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    digest = hashlib.sha1(
        f"{platform.machine()}|{feats}".encode()).hexdigest()[:12]
    return digest


def enable_compilation_cache() -> None:
    """Idempotently point JAX's persistent compilation cache at disk."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    path = tuning.env_read("TPU_COOC_COMPILE_CACHE")
    if path == "":
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return  # the embedding application already configured one
        if path is None:
            repo = os.path.dirname(os.path.dirname(__file__))
            if os.path.isdir(os.path.join(repo, ".git")):
                path = os.path.join(repo, ".xla_cache")  # dev checkout
            else:
                path = os.path.join(
                    os.path.expanduser("~"), ".cache", "tpu_cooccurrence",
                    "xla")
        # The workspace (and this cache dir) can move between hosts with
        # different CPU feature sets; XLA:CPU AOT results are
        # feature-specific and loading a foreign one risks SIGILL. Key the
        # cache by a host fingerprint so each machine gets its own bucket.
        path = os.path.join(path, _host_fingerprint())
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as exc:  # pragma: no cover - version-dependent flags
        LOG.info("persistent compilation cache unavailable: %s", exc)
