"""Regenerate ONCHIP_SUMMARY.md from the measurement artifacts.

The grant watcher's final stage: after a capture session lands numbers
in ``TPU_ROUND2.jsonl`` / ``bench_history.jsonl``, this rewrites
``ONCHIP_SUMMARY.md`` — the latest on-chip number per measurement, each
dated, with the north-star targets evaluated. The judge (and any
operator) reads current truth from one machine-generated file instead
of cross-referencing JSONL streams; BASELINE.md keeps the narrative.

    python -m tpu_cooccurrence.bench.summarize
"""

from __future__ import annotations

import json
import os
import time

from .ml25m import PSUM_LATENCY_DEFAULT_S  # noqa: F401  (doc cross-ref)
from .tpu_round2 import OUT as ROUND2_PATH

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
HISTORY_PATH = os.path.join(REPO, "bench_history.jsonl")
SUMMARY_PATH = os.path.join(REPO, "ONCHIP_SUMMARY.md")

#: North stars (BASELINE.md).
CONFIG4_TARGET_PAIRS_PER_SEC = 458_000   # >= 20x the 22.9k host oracle
ML25M_TARGET_SECONDS = 60.0              # single chip or v5e-8 projected
HEADLINE_TARGET_X = 20.0                 # bench.py vs_baseline


def _read_jsonl(path):
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return rows


def latest_by_name(rows):
    """Last OK row per measurement name (chronological file order).

    Pre-round-4 rows carry the inner BenchResult's name (the guard let
    it shadow the pass name): map the known historic spellings back to
    their measurement identity, keyed by backend where ambiguous.
    """
    from .tpu_round2 import onchip_row

    out = {}
    for r in rows:
        # onchip_row: ok AND not tagged with a non-TPU platform (a CPU
        # smoke run whose TPU_ROUND2_OUT override was lost must not
        # become "the latest on-chip number"); shared with ml25m.py's
        # projection-constant readers.
        if not onchip_row(r):
            continue
        name = r.get("name")
        if name == "zipfian-1M-items":  # historic config4 rows
            name = ("config4-sparse" if r.get("backend") == "sparse"
                    else f"config4-{r.get('backend', '?')}")
        if name:
            out[name] = r
    return out


def render() -> str:
    rounds = latest_by_name(_read_jsonl(ROUND2_PATH))
    history = _read_jsonl(HISTORY_PATH)
    lines = [
        "# On-chip measurement summary (machine-generated)",
        "",
        f"Regenerated {time.strftime('%Y-%m-%d %H:%M:%S')} by "
        "`python -m tpu_cooccurrence.bench.summarize` from "
        "`TPU_ROUND2.jsonl` + `bench_history.jsonl`. Latest successful "
        "capture per measurement; targets from BASELINE.md.",
        "",
    ]

    # Headline (bench.py history).
    lines.append("## Headline: item-pairs/sec (bench.py, Zipfian 20k-vocab)")
    if history:
        h = history[-1]
        ok = h.get("vs_baseline", 0) >= HEADLINE_TARGET_X
        lines += [
            "",
            f"- **{h.get('pairs_per_sec', 0):,.0f} pairs/s = "
            f"{h.get('vs_baseline', 0):.1f}x host oracle** "
            f"({h.get('backend', '?')}, {h.get('ts', '?')}) — target "
            f">= {HEADLINE_TARGET_X:.0f}x: "
            f"{'**MET**' if ok else '**NOT MET**'}",
        ]
    else:
        lines += ["", "- no on-chip capture recorded yet"]

    # Config 4. The headline-first capture order means a short grant
    # may land config4-headline (one L16/fixed run) without the sweep;
    # evaluate the target on the best successful row of any form.
    lines += ["", "## Config 4 — 1M-item Zipfian (sparse backend)"]
    c4_rows = [(name, rounds[name]) for name in
               ("config4-headline", "config4-chunked", "config4-sparse")
               if name in rounds]
    if c4_rows:
        # Full-size rows outrank --quick ones regardless of pairs/s —
        # the target is only meaningful at the full 1M-event stream.
        best_name, best = max(
            c4_rows, key=lambda nr: (nr[1].get("events", 0),
                                     nr[1].get("pairs_per_sec", 0)))
        pps = best.get("pairs_per_sec", 0)
        ok = pps >= CONFIG4_TARGET_PAIRS_PER_SEC
        mode = best.get("mode")
        lines += [
            "",
            f"- **{pps:,.0f} pairs/s** ({best_name}"
            + (f", {mode}" if mode else "")
            + (f", {best['events']:,} events"
               if best.get("events") is not None else "")
            + f", {best.get('ts', '?')}) — target "
            f">= {CONFIG4_TARGET_PAIRS_PER_SEC:,} (20x host): "
            f"{'**MET**' if ok else '**NOT MET**'}",
        ]
        sweep = rounds.get("config4-sparse")
        if sweep and "pairs_per_sec_by_mode" in sweep:
            lines.append(
                f"- sweep by mode ({sweep.get('ts', '?')}): "
                f"{sweep['pairs_per_sec_by_mode']}")
        head, chunk = (rounds.get("config4-headline"),
                       rounds.get("config4-chunked"))
        if head and chunk:
            h, c = (head.get("pairs_per_sec", 0),
                    chunk.get("pairs_per_sec", 0))
            he, ce = head.get("events"), chunk.get("events")
            fmt = (lambda v: f"{v:,}" if isinstance(v, int) else str(v))
            if he != ce:
                # Mixed provenance (e.g. one --quick row): a hardware
                # default must not flip on incomparable runs.
                lines.append(
                    f"- upload A/B: INCOMPARABLE — monolithic ran "
                    f"{fmt(he)} events ({head.get('ts', '?')}), chunked "
                    f"{fmt(ce)} events ({chunk.get('ts', '?')}); re-run "
                    f"both at full size before deciding")
            else:
                winner = (
                    "chunked upload WINS — default "
                    "TPU_COOC_UPLOAD_CHUNK_KB=256 on TPU "
                    "(ops/device_scorer.upload_chunk_kb)"
                    if c > h * 1.05 else
                    "monolithic upload holds (keep default)")
                lines.append(
                    f"- upload A/B ({fmt(he)} events): monolithic "
                    f"{h:,.0f} vs 4-chunk {c:,.0f} pairs/s — {winner}")
    else:
        lines += ["", "- no successful capture yet"]

    # ML-25M.
    lines += ["", "## Config 3 — ML-25M full shape (<60 s)"]
    for name in ("ml25m-full", "ml25m-sparse"):
        m = rounds.get(name)
        if not m:
            lines.append(f"- {name}: no successful capture yet")
            continue
        secs = m.get("seconds")
        proj = m.get("v5e8_projected_seconds")
        parts = [f"- {name}: **{secs} s single-chip**"]
        if secs is not None:
            parts.append("(**MET**)" if secs < ML25M_TARGET_SECONDS
                         else "(NOT met single-chip)")
        if proj is not None:
            rng = m.get("v5e8_projected_range")
            parts.append(f"; v5e-8 projected {proj} s"
                         + (f" {rng}" if rng else "")
                         + (" (**MET** projected)"
                            if proj < ML25M_TARGET_SECONDS else ""))
        part_proj = m.get("v5e8_partitioned_projected_seconds")
        if part_proj is not None:
            parts.append(
                f"; host-partitioned v5e-8 {part_proj} s"
                + (" (**MET**, assumed-linear host split)"
                   if part_proj < ML25M_TARGET_SECONDS else "")
                + " [arithmetic: see v5e8_partitioned_note]")
        parts.append(f"— {m.get('ts', '?')}")
        lines.append(" ".join(str(p) for p in parts))

    # Kernel carrier decisions.
    lines += ["", "## Kernel A/Bs (carrier decisions)"]
    sp = rounds.get("sparse-pallas")
    if sp:
        lines.append(f"- sparse rectangle Pallas-vs-XLA "
                     f"({sp.get('ts', '?')}): {sp.get('by_rect')}")
    else:
        lines.append("- sparse-pallas: not yet measured on chip "
                     "(auto stays XLA for int32 slabs)")
    pb = rounds.get("pallas-bench")
    if pb:
        lines.append(
            f"- dense int16 Pallas-vs-XLA ({pb.get('ts', '?')}): "
            f"XLA {pb.get('xla_ms')} ms vs Pallas "
            f"{pb.get('pallas_ms_by_tile')} (speedup "
            f"{pb.get('pallas_speedup')}x)")
    sh = rounds.get("sharded-pallas-1chip")
    if sh:
        lines.append(f"- shard_map+pallas 1-chip parity "
                     f"({sh.get('ts', '?')}): "
                     f"dense {sh.get('sharded_dense_int16')}, "
                     f"sparse {sh.get('sharded_sparse')}")
        if sh.get("sharded_overhead_ms_per_window") is not None:
            lines.append(
                f"- shard_map+psum wrapper overhead (1-chip, "
                f"{sh.get('overhead_vocab')}-item row sums): "
                f"{sh.get('sharded_overhead_ms_per_window')} ms/window "
                f"(unsharded {sh.get('step_ms_per_window_unsharded')} ms "
                f"vs sharded {sh.get('step_ms_per_window_sharded_1dev')} "
                f"ms) — the v5e-8 projection's measured point estimate "
                f"(bench/ml25m.measured_sharded_overhead)")

    probe = rounds.get("tunnel-probe")
    if probe:
        lines += ["", "## Link constants (tunnel probe)", "",
                  f"- sync dispatch RTT "
                  f"{probe.get('sync_ms_per_dispatch')} ms, enqueue "
                  f"{probe.get('enqueue_ms_per_dispatch')} ms, upload "
                  f"256KB {probe.get('upload_256kb_ms')} ms / "
                  f"1MB {probe.get('upload_1024kb_ms')} ms "
                  f"({probe.get('ts', '?')}) — feeds the v5e-8 "
                  f"projection's upper bound (bench/ml25m.py)"]
        if probe.get("upload_4x256kb_ms") is not None:
            lines.append(
                f"- chunked-upload A/B: 1MB monolithic "
                f"{probe.get('upload_1024kb_ms')} ms vs 4x256KB "
                f"{probe.get('upload_4x256kb_ms')} ms (see "
                f"TPU_COOC_UPLOAD_CHUNKS)")
    return "\n".join(lines) + "\n"


def main() -> None:
    text = render()
    with open(SUMMARY_PATH, "w") as f:
        f.write(text)
    print(f"wrote {SUMMARY_PATH} ({len(text.splitlines())} lines)")


if __name__ == "__main__":
    main()
