"""Full MovieLens-25M-shape assessment: the <60 s north-star check.

BASELINE.json's second target: "full MovieLens-25M item-item matrix in
<60 s on a TPU v5e-8". This runner measures it honestly instead of
extrapolating from the 500k-event stand-in slice (VERDICT round 1, weak
item 3):

* the FULL 25M-event, 62k-item, 162k-user shape (real ratings.csv when
  ``MOVIELENS_25M`` points at it; otherwise the shape-matched Zipfian
  stand-in — labeled), streamed through the production job in bounded
  chunks, sliding windows + top-k (benchmark config 3's setup);
* the backend that carries that vocabulary on one chip: dense device,
  reference-style int16 counts (7.7 GB HBM at 62k items);
* a stated, formula-explicit projection to v5e-8 from the single-chip
  measurement: the sharded backend splits every device stage (scatter
  update, gather+LLR+top-K) across 8 item-sharded chips with one psum
  per window (`parallel/sharded.py`), while host-side sampling is not
  sharded in the single-controller runtime — so
  ``projected = host_seconds + device_seconds / 8 + windows * psum_lat``.
  Host and device seconds are separated by the job's per-window step
  timer; the psum term uses PSUM_LATENCY_S per window (ICI all-reduce of
  the [62k] row-sum vector, sub-millisecond on v5e ICI; the constant is
  stated, not hidden).

``--host-only`` runs the identical stream through sampling with a null
scorer — the host-side floor any backend pays; useful on CPU-only boxes
(this container's 1 core) and for separating the two budget halves.

Usage:
    python -m tpu_cooccurrence.bench.ml25m [--events N] [--host-only]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from ..config import Backend, Config
from ..job import CooccurrenceJob
from ..metrics import OBSERVED_COOCCURRENCES
from ..state.results import TopKBatch
from .configs import _movielens_25m

# Per-window ICI all-reduce latency charged in the v5e-8 projection: one
# psum of an int32 [62k] row-sum vector (~250 KB) per fired window. v5e
# ICI moves that in tens of microseconds; 200 us is a deliberately fat
# allowance for launch + sync skew.
PSUM_LATENCY_S = 200e-6

N_EVENTS_FULL = 25_000_000


class NullScorer:
    """Swallows pair deltas: isolates the host-side (sampling) floor."""

    last_dispatched_rows = 0

    def __init__(self, top_k: int) -> None:
        self.top_k = top_k

    def process_window(self, ts, pairs) -> TopKBatch:
        return TopKBatch.empty(self.top_k)

    def flush(self) -> TopKBatch:
        return TopKBatch.empty(self.top_k)


def run_full(n_events: int, host_only: bool, chunk: int = 2_000_000,
             backend: Backend = Backend.DEVICE) -> dict:
    """``backend``: DEVICE is the dense int16 carrier; SPARSE scores only
    nonzero cells (~60x fewer at this shape — 54M pairs over a 62k vocab
    leave most of each dense row empty) at the price of host index work,
    so the chip decides which carries config 3 (bench/tpu_round2.py
    measures both)."""
    users, items, ts, standin = _movielens_25m(limit=n_events)
    n = len(users)
    dense = backend == Backend.DEVICE
    cfg = Config(window_size=4000, window_slide=1000, seed=3,
                 item_cut=500, user_cut=500, backend=backend,
                 count_dtype="int16" if dense else "int32",
                 num_items=int(items.max()) + 1 if dense else 0)
    job = CooccurrenceJob(
        cfg, scorer=NullScorer(cfg.top_k) if host_only else None)
    start = time.monotonic()
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        job.add_batch(users[lo:hi], items[lo:hi], ts[lo:hi])
    job.finish()
    seconds = time.monotonic() - start
    pairs = job.counters.get(OBSERVED_COOCCURRENCES)
    summary = job.step_timer.summary()
    host_s = summary["sample_seconds"]
    device_s = summary["score_seconds"]
    windows = summary["windows"]
    out = {
        "name": ("ml25m-full" + ("-hostonly" if host_only else "")
                 + ("" if dense else "-sparse")),
        "backend": "null" if host_only else cfg.backend.value,
        "events": n,
        "pairs": int(pairs),
        "windows": int(windows),
        "seconds": round(seconds, 2),
        "pairs_per_sec": round(pairs / max(seconds, 1e-9), 1),
        "host_sample_seconds": round(host_s, 2),
        "device_score_seconds": round(device_s, 2),
        "synthetic_standin": standin,
    }
    if not host_only:
        projected = host_s + device_s / 8 + windows * PSUM_LATENCY_S
        out["v5e8_projected_seconds"] = round(projected, 2)
        out["v5e8_projection"] = (
            "host + device/8 + windows*psum: "
            f"{host_s:.1f} + {device_s:.1f}/8 + "
            f"{windows}*{PSUM_LATENCY_S*1e6:.0f}us")
        out["under_60s_single_chip"] = seconds < 60
        out["under_60s_v5e8_projected"] = projected < 60
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", type=int, default=N_EVENTS_FULL)
    ap.add_argument("--host-only", action="store_true",
                    help="null scorer: measure the host sampling floor only")
    args = ap.parse_args()
    print(json.dumps(run_full(args.events, args.host_only)), flush=True)


if __name__ == "__main__":
    main()
