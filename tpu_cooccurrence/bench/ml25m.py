"""Full MovieLens-25M-shape assessment: the <60 s north-star check.

BASELINE.json's second target: "full MovieLens-25M item-item matrix in
<60 s on a TPU v5e-8". This runner measures it honestly instead of
extrapolating from the 500k-event stand-in slice (VERDICT round 1, weak
item 3):

* the FULL 25M-event, 59k-item, 162.5k-user shape (real ratings.csv when
  ``MOVIELENS_25M`` points at it; otherwise the shape-matched Zipfian
  stand-in — labeled), streamed through the production job in bounded
  chunks, sliding windows + top-k (benchmark config 3's setup);
* the backend that carries that vocabulary on one chip: dense device,
  reference-style int16 counts (7.0 GB HBM at 59,047 items);
* a stated, formula-explicit projection to v5e-8 from the single-chip
  measurement: the sharded backend splits every device stage (scatter
  update, gather+LLR+top-K) across 8 item-sharded chips with one psum
  per window (`parallel/sharded.py`), while host-side sampling is not
  sharded in the single-controller runtime — so
  ``projected = host_seconds + device_seconds / 8 + windows * psum_lat``.
  Host and device seconds are separated by the job's per-window step
  timer. The psum term's point estimate is the stated on-pod allowance
  (PSUM_LATENCY_DEFAULT_S — ICI all-reduce of the [59k] row-sum vector
  is sub-millisecond on v5e); the reported ``[low, high]`` range uses
  zero exposed latency as the floor and the tunnel probe's MEASURED
  synchronized-dispatch RTT as the ceiling. The measured RTT includes
  axon-tunnel transport a locally-attached pod never pays, which is
  exactly why it bounds rather than replaces the point estimate — both
  constants and their provenance are in the JSON.

``--host-only`` runs the identical stream through sampling with a null
scorer — the host-side floor any backend pays; useful on CPU-only boxes
(this container's 1 core) and for separating the two budget halves.

Usage:
    python -m tpu_cooccurrence.bench.ml25m [--events N] [--host-only]
"""

from __future__ import annotations

import argparse
import contextlib
import json
import time

from ..config import Backend, Config
from ..job import CooccurrenceJob
from ..metrics import OBSERVED_COOCCURRENCES
from ..state.results import TopKBatch
from .configs import _movielens_25m

# Fallback per-window ICI all-reduce latency for the v5e-8 projection
# when no measured dispatch RTT exists yet: one psum of an int32 [59k]
# row-sum vector (~250 KB) per fired window. v5e ICI moves that in tens
# of microseconds; 200 us is a deliberately fat allowance for launch +
# sync skew. measured_psum_latency() replaces this with the tunnel
# probe's measured synchronized-dispatch RTT the moment one exists
# (VERDICT r3, Next #7: the projection's constants must come from
# measurement or carry error bars — it does both now).
PSUM_LATENCY_DEFAULT_S = 200e-6


def _latest_row(name: str, required_key: str):
    """Latest usable TPU_ROUND2.jsonl row of ``name`` carrying the key
    (``onchip_row``: ok and not tagged with a non-TPU platform — a CPU
    smoke row must not become a projection constant)."""
    from .tpu_round2 import OUT, onchip_row

    latest = None
    try:
        with open(OUT) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if (obj.get("name") == name and onchip_row(obj)
                        and required_key in obj):
                    latest = obj
    except OSError:
        pass
    return latest


def measured_psum_latency():
    """(latency_s, source): the latest measured synchronized-dispatch RTT
    from the tunnel probe (TPU_ROUND2.jsonl), else the stated default.

    A per-window psum costs one synchronized collective launch; the
    probe's ``sync_ms_per_dispatch`` (tiny kernel, block after each) is
    the measured stand-in for that launch+sync cost on this hardware.
    """
    latest = _latest_row("tunnel-probe", "sync_ms_per_dispatch")
    if latest is not None:
        return (latest["sync_ms_per_dispatch"] / 1e3,
                "measured sync dispatch RTT, tunnel transport included "
                f"({latest.get('ts', '?')})")
    return PSUM_LATENCY_DEFAULT_S, "assumed default (no probe capture yet)"


def measured_sharded_overhead():
    """(seconds_per_window, source) for the projection's point estimate
    (VERDICT r4, Next #7): the sharded-pallas-1chip stage times the SAME
    windows through the unsharded sparse scorer and a 1-device-mesh
    sharded one on the real chip; the difference is the measured
    shard_map+psum wrapper cost per window at the config-3 row-sum
    scale. Present => the projection cites zero assumed constants.
    Returns (None, reason) before any capture."""
    latest = _latest_row("sharded-pallas-1chip",
                         "sharded_overhead_ms_per_window")
    if latest is not None:
        return (latest["sharded_overhead_ms_per_window"] / 1e3,
                "measured 1-chip shard_map+psum overhead per window "
                f"({latest.get('ts', '?')})")
    return None, "no sharded-pallas-1chip capture yet"

N_EVENTS_FULL = 25_000_000


class NullScorer:
    """Swallows pair deltas: isolates the host-side (sampling) floor."""

    last_dispatched_rows = 0

    def __init__(self, top_k: int) -> None:
        self.top_k = top_k

    def process_window(self, ts, pairs) -> TopKBatch:
        return TopKBatch.empty(self.top_k)

    def flush(self) -> TopKBatch:
        return TopKBatch.empty(self.top_k)


@contextlib.contextmanager
def sparse_device_mocked():
    """Patch the sparse scorer's device dispatches to host no-ops.

    ``--host-only --backend sparse`` then measures the TRUE sparse host
    floor — sampling + windowing + slab index + update/meta packing —
    which NullScorer (sampling only) understates. Each stub returns its
    donated inputs unchanged, so no device work is enqueued and the
    scorer's host-side control flow runs exactly as in production.
    (Round 3's 25.2 s measurement used ad-hoc mocks that never landed
    in-repo; this makes the number reproducible.)
    """
    import tpu_cooccurrence.state.sparse_scorer as ss

    saved = {}

    def patch(name, fn):
        saved[name] = getattr(ss, name)
        setattr(ss, name, fn)

    patch("_apply_update",
          lambda cnt, dst, rs, upd, bounds: (cnt, dst, rs))
    patch("_apply_moves_update",
          lambda cnt, dst, rs, mv, upd, bounds, L: (cnt, dst, rs))
    patch("_apply_update_chunked",
          lambda cnt, dst, rs, parts, bounds: (cnt, dst, rs))
    patch("_apply_moves_update_chunked",
          lambda cnt, dst, rs, mv, parts, bounds, L: (cnt, dst, rs))
    patch("_score_into_table", lambda tbl, *a, **k: tbl)
    patch("_score_window_into_table", lambda tbl, *a, **k: tbl)
    patch("_compact_gather", lambda cnt, dst, gmap, cap: (cnt, dst))
    try:
        yield
    finally:
        for name, fn in saved.items():
            setattr(ss, name, fn)


def measure_full(n_events: int, host_only: bool, chunk: int = 2_000_000,
                 backend: Backend = Backend.DEVICE) -> dict:
    """The MEASUREMENT half of :func:`run_full`: run the stream, return
    the base result row plus the unrounded stage seconds the projection
    needs. Split from :func:`project_v5e8` so consumers that only vary
    the projection *constants* (the capture file) can share one
    measured run — the projection is arithmetic over this dict and the
    tracked JSONL, never a re-measurement.

    ``backend``: DEVICE is the dense int16 carrier; SPARSE scores only
    nonzero cells (~60x fewer at this shape — 54M pairs over a 59k vocab
    leave most of each dense row empty) at the price of host index work,
    so the chip decides which carries config 3 (bench/tpu_round2.py
    measures both)."""
    users, items, ts, standin_model = _movielens_25m(limit=n_events)
    n = len(users)
    dense = backend == Backend.DEVICE
    cfg = Config(window_size=4000, window_slide=1000, seed=3,
                 item_cut=500, user_cut=500, backend=backend,
                 count_dtype="int16" if dense else "int32",
                 num_items=int(items.max()) + 1 if dense else 0)
    # --host-only: sampling-only floor (NullScorer) for the dense
    # carrier; for the sparse carrier the honest floor also includes
    # the slab index + packing host work, so the REAL scorer runs with
    # its device dispatches stubbed to no-ops.
    mock_sparse = host_only and not dense
    ctx = sparse_device_mocked() if mock_sparse else contextlib.nullcontext()
    with ctx:
        job = CooccurrenceJob(
            cfg, scorer=(NullScorer(cfg.top_k)
                         if host_only and not mock_sparse else None))
        start = time.monotonic()
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            job.add_batch(users[lo:hi], items[lo:hi], ts[lo:hi])
        job.finish()
        seconds = time.monotonic() - start
    pairs = job.counters.get(OBSERVED_COOCCURRENCES)
    summary = job.step_timer.summary()
    host_s = summary["sample_seconds"]
    device_s = summary["score_seconds"]
    windows = summary["windows"]
    out = {
        "name": ("ml25m-full" + ("-hostonly" if host_only else "")
                 + ("" if dense else "-sparse")),
        "backend": ("sparse-device-mocked" if mock_sparse
                    else "null" if host_only else cfg.backend.value),
        "events": n,
        "pairs": int(pairs),
        "windows": int(windows),
        "seconds": round(seconds, 2),
        "pairs_per_sec": round(pairs / max(seconds, 1e-9), 1),
        "host_sample_seconds": round(host_s, 2),
        "device_score_seconds": round(device_s, 2),
        "synthetic_standin": standin_model is not None,
        **({"standin_model": standin_model} if standin_model else {}),
    }
    return {"out": out, "host_s": host_s, "device_s": device_s,
            "windows": windows, "seconds": seconds,
            "host_only": host_only}


def project_v5e8(measured: dict) -> dict:
    """The PROJECTION half of :func:`run_full`: fold the v5e-8
    projection (constants from the tracked capture JSONL, arithmetic
    over the measured stage seconds) into a copy of the measured row.
    Host-only floors carry no projection, exactly as before."""
    out = dict(measured["out"])
    host_s = measured["host_s"]
    device_s = measured["device_s"]
    windows = measured["windows"]
    seconds = measured["seconds"]
    if not measured["host_only"]:
        psum_hi_s, psum_src = measured_psum_latency()
        overhead_s, overhead_src = measured_sharded_overhead()
        # Point estimate: the measured 1-chip shard_map+psum wrapper
        # cost per window when a capture exists (VERDICT r4 Next #7 —
        # zero assumed constants), else the stated on-pod allowance.
        # The probe's sync RTT includes tunnel transport a locally-
        # attached pod never pays, so it serves as the explicit UPPER
        # bound instead of inflating the point estimate; the lower
        # bound is collectives fully overlapped with compute.
        if overhead_s is not None:
            psum_s = overhead_s
            point_src = overhead_src
        else:
            psum_s = PSUM_LATENCY_DEFAULT_S
            point_src = "assumed on-pod allowance (point estimate)"
        projected = host_s + device_s / 8 + windows * psum_s
        proj_low = host_s + device_s / 8
        proj_high = (host_s + device_s / 8
                     + windows * max(psum_hi_s, 2 * psum_s))
        out["v5e8_projected_seconds"] = round(projected, 2)
        out["v5e8_projected_range"] = [round(proj_low, 2),
                                       round(proj_high, 2)]
        out["psum_latency_s"] = psum_s
        out["psum_latency_source"] = point_src
        out["psum_latency_upper_s"] = psum_hi_s
        out["psum_latency_upper_source"] = psum_src
        out["v5e8_projection"] = (
            "host + device/8 + windows*psum: "
            f"{host_s:.1f} + {device_s:.1f}/8 + "
            f"{windows}*{psum_s*1e6:.0f}us "
            f"[upper: {psum_hi_s*1e6:.0f}us]")
        out["under_60s_single_chip"] = seconds < 60
        out["under_60s_v5e8_projected"] = projected < 60
        # Secondary projection: at the calibrated workload the HOST term
        # binds (round 5: 52 s dense floor vs device/8), and the
        # framework's --partition-sampling splits exactly that term
        # across the pod host's worker processes (u % P partitioning;
        # correctness pinned by tests/test_multihost.py and the
        # randomized multihost sweeps). Its LINEAR host scaling is
        # arithmetic, not a measurement — this box has one core — so
        # the row is labeled and kept separate from the primary
        # projection, which assumes no host partitioning at all.
        out["v5e8_partitioned_projected_seconds"] = round(
            host_s / 8 + device_s / 8 + windows * psum_s, 2)
        out["v5e8_partitioned_note"] = (
            "host/8 + device/8 + windows*psum under --partition-sampling"
            " (8 worker processes on the pod host); host scaling assumed"
            " linear — unmeasurable on this 1-core box")
    return out


def run_full(n_events: int, host_only: bool, chunk: int = 2_000_000,
             backend: Backend = Backend.DEVICE) -> dict:
    """Measure + project in one call (the CLI entry point's form)."""
    return project_v5e8(measure_full(n_events, host_only, chunk, backend))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", type=int, default=N_EVENTS_FULL)
    ap.add_argument("--host-only", action="store_true",
                    help="measure the host floor only (dense: sampling "
                         "via a null scorer; sparse: the real scorer "
                         "with device dispatches stubbed)")
    ap.add_argument("--backend", type=Backend, default=Backend.DEVICE,
                    choices=[Backend.DEVICE, Backend.SPARSE],
                    metavar="{device,sparse}")
    args = ap.parse_args()
    print(json.dumps(run_full(args.events, args.host_only,
                              backend=args.backend)), flush=True)


if __name__ == "__main__":
    main()
