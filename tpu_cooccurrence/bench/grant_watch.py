"""Unattended TPU-grant watcher: capture chip measurements with nobody present.

The tunneled chip's grant comes and goes on hour-plus timescales (dead
for whole sessions at a stretch), and the full measurement pass has so
far only ever run when a person happened to be watching while the grant
was up. This module is the fix (VERDICT r3, Next #1): one command an
operator (or the round driver) leaves running,

    python -m tpu_cooccurrence.bench.grant_watch

which loops { cheap subprocess probe with a hard timeout; on grant ->
run the capture stages, each in its own deadline'd subprocess; append
everything to the usual artifacts; keep looping }. A grant landing
between builder sessions is no longer wasted.

Design constraints, all learned on this tunnel:

* The watcher itself NEVER imports jax — a dead tunnel hangs backend
  init for minutes, and the axon plugin is registered at every
  interpreter start (sitecustomize). All chip contact happens in child
  processes with hard timeouts.
* Probe = actually execute an op (`(jnp.ones(8)+1).sum()`) — device
  *listing* can succeed while execution hangs.
* Stages run scarce-first: the capture order inside ``tpu_round2``
  already puts the tunnel probe (feeds projection constants) and the
  two north-star configs before the long tails, so a short grant still
  settles the headline questions.
* Between stages the grant is re-probed; a mid-capture death skips the
  remaining stages and falls back to watching instead of hanging. The
  per-measurement JSONL appends inside ``tpu_round2`` preserve partial
  progress regardless.

Every probe/stage outcome appends one JSON line to ``GRANT_WATCH.jsonl``
at the repo root. Reference for what is being raced: the perf machinery
at FlinkCooccurrences.java:173-181 (Duration + accumulator dump).
"""

from __future__ import annotations

import argparse
import fcntl
import json
import os
import re
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
LOG_PATH = os.path.join(REPO, "GRANT_WATCH.jsonl")

#: bench.py's internal child deadlines (it imports these back — single
#: owner, so the watcher's stage backstop can never fall below the
#: child's own budget).
BENCH_ACCEL_DEADLINE_S = float(os.environ.get("BENCH_ACCEL_DEADLINE_S",
                                              2400))
BENCH_CPU_DEADLINE_S = float(os.environ.get("BENCH_CPU_DEADLINE_S", 3600))

#: Code the probe child runs. Executes a real op: the axon plugin can
#: enumerate a device whose pool has no capacity, and then the first
#: dispatch (not the listing) is what hangs.
PROBE_CODE = ("import jax, jax.numpy as jnp; "
              "x = (jnp.ones(8) + 1).sum(); x.block_until_ready(); "
              "print('GRANT-' + jax.default_backend())")


def log_event(event: dict, path: str = LOG_PATH) -> None:
    event = {"ts": time.strftime("%Y-%m-%d %H:%M:%S"), **event}
    with open(path, "a") as f:
        f.write(json.dumps(event) + "\n")
    print(json.dumps(event), flush=True)


def probe_backend(timeout_s: float = 240.0) -> Optional[str]:
    """Backend name the probe child executed on ('tpu', 'cpu', ...), or
    None if it hung past the deadline or crashed.

    The distinction matters to callers: 'cpu' means no accelerator is
    configured at all (an honest CPU box), while None means a configured
    tunnel is dead — bench.py labels only the latter 'cpu-fallback'.
    Generous timeout: a live tunnel's first contact legitimately takes
    minutes (grant handshake + first compile); a dead one hangs past any
    bound, which the timeout converts into None.
    """
    try:
        r = subprocess.run([sys.executable, "-c", PROBE_CODE],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    m = re.search(r"GRANT-(\w+)", r.stdout)
    return m.group(1) if m else None


def probe_once(timeout_s: float = 240.0) -> bool:
    """True iff a JAX accelerator executes an op right now."""
    backend = probe_backend(timeout_s)
    return backend is not None and backend != "cpu"


def default_stages(quick: bool = False) -> List[tuple]:
    """(name, argv, deadline_s[, needs_grant]) capture stages,
    scarce-first. ``needs_grant=False`` stages (offline artifact
    rewrites) still run after a mid-capture grant loss.

    Each ``tpu_round2`` measurement is its own stage (``--only NAME``)
    with its own deadline: the 2026-07-31 grant session showed that a
    measurement that HANGS on a mid-capture grant death (rather than
    raising) burns the whole remaining stage budget — per-measurement
    stages cap that at one measurement's deadline, and the watch
    loop's re-probe between failed stages skips the rest of the chip
    work the moment the tunnel is actually gone. Headline-first order:
    one number per north star before anything long. ``bench.py`` is
    the driver's official artifact; it appends to
    ``bench_history.jsonl`` on-chip so a later cpu-fallback round can
    cite the capture.
    """
    def round2(only: str, deadline_s: float,
               quick_deadline_s: float) -> tuple:
        argv = [sys.executable, "-m", "tpu_cooccurrence.bench.tpu_round2",
                "--only", only]
        if quick:
            argv.append("--quick")
        return (f"tpu_round2:{only}", argv,
                quick_deadline_s if quick else deadline_s)

    # bench.py enforces its own internal deadlines (probe 240s + accel
    # child + cpu-fallback child, env-tunable); the stage deadline is a
    # strict backstop ABOVE that budget so the watcher never kills a
    # capture bench.py itself still considers legitimate.
    bench_budget = (240.0 + BENCH_ACCEL_DEADLINE_S + BENCH_CPU_DEADLINE_S
                    + 360.0)
    return [
        # Deadlines: prior on-chip walls (pallas-bench 596s,
        # TPU_ROUND2.jsonl) + first-contact compiles at tunnel speed,
        # with generous slack — they are hang backstops, not
        # performance expectations. The ml25m/config5 budgets are sized
        # to the CALIBRATED stand-ins (round 5): the honest ML-25M
        # workload is 435M pairs (8x the legacy shape; 110 s of host
        # floor alone on this box) and Instacart ~46M, so the legacy
        # 1800 s ceilings would convert a legitimately-running
        # measurement into a session-voiding timeout.
        round2("tunnel-probe", 600.0, 300.0),
        round2("config4-headline", 1200.0, 600.0),
        round2("config4-chunked", 1200.0, 600.0),
        round2("ml25m-sparse", 4200.0, 900.0),
        round2("sparse-pallas", 1200.0, 600.0),
        round2("ml25m-full", 4200.0, 900.0),
        round2("sharded-pallas-1chip", 1200.0, 600.0),
        round2("config4-sparse", 2400.0, 900.0),
        round2("config5-sparse", 1800.0, 600.0),
        round2("pallas-bench", 1800.0, 600.0),
        round2("configs", 4200.0, 900.0),
        ("bench.py", [sys.executable, os.path.join(REPO, "bench.py")],
         bench_budget),
        # Regenerate the machine-written summary so a capture session
        # leaves current-truth numbers in one readable artifact — even a
        # PARTIAL session (tpu_round2 appends per measurement, so a
        # grant dying mid-pass still left fresh rows to summarize).
        ("summarize", [sys.executable, "-m",
                       "tpu_cooccurrence.bench.summarize"], 120.0, False),
    ]


def _boost_stage_priority(pid: int) -> None:
    """Niceness boost from the parent (no preexec_fn: that forces the
    fork path, unsafe under threads): grant time is scarcer than
    anything else on this box, so capture stages win CPU against
    background suites/sweeps instead of letting contention inflate
    measured host walls. PRIO_PGRP (the stage leads its own group via
    start_new_session) renices the leader AND any grandchildren it
    managed to fork before this call lands; later forks inherit.

    Sandbox caveat (root-caused 2026-08-03): gVisor kernels (``runsc``,
    reporting Linux 4.4.0) ACCEPT ``PRIO_PGRP`` and return success
    without applying it — every group member keeps niceness 0. So the
    group renice is verified via ``getpriority`` on the leader and,
    when it did not land (gVisor, or the leader's ``setsid`` racing
    this call so the group id does not exist yet), the leader is
    reniced directly with ``PRIO_PROCESS``; grandchildren forked after
    that inherit its niceness."""
    try:
        try:
            os.setpriority(os.PRIO_PGRP, pid, -10)
        except OSError:
            pass  # group not born yet: fall through to the leader
        if os.getpriority(os.PRIO_PROCESS, pid) > -10:
            os.setpriority(os.PRIO_PROCESS, pid, -10)
    except OSError:
        pass  # not privileged (needs CAP_SYS_NICE) or stage already gone


#: Error-text markers that identify a TRANSIENT on-chip failure — the
#: recorded 2026-07-31 class (`UNAVAILABLE: TPU backend setup/compile
#: error` arriving while the tunnel probe stayed green) plus its
#: grpc-status siblings. Deliberately narrow: a deterministic failure
#: (shape bug, assertion) must not be retried on scarce grant time.
TRANSIENT_ERROR_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "Socket closed",
    "Connection reset", "failed to connect",
)


def is_transient_failure(stderr_tail: str) -> bool:
    """True iff a failed stage's stderr looks like a transient
    tunnel/backend error worth retrying while the probe is green."""
    return any(m in (stderr_tail or "") for m in TRANSIENT_ERROR_MARKERS)


def run_stage(name: str, argv: Sequence[str], deadline_s: float,
              log_path: str = LOG_PATH) -> Tuple[str, str]:
    """Run one capture stage under a hard deadline; never raises.

    Returns ``(status, stderr_tail)``. Status is ``"ok"`` (exit 0),
    ``"failed"`` (ran to completion with a nonzero exit — e.g.
    tpu_round2 recording a failed measurement), ``"timeout"`` (deadline
    kill), ``"error"`` (could not spawn). The caller treats failed
    differently from timed-out: a failure is a recorded result, a
    timeout is a truncated session. The stderr tail lets the caller
    classify a failure as transient (``is_transient_failure``) for the
    bounded retry path.

    The stage runs in its own process group and a timeout kills the
    WHOLE group — stages like bench.py spawn measurement grandchildren
    holding the chip, and killing only the leader would leave them
    orphaned on the scarce grant.
    """
    try:
        load1 = round(os.getloadavg()[0], 2)
    except OSError:  # pragma: no cover - platform-dependent
        load1 = None
    # load1 is measurement provenance: a capture racing a test suite or
    # sweep on this box inflates host-side walls; the log says so.
    log_event({"event": "stage-start", "stage": name,
               "deadline_s": deadline_s, "load1": load1}, log_path)
    start = time.monotonic()
    # Capture purity: stale operator exports must not shrink, redirect,
    # or silently re-pin a scarce grant capture (TPU_COOC_SMOKE_EVENTS
    # =2000 left over from test iteration would make every config4 row
    # garbage; a leftover TPU_COOC_UPLOAD_CHUNK_KB would change what the
    # unpinned passes measure while summarize compares them against the
    # pinned A/B arms). The A/B passes re-pin their own arms explicitly,
    # so stripping the knobs here is always safe.
    env = {k: v for k, v in os.environ.items()
           if k not in ("TPU_COOC_SMOKE_EVENTS", "TPU_ROUND2_OUT",
                        "TPU_COOC_UPLOAD_CHUNKS",
                        "TPU_COOC_UPLOAD_CHUNK_KB",
                        "TPU_COOC_SCORE_LADDER",
                        "TPU_COOC_FIXED_SCORE")}
    try:
        proc = subprocess.Popen(list(argv), cwd=REPO, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                start_new_session=True)
    except OSError as exc:
        log_event({"event": "stage-error", "stage": name, "ok": False,
                   "error": repr(exc)}, log_path)
        return "error", repr(exc)
    _boost_stage_priority(proc.pid)
    try:
        out, err = proc.communicate(timeout=deadline_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.communicate()
        log_event({"event": "stage-timeout", "stage": name, "ok": False,
                   "wall_s": round(time.monotonic() - start, 1)}, log_path)
        return "timeout", ""
    ok = proc.returncode == 0
    err_tail = (err or "")[-2000:]
    log_event({"event": "stage-end", "stage": name, "ok": ok,
               "rc": proc.returncode,
               "wall_s": round(time.monotonic() - start, 1),
               "stdout_tail": (out or "")[-2000:],
               **({} if ok else {"stderr_tail": err_tail})},
              log_path)
    return ("ok" if ok else "failed"), err_tail


#: Usable-capture contract: groups of alternative headline stages. If a
#: session RAN any stage of a group, at least one member must succeed
#: for the session to count as a complete capture — otherwise a
#: transient on-chip failure of every north-star measurement (the
#: recorded 2026-07-31 'UNAVAILABLE' class) would satisfy
#: ``--max-captures 1`` with zero usable numbers. Groups are ORs so a
#: deterministically-failing variant can't wedge the watcher as long as
#: any alternative form of the number lands.
REQUIRED_STAGE_GROUPS = (
    ("tpu_round2:config4-headline", "tpu_round2:config4-chunked",
     "tpu_round2:config4-sparse"),
    ("tpu_round2:ml25m-sparse", "tpu_round2:ml25m-full"),
)


def watch(interval_s: float = 300.0, probe_timeout_s: float = 240.0,
          max_cycles: Optional[int] = None, quick: bool = False,
          max_captures: Optional[int] = None,
          log_path: str = LOG_PATH,
          stages: Optional[List[Tuple[str, List[str], float]]] = None,
          heartbeat_every: int = 12,
          recapture_cooldown_s: float = 3600.0,
          stage_retries: int = 2,
          retry_backoff_s: float = 20.0,
          liveness_timeout_s: float = 60.0) -> int:
    """The watch loop. Returns the number of COMPLETE capture sessions.

    Complete = every stage RAN to completion under its deadline and the
    grant survived the whole session. A ``tpu_round2`` measurement
    stage that exits nonzero with the grant still up is logged — its
    failure IS a recorded result in TPU_ROUND2.jsonl — but does NOT
    void the session: otherwise one deterministically-failing
    measurement would make an unattended ``max_captures`` watcher
    re-burn every future grant re-running the full stage list forever.
    Timeouts, spawn errors, mid-capture grant loss, and failures of the
    artifact stages (bench.py, summarize — their nonzero exit means the
    session's deliverable is missing) DO void it, as does a
    ``REQUIRED_STAGE_GROUPS`` headline group whose every ran member
    failed (a transient failure of all north-star forms must not
    satisfy ``max_captures``), so ``max_captures=1`` keeps watching
    until one usable capture exists.

    ``max_cycles``/``max_captures`` bound the loop for tests and for
    drivers that only need one capture; the operator default (both
    None) loops until killed.

    ``recapture_cooldown_s``: after a COMPLETE capture, chip stages
    pause this long even if the grant stays up — a multi-hour grant
    must not be hammered with back-to-back duplicate 1-2 h capture
    passes on a shared chip. Incomplete sessions retry immediately
    (headline-first order makes the retry cheap).

    ``stage_retries``/``retry_backoff_s``: a chip stage that FAILS with
    a transient error signature (``is_transient_failure`` — the
    2026-07-31 `UNAVAILABLE` class) while an immediate liveness probe
    still sees the grant is retried up to ``stage_retries`` times with
    linear backoff, instead of being recorded as the session's only
    attempt. Deterministic failures (no transient marker) and timeouts
    are never retried — grant minutes are the scarce resource.

    ``liveness_timeout_s``: deadline for the cheap BETWEEN-stage probes
    (post-failure re-probe, retry gating). Deliberately much shorter
    than ``probe_timeout_s``: the 240 s default exists for a cold
    grant's first contact, but mid-session a healthy tunnel usually
    answers in seconds — a session with many deterministically-failing
    stages must not burn ~40 min of grant time on inter-stage probes
    alone. Because each probe is a fresh interpreter whose handshake
    CAN legitimately outlast the short deadline (busy shared chip,
    tunnel-speed first compile), a failed quick probe is never enough
    to void a session: the grant-lost decision re-confirms with the
    full ``probe_timeout_s`` before skipping the remaining chip stages.
    A failed quick probe merely skips an optional retry.
    """
    # Single-watcher lock: two watchers would race duplicate capture
    # sessions on the scarce chip. Held for the watch's lifetime and
    # released in the finally below; a second instance fails fast.
    # Mode "a": a failed second start must not truncate the holder's
    # recorded PID (an operator reads it to find who holds the lock).
    lock_path = log_path + ".lock"
    lock_file = open(lock_path, "a")
    try:
        fcntl.flock(lock_file, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        lock_file.close()
        raise SystemExit(
            f"another grant_watch holds {lock_path}; refusing to start "
            "a second watcher (duplicate captures would race the chip)")
    lock_file.truncate(0)
    lock_file.write(f"{os.getpid()}\n")
    lock_file.flush()
    try:
        return _watch_locked(
            interval_s, probe_timeout_s, max_cycles, quick, max_captures,
            log_path, stages, heartbeat_every, recapture_cooldown_s,
            stage_retries, retry_backoff_s, liveness_timeout_s)
    finally:
        lock_file.close()  # releases the flock


def _watch_locked(interval_s, probe_timeout_s, max_cycles, quick,
                  max_captures, log_path, stages, heartbeat_every,
                  recapture_cooldown_s, stage_retries, retry_backoff_s,
                  liveness_timeout_s) -> int:
    captures = 0
    sessions = 0
    cycle = 0
    probes = 0
    last_complete = None
    log_event({"event": "watch-start", "interval_s": interval_s,
               "quick": quick}, log_path)
    while True:
        cycle += 1
        cycle_start = time.monotonic()
        cooling = (last_complete is not None
                   and time.monotonic() - last_complete
                   < recapture_cooldown_s)
        if not cooling:
            probes += 1
        granted = False if cooling else probe_once(probe_timeout_s)
        if granted:
            log_event({"event": "grant", "cycle": cycle}, log_path)
            truncated = False
            lost = False
            statuses = {}
            for stage in (stages if stages is not None
                          else default_stages(quick)):
                name, argv, deadline = stage[:3]
                needs_grant = stage[3] if len(stage) > 3 else True
                if lost and needs_grant:
                    continue  # don't burn chip stages on a dead tunnel
                status, err_tail = run_stage(name, argv, deadline,
                                             log_path)
                # Bounded retry of TRANSIENT chip failures while the
                # grant is demonstrably still up: the 2026-07-31 session
                # lost its only config-4 attempt to one `UNAVAILABLE`
                # compile error that a single retry would have cleared
                # (the probe was green seconds later). Deterministic
                # failures and timeouts are not retried. quick_probe
                # carries the most recent liveness result forward so the
                # grant-lost check below doesn't immediately re-hang on
                # a tunnel a gate probe just found dead.
                attempt = 0
                quick_probe = None  # None = no probe since last run
                while (status == "failed" and needs_grant
                       and attempt < stage_retries
                       and is_transient_failure(err_tail)):
                    attempt += 1
                    backoff = retry_backoff_s * attempt
                    # Backoff FIRST, probe second: a probe taken before
                    # the sleep is backoff-seconds stale by launch time,
                    # and a retry launched onto a tunnel that died
                    # during the sleep hangs to the stage deadline —
                    # converting a recorded failure into a voided
                    # session, strictly worse than not retrying.
                    time.sleep(backoff)
                    quick_probe = probe_once(liveness_timeout_s)
                    if not quick_probe:
                        break  # not demonstrably up: skip the retry
                    log_event({"event": "stage-retry", "stage": name,
                               "attempt": attempt,
                               "backoff_s": backoff}, log_path)
                    status, err_tail = run_stage(name, argv, deadline,
                                                 log_path)
                    quick_probe = None  # stale after another stage run
                statuses[name] = status
                if status in ("timeout", "error"):
                    truncated = True  # hung or unrunnable: not a result
                elif status == "failed" and not name.startswith(
                        "tpu_round2"):
                    # Only tpu_round2 measurement stages may fail
                    # without voiding the session (their failure IS a
                    # recorded result in TPU_ROUND2.jsonl). A failed
                    # bench.py or summarize means the session's
                    # deliverable is missing.
                    truncated = True
                if status != "ok" and needs_grant:
                    # Grant-lost check, two-tier: reuse the retry gate's
                    # probe when fresh, else a quick probe; a negative
                    # is re-confirmed with the full cold-contact timeout
                    # before voiding — a fresh probe interpreter's
                    # handshake can outlast the quick deadline on a
                    # perfectly healthy grant, and wrongly skipping the
                    # remaining chip stages costs the whole session.
                    alive = quick_probe
                    if alive is None:
                        alive = probe_once(liveness_timeout_s)
                    if not alive:
                        alive = probe_once(probe_timeout_s)
                    if not alive:
                        # Stage failed AND the tunnel is gone: skip the
                        # remaining chip stages; offline stages (the
                        # summary rewrite) still run on the partial
                        # capture.
                        log_event({"event": "grant-lost",
                                   "cycle": cycle}, log_path)
                        lost = True
            sessions += 1
            # Headline contract: a group that ran but produced no
            # success (e.g. a transient UNAVAILABLE on every config-4
            # form) leaves the session unusable — keep watching.
            missing_groups = [
                g for g in REQUIRED_STAGE_GROUPS
                if any(n in statuses for n in g)
                and not any(statuses.get(n) == "ok" for n in g)]
            failed_stages = [n for n, s in statuses.items() if s != "ok"]
            complete = not truncated and not lost and not missing_groups
            if complete:
                captures += 1
                last_complete = time.monotonic()
            log_event({"event": "capture-done", "cycle": cycle,
                       "complete": complete, "sessions": sessions,
                       "captures": captures,
                       **({"failed_stages": failed_stages}
                          if failed_stages else {}),
                       **({"missing_headline_groups":
                           [list(g) for g in missing_groups]}
                          if missing_groups else {})}, log_path)
            if max_captures is not None and captures >= max_captures:
                break
        elif cycle % heartbeat_every == 1 or heartbeat_every <= 1:
            # Dead-tunnel cycles log a periodic heartbeat, not every
            # probe: the JSONL is a tracked artifact and a day of
            # 5-minute probes would be pure churn. During the
            # post-capture cooldown no probe ran, so the grant state is
            # unknown — log that, not a spurious no-grant.
            log_event({"event": "cooldown" if cooling else "no-grant",
                       "cycle": cycle}, log_path)
        if max_cycles is not None and cycle >= max_cycles:
            break
        # Probe cadence, not sleep cadence: a 4-minute dead-probe hang
        # already consumed most of the interval.
        remaining = interval_s - (time.monotonic() - cycle_start)
        if remaining > 0:
            time.sleep(remaining)
    log_event({"event": "watch-end", "cycles": cycle, "probes": probes,
               "sessions": sessions, "captures": captures}, log_path)
    return captures


def status(log_path: str = LOG_PATH) -> dict:
    """Summarize a watch log: loop cycles, probes, grants, captures.

    ``cycles`` counts loop iterations (including post-capture cooldown
    cycles in which no probe ran); ``probes_run`` counts actual tunnel
    probes, summed from watch-end rows (runs still in flight have not
    written one, so it can trail ``cycles``)."""
    out = {"log": log_path, "exists": os.path.exists(log_path),
           "first_ts": None, "last_ts": None, "last_event": None,
           "cycles": 0, "probes_run": 0, "grants": 0,
           "stage_retries": 0, "captures_complete": 0,
           "last_capture_ts": None}
    if not out["exists"]:
        return out
    # Cycles accumulate ACROSS watch runs (each run restarts at cycle 1):
    # a run's count is its watch-end total when present (dead-tunnel
    # cycles are heartbeat-sampled, so per-event maxima undercount), else
    # the largest cycle any of its events carried.
    total_cycles = 0
    run_max = 0
    with open(log_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue
            out["first_ts"] = out["first_ts"] or e.get("ts")
            out["last_ts"] = e.get("ts")
            out["last_event"] = e.get("event")
            ev = e.get("event")
            if ev == "watch-start":
                total_cycles += run_max
                run_max = 0
            elif ev == "watch-end":
                run_max = max(run_max, e.get("cycles", 0))
                out["probes_run"] += e.get("probes", e.get("cycles", 0))
            elif "cycle" in e:
                run_max = max(run_max, e.get("cycle", 0))
            if ev == "grant":
                out["grants"] += 1
            if ev == "stage-retry":
                out["stage_retries"] += 1
            if ev == "capture-done":
                if e.get("complete"):
                    out["captures_complete"] += 1
                out["last_capture_ts"] = e.get("ts")
    out["cycles"] = total_cycles + run_max
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between probe starts (default 300)")
    ap.add_argument("--probe-timeout", type=float, default=240.0,
                    help="hard deadline per probe subprocess (default 240)")
    ap.add_argument("--max-cycles", type=int, default=None,
                    help="stop after N probe cycles (default: forever)")
    ap.add_argument("--max-captures", type=int, default=None,
                    help="stop after N completed capture sessions "
                         "(default: forever)")
    ap.add_argument("--once", action="store_true",
                    help="single probe cycle (= --max-cycles 1)")
    ap.add_argument("--quick", action="store_true",
                    help="run tpu_round2 --quick (tunnel sanity shapes)")
    ap.add_argument("--status", action="store_true",
                    help="summarize GRANT_WATCH.jsonl and exit (no probe)")
    ap.add_argument("--recapture-cooldown", type=float, default=3600.0,
                    help="seconds to pause chip stages after a COMPLETE "
                         "capture while the grant stays up (default 3600)")
    ap.add_argument("--stage-retries", type=int, default=2,
                    help="max retries of a transiently-failed chip stage "
                         "while the liveness probe stays green (default 2)")
    ap.add_argument("--retry-backoff", type=float, default=20.0,
                    help="linear backoff base between stage retries, "
                         "seconds (default 20)")
    ap.add_argument("--liveness-timeout", type=float, default=60.0,
                    help="deadline for cheap between-stage liveness "
                         "probes (default 60; the full --probe-timeout "
                         "covers only cold first contact)")
    args = ap.parse_args()
    if args.status:
        print(json.dumps(status()))
        return
    watch(interval_s=args.interval, probe_timeout_s=args.probe_timeout,
          max_cycles=1 if args.once else args.max_cycles,
          max_captures=args.max_captures, quick=args.quick,
          recapture_cooldown_s=args.recapture_cooldown,
          stage_retries=args.stage_retries,
          retry_backoff_s=args.retry_backoff,
          liveness_timeout_s=args.liveness_timeout)


if __name__ == "__main__":
    main()
