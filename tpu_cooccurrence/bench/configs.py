"""The five BASELINE.md benchmark configurations.

| # | Config                                                        |
|---|---------------------------------------------------------------|
| 1 | batch word co-occurrence on tiny text file (local, CPU)       |
| 2 | MovieLens-100K user->item baskets, tumbling count window      |
| 3 | MovieLens-25M sessions, sliding time window + top-k           |
| 4 | Zipfian synthetic basket stream (1M items, a=1.1), 8 shards   |
| 5 | Instacart order-product baskets, incremental streaming update |

Real dataset files are used when present (paths via env:
``MOVIELENS_100K``, ``MOVIELENS_25M``, ``INSTACART_ORDERS``/
``INSTACART_ORDER_PRODUCTS``); otherwise shape-matched synthetic stand-ins
are generated (this environment has no network egress), and the report
labels them as such.

Metric: item-pairs/sec = ObservedCooccurrences / wall-clock (BASELINE.md).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple


from ..config import Backend, Config
from ..io import synthetic
from ..job import CooccurrenceJob
from ..metrics import OBSERVED_COOCCURRENCES

TINY_TEXT = """the quick brown fox jumps over the lazy dog
pack my box with five dozen liquor jugs
how vexingly quick daft zebras jump
the five boxing wizards jump quickly
sphinx of black quartz judge my vow
the quick onyx goblin jumps over the lazy dwarf
"""


@dataclasses.dataclass
class BenchResult:
    name: str
    backend: str
    events: int
    pairs: int
    seconds: float
    synthetic_standin: bool
    #: Which synthetic model produced the stand-in stream (None for real
    #: files): "zipf" (legacy shape-matched Zipf) or "calibrated-v1"
    #: (marginals fitted to the dataset's published spectra — see
    #: docs/calibrated_standins.md).
    standin_model: Optional[str] = None

    @property
    def pairs_per_sec(self) -> float:
        return self.pairs / max(self.seconds, 1e-9)

    def as_dict(self) -> Dict:
        return {
            "name": self.name,
            "backend": self.backend,
            "events": self.events,
            "pairs": self.pairs,
            "seconds": round(self.seconds, 3),
            "pairs_per_sec": round(self.pairs_per_sec, 1),
            "synthetic_standin": self.synthetic_standin,
            **({"standin_model": self.standin_model}
               if self.standin_model else {}),
        }


def _run(name: str, cfg: Config, users, items, ts,
         standin_model: Optional[str]) -> BenchResult:
    """``standin_model``: None = real (or non-stand-in) input; a string
    names the synthetic model that stands in for a real dataset."""
    job = CooccurrenceJob(cfg)
    start = time.monotonic()
    job.add_batch(users, items, ts)
    job.finish()
    seconds = time.monotonic() - start
    return BenchResult(name, cfg.backend.value, len(users),
                       job.counters.get(OBSERVED_COOCCURRENCES), seconds,
                       standin_model is not None, standin_model)


def config1_tiny_text(backend: Backend = Backend.DEVICE) -> BenchResult:
    """Batch word co-occurrence on a tiny text (one window, skip-cuts)."""
    users, items, ts = synthetic.word_cooccurrence_stream(TINY_TEXT * 50)
    n_items = int(items.max()) + 1
    cfg = Config(window_size=1_000_000, skip_cuts=True, seed=1,
                 backend=backend, num_items=n_items)
    return _run("tiny-text-batch", cfg, users, items, ts, None)


def _movielens_100k() -> Tuple:
    """(users, items, ts, standin_model): model is None for real files —
    the helper that picks the generator owns the provenance label."""
    path = os.environ.get("MOVIELENS_100K", "")
    if path and os.path.exists(path):
        (users, items, ts), = synthetic.movielens_interactions(path)
        return users, items, ts, None
    # Stand-in calibrated to the published ML-100K marginals (943
    # users x 1,682 movies, top-3 movie counts, >=20 ratings/user).
    users, items, ts = synthetic.ml100k_calibrated()
    return users, items, ts, "calibrated-v1"


def config2_ml100k(backend: Backend = Backend.DEVICE) -> BenchResult:
    users, items, ts, model = _movielens_100k()
    cfg = Config(window_size=4000, seed=2, item_cut=500, user_cut=500,
                 backend=backend, num_items=int(items.max()) + 1)
    return _run("ml-100k-tumbling", cfg, users, items, ts, model)


def _movielens_25m(limit: Optional[int]) -> Tuple:
    path = os.environ.get("MOVIELENS_25M", "")
    if path and os.path.exists(path):
        (users, items, ts), = synthetic.movielens_interactions(path)
        if limit:
            users, items, ts = users[:limit], items[:limit], ts[:limit]
        return users, items, ts, None
    n = limit or 2_000_000
    # Stand-in calibrated to the published ML-25M marginals (162,541
    # users x 59,047 movies, near-tied top movies at ~81.5k ratings,
    # >=20 ratings/user) — a plain Zipf alpha misses the real head by
    # construction (docs/calibrated_standins.md has the deltas).
    users, items, ts = synthetic.ml25m_calibrated(n)
    return users, items, ts, "calibrated-v1"


def _dense_cfg_extras(backend: Backend, items) -> Dict:
    """int16 counts whenever a dense (device/sharded) backend carries the
    config — that is what fits these vocabularies on chip."""
    dense = backend in (Backend.DEVICE, Backend.SHARDED)
    return {
        "count_dtype": "int16" if dense else "int32",
        "num_items": int(items.max()) + 1 if dense else 0,
    }


def config3_ml25m_sliding(backend: Backend = Backend.DEVICE,
                          limit: Optional[int] = 500_000) -> BenchResult:
    """59k-item vocab (the calibrated stand-in carries ML-25M's real
    59,047 movies): a dense int32 C (13.9 GB) misses one chip's HBM,
    but reference-style int16 counts (7.0 GB) fit — so the dense device
    backend carries this config instead of the host-matrix hybrid."""
    users, items, ts, model = _movielens_25m(limit)
    cfg = Config(window_size=4000, window_slide=1000, seed=3,
                 item_cut=500, user_cut=500, backend=backend,
                 **_dense_cfg_extras(backend, items))
    return _run("ml-25m-sliding", cfg, users, items, ts, model)


def config4_zipfian_1m(backend: Backend = Backend.SPARSE,
                            n_events: int = 1_000_000) -> BenchResult:
    """1M-item Zipfian stream. Dense device state is infeasible at this
    vocabulary; the device-resident sparse slab backend carries it (the
    host-matrix hybrid remains as the fallback comparison point)."""
    users, items, ts = synthetic.zipfian_interactions(
        n_events, n_items=1_000_000, n_users=100_000, alpha=1.1, seed=4,
        events_per_ms=200)
    cfg = Config(window_size=100, seed=4, item_cut=500, user_cut=500,
                 backend=backend)
    return _run("zipfian-1M-items", cfg, users, items, ts, None)


def _instacart() -> Tuple:
    orders = os.environ.get("INSTACART_ORDERS", "")
    order_products = os.environ.get("INSTACART_ORDER_PRODUCTS", "")
    if orders and os.path.exists(orders) and os.path.exists(order_products):
        (users, items, ts), = synthetic.instacart_interactions(
            orders, order_products)
        return users, items, ts, None
    # Stand-in calibrated to the published Instacart marginals (user
    # order counts 4..100 mean 16.6, basket sizes mean ~10 median 8,
    # Banana-headed product spectrum). Scale via BENCH_BASKETS;
    # persistent histories make the pair volume grow quadratically in
    # per-user interactions.
    n_baskets = int(os.environ.get("BENCH_BASKETS", 20_000))
    users, items, ts = synthetic.instacart_calibrated(n_baskets)
    return users, items, ts, "calibrated-v1"


def config5_instacart(backend: Backend = Backend.DEVICE) -> BenchResult:
    """~50k-item vocab: int16 counts (5 GB dense C) keep this on the dense
    device backend (17x the hybrid's throughput here)."""
    users, items, ts, model = _instacart()
    cfg = Config(window_size=1000, seed=5, item_cut=500, user_cut=500,
                 backend=backend, **_dense_cfg_extras(backend, items))
    return _run("instacart-incremental", cfg, users, items, ts, model)


ALL_CONFIGS: List[Tuple[str, Callable[[], BenchResult]]] = [
    ("1-tiny-text", config1_tiny_text),
    ("2-ml100k", config2_ml100k),
    ("3-ml25m-sliding", config3_ml25m_sliding),
    ("4-zipfian-1M", config4_zipfian_1m),
    ("5-instacart", config5_instacart),
]


def run_all() -> Iterator[BenchResult]:
    for _name, fn in ALL_CONFIGS:
        yield fn()


def main() -> None:
    import json

    # Stream each result as it completes (config 3-5 take minutes each).
    for res in run_all():
        print(json.dumps(res.as_dict()), flush=True)


if __name__ == "__main__":
    main()
