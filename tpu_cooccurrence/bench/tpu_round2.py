"""Round-2 TPU measurement pass: every pending on-chip number, one run.

The tunneled chip comes and goes; this script captures all round-2
TPU-gated measurements in one sitting and appends JSON lines to
``TPU_ROUND2.jsonl`` at the repo root (one object per measurement, with
failures recorded rather than aborting the pass):

1. config4-headline — the 1M-item Zipfian north star in ONE number
                      (single L16/fixed run; target: >=458k pairs/s =
                      20x the measured 22.9k host-oracle baseline,
                      BASELINE.md). config4-sparse is the 4-mode sweep.
2. ml25m-sparse / ml25m-full — the two config-3 carrier candidates,
                      25M events + v5e-8 projection (bench/ml25m.py).
3. sparse-pallas / sharded-pallas-1chip / pallas-bench — kernel-vs-XLA
                      A/Bs with on-hardware parity checks.
4. configs          — the five BASELINE.md benchmark configs.

Each measurement can run alone via ``--only NAME`` — grant_watch runs
them as separate deadline'd stages so a hang costs one measurement,
not the pass.

(config4-hybrid was the round-1 carrier comparison row; the hybrid
backend lost it 2.2x on-chip and was retired round 3.)

Usage (on a TPU-attached interpreter — no JAX_PLATFORMS override):
    python -m tpu_cooccurrence.bench.tpu_round2 [--quick]
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time
import traceback

from tpu_cooccurrence import tuning

#: TPU_ROUND2_OUT overrides the artifact path — for CPU smoke tests of
#: the measurement machinery (which must not bitrot between grants, nor
#: pollute the tracked JSONL with CPU rows).
OUT = os.environ.get("TPU_ROUND2_OUT") or os.path.join(
    os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "TPU_ROUND2.jsonl")


def emit(obj: dict) -> None:
    obj["ts"] = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(OUT, "a") as f:
        f.write(json.dumps(obj) + "\n")
    print(json.dumps(obj), flush=True)


def onchip_row(r: dict) -> bool:
    """Shared predicate for TPU_ROUND2.jsonl readers (summarize.py,
    ml25m.py): an ok row is usable as an on-chip number unless its
    platform tag says otherwise. A CPU smoke run whose TPU_ROUND2_OUT
    override was lost must poison neither the summary nor the
    projection constants. Historic rows predate the tag and pass
    untagged — their capture sessions were TPU-only."""
    if not r.get("ok"):
        return False
    platform = r.get("jax_platform")
    return platform is None or platform == "tpu"


def _backend_tag() -> dict:
    """Per-row platform provenance: grant_watch runs each measurement as
    its own `--only` subprocess, so the one-per-session env row may not
    exist in the same process (or at all, if tunnel-probe was skipped) —
    without this tag a row can't be told apart from an accidental CPU
    run. The key is ``jax_platform``, NOT ``backend``: several
    measurement dicts already carry a ``backend`` field meaning the
    *job* backend ("sparse", "device-int16", ...) which summarize.py
    keys on — the platform tag must neither be shadowed by it nor
    shadow it. Reads only jax's CACHED default backend: triggering a
    first backend init here (e.g. in the error path of a measurement
    that died before any dispatch, on a now-dead tunnel) could hang
    past the stage deadline and convert a recorded failure into a
    voided session. Uninitialized ⇒ no tag, honestly."""
    try:
        from jax._src import xla_bridge

        backend = xla_bridge._default_backend  # cached; None if uninit
        return {} if backend is None else {"jax_platform": backend.platform}
    except Exception:  # pragma: no cover - private-API drift
        return {}


def guard(name: str):
    def deco(fn):
        def run(*a, **k):
            start = time.monotonic()
            try:
                res = dict(fn(*a, **k))
                # The measurement NAME is the pass's identity; an inner
                # BenchResult's own "name" must not shadow it (it did
                # through round 3 — config4 rows landed as
                # "zipfian-1M-items"; summarize.py accepts both).
                if "name" in res:
                    res["config"] = res.pop("name")
                emit({"name": name, "ok": True, **_backend_tag(),
                      "wall_s": round(time.monotonic() - start, 1), **res})
                return True
            except Exception as exc:  # record and continue the pass
                emit({"name": name, "ok": False, **_backend_tag(),
                      "error": repr(exc),
                      "trace": traceback.format_exc()[-1500:]})
                return False
        return run
    return deco


@guard("tunnel-probe")
def tunnel_probe_pass(quick: bool) -> dict:
    """First thing in the pass: ~2 minutes of dispatch/transfer-latency
    separation (enqueue vs sync RTT, upload bandwidth, fetch overlap) —
    the numbers every ladder/deferral decision keys on. Runs before the
    long measurements so a short grant still captures them."""
    from .tunnel_probe import probe

    return probe()


@guard("config5-sparse")
def config5_sparse(quick: bool) -> dict:
    """Instacart shape on the sparse backend (50k vocab): the same
    nonzero-cells-only argument as ml25m-sparse — the chip picks the
    config-5 carrier."""
    from ..config import Backend
    from .configs import config5_instacart

    if quick:
        # Quick mode exists to sanity-check the tunnel cheaply; the
        # Instacart shape takes minutes (same rule as all_configs).
        return {"skipped": "config 5 takes minutes; run without --quick"}
    # Single measured run (grant time is the scarce resource): unlike
    # config4's per-ladder warmups this shape runs minutes, so the
    # one-time jit compile it absorbs is noise, not signal.
    return config5_instacart(backend=Backend.SPARSE).as_dict()


@guard("config4-sparse")
def config4_sparse(quick: bool) -> dict:
    from .configs import config4_zipfian_1m

    n = _config4_events(quick)
    # Two-axis sweep: score ladder x fixed-shape scoring. With fixed
    # shapes ON (the TPU default) every bucket pads to its constant
    # rectangle, so the ladder only decides the bucket set; the
    # "L16/var" point re-measures the round-2 variable-padding mode
    # (whose prior numbers were 71.9k @16 / 65.5k @4 before results
    # were deferred). Warmup populates the jit caches; measure the
    # second run of each.
    by_mode = {}
    best = None
    with _env_overrides(TPU_COOC_SCORE_LADDER="4",
                        TPU_COOC_FIXED_SCORE="1"):
        for ladder, fixed in (("4", "1"), ("16", "1"), ("64", "1"),
                              ("16", "0")):
            os.environ["TPU_COOC_SCORE_LADDER"] = ladder
            os.environ["TPU_COOC_FIXED_SCORE"] = fixed
            config4_zipfian_1m(n_events=n)
            r = config4_zipfian_1m(n_events=n)
            key = f"L{ladder}/{'fixed' if fixed == '1' else 'var'}"
            by_mode[key] = round(r.pairs_per_sec, 1)
            if best is None or r.pairs_per_sec > best.pairs_per_sec:
                best = r
    d = best.as_dict()
    d["pairs_per_sec_by_mode"] = by_mode
    d["vs_host_baseline_22.9k"] = round(best.pairs_per_sec / 22_900, 2)
    return d


@contextlib.contextmanager
def _env_overrides(**overrides: str):
    """Set env vars for the duration, restoring the operator's values
    (shared by the config4 passes; the remaining passes read the
    ambient settings on purpose)."""
    prior = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _config4_events(quick: bool) -> int:
    """Event count for the config-4 passes. TPU_COOC_SMOKE_EVENTS
    shrinks it for CPU smoke tests of the measurement machinery (which
    must not bitrot between grants). On an accelerator backend the
    knob is IGNORED with a warning: a stale export must not shrink a
    scarce grant capture into garbage rows (grant_watch additionally
    strips it from stage env). Every row records its ``events``
    regardless."""
    smoke = tuning.env_read("TPU_COOC_SMOKE_EVENTS")
    if smoke:
        import jax

        if jax.default_backend() == "cpu":
            return max(1_000, int(smoke))
        print(f"tpu_round2: ignoring TPU_COOC_SMOKE_EVENTS={smoke} on "
              f"backend {jax.default_backend()!r} — smoke sizes would "
              "corrupt a grant capture", file=sys.stderr)
    return 200_000 if quick else 1_000_000


def _config4_single(quick: bool, mode_label: str, **extra_env: str) -> dict:
    """One warmup + one measured run of config 4 in L16/fixed mode.

    Pins every knob the A/B rows vary — including UPLOAD_CHUNKS, so an
    ambient operator setting can't contaminate the monolithic arm of
    the upload comparison."""
    from .configs import config4_zipfian_1m

    n = _config4_events(quick)
    env = dict(TPU_COOC_SCORE_LADDER="16", TPU_COOC_FIXED_SCORE="1",
               TPU_COOC_UPLOAD_CHUNKS="1", TPU_COOC_UPLOAD_CHUNK_KB="0")
    env.update(extra_env)
    with _env_overrides(**env):
        config4_zipfian_1m(n_events=n)  # warmup: populate jit caches
        r = config4_zipfian_1m(n_events=n)
    d = r.as_dict()
    d["mode"] = mode_label
    d["vs_host_baseline_22.9k"] = round(r.pairs_per_sec / 22_900, 2)
    return d


@guard("config4-headline")
def config4_headline(quick: bool) -> dict:
    """North star #1 in ONE number, fast: a single run of the
    best-known mode (L16/fixed — the TPU default) instead of the 4-mode
    sweep, so a short grant session still settles the headline before
    anything long runs. The 2026-07-31 grant lived ~18 minutes and the
    sweep (8 full 1M-event runs + tunnel-speed compiles) consumed all
    of it without emitting; this row exists so that can't recur. The
    full sweep remains as config4-sparse."""
    return _config4_single(quick, "L16/fixed")


@guard("config4-chunked")
def config4_chunked(quick: bool) -> dict:
    """config4-headline with the update upload split into 4 transfers
    (TPU_COOC_UPLOAD_CHUNKS=4): the 2026-07-31 tunnel probe measured a
    per-transfer cost cliff between 256 KB and 1 MB, and config-4's
    ~0.8 MB/window update sits above it. Compare against the
    config4-headline row — if this wins on-chip, default
    TPU_COOC_UPLOAD_CHUNK_KB=256 on TPU (the adaptive policy,
    ops/device_scorer.upload_chunk_kb — fixed K leaves outsized
    windows above the cliff)."""
    return _config4_single(quick, "L16/fixed/chunks4",
                           TPU_COOC_UPLOAD_CHUNKS="4")


@guard("ml25m-full")
def ml25m_full(quick: bool) -> dict:
    from .ml25m import run_full

    return run_full(2_000_000 if quick else 25_000_000, host_only=False)


@guard("ml25m-sparse")
def ml25m_sparse(quick: bool) -> dict:
    """The sparse carrier candidate: scores only nonzero cells (~60x
    fewer than dense at this shape) for more host index work — the chip
    decides which backend carries config 3."""
    from ..config import Backend
    from .ml25m import run_full

    return run_full(2_000_000 if quick else 25_000_000, host_only=False,
                    backend=Backend.SPARSE)


@guard("sparse-pallas")
def sparse_pallas(quick: bool) -> dict:
    """A/B the sparse rectangle scorer: XLA gather+LLR+top_k vs the fused
    Pallas kernel, at the fixed-shape rectangle sizes config 4 actually
    dispatches (VERDICT r3, Next #2 — pre-built so a 247x-style cliff
    like dense int16's costs a measurement, not a grant cycle). The
    result decides whether SparseDeviceScorer's pallas auto rule stays
    OFF for int32 slabs or flips on."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..state.sparse_scorer import (SparseDeviceScorer, _score_slab,
                                       _score_slab_pallas, fixed_block)

    rng = np.random.default_rng(0)
    num_items = 1 << 20 if not quick else 1 << 16  # config-4 vocab scale
    top_k = 10
    row_sums = jnp.asarray(rng.integers(1, 1 << 20, num_items),
                           dtype=jnp.int32)
    observed = np.float32(1e9)
    budget = SparseDeviceScorer.FIXED_BUDGET
    row_cap = SparseDeviceScorer.FIXED_ROW_CAP

    def timeit(fn, n=5):
        jax.block_until_ready(fn())  # compile
        start = time.monotonic()
        for _ in range(n):
            jax.block_until_ready(fn())
        return (time.monotonic() - start) / n

    def parity(a, b) -> dict:
        """On-HARDWARE parity of two packed [2, S, K] results. CPU
        interpret mode already pins this; re-checking compiled-on-chip
        catches Mosaic miscompiles (a known class: carried-scratch/
        bitcast issues appear only at real grid sizes — see
        ops/pallas_score.py)."""
        from ..ops.pallas_score import topk_parity

        a, b = np.asarray(a), np.asarray(b)
        ok, mism = topk_parity(a[0], a[1].view(np.int32),
                               b[0], b[1].view(np.int32))
        return {"scores_allclose": ok, "id_mismatches": mism}

    by_rect = {}
    for R in (256, 1024, 4096):
        S = fixed_block(R, budget, row_cap)
        if quick:
            S = min(S, 512)
        # Rows at ~R/2 occupancy (post-pow-4-bucketing typical fill).
        lens = rng.integers(R // 4, R + 1, S).astype(np.int32)
        starts = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int32)
        cap = int(lens.sum()) + 8
        cnt = jnp.asarray(rng.integers(0, 50, cap), dtype=jnp.int32)
        dst = jnp.asarray(rng.integers(0, num_items, cap), dtype=jnp.int32)
        meta = np.zeros((3, S), dtype=np.int32)
        meta[0] = rng.choice(num_items, S, replace=False)
        meta[1] = starts
        meta[2] = lens
        meta_j = jnp.asarray(meta)
        xla_out = _score_slab(cnt, dst, row_sums, meta_j, observed,
                              top_k=top_k, R=R)
        xla_s = timeit(lambda: _score_slab(
            cnt, dst, row_sums, meta_j, observed, top_k=top_k, R=R))
        try:
            interp = jax.default_backend() != "tpu"
            pl_out = _score_slab_pallas(cnt, dst, row_sums, meta_j,
                                        observed, top_k=top_k, R=R,
                                        interpret=interp)
            pl_s = timeit(lambda: _score_slab_pallas(
                cnt, dst, row_sums, meta_j, observed, top_k=top_k, R=R,
                interpret=interp))
            by_rect[f"R{R}xS{S}"] = {
                "xla_ms": round(xla_s * 1e3, 2),
                "pallas_ms": round(pl_s * 1e3, 2),
                "pallas_speedup": round(xla_s / pl_s, 3),
                "parity": parity(xla_out, pl_out),
            }
        except Exception as exc:
            by_rect[f"R{R}xS{S}"] = {
                "xla_ms": round(xla_s * 1e3, 2),
                "pallas_error": repr(exc)[:200],
            }
    return {"count_dtype": "int32", "vocab": num_items,
            "by_rect": by_rect}


@guard("sharded-pallas-1chip")
def sharded_pallas_1chip(quick: bool) -> dict:
    """End-to-end validation of the kernel-inside-shard_map paths on ONE
    real chip (a 1-device mesh): both sharded backends run --pallas on
    vs off on the same stream and the results must match. Multi-chip
    meshes aren't reachable over the tunnel; this proves
    compile+execute+parity of the exact shard_map+pallas programs a pod
    would run (the CPU tests only ever exercise them interpreted)."""
    import numpy as np

    from ..parallel.mesh import make_mesh
    from ..parallel.sharded import ShardedScorer
    from ..parallel.sharded_sparse import ShardedSparseScorer
    from ..sampling.reservoir import PairDeltaBatch

    rng = np.random.default_rng(3)
    n, items = (20_000, 256) if quick else (60_000, 512)
    src = rng.integers(0, items, n).astype(np.int64)
    dst = rng.integers(0, items, n).astype(np.int64)
    keep = src != dst
    pairs = PairDeltaBatch(src[keep], dst[keep],
                           np.ones(int(keep.sum()), dtype=np.int32))
    mesh = make_mesh(1)

    def compare(mk):
        out = {}
        for pl in ("on", "off"):
            sc = mk(pl)
            sc.process_window(0, pairs)
            batches = [sc.flush(), sc.flush()]
            out[pl] = {int(r): (v.copy(), i.copy())
                       for b in batches
                       for r, i, v in zip(b.rows, b.idx, b.vals)}
        from ..ops.pallas_score import topk_parity

        rows_match = set(out["on"]) == set(out["off"])
        common = sorted(set(out["on"]) & set(out["off"]))
        if not common:
            # Disjoint/empty row sets ARE the parity failure this check
            # exists to catch — report it, don't crash on np.stack([]).
            return {"rows": len(out["off"]), "rows_on": len(out["on"]),
                    "rows_match": rows_match, "scores_allclose": False,
                    "id_mismatches": -1}
        v_on = np.stack([out["on"][r][0] for r in common])
        i_on = np.stack([out["on"][r][1] for r in common])
        v_off = np.stack([out["off"][r][0] for r in common])
        i_off = np.stack([out["off"][r][1] for r in common])
        ok, id_mism = topk_parity(v_off, i_off, v_on, i_on)
        return {"rows": len(out["off"]), "rows_match": rows_match,
                "scores_allclose": ok, "id_mismatches": id_mism}

    # VERDICT r4 Next #7: the shard_map+psum wrapper's per-window cost,
    # measured on the one real device at the config-3 row-sum scale —
    # the same windows through the unsharded sparse scorer and a
    # 1-device-mesh sharded one; the difference is the wrapper term
    # (shard_map launch + the per-window row-sum psum a pod pays) the
    # v5e-8 projection previously covered with an assumed allowance.
    from ..state.sparse_scorer import SparseDeviceScorer

    vocab = 59_047  # config 3's calibrated ML-25M vocabulary
    n_w = 3 if quick else 6
    per_w = 10_000 if quick else 30_000
    r2 = np.random.default_rng(7)
    windows = []
    for w in range(n_w + 1):
        s = r2.integers(0, vocab, per_w).astype(np.int64)
        d = r2.integers(0, vocab, per_w).astype(np.int64)
        k = s != d
        windows.append((w, PairDeltaBatch(
            s[k], d[k], np.ones(int(k.sum()), dtype=np.int32))))

    def step_time(sc):
        sc.process_window(*windows[0])  # compile + first-touch growth
        sc.flush()
        start = time.monotonic()
        for w, p in windows[1:]:
            sc.process_window(w, p)
        sc.flush()  # deferred results: the fetch closes the timing
        return (time.monotonic() - start) / n_w

    t_plain = step_time(SparseDeviceScorer(10, defer_results=True,
                                           fixed_shapes=True))
    t_sharded = step_time(ShardedSparseScorer(10, mesh=mesh,
                                              defer_results=True,
                                              fixed_shapes=True))
    return {
        "sharded_dense_int16": compare(lambda pl: ShardedScorer(
            items, 10, mesh=mesh, count_dtype="int16", use_pallas=pl)),
        "sharded_sparse": compare(lambda pl: ShardedSparseScorer(
            10, mesh=mesh, defer_results=True, fixed_shapes=True,
            use_pallas=pl)),
        "step_ms_per_window_unsharded": round(t_plain * 1e3, 2),
        "step_ms_per_window_sharded_1dev": round(t_sharded * 1e3, 2),
        "sharded_overhead_ms_per_window": round(
            max(0.0, t_sharded - t_plain) * 1e3, 3),
        "overhead_vocab": vocab,
        "overhead_pairs_per_window": per_w,
    }


@guard("pallas-bench")
def pallas_bench(quick: bool) -> dict:
    """The kernel's target case: int16 counts at a max-vocab shape, where
    the XLA path's transient f32 score matrix doubles working HBM."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..ops.device_scorer import _score
    from ..ops.pallas_score import pallas_score_topk

    num_items = 20_480 if quick else 61_440  # multiple of the 512 tile
    s = 2048 if quick else 8192
    top_k = 10
    rng = np.random.default_rng(0)
    C = jnp.asarray(rng.integers(0, 50, (num_items, num_items)),
                    dtype=jnp.int16)
    row_sums = jnp.asarray(rng.integers(1, 1 << 20, num_items),
                           dtype=jnp.int32)
    rows = jnp.asarray(rng.integers(0, num_items, s), dtype=jnp.int32)
    observed = np.float32(1e9)

    def timeit(fn, n=5):
        fn()  # compile
        start = time.monotonic()
        for _ in range(n):
            jax.block_until_ready(fn())
        return (time.monotonic() - start) / n

    xla_s = timeit(lambda: _score(C, row_sums, rows, observed,
                                  top_k=top_k, packed=True))
    # Tile sweep: wider tiles amortize the sequential top-K merge (and its
    # per-tile threshold check) at the cost of a bigger VMEM working set.
    pallas_ms = {}
    for tile in (512, 1024, 2048):
        if num_items % tile:
            continue
        try:
            pl_s = timeit(lambda: pallas_score_topk(
                C, row_sums, rows, observed, top_k=top_k, tile=tile,
                packed=True))
            pallas_ms[str(tile)] = round(pl_s * 1e3, 2)
        except Exception as exc:
            pallas_ms[str(tile)] = f"failed: {exc!r}"[:200]
    best = min((v for v in pallas_ms.values() if isinstance(v, float)),
               default=None)
    return {"shape": [s, num_items], "count_dtype": "int16",
            "xla_ms": round(xla_s * 1e3, 2),
            "pallas_ms_by_tile": pallas_ms,
            "pallas_speedup": (round(xla_s * 1e3 / best, 3)
                               if best else None)}


@guard("configs")
def all_configs(quick: bool) -> dict:
    from .configs import ALL_CONFIGS

    # --quick runs only the two small configs (the tunnel session is the
    # scarce resource; config 4 already ran as its own measurement).
    fns = [fn for _name, fn in ALL_CONFIGS]
    if quick:
        fns = fns[:2]
    return {"results": [fn().as_dict() for fn in fns]}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small shapes (tunnel sanity, not headline numbers)")
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of measurement names")
    args = ap.parse_args()
    # Scarce-first order: the probe (projection constants) and ONE
    # number per north star run before anything long (config4-headline
    # is a single-mode run; the 4-mode sweep is config4-sparse, after
    # the carrier rows), so a short grant still settles the headline
    # questions; sparse-pallas decides the config-4 carrier kernel in
    # the same sitting.
    passes = {
        "tunnel-probe": tunnel_probe_pass,
        "config4-headline": config4_headline,
        "config4-chunked": config4_chunked,
        "ml25m-sparse": ml25m_sparse,
        "sparse-pallas": sparse_pallas,
        "ml25m-full": ml25m_full,
        "sharded-pallas-1chip": sharded_pallas_1chip,
        "config4-sparse": config4_sparse,
        "config5-sparse": config5_sparse,
        "pallas-bench": pallas_bench,
        "configs": all_configs,
    }
    only = set(args.only.split(",")) if args.only else None
    if only:
        unknown = only - set(passes)
        if unknown:
            ap.error(f"unknown measurement(s) {sorted(unknown)}; "
                     f"choose from {sorted(passes)}")
    # Persistent compile cache: grant time is scarce and tunnel-speed
    # compiles dominated the 2026-07-31 session. The scorers enable it
    # lazily at init, but measurements that die before a scorer exists
    # (or pure-probe passes) would compile uncached — enable it up
    # front. xla_cache handles host fingerprinting and opt-out.
    from ..xla_cache import enable_compilation_cache

    enable_compilation_cache()
    import jax

    # One env row per capture session, not one per --only subprocess:
    # grant_watch runs each measurement as its own stage and the
    # tracked JSONL would otherwise gain ~11 identical rows a session.
    if only is None or "tunnel-probe" in only:
        emit({"name": "env", "ok": True,
              "devices": [str(d) for d in jax.devices()],
              "backend": jax.default_backend(), "quick": args.quick})
    all_ok = True
    for name, fn in passes.items():
        if only is None or name in only:
            all_ok = bool(fn(args.quick)) and all_ok
    # Per-measurement stage runs (grant_watch) key their re-probe logic
    # off the exit code; a failed measurement must not exit 0.
    if not all_ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
