"""bench.regress: the bench-history regression gate.

``python -m tpu_cooccurrence.bench.regress`` replays
``bench_history.jsonl`` (one JSON entry per on-chip bench run, appended
by ``bench.py``) and flags metric deltas beyond the history's own noise
band — the gate ROADMAP open item #5 requires before any knob may
self-tune, and the verify skill's post-bench step.

Method: per tracked metric (flattened dotted leaves of the history
entries, e.g. ``serving.qps``), take the history's **median** and
**MAD** (median absolute deviation — robust to the odd outlier run a
shared host produces) and flag the candidate when it lands beyond
``median ± max(mad_k * MAD, rel_floor * |median|)`` on the metric's
BAD side (each tracked metric declares its good direction; a 2x
pairs/s IMPROVEMENT is news, not a regression). The relative floor
keeps a freakishly quiet history (MAD ~ 0) from flagging ordinary
jitter. History entries compare within the same ``backend`` only — cpu
fallback numbers must never band a TPU run.

Exit code: 1 when any tracked metric regresses, 0 otherwise —
including when history is too thin to band (< ``min_history`` prior
entries): a gate that cries wolf on its second-ever run would be
deleted by round three.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

#: Default history file (bench.py's append target), repo-root relative.
DEFAULT_HISTORY = "bench_history.jsonl"

#: Tracked metrics: flattened dotted key -> direction. "higher" = a
#: drop regresses (throughput-like), "lower" = a rise regresses
#: (latency/cost-like). Anything not listed is informational only.
KEY_METRICS: Dict[str, str] = {
    "pairs_per_sec": "higher",
    "vs_baseline": "higher",
    "fused.vs_chained": "higher",
    "fused_sparse.vs_chained": "higher",
    "fused_gang.vs_chained": "higher",
    "compression.rows_per_hbm_byte_gain": "higher",
    "serving.qps": "higher",
    "fleet.aggregate_qps": "higher",
    "serving.query_p99_s": "lower",
    "fleet.query_p99_s": "lower",
    "checkpoint.commit_bytes_ratio": "lower",
    "rescale.seam_stall_seconds": "lower",
}

#: Minimum same-backend prior entries before a metric is banded.
MIN_HISTORY = 3

#: Noise-band half-width: max(MAD_K * MAD, REL_FLOOR * |median|).
MAD_K = 5.0
REL_FLOOR = 0.10


def flatten(entry: dict, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a history entry as dotted keys. The embedded
    ``regression`` verdict (this module's own output, recorded back
    into history by bench.py) is skipped — the gate must never band
    its own prior verdicts."""
    out: Dict[str, float] = {}
    for key, value in entry.items():
        if key in ("regression", "ts", "note"):
            continue
        dotted = f"{prefix}{key}"
        if isinstance(value, bool):
            continue
        if isinstance(value, (int, float)):
            out[dotted] = float(value)
        elif isinstance(value, dict):
            out.update(flatten(value, prefix=f"{dotted}."))
    return out


def read_history(path: str) -> List[dict]:
    """History entries, skipping unparseable lines (same torn-tail
    tolerance as the journal readers)."""
    entries: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(entry, dict):
                    entries.append(entry)
    except OSError:
        pass
    return entries


def _median(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def evaluate(history: List[dict], candidate: dict,
             min_history: int = MIN_HISTORY, mad_k: float = MAD_K,
             rel_floor: float = REL_FLOOR) -> dict:
    """Band every tracked metric of ``candidate`` against the
    same-backend ``history`` entries. Returns the verdict dict bench.py
    embeds as ``out["regression"]``::

        {"ok": bool, "checked": N, "regressions": [per-metric dicts],
         "insufficient_history": [metric names], "backend": ...}
    """
    backend = str(candidate.get("backend", ""))
    prior = [flatten(e) for e in history
             if str(e.get("backend", "")) == backend]
    cand = flatten(candidate)
    regressions: List[dict] = []
    thin: List[str] = []
    checked = 0
    for metric, direction in KEY_METRICS.items():
        if metric not in cand:
            continue
        series = [p[metric] for p in prior if metric in p]
        if len(series) < min_history:
            thin.append(metric)
            continue
        checked += 1
        med = _median(series)
        mad = _median([abs(v - med) for v in series])
        band = max(mad_k * mad, rel_floor * abs(med))
        value = cand[metric]
        bad = (value < med - band if direction == "higher"
               else value > med + band)
        if bad:
            regressions.append({
                "metric": metric, "value": round(value, 6),
                "median": round(med, 6), "band": round(band, 6),
                "direction": direction, "n_history": len(series),
            })
    return {
        "ok": not regressions,
        "backend": backend,
        "checked": checked,
        "regressions": regressions,
        "insufficient_history": thin,
    }


def evaluate_latest(history: List[dict],
                    min_history: int = MIN_HISTORY) -> Tuple[dict, dict]:
    """CLI form: treat the newest history entry as the candidate and
    band it against everything before it. Returns (candidate,
    verdict)."""
    if not history:
        return {}, {"ok": True, "backend": "", "checked": 0,
                    "regressions": [],
                    "insufficient_history": list(KEY_METRICS)}
    candidate = history[-1]
    return candidate, evaluate(history[:-1], candidate,
                               min_history=min_history)


def render_text(candidate: dict, verdict: dict) -> str:
    lines = [f"bench.regress: backend={verdict['backend'] or '?'} "
             f"checked={verdict['checked']} metric(s)"]
    if candidate.get("ts"):
        lines[0] += f" candidate ts={candidate['ts']}"
    for reg in verdict["regressions"]:
        arrow = "below" if reg["direction"] == "higher" else "above"
        lines.append(
            f"  REGRESSION {reg['metric']}: {reg['value']} is {arrow} "
            f"median {reg['median']} +/- band {reg['band']} "
            f"(n={reg['n_history']})")
    if verdict["insufficient_history"]:
        lines.append(
            "  insufficient history (<%d same-backend entries): %s"
            % (MIN_HISTORY, ", ".join(verdict["insufficient_history"])))
    lines.append("verdict: " + ("OK" if verdict["ok"] else "REGRESSED"))
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_cooccurrence.bench.regress",
        description="Replay bench_history.jsonl and flag metric deltas "
                    "beyond the history's noise band (median +/- MAD "
                    "per metric, per backend). Exit 1 on regression.")
    p.add_argument("--history", default=DEFAULT_HISTORY,
                   help="bench history JSONL (default: "
                        f"{DEFAULT_HISTORY} in the cwd)")
    p.add_argument("--candidate", default=None,
                   help="JSON file holding the candidate bench output "
                        "(bench.py's stdout); default: the newest "
                        "history entry")
    p.add_argument("--min-history", type=int, default=MIN_HISTORY,
                   dest="min_history",
                   help="same-backend entries required before a metric "
                        "is banded (thinner history passes the gate)")
    p.add_argument("--format", default="text", choices=("text", "json"),
                   dest="format")
    args = p.parse_args(argv)
    history = read_history(args.history)
    if args.candidate:
        with open(args.candidate, "r", encoding="utf-8") as f:
            candidate = json.load(f)
        # bench.py's stdout names the headline "value"; history names
        # it "pairs_per_sec" — normalize so one metric table serves.
        if "pairs_per_sec" not in candidate and "value" in candidate:
            candidate = dict(candidate)
            candidate["pairs_per_sec"] = candidate["value"]
        verdict = evaluate(history, candidate,
                           min_history=args.min_history)
    else:
        candidate, verdict = evaluate_latest(
            history, min_history=args.min_history)
    if args.format == "json":
        sys.stdout.write(json.dumps(
            {"candidate_ts": candidate.get("ts"), **verdict},
            sort_keys=True) + "\n")
    else:
        sys.stdout.write(render_text(candidate, verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
