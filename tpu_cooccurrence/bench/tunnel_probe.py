"""Tunnel/dispatch microbenchmark: where does a window's wall time go?

On a tunneled single chip (axon) every dispatch, host->device transfer,
and device->host fetch may pay link latency. This probe separates:

1. enqueue cost    — is dispatch async (returns before completion)?
2. dispatch RTT    — serialized tiny kernels, one blocking sync at end
3. upload cost     — numpy -> device transfer of window-sized buffers
4. fetch RTT       — device -> host of a top-K-result-sized buffer
5. async fetch     — copy_to_host_async overlap effectiveness

Prints one JSON object. Run on the TPU-attached interpreter:
    python -m tpu_cooccurrence.bench.tunnel_probe
"""

from __future__ import annotations

import json
import time

import numpy as np

import jax
import jax.numpy as jnp


def probe() -> dict:
    """Run all probe sections and return the result dict."""
    out = {"devices": [str(d) for d in jax.devices()],
           "backend": jax.default_backend()}

    @jax.jit
    def tiny(x):
        return x + 1

    x = jnp.zeros((8,), jnp.int32)
    tiny(x).block_until_ready()  # compile

    # 1+2: enqueue vs completion of N chained tiny kernels.
    n = 50
    start = time.monotonic()
    y = x
    for _ in range(n):
        y = tiny(y)
    enqueue_s = time.monotonic() - start
    y.block_until_ready()
    chain_s = time.monotonic() - start
    out["enqueue_ms_per_dispatch"] = round(enqueue_s / n * 1e3, 3)
    out["chained_ms_per_dispatch"] = round(chain_s / n * 1e3, 3)

    # 2b: serialized round trips — block after EVERY tiny kernel.
    start = time.monotonic()
    y = x
    for _ in range(n):
        y = tiny(y).block_until_ready()
    out["sync_ms_per_dispatch"] = round(
        (time.monotonic() - start) / n * 1e3, 3)

    # 3: upload of a window-sized packed update buffer. The ladder
    # brackets the cliff the 2026-07-31 capture found between 256 KB
    # (0.3 ms, ~850 MB/s) and 1 MB (11.6 ms, ~86 MB/s) — if it is a
    # per-transfer threshold, the scorers' ~0.8 MB/window uploads can
    # ride under it by splitting (see 3b and TPU_COOC_UPLOAD_CHUNKS /
    # TPU_COOC_UPLOAD_CHUNK_KB in ops/device_scorer.py).
    @jax.jit
    def consume(b):
        return b.sum()

    for kb in (128, 256, 384, 512, 768, 1024, 2048):
        buf = np.zeros((2, kb * 128), dtype=np.int32)  # kb KiB total
        consume(jnp.asarray(buf)).block_until_ready()
        reps = 10
        start = time.monotonic()
        for _ in range(reps):
            consume(jnp.asarray(buf)).block_until_ready()
        out[f"upload_{kb}kb_ms"] = round(
            (time.monotonic() - start) / reps * 1e3, 2)

    # 3b: the same 1 MB as 4 separate 256 KB arguments of ONE jitted
    # call (4 transfers, 1 dispatch) vs the monolithic upload above.
    @jax.jit
    def consume4(a, b, c, d):
        return a.sum() + b.sum() + c.sum() + d.sum()

    bufs = [np.zeros((2, 256 * 128), dtype=np.int32) for _ in range(4)]
    consume4(*map(jnp.asarray, bufs)).block_until_ready()
    reps = 10
    start = time.monotonic()
    for _ in range(reps):
        consume4(*map(jnp.asarray, bufs)).block_until_ready()
    out["upload_4x256kb_ms"] = round(
        (time.monotonic() - start) / reps * 1e3, 2)

    # 4: blocking fetch of a packed [2, 4096, 10] f32 result (~320 KB).
    res = jnp.ones((2, 4096, 10), jnp.float32)
    res.block_until_ready()
    reps = 10
    start = time.monotonic()
    for _ in range(reps):
        np.asarray(res)
    out["fetch_320kb_ms"] = round((time.monotonic() - start) / reps * 1e3, 2)

    # 5: async-copy overlap — start copy, do ~50 ms of host work, then
    # fetch. The spin is timed and subtracted (not a nominal 50 ms: timer
    # granularity/preemption can overshoot and bias the residual).
    tiny(res).block_until_ready()  # compile for this shape outside the timing
    start = time.monotonic()
    spun = 0.0
    for _ in range(reps):
        r2 = tiny(res)
        if hasattr(r2, "copy_to_host_async"):
            r2.copy_to_host_async()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.05:
            pass
        spun += time.monotonic() - t0
        np.asarray(r2)
    out["fetch_320kb_after_50ms_host_work_ms"] = round(
        ((time.monotonic() - start) - spun) / reps * 1e3, 2)
    return out


def main() -> None:
    print(json.dumps(probe()))


if __name__ == "__main__":
    main()
