"""Array-native top-K result store with lazy materialization.

The reference terminates its result stream in a no-op sink
(``FlinkCooccurrences.java:169-171``) — results exist only as a stream of
``(item, topK)`` records. We keep results *consumable*, but the hot path
must not pay Python-per-row costs: device backends hand back whole windows
as packed ``[S, K]`` arrays (:class:`TopKBatch`), and :class:`LatestResults`
absorbs them with O(S) numpy scatters into a dense pointer table. The
per-item ``[(other, score), ...]`` lists the public API exposes are built
lazily, only for items actually read (CLI dump, tests, checkpoint).

All stored ids are *dense* vocab indices; external ids appear only at the
materialization boundary (``IdMap.to_external_batch``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Iterator, List, Mapping, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class TopKBatch:
    """One window's top-K results in packed array form (dense-id space).

    ``vals`` may contain ``-inf`` for rows with fewer than K co-occurring
    items; the matching ``idx`` entries are garbage and are filtered at
    materialization time.
    """

    rows: np.ndarray  # [S] int32 dense item ids
    idx: np.ndarray   # [S, K] int32 dense other-item ids
    vals: np.ndarray  # [S, K] float32 scores (descending)

    def __len__(self) -> int:
        return len(self.rows)

    @staticmethod
    def empty(top_k: int) -> "TopKBatch":
        return TopKBatch(np.zeros(0, np.int32),
                         np.zeros((0, top_k), np.int32),
                         np.zeros((0, top_k), np.float32))

    @staticmethod
    def concatenate(rows_l, idx_l, vals_l, top_k: int) -> "TopKBatch":
        """Assemble per-chunk host arrays into one batch ([] -> empty)."""
        if not rows_l:
            return TopKBatch.empty(top_k)
        return TopKBatch(np.concatenate(rows_l), np.concatenate(idx_l),
                         np.concatenate(vals_l))

    def truncated(self, k: int) -> "TopKBatch":
        """This batch narrowed to its first ``k`` result columns.

        Scores are stored descending, so column truncation IS top-k'
        selection — the degradation plane's result-side shedding knob
        (``robustness/degrade.py``, level SHED_K): an O(1) numpy slice,
        no device round-trip and no recompile. Identity when ``k``
        already covers the batch.
        """
        if k >= self.idx.shape[1]:
            return self
        return TopKBatch(self.rows, self.idx[:, :k], self.vals[:, :k])


def materialize_dense(window_out) -> List[Tuple[int, List[Tuple[int, float]]]]:
    """Expand a backend's window output to (dense item, [(dense, score)]).

    Accepts either the packed :class:`TopKBatch` (device/sharded backends)
    or an already-materialized list (host backends). Debug/test helper —
    the job's hot path absorbs batches without this expansion.
    """
    if not isinstance(window_out, TopKBatch):
        return list(window_out)
    out = []
    for r in range(len(window_out.rows)):
        vals = window_out.vals[r]
        keep = np.isfinite(vals)
        out.append((int(window_out.rows[r]),
                    list(zip(window_out.idx[r][keep].tolist(),
                             vals[keep].astype(float).tolist()))))
    return out


def pack_rows(rows_list: List[Tuple[int, List[Tuple[int, float]]]],
              k: Optional[int] = None) -> TopKBatch:
    """Materialized list rows -> one padded :class:`TopKBatch`.

    Pads to width ``k`` (or the widest row) with idx 0 / ``-inf`` score
    lanes — the one definition of the list-to-packed convention, shared
    by :meth:`ResultsSnapshot.packed` and the serving snapshot builder's
    absorb path (two paddings that drift apart would silently corrupt
    the restore-seeded serving table).
    """
    if not rows_list:
        return TopKBatch.empty(max(k or 1, 1))
    if k is None:
        k = max(1, max(len(top) for _, top in rows_list))
    rows = np.asarray([item for item, _ in rows_list], dtype=np.int32)
    idx = np.zeros((len(rows_list), k), dtype=np.int32)
    vals = np.full((len(rows_list), k), -np.inf, dtype=np.float32)
    for r, (_, top) in enumerate(rows_list):
        for c, (j, s) in enumerate(top):
            idx[r, c] = j
            vals[r, c] = s
    return TopKBatch(rows, idx, vals)


class _ListBatch:
    """Adapter for host backends that produce per-row Python lists."""

    def __init__(self) -> None:
        self.rows: List[List[Tuple[int, float]]] = []

    def append(self, top: List[Tuple[int, float]]) -> int:
        self.rows.append(top)
        return len(self.rows) - 1

    def __len__(self) -> int:
        return len(self.rows)


def _materialize_row(b, row: int, vocab) -> List[Tuple[int, float]]:
    """One stored row -> ``[(external other, score), ...]`` (shared by the
    live store and its snapshots)."""
    if isinstance(b, _ListBatch):
        return [(vocab.to_external(j), s) for j, s in b.rows[row]]
    vals = b.vals[row]
    keep = np.isfinite(vals)
    if not keep.any():
        return []
    ext = vocab.to_external_batch(b.idx[row][keep].astype(np.int64))
    return list(zip(ext.tolist(), vals[keep].astype(float).tolist()))


class ResultsSnapshot(Mapping):
    """Consistent point-in-time view of a :class:`LatestResults`.

    Constructed by :meth:`LatestResults.snapshot` *under the store's
    lock*: the pointer arrays are copied, the batch list is
    shallow-copied, and batch contents are immutable once absorbed
    (compaction builds new batches and a new list; list-batch appends
    never move existing rows) — so every read here is lock-free and
    cannot interleave with concurrent absorption. This is what the
    stdout emitters and the serving snapshot builder consume; iterating
    the live store mid-run reads a moving target.
    """

    def __init__(self, vocab, batches: list, ptr_batch: np.ndarray,
                 ptr_row: np.ndarray) -> None:
        self._vocab = vocab
        self.batches = batches
        self.ptr_batch = ptr_batch
        self.ptr_row = ptr_row
        self._n_vocab = len(vocab)  # vocab grows; pin the extent too

    def _live_dense(self) -> np.ndarray:
        n = min(len(self.ptr_batch), self._n_vocab)
        return np.nonzero(self.ptr_batch[:n] >= 0)[0]

    def __len__(self) -> int:
        return int(len(self._live_dense()))

    def __iter__(self) -> Iterator[int]:
        live = self._live_dense()
        if len(live) == 0:
            return iter(())
        return iter(self._vocab.to_external_batch(live).tolist())

    def __contains__(self, ext_item) -> bool:
        dense = self._vocab.to_dense(ext_item)
        return (dense is not None and dense < len(self.ptr_batch)
                and self.ptr_batch[dense] >= 0)

    def __getitem__(self, ext_item) -> List[Tuple[int, float]]:
        dense = self._vocab.to_dense(ext_item)
        if (dense is None or dense >= len(self.ptr_batch)
                or self.ptr_batch[dense] < 0):
            raise KeyError(ext_item)
        return _materialize_row(self.batches[self.ptr_batch[dense]],
                                int(self.ptr_row[dense]), self._vocab)

    def packed(self) -> TopKBatch:
        """Live rows as one packed dense-id batch (list-backed rows are
        padded in) — the serving builder's restore-seed input."""
        live = self._live_dense()
        if not len(live):
            return TopKBatch.empty(1)
        bids = self.ptr_batch[live]
        rows = self.ptr_row[live]
        k = 1
        for bid in np.unique(bids):
            b = self.batches[bid]
            if isinstance(b, _ListBatch):
                k = max(k, max((len(r) for r in b.rows), default=0))
            else:
                k = max(k, b.idx.shape[1])
        out_rows, out_idx, out_vals = [], [], []
        for bid in np.unique(bids):
            b = self.batches[bid]
            sel = bids == bid
            r = rows[sel]
            out_rows.append(live[sel].astype(np.int32))
            if isinstance(b, _ListBatch):
                sub = pack_rows(
                    [(int(d), b.rows[row])
                     for d, row in zip(live[sel].tolist(), r.tolist())],
                    k=k)
                idx, vals = sub.idx, sub.vals
            else:
                idx = np.zeros((len(r), k), dtype=np.int32)
                vals = np.full((len(r), k), -np.inf, dtype=np.float32)
                idx[:, : b.idx.shape[1]] = b.idx[r]
                vals[:, : b.vals.shape[1]] = b.vals[r]
            out_idx.append(idx)
            out_vals.append(vals)
        return TopKBatch(np.concatenate(out_rows),
                         np.concatenate(out_idx),
                         np.concatenate(out_vals))


class LatestResults(Mapping):
    """``{external item -> [(external other, score), ...]}`` view, array-backed.

    A dense pointer table maps each item to its most recent result row
    across all absorbed batches; superseded rows linger until
    :meth:`_compact` trims them (triggered when dead rows dominate).

    Absorption and reads are lock-serialized: in pipelined execution
    (``pipeline.py``) the scorer worker drains finished top-K tables into
    this store one step behind the device frontier while the caller
    thread may concurrently read (``--emit-updates`` consumers, progress
    probes). The lock is per-window/per-read scale, far off the hot path;
    serial mode pays only an uncontended acquire per window.
    """

    _COMPACT_MIN_ROWS = 1 << 20

    def __init__(self, vocab) -> None:
        self._vocab = vocab
        self._batches: list = []
        self._ptr_batch = np.full(1024, -1, dtype=np.int64)
        self._ptr_row = np.zeros(1024, dtype=np.int64)
        self._total_rows = 0
        # RLock: absorb paths call _compact (and _compact calls absorb/
        # set_row) while already holding it.
        self._lock = threading.RLock()

    # -- absorption (hot path) ------------------------------------------

    def _ensure(self, n: int) -> None:
        if n <= len(self._ptr_batch):
            return
        cap = len(self._ptr_batch)
        while cap < n:
            cap *= 2
        grown = np.full(cap, -1, dtype=np.int64)
        grown[: len(self._ptr_batch)] = self._ptr_batch
        grown_rows = np.zeros(cap, dtype=np.int64)
        grown_rows[: len(self._ptr_row)] = self._ptr_row
        self._ptr_batch = grown
        self._ptr_row = grown_rows

    def absorb_batch(self, batch: TopKBatch) -> None:
        if len(batch) == 0:
            return
        with self._lock:
            bid = len(self._batches)
            self._batches.append(batch)
            rows = batch.rows.astype(np.int64)
            self._ensure(int(rows.max()) + 1)
            self._ptr_batch[rows] = bid
            self._ptr_row[rows] = np.arange(len(rows), dtype=np.int64)
            self._total_rows += len(rows)
            if (self._total_rows >= self._COMPACT_MIN_ROWS
                    and self._total_rows > 2 * len(self)):
                self._compact()

    def set_row(self, dense_item: int, top: List[Tuple[int, float]]) -> None:
        """Single-row update from a host (list-producing) backend."""
        with self._lock:
            if (not self._batches
                    or not isinstance(self._batches[-1], _ListBatch)):
                self._batches.append(_ListBatch())
            bid = len(self._batches) - 1
            row = self._batches[bid].append(top)
            self._ensure(dense_item + 1)
            self._ptr_batch[dense_item] = bid
            self._ptr_row[dense_item] = row
            self._total_rows += 1
            if (self._total_rows >= self._COMPACT_MIN_ROWS
                    and self._total_rows > 2 * len(self)):
                self._compact()

    def _compact(self) -> None:
        """Drop superseded rows: rebuild live array rows into one batch."""
        live = np.nonzero(self._ptr_batch[: len(self._vocab)] >= 0)[0]
        bids = self._ptr_batch[live]
        rows = self._ptr_row[live]
        keep_lists = []  # list batches are kept as-is (host paths are small)
        arr_rows, arr_idx, arr_vals = [], [], []
        for bid in np.unique(bids):
            b = self._batches[bid]
            sel = bids == bid
            r = rows[sel]
            if isinstance(b, _ListBatch):
                keep_lists.append((bid, b, live[sel], r))
                continue
            arr_rows.append(b.rows[r])
            arr_idx.append(b.idx[r])
            arr_vals.append(b.vals[r])
        self._batches = []
        self._ptr_batch[:] = -1
        self._total_rows = 0
        if arr_rows:
            merged = TopKBatch(np.concatenate(arr_rows),
                               np.concatenate(arr_idx),
                               np.concatenate(arr_vals))
            self.absorb_batch(merged)
        for _, b, dense_ids, r in keep_lists:
            for d, row in zip(dense_ids.tolist(), r.tolist()):
                self.set_row(d, b.rows[row])

    # -- Mapping API (lazy, cold path) ----------------------------------

    def _live_dense(self) -> np.ndarray:
        n = min(len(self._ptr_batch), len(self._vocab))
        return np.nonzero(self._ptr_batch[:n] >= 0)[0]

    def __len__(self) -> int:
        with self._lock:
            return int(len(self._live_dense()))

    def __iter__(self) -> Iterator[int]:
        with self._lock:
            live = self._live_dense()
            if len(live) == 0:
                return iter(())
            return iter(self._vocab.to_external_batch(live).tolist())

    def __contains__(self, ext_item) -> bool:
        dense = self._vocab.to_dense(ext_item)
        with self._lock:
            return (dense is not None and dense < len(self._ptr_batch)
                    and self._ptr_batch[dense] >= 0)

    def __getitem__(self, ext_item) -> List[Tuple[int, float]]:
        dense = self._vocab.to_dense(ext_item)
        with self._lock:
            if (dense is None or dense >= len(self._ptr_batch)
                    or self._ptr_batch[dense] < 0):
                raise KeyError(ext_item)
            b = self._batches[self._ptr_batch[dense]]
            row = int(self._ptr_row[dense])
        return _materialize_row(b, row, self._vocab)

    def snapshot(self) -> ResultsSnapshot:
        """Consistent copy for lock-free reading (stdout emitters, the
        serving seed). Pointer arrays copy under the lock; batches are
        shared by reference (immutable once absorbed — see
        :class:`ResultsSnapshot`). O(vocab extent) memcpy, no row data
        copied."""
        with self._lock:
            return ResultsSnapshot(self._vocab, list(self._batches),
                                   self._ptr_batch.copy(),
                                   self._ptr_row.copy())

    # -- checkpoint helpers ---------------------------------------------

    def clear(self) -> None:
        with self._lock:
            self._batches = []
            self._ptr_batch[:] = -1
            self._total_rows = 0
