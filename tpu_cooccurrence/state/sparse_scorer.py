"""Device-resident sparse backend: HBM slab matrix + host-side index.

The TPU-first answer to the 1M-item regime (benchmark config 4), where a
dense item x item ``C`` is infeasible and the hybrid backend's
ship-rows-per-window design drowns in host<->device transfer: the
co-occurrence matrix *values* live permanently in device HBM and only the
window's aggregated deltas travel up / packed top-K results travel down.
Per window that is a few hundred KB instead of the hybrid's padded
[S, R] count rectangles — on a bandwidth/latency-bound link (the tunneled
single chip here; DCN-attached hosts in general) transfer volume is the
whole game.

Design (no reference analogue — the reference delegates all state to
Flink's heap, ``ItemRowRescorerTwoInputStreamOperator.java:33-37``):

* **Host keeps the index, device keeps the data.** The host maintains the
  sorted packed-key array of all matrix cells (:class:`SlabIndex`) plus,
  per cell, the *device slot* its count lives in. Every placement
  decision (slot assignment, row growth, compaction) is host-computed
  numpy; the device never needs data-dependent control flow — every
  kernel is a fixed-shape scatter/gather jit, exactly what XLA wants.
* **Per-row slab allocation.** Each item row owns a contiguous device
  region with power-of-two capacity. New cells append at ``start+len``;
  an outgrown row is relocated by an on-device gather/scatter (the move
  *instructions* — old start, new start, length — are the only upload).
  Freed regions are reclaimed by an infrequent whole-heap compaction.
* **Scoring reads HBM, not the wire.** Updated rows are scored in
  length-bucketed ``[S_pad, R]`` rectangles gathered *on device* from the
  slab (``cnt``/``dst`` arrays), with row sums resident too; only the
  packed ``[2, S, K]`` result is fetched, one window late (same
  result pipeline as the other device backends).

Per-cell device cost: 8 bytes (int32 count + int32 partner id) + amortized
slack from power-of-two row caps — ~16 GB HBM holds ~1e9 cells, far above
any stream the cuts (fMax/kMax, ``Configuration.java:151-152``) admit.

Tie-breaking among equal scores: ``lax.top_k`` keeps the lowest slot
index, i.e. the earliest-*inserted* cell of the row — which matches the
reference's heap behavior (it keeps the earlier entry) rather than the
dense backend's lowest-item-id rule. All cross-backend tests compare ids
only where score gaps exceed tolerance.

:class:`SlabIndex` is row-id-space agnostic so the multi-chip backend
(``parallel/sharded_sparse.py``) can keep one index per shard over
shard-local row ids and slots.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import tuning
from ..metrics import Counters, RESCORED_ITEMS, ROW_SUM_PROCESS_WINDOW
from ..observability import LEDGER, StageClock
from ..observability.registry import REGISTRY
from ..robustness import faults
from ..ops.aggregate import (AggregatedPairs, aggregate_window_coo,
                             distinct_sorted, merge_sorted_insert,
                             narrow_deltas_int32)
from ..ops.device_scorer import (DeferredResultsTable, pad_pow2, pad_pow4,
                                 split_upload_auto)
from ..ops.donation import donate_argnums
from ..ops.llr import llr_stable
from ..sampling.reservoir import PairDeltaBatch, _ragged_arange
from .results import TopKBatch

# Scatter index sentinel: >= any capacity, dropped by mode="drop".
_SENT = np.int32(2**31 - 1)


def _moves_body(cnt, dst, mv, L: int):
    """Relocate outgrown rows inside the slab (trace body).

    ``mv``: [3, Mv] int32 (old_start, new_start, len); padded rows carry
    len == 0. Reads and writes never overlap: new regions are freshly
    allocated past the heap end or in compacted space.
    """
    old_start, new_start, ln = mv[0], mv[1], mv[2]
    col = jnp.arange(L, dtype=jnp.int32)[None, :]
    valid = col < ln[:, None]
    src_idx = jnp.where(valid, old_start[:, None] + col, 0)
    out_idx = jnp.where(valid, new_start[:, None] + col, _SENT)
    cnt = cnt.at[out_idx.ravel()].set(cnt[src_idx].ravel(), mode="drop")
    dst = dst.at[out_idx.ravel()].set(dst[src_idx].ravel(), mode="drop")
    return cnt, dst


def _update_body(cnt, dst, row_sums, upd, bounds):
    """Apply one window's state changes (trace body).

    ``upd``: [2, N] int32 — three concatenated sections along axis 1
    (boundaries in ``bounds``; intra-section padding uses sentinel
    indices, dropped by the scatters):

      [0, b0)   new cells:   (slot, partner item id) — writes ``dst``,
                zeroes ``cnt`` (slots may hold stale bytes from a freed
                region)
      [b0, b1)  cell deltas: (slot, +/-count) — scatter-add into ``cnt``
      [b1, N)   row sums:    (item, +/-sum)   — scatter-add into
                ``row_sums``

    Section order matters: new-cell zeroing must precede the delta add.
    """
    cnt, dst = _apply_cells(cnt, dst, upd, bounds)
    pos = jnp.arange(upd.shape[1], dtype=jnp.int32)
    rs_idx = jnp.where(pos >= bounds[1], upd[0], _SENT)
    row_sums = row_sums.at[rs_idx].add(
        jnp.where(pos >= bounds[1], upd[1], 0), mode="drop")
    return cnt, dst, row_sums


_apply_update = functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1, 2))(
    _update_body)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1, 2))
def _apply_update_chunked(cnt, dst, row_sums, upd_parts, bounds):
    """_apply_update with the update buffer arriving as K separate
    transfers; the concatenate is device-side and fuses away."""
    return _update_body(cnt, dst, row_sums,
                        jnp.concatenate(upd_parts, axis=1), bounds)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1, 2), static_argnames=("L",))
def _apply_moves_update_chunked(cnt, dst, row_sums, mv, upd_parts, bounds,
                                L: int):
    cnt, dst = _moves_body(cnt, dst, mv, L)
    return _update_body(cnt, dst, row_sums,
                        jnp.concatenate(upd_parts, axis=1), bounds)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1, 2),
                   static_argnames=("n_pad",))
def _apply_update_packed(cnt, dst, row_sums, words_i, words_v, header, *,
                         n_pad: int):
    """_apply_update with the window buffer arriving in the compressed
    wire format (state/wire.py: per-section delta + zigzag + bit-pack);
    the decode prologue is gathers/shifts/cumsums feeding the SAME
    ``_update_body`` scatter unchanged."""
    from .wire import decode_update

    upd, bounds = decode_update(words_i, words_v, header, n_pad)
    return _update_body(cnt, dst, row_sums, upd, bounds)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1, 2),
                   static_argnames=("n_pad", "L"))
def _apply_moves_update_packed(cnt, dst, row_sums, mv, words_i, words_v,
                               header, *, n_pad: int, L: int):
    from .wire import decode_update

    cnt, dst = _moves_body(cnt, dst, mv, L)
    upd, bounds = decode_update(words_i, words_v, header, n_pad)
    return _update_body(cnt, dst, row_sums, upd, bounds)


@functools.partial(jax.jit, donate_argnums=donate_argnums(2, 3))
def _promote_cells(cnt, dst, cnt_w, dst_w, src_slots, dst_slots):
    """Move promoted rows' cells from the narrow slab into the wide
    int32 side-table (``src_slots`` padded with 0 — a safe gather —
    ``dst_slots`` padded with the sentinel, dropped). The cast widens,
    so it is exact for any narrow cell."""
    vals = cnt[src_slots].astype(jnp.int32)
    cnt_w = cnt_w.at[dst_slots].set(vals, mode="drop")
    dst_w = dst_w.at[dst_slots].set(dst[src_slots], mode="drop")
    return cnt_w, dst_w


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1, 2), static_argnames=("L",))
def _apply_moves_update(cnt, dst, row_sums, mv, upd, bounds, L: int):
    """Row relocations + the window update in ONE dispatch.

    Zipfian streams relocate rows nearly every window (hot rows keep
    outgrowing their pow-2 caps), so fusing the two kernels removes a
    per-window dispatch — on a high-latency tunnel each dispatch is wall
    time. Moves run first: the window's new-cell slots already assume the
    relocated layout.

    Trade-off, deliberate: the fused program is keyed by the cartesian
    (mv_pad, L, n_pad) where the split kernels were keyed by the two
    sums — more cold-start compiles, amortized by the coarse pow-4
    ladders and the on-disk XLA cache, in exchange for one fewer
    dispatch on nearly every window."""
    cnt, dst = _moves_body(cnt, dst, mv, L)
    return _update_body(cnt, dst, row_sums, upd, bounds)


def _apply_cells(cnt, dst, upd, bounds):
    """New-cell + delta sections of an update buffer (shared with the
    sharded backend, whose row sums update separately — replicated).

    The delta add narrows to the slab's cell dtype (a no-op for int32
    slabs): exact by the promotion invariant — a row still on a narrow
    slab has row sum < 2^(w-1), so every cell value and window delta it
    can see fits the dtype (state/wire.cell_promote_threshold).
    """
    idx, val = upd[0], upd[1]
    pos = jnp.arange(upd.shape[1], dtype=jnp.int32)
    is_new = pos < bounds[0]
    is_delta = (pos >= bounds[0]) & (pos < bounds[1])
    new_idx = jnp.where(is_new, idx, _SENT)
    dst = dst.at[new_idx].set(val, mode="drop")
    cnt = cnt.at[new_idx].set(0, mode="drop")
    d_idx = jnp.where(is_delta, idx, _SENT)
    cnt = cnt.at[d_idx].add(
        jnp.where(is_delta, val, 0).astype(cnt.dtype), mode="drop")
    return cnt, dst


def gather_rect(cnt, dst, row_sums, meta, R: int):
    """XLA rectangle gather shared by the XLA and Pallas scorers.

    Returns ``(k11i, valid, ds, rsj, rsi)``: counts [S, R] int32, the
    live-cell mask (zero cells — cancelled counts — are not scored),
    partner ids (0 where invalid), partner row sums f32 (0 where
    invalid), and the scored rows' own sums as an f32 column. One
    definition so the kernel's drop-in contract cannot drift from
    ``_score_rect``'s masking rules.
    """
    rowids, starts, lens = meta[0], meta[1], meta[2]
    col = jnp.arange(R, dtype=jnp.int32)[None, :]
    in_row = col < lens[:, None]
    idx = jnp.where(in_row, starts[:, None] + col, 0)
    k11i = jnp.where(in_row, cnt[idx], 0)
    valid = k11i != 0
    ds = jnp.where(valid, dst[idx], 0)
    rsj = jnp.where(valid, row_sums[ds], 0).astype(jnp.float32)
    rsi = row_sums[rowids].astype(jnp.float32)[:, None]
    return k11i, valid, ds, rsj, rsi


def _score_rect(cnt, dst, row_sums, meta, observed, top_k: int, R: int):
    """LLR + top-K over one length bucket of updated rows (trace body).

    ``meta``: [3, S_pad] int32 (row id, slab start, row len); padded rows
    carry len == 0 and score all -inf. ``meta[0]`` row ids index
    ``row_sums`` (global id space); starts index the local slab.
    """
    k11i, valid, ds, rsj, rsi = gather_rect(cnt, dst, row_sums, meta, R)
    k11 = k11i.astype(jnp.float32)
    k12 = rsi - k11
    k21 = rsj - k11
    k22 = observed + k11 - k12 - k21
    scores = llr_stable(k11, k12, k21, k22)
    scores = jnp.where(valid, scores, -jnp.inf)
    vals, kidx = jax.lax.top_k(scores, top_k)
    ids = jnp.take_along_axis(ds, kidx, axis=1)
    return jnp.stack([vals, jax.lax.bitcast_convert_type(ids, jnp.float32)])


_score_slab = functools.partial(jax.jit, static_argnames=("top_k", "R"))(
    _score_rect)


@functools.partial(jax.jit, static_argnames=("top_k", "R", "interpret"))
def _score_slab_pallas(cnt, dst, row_sums, meta, observed, *,
                       top_k: int, R: int, interpret: bool = False):
    """Jitted fused-kernel counterpart of :data:`_score_slab` (pipelined,
    non-deferred path): same packed [2, S, K] return."""
    from ..ops.pallas_score import pallas_score_rect

    return pallas_score_rect(cnt, dst, row_sums, meta, observed,
                             top_k=top_k, R=R, interpret=interpret)


def _rect_into_table(tbl, cnt, dst, row_sums, meta, observed,
                     top_k: int, R: int, pallas: bool = False,
                     interpret: bool = False):
    """Score one rectangle and scatter it into the results table (trace
    body shared by the per-bucket and fused-window dispatch forms).
    ``pallas`` routes the rectangle through the fused LLR+top-K kernel
    (``ops/pallas_score.pallas_score_rect``, same packed wire format);
    the scatter is identical either way."""
    if pallas:
        from ..ops.pallas_score import pallas_score_rect

        packed = pallas_score_rect(cnt, dst, row_sums, meta, observed,
                                   top_k=top_k, R=R, interpret=interpret)
    else:
        packed = _score_rect(cnt, dst, row_sums, meta, observed, top_k, R)
    rowids = jnp.where(meta[2] > 0, meta[0], _SENT)
    return tbl.at[:, rowids].set(packed, mode="drop")


@functools.partial(jax.jit, donate_argnums=donate_argnums(0),
                   static_argnames=("top_k", "R", "pallas", "interpret"))
def _score_into_table(tbl, cnt, dst, row_sums, meta, observed, *,
                      top_k: int, R: int, pallas: bool = False,
                      interpret: bool = False):
    """Score one length bucket and scatter the packed result straight into
    the device-resident latest-results table (``[2, items_cap, K]``) —
    nothing returns to the host. The deferred-results mode's whole point:
    on a high-latency link the per-window result downlink (tens of MB on
    large windows) disappears; the host fetches the table once at flush.
    """
    return _rect_into_table(tbl, cnt, dst, row_sums, meta, observed,
                            top_k, R, pallas, interpret)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0),
                   static_argnames=("top_k", "plan", "interpret"))
def _score_window_into_table(tbl, cnt, dst, row_sums, meta_all, observed, *,
                             top_k: int, plan, interpret: bool = False):
    """ALL of a window's scoring in one dispatch (fixed-shape mode).

    ``plan``: static tuple of ``(R, S, offset, pallas)`` rectangles;
    ``meta_all`` is their [3, sum(S)] concatenation (one upload). Fixed
    shapes make the rectangle sizes pure functions of R, and the caller
    dispatches a monotone high-water set of buckets (empty ones as
    all-padding), so the plan only ever GROWS — at most one program per
    bucket the stream ever occupied (measured: 3 over both benchmark
    streams), and the per-window dispatch count drops from
    one-per-bucket to one. ``pallas`` per rectangle: wide buckets can
    ride the fused kernel while narrow ones stay XLA, inside the same
    dispatch."""
    for R, S, off, use_pl in plan:
        meta = jax.lax.slice(meta_all, (0, off), (3, off + S))
        tbl = _rect_into_table(tbl, cnt, dst, row_sums, meta, observed,
                               top_k, R, use_pl, interpret)
    return tbl


def _fused_sparse_body(cnt, dst, row_sums, tbl, reg_start, reg_len, upd,
                       bounds, reg_upd, rows_all, observed, top_k: int,
                       plan, interpret: bool):
    """ONE-dispatch fused sparse window (trace body shared by the packed
    and raw wire forms).

    Stages, in order, all inside one program:

      1. ``_update_body``   — the window's new-cell / delta / row-sum
                              scatter (Insum-style indirect addressing
                              into slab cells; pad lanes carry the
                              sentinel no-op scatter, exactly like the
                              chained upload).
      2. registry sync      — ``reg_upd`` ([3, Rp]: row, start, len;
                              sentinel-padded) scatters the host
                              registry's dirty rows into the
                              device-resident (start, len) mirror, so
                              stage 3 resolves rows to slab rectangles
                              without a per-window meta upload.
      3. bucketed rescore   — for each static ``plan`` rectangle, the
                              touched rows' (start, len) are GATHERED
                              from the device mirror (the on-device
                              registry probe) and the SHARED score body
                              (``_score_rect`` / ``pallas_score_rect``)
                              scatters packed top-K into the results
                              table. Pad slots carry ``_SENT`` row ids:
                              their gathers clamp harmlessly and their
                              scatter drops, mirroring the chained
                              path's len==0 padding.

    Sharing ``_update_body`` and ``_rect_into_table`` with the chained
    dispatches is the bit-parity argument: the fused window cannot
    drift numerically because there is no second implementation.
    """
    cnt, dst, row_sums = _update_body(cnt, dst, row_sums, upd, bounds)
    reg_start = reg_start.at[reg_upd[0]].set(reg_upd[1], mode="drop")
    reg_len = reg_len.at[reg_upd[0]].set(reg_upd[2], mode="drop")
    for R, S, off, use_pl in plan:
        rowids = jax.lax.slice(rows_all, (off,), (off + S,))
        meta = jnp.stack([rowids, reg_start[rowids], reg_len[rowids]])
        tbl = _rect_into_table(tbl, cnt, dst, row_sums, meta, observed,
                               top_k, R, use_pl, interpret)
    return cnt, dst, row_sums, tbl, reg_start, reg_len


@functools.partial(jax.jit,
                   donate_argnums=donate_argnums(0, 1, 2, 3, 4, 5),
                   static_argnames=("n_pad", "top_k", "plan", "interpret"))
def _fused_sparse_window_packed(cnt, dst, row_sums, tbl, reg_start, reg_len,
                                words_i, words_v, header, reg_upd, rows_all,
                                observed, *, n_pad: int, top_k: int, plan,
                                interpret: bool = False):
    """Packed-wire form: the PR-7 bit-packed uplink is decoded by the
    ``decode_update`` prologue (gathers/shifts/uint32-wraparound cumsums)
    INSIDE the fused program, feeding the same scatter — wire compression
    and fusion compose instead of excluding each other."""
    from .wire import decode_update

    upd, bounds = decode_update(words_i, words_v, header, n_pad)
    return _fused_sparse_body(cnt, dst, row_sums, tbl, reg_start, reg_len,
                              upd, bounds, reg_upd, rows_all, observed,
                              top_k, plan, interpret)


@functools.partial(jax.jit,
                   donate_argnums=donate_argnums(0, 1, 2, 3, 4, 5),
                   static_argnames=("top_k", "plan", "interpret"))
def _fused_sparse_window_raw(cnt, dst, row_sums, tbl, reg_start, reg_len,
                             upd, bounds, reg_upd, rows_all, observed, *,
                             top_k: int, plan, interpret: bool = False):
    """Raw-wire form (``--wire-format raw``): the update buffer ships
    uncompressed, the rest of the program is identical."""
    return _fused_sparse_body(cnt, dst, row_sums, tbl, reg_start, reg_len,
                              upd, bounds, reg_upd, rows_all, observed,
                              top_k, plan, interpret)


@functools.partial(jax.jit, static_argnames=("n",))
def _grow(arr, n: int):
    # No donation: the output is a different buffer size, so XLA could
    # never reuse the input allocation anyway.
    return jnp.zeros((n,), arr.dtype).at[: arr.shape[0]].set(arr)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1), static_argnames=("cap",))
def _compact_gather(cnt, dst, gmap, cap: int):
    """Rebuild the slab through a host-supplied gather map (compaction)."""
    return (jnp.zeros((cap,), cnt.dtype).at[: gmap.shape[0]].set(cnt[gmap]),
            jnp.zeros((cap,), dst.dtype).at[: gmap.shape[0]].set(dst[gmap]))


class SlabCapacityError(ValueError):
    """Slab/registry capacity crossed the int32 slot space (2^31 cells).

    A permanent configuration error (the cell-addressing wire format is
    int32 by design): the CLI maps it to the supervisor's EX_CONFIG so a
    restart loop is never spent on a stream that cannot fit. Raised by
    the growth paths instead of silently wrapping through
    ``.astype(np.int32)`` as the pre-guard code did.
    """


def _pow2ceil(x: np.ndarray, minimum: int) -> np.ndarray:
    v = np.maximum(x, minimum).astype(np.int64)
    out = 1 << np.ceil(np.log2(v)).astype(np.int64)
    if int(out.max(initial=0)) >= 2**31:
        raise SlabCapacityError(
            f"capacity growth to {int(out.max())} cells crosses the int32 "
            f"slot space (2^31); the sparse backend's cell addressing is "
            f"int32 — shard the stream (--num-shards) instead")
    return out.astype(np.int32)


def _pad_words(words: np.ndarray) -> np.ndarray:
    """Pad an encoded word stream to a pow2 transfer bucket with at
    least one trailing guard word (the jit decode gathers word+1)."""
    out = np.zeros(pad_pow2(len(words) + 1, minimum=256), dtype=np.uint32)
    out[: len(words)] = words
    return out


def resolve_fixed_shapes(fixed_shapes, defer_results: bool) -> bool:
    """Resolve a fixed-shape request (None = env TPU_COOC_FIXED_SCORE or
    auto) and enforce the defer-only contract — shared by the
    single-device and sharded sparse scorers."""
    if fixed_shapes is None:
        env = tuning.env_read("TPU_COOC_FIXED_SCORE", "auto")
        env = env.strip().lower()
        if env in ("1", "on", "true", "yes"):
            fixed_shapes = True
        elif env in ("0", "off", "false", "no"):
            fixed_shapes = False
        elif env in ("auto", ""):
            # Fixed rectangles only make sense when results stay on
            # device: the pipelined path fetches each packed block, and
            # a full [2, s_block, K] fetch per bucket would ship
            # megabytes of padding over the very link this mode exists
            # to spare.
            fixed_shapes = (jax.default_backend() == "tpu"
                            and defer_results)
        else:
            raise ValueError(
                f"TPU_COOC_FIXED_SCORE must be 0/1/auto, got {env!r}")
    if fixed_shapes and not defer_results:
        # An explicit request that cannot take effect must not be
        # silently downgraded — a fixed-vs-variable A/B would then
        # compare two identical variable runs.
        raise ValueError(
            "fixed-shape scoring needs deferred results (it is "
            "incompatible with --emit-updates: the per-window result "
            "fetch would ship the padded rectangles)")
    return bool(fixed_shapes)


def fixed_block(R: int, budget: int, row_cap: int) -> int:
    """Fixed-mode rectangle rows for bucket width ``R``: budget-bounded,
    upload-capped, and >= the top_k-compatible minimum."""
    return max(min(budget // R, row_cap), 16)


def ladder_bits(ladder: int) -> int:
    """Validate a score-bucket ladder base (power of two >= 2) and return
    its log2. The single owner of the ladder contract — scorers validate
    through this at construction, and :func:`bucket_r` / :func:`score_buckets` share it so bucket rounding and rectangle widths
    cannot drift apart."""
    k = ladder.bit_length() - 1
    if k < 1 or ladder != (1 << k):
        raise ValueError(
            f"score ladder must be a power of two >= 2, got {ladder} "
            f"(TPU_COOC_SCORE_LADDER)")
    return k


def bucket_r(b: int, min_r: int, ladder: int) -> int:
    """Rectangle width of bucket ``b``: ``min_r * ladder^b``."""
    return min_r << (ladder_bits(ladder) * b)


def score_buckets(lens: np.ndarray, min_r: int, ladder: int = 4):
    """Length buckets: bucket b scores rows at ``R = bucket_r(b)`` (the
    smallest b with R >= len). Returns (bucket-per-row, order sorted
    by bucket). Integer math, exact at powers:
    ``shift = ceil(len / 2^floor(log2 min_r)) - 1``;
    ``b = ceil(log2(shift+1) / k)`` for ``ladder = 2^k`` via frexp's
    exponent (``frexp(s)[1] = floor(log2 s) + 1``, ``frexp(0) = 0``).

    The ladder trades padded device compute for dispatch count: pow-4
    (default) pads rows <=4x and yields ~5-6 dispatches per window on a
    Zipfian length mix; pow-16 pads <=16x (device-only work) but about
    halves the dispatches — the better point when every dispatch pays a
    high-latency link round trip (tunneled chips, remote coordinators).
    """
    k = ladder_bits(ladder)
    shift = (np.maximum(lens, 1) - 1) >> (min_r.bit_length() - 1)
    bucket = (np.frexp(shift.astype(np.float64))[1] + k - 1) // k
    return bucket, np.argsort(bucket, kind="stable")


# -- row registries -----------------------------------------------------
#
# The per-row slab placement record (start, len, cap). Two storage
# strategies behind one batch API:
#
#   dense   — the original three int32 arrays over the whole row space
#             (12 B per *possible* row, O(1) everything).
#   bitmap  — SMASH-style: a one-bit-per-row occupancy bitmap plus a
#             per-64-bit-word rank directory (exclusive popcount prefix
#             sums — the hierarchical index), with (start, len, cap)
#             packed densely over *occupied* rows in row-id order.
#             Membership and field gathers are O(1) per row (word rank +
#             in-word popcount); host RSS is 2 bits per possible row +
#             12 B per occupied row — at 1M possible rows with a sparse
#             vocabulary this is an order of magnitude under dense
#             (pinned by tests/test_slab_registry.py).
#
# Default: bitmap (env TPU_COOC_ROW_INDEX=dense opts out for A/B).


if hasattr(np, "bitwise_count"):  # numpy >= 2.0
    def _popcount(words: np.ndarray) -> np.ndarray:
        return np.bitwise_count(words)
else:  # portable fallback: byte-table popcount over the uint8 view
    _POP8 = np.asarray([bin(i).count("1") for i in range(256)],
                       dtype=np.uint8)

    def _popcount(words: np.ndarray) -> np.ndarray:
        return _POP8[words.view(np.uint8).reshape(-1, 8)].sum(
            axis=1).astype(np.uint64)


class _RegistryDirtyLog:
    """Dirty-row tracking shared by both registry layouts.

    The fused sparse window keeps a DEVICE-resident mirror of the
    (start, len) columns (``SparseDeviceScorer`` reg views) so the
    scoring half of the one-dispatch program can resolve rows to slab
    rectangles without a per-window meta upload. The mirror syncs by
    delta: every host-side registry mutation logs its rows here, and
    the next fused dispatch uplinks exactly those rows' (start, len).
    Off (``None``) unless the fused path enables it — the steady-state
    chained path pays nothing.
    """

    #: Logged-entry bound: past this the log collapses to the all-dirty
    #: flag (next fused window does one full occupied-rows resync).
    #: Bounds memory when the fused path is enabled but windows route
    #: chained indefinitely (e.g. every touched row went wide) — the
    #: log would otherwise grow by one array per window forever.
    DIRTY_CAP = 1 << 20

    def __init__(self) -> None:
        self._dirty_log = None  # None = tracking off
        self._dirty_count = 0
        self._all_dirty = False

    def enable_dirty_log(self) -> None:
        if self._dirty_log is None:
            self._dirty_log = []

    def _mark_dirty(self, rows) -> None:
        if self._dirty_log is None or self._all_dirty or not len(rows):
            return
        self._dirty_log.append(np.asarray(rows, dtype=np.int64))
        self._dirty_count += len(rows)
        if self._dirty_count > self.DIRTY_CAP:
            self._mark_all_dirty()

    def _mark_all_dirty(self) -> None:
        if self._dirty_log is not None:
            self._all_dirty = True
            self._dirty_log.clear()
            self._dirty_count = 0

    def drain_dirty(self):
        """``(rows, all_dirty)`` accumulated since the last drain. With
        ``all_dirty`` the caller must resync every occupied row (the
        wholesale-rebuild paths — restore, reset — and a capped log)."""
        all_d = self._all_dirty
        if all_d or self._dirty_log is None or not self._dirty_log:
            rows = np.zeros(0, dtype=np.int64)
        elif len(self._dirty_log) == 1:
            rows = np.unique(self._dirty_log[0])
        else:
            rows = np.unique(np.concatenate(self._dirty_log))
        if self._dirty_log is not None:
            self._dirty_log.clear()
        self._dirty_count = 0
        self._all_dirty = False
        return rows, all_d


class DenseRowRegistry(_RegistryDirtyLog):
    """Original dense triple: three int32 arrays over the row space."""

    kind = "dense"

    def __init__(self, rows_capacity: int) -> None:
        super().__init__()
        cap = max(int(rows_capacity), 64)
        self.start = np.zeros(cap, dtype=np.int32)
        self.length = np.zeros(cap, dtype=np.int32)
        self.cap = np.zeros(cap, dtype=np.int32)

    @property
    def rows_cap(self) -> int:
        return len(self.start)

    @property
    def nbytes(self) -> int:
        return self.start.nbytes + self.length.nbytes + self.cap.nbytes

    def ensure(self, max_row: int) -> None:
        if max_row < self.rows_cap:
            return
        new_cap = int(_pow2ceil(np.asarray([max_row + 1]), 1024)[0])
        for name in ("start", "length", "cap"):
            old = getattr(self, name)
            grown = np.zeros(new_cap, dtype=old.dtype)
            grown[: len(old)] = old
            setattr(self, name, grown)

    def get(self, rows: np.ndarray):
        rows = np.asarray(rows, dtype=np.int64)
        if len(rows) and int(rows.max()) >= self.rows_cap:
            # Beyond-capacity rows read as absent (0, 0, 0).
            safe = np.minimum(rows, self.rows_cap - 1)
            in_r = rows < self.rows_cap
            return (np.where(in_r, self.start[safe], 0).astype(np.int32),
                    np.where(in_r, self.length[safe], 0).astype(np.int32),
                    np.where(in_r, self.cap[safe], 0).astype(np.int32))
        return self.start[rows], self.length[rows], self.cap[rows]

    def update(self, rows: np.ndarray, start=None, length=None,
               cap=None) -> None:
        rows = np.asarray(rows, dtype=np.int64)
        if len(rows):
            self.ensure(int(rows.max()))
        self._mark_dirty(rows)
        if start is not None:
            self.start[rows] = start
        if length is not None:
            self.length[rows] = length
        if cap is not None:
            self.cap[rows] = cap

    def clear(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows, dtype=np.int64)
        rows = rows[rows < self.rows_cap]
        self._mark_dirty(rows)
        self.start[rows] = 0
        self.length[rows] = 0
        self.cap[rows] = 0

    def occupied(self) -> np.ndarray:
        return np.flatnonzero(self.cap > 0).astype(np.int32)

    def reset(self) -> None:
        self._mark_all_dirty()
        self.start[:] = 0
        self.length[:] = 0
        self.cap[:] = 0


class BitmapRowRegistry(_RegistryDirtyLog):
    """Bitmap + rank directory + packed per-occupied-row fields.

    ``bits`` holds one occupancy bit per possible row; ``rank`` holds the
    exclusive popcount prefix sum per 64-bit word (the hierarchy level
    that makes rank O(1): packed position of row r =
    ``rank[r >> 6] + popcount(bits[r >> 6] below bit r)``). The packed
    field arrays stay in row-id order; batch inserts merge new rows per
    window (one ``np.insert`` pass, mirroring the sorted cell index's
    merge cadence). Rows are never removed — ``clear`` zeroes the fields
    (a freed row costs 12 packed bytes until a rebuild), matching the
    dense registry's observable behavior exactly.
    """

    kind = "bitmap"

    def __init__(self, rows_capacity: int) -> None:
        super().__init__()
        cap = max(int(rows_capacity), 64)
        cap = int(_pow2ceil(np.asarray([cap]), 64)[0])
        self.bits = np.zeros(cap // 64, dtype=np.uint64)
        self.rank = np.zeros(cap // 64, dtype=np.int64)
        self.start = np.zeros(0, dtype=np.int32)
        self.length = np.zeros(0, dtype=np.int32)
        self.cap = np.zeros(0, dtype=np.int32)

    @property
    def rows_cap(self) -> int:
        return len(self.bits) * 64

    @property
    def nbytes(self) -> int:
        return (self.bits.nbytes + self.rank.nbytes + self.start.nbytes
                + self.length.nbytes + self.cap.nbytes)

    def ensure(self, max_row: int) -> None:
        if max_row < self.rows_cap:
            return
        new_cap = int(_pow2ceil(np.asarray([max_row + 1]), 1024)[0])
        n_words = new_cap // 64
        grown = np.zeros(n_words, dtype=np.uint64)
        grown[: len(self.bits)] = self.bits
        self.bits = grown
        self.rank = np.zeros(n_words, dtype=np.int64)
        self._rebuild_rank()  # appended words inherit the running rank

    def _rebuild_rank(self) -> None:
        pc = _popcount(self.bits).astype(np.int64)
        np.cumsum(pc[:-1], out=self.rank[1:])
        self.rank[0] = 0

    def _pos(self, rows: np.ndarray):
        """(packed position, occupied) per row — O(1) membership.
        Beyond-capacity rows report unoccupied."""
        in_r = rows < self.rows_cap
        w = np.minimum(rows >> 6, len(self.bits) - 1)
        b = (rows & 63).astype(np.uint64)
        wbits = self.bits[w]
        occ = ((wbits >> b) & np.uint64(1)).astype(bool) & in_r
        below = wbits & ((np.uint64(1) << b) - np.uint64(1))
        return self.rank[w] + _popcount(below).astype(np.int64), occ

    def get(self, rows: np.ndarray):
        rows = np.asarray(rows, dtype=np.int64)
        pos, occ = self._pos(rows)
        s = np.zeros(len(rows), dtype=np.int32)
        ln = np.zeros(len(rows), dtype=np.int32)
        c = np.zeros(len(rows), dtype=np.int32)
        p = pos[occ]
        s[occ] = self.start[p]
        ln[occ] = self.length[p]
        c[occ] = self.cap[p]
        return s, ln, c

    def update(self, rows: np.ndarray, start=None, length=None,
               cap=None) -> None:
        """Batch insert-or-update. ``rows`` must be unique and sorted
        ascending (every caller passes ``np.unique`` output) so the
        packed arrays keep their row-id order through one insert pass."""
        rows = np.asarray(rows, dtype=np.int64)
        if not len(rows):
            return
        self.ensure(int(rows.max()))
        self._mark_dirty(rows)
        pos, occ = self._pos(rows)
        new = rows[~occ]
        if len(new):
            ins = pos[~occ]  # positions in the PRE-insert packed arrays
            self.start = np.insert(self.start, ins, 0)
            self.length = np.insert(self.length, ins, 0)
            self.cap = np.insert(self.cap, ins, 0)
            np.bitwise_or.at(self.bits, new >> 6,
                             np.uint64(1) << (new & 63).astype(np.uint64))
            self._rebuild_rank()
            pos, _occ = self._pos(rows)
        if start is not None:
            self.start[pos] = start
        if length is not None:
            self.length[pos] = length
        if cap is not None:
            self.cap[pos] = cap

    def clear(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows, dtype=np.int64)
        self._mark_dirty(rows)
        pos, occ = self._pos(rows)
        p = pos[occ]
        self.start[p] = 0
        self.length[p] = 0
        self.cap[p] = 0

    def occupied(self) -> np.ndarray:
        ids = np.flatnonzero(np.unpackbits(
            self.bits.view(np.uint8), bitorder="little"))
        return ids[self.cap > 0].astype(np.int32)

    def reset(self) -> None:
        self._mark_all_dirty()
        self.bits[:] = 0
        self.rank[:] = 0
        self.start = np.zeros(0, dtype=np.int32)
        self.length = np.zeros(0, dtype=np.int32)
        self.cap = np.zeros(0, dtype=np.int32)


def make_row_registry(rows_capacity: int, kind: Optional[str] = None):
    """Row-registry factory: ``kind`` or env ``TPU_COOC_ROW_INDEX``
    (default bitmap — the compressed index is the production layout;
    dense remains for A/B and as the reference implementation)."""
    if kind is None:
        kind = tuning.env_read("TPU_COOC_ROW_INDEX", "bitmap").strip().lower()
    if kind == "dense":
        return DenseRowRegistry(rows_capacity)
    if kind == "bitmap":
        return BitmapRowRegistry(rows_capacity)
    raise ValueError(
        f"TPU_COOC_ROW_INDEX must be bitmap or dense, got {kind!r}")


class _RowField:
    """Read-only vectorized view of one registry column — compatibility
    shim for callers that indexed the old dense arrays directly
    (``index.row_start[rows]``). Scalar in, scalar out."""

    def __init__(self, reg, field: int) -> None:
        self._reg = reg
        self._field = field

    def __getitem__(self, rows):
        scalar = np.isscalar(rows) or getattr(rows, "ndim", 1) == 0
        out = self._reg.get(np.atleast_1d(np.asarray(rows)))[self._field]
        return out[0] if scalar else out

    def __len__(self) -> int:
        return self._reg.rows_cap


@dataclasses.dataclass
class AllocPlan:
    """Device-facing output of one window's :meth:`SlabIndex.apply`."""

    mv: Optional[np.ndarray]      # [3, Mv_pad] int32 move instructions
    mv_len: int                   # static rectangle width for the move kernel
    slots: np.ndarray             # slab slot per window cell (d_key order)
    new_sel: np.ndarray           # bool per window cell: newly inserted

    @property
    def n_new(self) -> int:
        return int(self.new_sel.sum())


class SlabIndex:
    """Sorted-key cell index + per-row slab registry + allocator.

    Row-id-space agnostic: callers pack keys as ``row << 32 | dst`` in
    whatever row space they shard by (global for the single-device
    backend, shard-local for the sharded one). Slots are offsets into the
    caller's slab arrays; the index never touches a device.

    Invariant the allocator and compactor rely on: a row's live slots are
    always exactly ``[start, start + len)`` (appends are contiguous and
    cells are never removed), so within-row slot offsets are dense.

    Per-row placement lives in a pluggable row registry (default: the
    SMASH-style bitmap + rank index, ``BitmapRowRegistry``); the old
    dense-array access pattern stays available through the read-only
    ``row_start`` / ``row_len`` / ``row_cap`` views.
    """

    def __init__(self, rows_capacity: int = 1 << 10,
                 row_index: Optional[str] = None) -> None:
        self.g_key = np.zeros(0, dtype=np.int64)
        self.g_slot = np.zeros(0, dtype=np.int32)
        self.rows = make_row_registry(rows_capacity, row_index)
        self.heap_end = 0
        self.garbage = 0  # cells in freed (moved-out) regions
        self.compactions = 0

    def __len__(self) -> int:
        return len(self.g_key)

    @property
    def rows_cap(self) -> int:
        return self.rows.rows_cap

    @property
    def row_start(self) -> _RowField:
        return _RowField(self.rows, 0)

    @property
    def row_len(self) -> _RowField:
        return _RowField(self.rows, 1)

    @property
    def row_cap(self) -> _RowField:
        return _RowField(self.rows, 2)

    @property
    def nbytes(self) -> int:
        """Host RSS of the index structures (registry + cell index) —
        the ``cooc_host_index_rss_bytes`` gauge and the bench's
        ``host_index_rss_bytes`` field read this."""
        return self.rows.nbytes + self.g_key.nbytes + self.g_slot.nbytes

    def ensure_rows(self, max_row: int) -> None:
        self.rows.ensure(max_row)

    def apply(self, d_key: np.ndarray) -> AllocPlan:
        """Classify one window's (sorted unique) cell keys against the
        index, allocate slots for the new ones (recording relocations of
        outgrown rows), and insert them. Returns the device-facing plan;
        the caller dispatches moves BEFORE any cell writes and must size
        its slab to ``heap_end`` beforehand."""
        pos = np.searchsorted(self.g_key, d_key)
        if len(self.g_key):
            safe = np.minimum(pos, len(self.g_key) - 1)
            exists = self.g_key[safe] == d_key
        else:
            exists = np.zeros(len(d_key), dtype=bool)
        new_key = d_key[~exists]
        mv = None
        mv_len = 0
        new_slots = np.zeros(0, dtype=np.int32)
        if len(new_key):
            mv, mv_len, new_slots = self._allocate(new_key)
        slots = np.empty(len(d_key), dtype=np.int32)
        slots[exists] = self.g_slot[pos[exists]]
        if len(new_key):
            slots[~exists] = new_slots
            self.g_key, self.g_slot = merge_sorted_insert(
                self.g_key, self.g_slot, pos[~exists], new_key, new_slots)
        return AllocPlan(mv, mv_len, slots, ~exists)

    def _shift_moved(self, rows: np.ndarray, old_starts: np.ndarray,
                     lens: np.ndarray, new_starts: np.ndarray,
                     disjoint: bool = False) -> None:
        """Re-point the index at relocated rows' new slots (their g_key
        segment is contiguous in the sorted layout).

        ``disjoint``: every new region lies beyond the old heap end
        (the _allocate growth case, never compaction's overlapping
        re-lay) — a hint subclasses use to pick an in-place fast path;
        this sorted implementation edits only g_slot values and needs
        no distinction."""
        seg_lo = np.searchsorted(self.g_key, rows.astype(np.int64) << 32)
        idx = np.repeat(seg_lo, lens) + _ragged_arange(lens)
        self.g_slot[idx] += np.repeat(new_starts - old_starts, lens)

    def keys_and_slots(self):
        """(sorted packed cell keys, matching slots) — the checkpoint
        view. The sorted index holds exactly this already."""
        return self.g_key, self.g_slot

    def _allocate(self, new_key: np.ndarray):
        n_src = (new_key >> 32).astype(np.int64)
        rows_new, first_idx, counts = np.unique(
            n_src, return_index=True, return_counts=True)
        rows_new32 = rows_new.astype(np.int32)
        self.ensure_rows(int(rows_new32.max()))
        r_start, r_len, r_cap = self.rows.get(rows_new)
        need = r_len + counts.astype(np.int32)
        grow_mask = need > r_cap
        mv = None
        mv_len = 0
        if grow_mask.any():
            grow_rows = rows_new32[grow_mask]
            new_caps = _pow2ceil(need[grow_mask], minimum=4)
            new_end = self.heap_end + int(new_caps.astype(np.int64).sum())
            if new_end >= 2**31:
                raise SlabCapacityError(
                    f"slab heap growth to {new_end} cells crosses the "
                    f"int32 slot space (2^31); shard the stream "
                    f"(--num-shards) instead")
            offs = (self.heap_end
                    + np.concatenate([[0], np.cumsum(new_caps)[:-1]])
                    ).astype(np.int32)
            self.heap_end = new_end
            old_start = r_start[grow_mask].copy()
            old_len = r_len[grow_mask].copy()
            self.garbage += int(r_cap[grow_mask].sum())
            moved = old_len > 0
            if moved.any():
                # Growth offsets start at the old heap_end: disjoint.
                self._shift_moved(grow_rows[moved], old_start[moved],
                                  old_len[moved], offs[moved],
                                  disjoint=True)
                mv_count = int(moved.sum())
                mv_len = int(pad_pow4(int(old_len[moved].max()), minimum=8))
                mv_pad = pad_pow4(mv_count, minimum=8)
                mv = np.zeros((3, mv_pad), dtype=np.int32)
                mv[0, :mv_count] = old_start[moved]
                mv[1, :mv_count] = offs[moved]
                mv[2, :mv_count] = old_len[moved]
            self.rows.update(grow_rows, start=offs, cap=new_caps)
        # Append slots: start + len + within-row rank (new_key is sorted,
        # so same-row entries are contiguous and rank is positional).
        rank = (np.arange(len(new_key))
                - np.repeat(first_idx, counts)).astype(np.int32)
        k_start, k_len, _ = self.rows.get(n_src)
        new_slots = (k_start + k_len + rank).astype(np.int32)
        self.rows.update(rows_new32, length=need)
        return mv, mv_len, new_slots

    def needs_compaction(self, min_heap: int) -> bool:
        # Threshold at 1/3: pure cap-doubling alone converges to garbage
        # just UNDER half the heap (sum of freed caps 4+8+..+C/2 = C-4 per
        # row vs live cap C), so a 1/2 threshold would never fire.
        return self.garbage * 3 > self.heap_end and self.heap_end > min_heap

    def _adopt_alloc(self, rows: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """Allocate fresh contiguous regions for currently-absent ``rows``
        (sorted unique) and register them; returns the cell slots in the
        caller's per-row cell order. Shared by both index layouts'
        :meth:`adopt_rows`."""
        rows = np.asarray(rows, dtype=np.int64)
        lens32 = np.asarray(lens, dtype=np.int32)
        self.ensure_rows(int(rows.max()))
        caps = _pow2ceil(lens32, minimum=4)
        new_end = self.heap_end + int(caps.astype(np.int64).sum())
        if new_end >= 2**31:
            raise SlabCapacityError(
                f"slab heap growth to {new_end} cells crosses the int32 "
                f"slot space (2^31); shard the stream (--num-shards) "
                f"instead")
        starts = (self.heap_end
                  + np.concatenate([[0], np.cumsum(caps)[:-1]])
                  ).astype(np.int32)
        self.heap_end = new_end
        self.rows.update(rows, start=starts, length=lens32, cap=caps)
        return (np.repeat(starts, lens32)
                + _ragged_arange(lens32)).astype(np.int32)

    def adopt_rows(self, rows: np.ndarray, keys: np.ndarray,
                   lens: np.ndarray) -> np.ndarray:
        """Re-insert absent rows' cells with their given per-row order
        PRESERVED (``keys`` concatenated per row in within-row slab
        order, ``lens`` per row). The tiered store's promotion path: the
        re-promoted row must reproduce its pre-spill slab layout because
        top-K tie-breaking among equal scores is slot-ordered — a
        key-ordered re-insert (what :meth:`apply` would do) could flip
        ties against the spill-off run. Returns the slots, keys-aligned
        — valid until the next :meth:`apply` (which may relocate an
        adopted row that outgrows its capacity; re-resolve through
        :meth:`lookup` afterwards).
        """
        slots = self._adopt_alloc(rows, lens)
        if len(keys):
            order = np.argsort(keys, kind="stable")
            sk = keys[order]
            ss = slots[order]
            pos = np.searchsorted(self.g_key, sk)
            self.g_key, self.g_slot = merge_sorted_insert(
                self.g_key, self.g_slot, pos, sk, ss)
        return slots

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Current slots of keys KNOWN to be present. The promotion
        path resolves its cells' slots through this AFTER the window's
        :meth:`apply` — apply may have relocated an adopted row (a new
        cell outgrowing the fresh capacity), and a slot captured at
        adopt time would then point into the freed region."""
        pos = np.searchsorted(self.g_key, keys)
        if len(keys):
            safe = np.minimum(pos, max(len(self.g_key) - 1, 0))
            if (len(self.g_key) == 0 or (pos >= len(self.g_key)).any()
                    or not np.array_equal(self.g_key[safe], keys)):
                raise KeyError("lookup of absent cell keys — promotion "
                               "contract violated")
        return self.g_slot[pos].astype(np.int32)

    def row_cells(self, rows: np.ndarray):
        """Live cells of ``rows`` as ``(keys, slots)``, rows concatenated
        in order (keys sorted within each row — the sorted layout's
        per-row segments are key-ordered). The promotion path reads a
        row's cells through this before handing them to the wide index."""
        lo = np.searchsorted(self.g_key, rows.astype(np.int64) << 32)
        _s, lens, _c = self.rows.get(rows)
        idx = np.repeat(lo, lens) + _ragged_arange(lens)
        return self.g_key[idx], self.g_slot[idx]

    def free_rows(self, rows: np.ndarray) -> None:
        """Drop rows and their cells from the index (cell-dtype promotion
        moved them to the wide side-table): the slab region becomes
        garbage for the next compaction and the keys are really deleted,
        so a freed key can re-insert later as a fresh cell (the
        compaction-reinsertion edge case, tests/test_slab_registry.py).
        Promotions are rare (Zipf head only); the O(total) segment
        delete is off the steady-state path."""
        _s, lens, cap = self.rows.get(rows)
        self.garbage += int(cap.sum())
        lo = np.searchsorted(self.g_key, rows.astype(np.int64) << 32)
        idx = np.repeat(lo, lens) + _ragged_arange(lens)
        self.g_key = np.delete(self.g_key, idx)
        self.g_slot = np.delete(self.g_slot, idx)
        self.rows.clear(rows)

    def compact(self) -> np.ndarray:
        """Defragment: re-lay rows contiguously (row-id order). Returns
        the slot-space gather map (new slab = old slab[gmap]); updates the
        index in place. The caller runs the device gather."""
        alloc = self.rows.occupied()
        old_starts, lens, _caps = self.rows.get(alloc)
        new_caps = _pow2ceil(lens, minimum=4)
        new_starts = np.concatenate(
            [[0], np.cumsum(new_caps)[:-1]]).astype(np.int32)
        new_end = int(new_caps.sum())
        within = _ragged_arange(lens).astype(np.int32)
        # Gather map in slot order; slots of a row are exactly
        # [start, start+len), so the map is dense per row.
        gmap = np.zeros(max(new_end, 1), dtype=np.int32)
        gmap[np.repeat(new_starts, lens) + within] = (
            np.repeat(old_starts, lens) + within)
        # Re-point the index at the compacted layout (the hook reads all
        # old positions before writing, so overlapping old/new regions of
        # different rows are safe).
        self._shift_moved(alloc, old_starts, lens, new_starts)
        self.rows.update(alloc, start=new_starts, cap=new_caps)
        self.heap_end = new_end
        self.garbage = 0
        self.compactions += 1
        return gmap

    def rebuild_from_keys(self, keys: np.ndarray) -> np.ndarray:
        """Reset to a fresh contiguous layout for ``keys`` (sorted packed
        cell keys, e.g. from a checkpoint). Returns the slot per key."""
        rows_all = (keys >> 32).astype(np.int64)
        self.rows.reset()
        if len(keys) == 0:
            self.g_key = keys.copy()
            self.g_slot = np.zeros(0, dtype=np.int32)
            self.heap_end = 0
            self.garbage = 0
            return self.g_slot
        self.ensure_rows(int(rows_all.max()))
        rows_u, counts = np.unique(rows_all, return_counts=True)
        rows_u32 = rows_u.astype(np.int32)
        caps = _pow2ceil(counts.astype(np.int32), minimum=4)
        starts = np.concatenate([[0], np.cumsum(caps)[:-1]]).astype(np.int32)
        self.rows.update(rows_u32, start=starts,
                         length=counts.astype(np.int32), cap=caps)
        self.heap_end = int(caps.sum())
        self.garbage = 0
        self.g_key = keys.copy()
        self.g_slot = (np.repeat(starts, counts)
                       + _ragged_arange(counts)).astype(np.int32)
        return self.g_slot



class HashSlabIndex(SlabIndex):
    """Native hash-table cell index: O(window cells) per window.

    The sorted base index pays an O(total cells) merge every window —
    measured at 90 s of a 463 s full-ML-25M CPU run once the matrix held
    14M cells. This variant keys cells in a C++ open-addressing table
    (``native/slab_hash.cpp``) plus a slot -> key reverse array (needed to
    re-point moved rows, which the sorted layout found by segment); the
    sorted view the checkpoints want is built on demand. Same public
    interface and allocator as the base class; use
    :func:`make_slab_index` to pick the best available implementation.
    """

    GROW_NUM, GROW_DEN = 3, 2  # grow when 3*n > 2*cap (load ~0.67)

    def __init__(self, rows_capacity: int = 1 << 10,
                 table_capacity: int = 1 << 14) -> None:
        from ..native import _ptr8, _ptr32, _ptr64, get_lib

        super().__init__(rows_capacity)
        self._p64, self._p32, self._p8 = _ptr64, _ptr32, _ptr8
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError(
                "HashSlabIndex needs the native library; use "
                "make_slab_index() to fall back to the sorted index")
        self._cap = int(table_capacity)
        if self._cap < 2 or self._cap & (self._cap - 1):
            raise ValueError(
                f"table_capacity must be a power of two >= 2, got "
                f"{table_capacity} (the probe mask is capacity - 1)")
        self._tkeys = np.full(self._cap, -1, dtype=np.int64)
        self._tvals = np.zeros(self._cap, dtype=np.int32)
        self._n = 0
        self.slot_key = np.full(1 << 10, -1, dtype=np.int64)
        self._moved_rows = np.zeros(0, dtype=np.int64)  # last _shift_moved

    def __len__(self) -> int:
        return self._n

    @staticmethod
    def _check_probe(exhausted: int) -> None:
        """Fail loudly on a bounded-probe exhaustion (contract violation:
        promised-present key absent, or a table the caller never grew)."""
        if exhausted:
            raise RuntimeError(
                f"slab hash probe exhausted the table for {exhausted} "
                f"keys — cell-index contract violated (corrupted reverse "
                f"map or un-grown table)")

    def _grow_table(self, need: int) -> None:
        if self.GROW_NUM * need <= self.GROW_DEN * self._cap:
            return
        cap = self._cap
        while self.GROW_NUM * need > self.GROW_DEN * cap:
            cap *= 2
        live = self._tkeys != -1
        keys = np.ascontiguousarray(self._tkeys[live])
        vals = np.ascontiguousarray(self._tvals[live])
        self._cap = cap
        self._tkeys = np.full(cap, -1, dtype=np.int64)
        self._tvals = np.zeros(cap, dtype=np.int32)
        self._check_probe(self._lib.slab_hash_insert(
            self._p64(self._tkeys), self._p32(self._tvals), cap - 1,
            self._p64(keys), self._p32(vals), len(keys)))

    def _ensure_slot_key(self, need: int) -> None:
        if need <= len(self.slot_key):
            return
        n = len(self.slot_key)
        while n < need:
            n *= 2
        grown = np.full(n, -1, dtype=np.int64)
        grown[: len(self.slot_key)] = self.slot_key
        self.slot_key = grown

    def apply(self, d_key: np.ndarray) -> AllocPlan:
        d_key = np.ascontiguousarray(d_key, dtype=np.int64)
        # The stale-slot re-probe below is only valid for rows moved by
        # THIS window's _allocate; drop last window's record up front so
        # staleness can never leak across windows.
        self._moved_rows = np.zeros(0, dtype=np.int64)
        n = len(d_key)
        slots = np.empty(n, dtype=np.int32)
        is_new = np.empty(n, dtype=np.uint8)
        self._check_probe(self._lib.slab_hash_lookup(
            self._p64(self._tkeys), self._p32(self._tvals), self._cap - 1,
            self._p64(d_key), n, self._p32(slots), self._p8(is_new)))
        new_sel = is_new.view(bool)
        new_key = d_key[new_sel]
        mv = None
        mv_len = 0
        if len(new_key):
            mv, mv_len, new_slots = self._allocate(new_key)
            slots[new_sel] = new_slots
            self._ensure_slot_key(self.heap_end)
            self.slot_key[new_slots] = new_key
            self._grow_table(self._n + len(new_key))
            new_slots = np.ascontiguousarray(new_slots)
            self._check_probe(self._lib.slab_hash_insert(
                self._p64(self._tkeys), self._p32(self._tvals),
                self._cap - 1, self._p64(new_key), self._p32(new_slots),
                len(new_key)))
            self._n += len(new_key)
            if mv is not None and not new_sel.all():
                # Allocation relocated rows, so the pre-allocation lookup
                # above returned stale slots for existing cells of MOVED
                # rows (the sorted index reads g_slot AFTER the shift) —
                # re-probe exactly those against the updated table.
                # Relocations fire nearly every window on Zipfian
                # streams, so the re-probe is masked to the moved rows'
                # cells, not the whole window.
                ex_pos = np.flatnonzero(~new_sel)
                # Membership via a dense row mask, not np.isin: isin
                # sorts both sides (O(n log n) per window) and this
                # line sits on the per-window hot path. Every existing
                # cell's row was registered through ensure_rows at
                # first insertion, so row ids index row_start-sized
                # arrays by the class invariant.
                mask = np.zeros(len(self.row_start), dtype=bool)
                mask[self._moved_rows] = True
                stale = ex_pos[mask[d_key[ex_pos] >> 32]]
                if len(stale):
                    ex_keys = np.ascontiguousarray(d_key[stale])
                    ex_slots = np.empty(len(ex_keys), dtype=np.int32)
                    scratch = np.empty(len(ex_keys), dtype=np.uint8)
                    self._check_probe(self._lib.slab_hash_lookup(
                        self._p64(self._tkeys), self._p32(self._tvals),
                        self._cap - 1, self._p64(ex_keys), len(ex_keys),
                        self._p32(ex_slots), self._p8(scratch)))
                    slots[stale] = ex_slots
        return AllocPlan(mv, mv_len, slots, new_sel.copy())

    def _shift_moved(self, rows: np.ndarray, old_starts: np.ndarray,
                     lens: np.ndarray, new_starts: np.ndarray,
                     disjoint: bool = False) -> None:
        # The reverse map recovers the moved cells' keys (the sorted
        # index found them by key-segment instead).
        self._moved_rows = rows  # apply() re-probes only these rows' cells
        self._ensure_slot_key(self.heap_end)
        if disjoint:
            # Growth relocations (every window on Zipfian streams): one
            # C pass copies each row's reverse-map keys and re-points
            # the table, skipping the ragged index/gather temporaries
            # below. Only valid when no new region overlaps an old one
            # — guaranteed by _allocate (offsets start at heap_end).
            self._check_probe(self._lib.slab_shift_rows(
                self._p64(self._tkeys), self._p32(self._tvals),
                self._cap - 1, self._p64(self.slot_key),
                self._p32(np.ascontiguousarray(old_starts,
                                               dtype=np.int32)),
                self._p32(np.ascontiguousarray(new_starts,
                                               dtype=np.int32)),
                self._p32(np.ascontiguousarray(lens, dtype=np.int32)),
                len(lens)))
            return
        old_idx = np.repeat(old_starts, lens) + _ragged_arange(lens)
        keys = np.ascontiguousarray(self.slot_key[old_idx])
        new_idx = (np.repeat(new_starts, lens)
                   + _ragged_arange(lens)).astype(np.int32)
        self.slot_key[new_idx] = keys
        self._check_probe(self._lib.slab_hash_update(
            self._p64(self._tkeys), self._p32(self._tvals), self._cap - 1,
            self._p64(keys), self._p32(np.ascontiguousarray(new_idx)),
            len(keys)))

    def rebuild_from_keys(self, keys: np.ndarray) -> np.ndarray:
        slots = super().rebuild_from_keys(keys)
        # The base rebuilt the registry and the sorted arrays; the hash
        # variant keeps the table + reverse map instead.
        keys = np.ascontiguousarray(self.g_key)
        slots = np.ascontiguousarray(self.g_slot)
        self.g_key = np.zeros(0, dtype=np.int64)
        self.g_slot = np.zeros(0, dtype=np.int32)
        cap = 1 << 14
        while self.GROW_NUM * len(keys) > self.GROW_DEN * cap:
            cap *= 2
        self._cap = cap
        self._tkeys = np.full(self._cap, -1, dtype=np.int64)
        self._tvals = np.zeros(self._cap, dtype=np.int32)
        if len(keys):
            self._check_probe(self._lib.slab_hash_insert(
                self._p64(self._tkeys), self._p32(self._tvals),
                self._cap - 1, self._p64(keys), self._p32(slots), len(keys)))
        self._n = len(keys)
        self.slot_key = np.full(max(1 << 10, _pow2ceil(
            np.asarray([max(self.heap_end, 1)]), 1024)[0]), -1,
            dtype=np.int64)
        if len(keys):
            self.slot_key[slots] = keys
        return slots

    def keys_and_slots(self):
        live = self._tkeys != -1
        keys = self._tkeys[live]
        slots = self._tvals[live]
        order = np.argsort(keys, kind="stable")
        return keys[order], slots[order]

    @property
    def nbytes(self) -> int:
        return (self.rows.nbytes + self._tkeys.nbytes + self._tvals.nbytes
                + self.slot_key.nbytes)

    def row_cells(self, rows: np.ndarray):
        """Hash-layout override: recover keys through the reverse map
        (insertion order within a row; the caller sorts jointly)."""
        starts, lens, _ = self.rows.get(rows)
        idx = np.repeat(starts, lens) + _ragged_arange(lens)
        return self.slot_key[idx].copy(), idx.astype(np.int32)

    def adopt_rows(self, rows: np.ndarray, keys: np.ndarray,
                   lens: np.ndarray) -> np.ndarray:
        """Hash-layout override: same preserved-order contract as the
        sorted base (see its docstring); the table and reverse map take
        the place of the sorted merge."""
        slots = self._adopt_alloc(rows, lens)
        if not len(keys):
            return slots
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        self._ensure_slot_key(self.heap_end)
        self.slot_key[slots] = keys
        self._grow_table(self._n + len(keys))
        slots_c = np.ascontiguousarray(slots)
        self._check_probe(self._lib.slab_hash_insert(
            self._p64(self._tkeys), self._p32(self._tvals), self._cap - 1,
            self._p64(keys), self._p32(slots_c), len(keys)))
        self._n += len(keys)
        return slots

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Hash-layout override of the present-keys slot resolve."""
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        slots = np.empty(len(keys), dtype=np.int32)
        missing = np.empty(len(keys), dtype=np.uint8)
        self._check_probe(self._lib.slab_hash_lookup(
            self._p64(self._tkeys), self._p32(self._tvals), self._cap - 1,
            self._p64(keys), len(keys), self._p32(slots),
            self._p8(missing)))
        if missing.view(bool).any():
            raise KeyError("lookup of absent cell keys — promotion "
                           "contract violated")
        return slots

    def free_rows(self, rows: np.ndarray) -> None:
        """Hash-layout override: the open-addressing table has no
        tombstones, so deletion rebuilds it minus the dead keys —
        promotions are rare enough that the rebuild is off the
        steady-state path."""
        starts, lens, cap = self.rows.get(rows)
        self.garbage += int(cap.sum())
        idx = np.repeat(starts, lens) + _ragged_arange(lens)
        dead = self.slot_key[idx]
        self.slot_key[idx] = -1
        live = self._tkeys != -1
        tk, tv = self._tkeys[live], self._tvals[live]
        keep = ~np.isin(tk, dead)
        tk = np.ascontiguousarray(tk[keep])
        tv = np.ascontiguousarray(tv[keep])
        self._tkeys = np.full(self._cap, -1, dtype=np.int64)
        self._tvals = np.zeros(self._cap, dtype=np.int32)
        if len(tk):
            self._check_probe(self._lib.slab_hash_insert(
                self._p64(self._tkeys), self._p32(self._tvals),
                self._cap - 1, self._p64(tk), self._p32(tv), len(tk)))
        self._n = len(tk)
        self.rows.clear(rows)


def make_slab_index(rows_capacity: int = 1 << 10) -> SlabIndex:
    """Best available cell index: the native hash table, else sorted."""
    from ..native import get_lib

    if get_lib() is not None:
        return HashSlabIndex(rows_capacity=rows_capacity)
    return SlabIndex(rows_capacity=rows_capacity)


class SparseDeviceScorer:
    """Single-device scorer over a :class:`SlabIndex`-managed HBM slab."""

    # Pipelined mode (pipeline.py) may hand this scorer pre-folded
    # AggregatedPairs — the producer thread runs the per-cell fold, and
    # process_window starts at slot allocation. Bit-identical either way
    # (the fold is the same aggregate_window_coo call).
    accepts_aggregated = True

    # Per-score-chunk padded-cell budget. Padding is device compute only —
    # it never crosses the wire in this backend — so the budget is sized
    # for HBM transients ([S, R] gather + scores), not transfer, and the
    # length ladder is coarse (default pow-4; TPU_COOC_SCORE_LADDER):
    # fewer dispatches beats tighter padding when every dispatch pays
    # tunnel round-trip latency.
    SCORE_BUDGET = 1 << 24
    # Fixed-shape mode budget (smaller: every window pays the full padded
    # rectangle, and its meta upload is wire bytes — see fixed_shapes).
    FIXED_BUDGET = 1 << 22
    # Per-bucket row cap in fixed-shape mode: bounds the [3, S_cap] meta
    # upload (12 B/row; 65536 rows = 768 KB) that every window ships.
    FIXED_ROW_CAP = 1 << 16

    def __init__(self, top_k: int, counters: Optional[Counters] = None,
                 development_mode: bool = False,
                 capacity: int = 1 << 16,
                 items_capacity: int = 1 << 10,
                 compact_min_heap: int = 1 << 16,
                 score_ladder: Optional[int] = None,
                 defer_results: bool = False,
                 fixed_shapes: Optional[bool] = None,
                 use_pallas: str = "auto",
                 cell_dtype: str = "int32",
                 wire_format: str = "raw",
                 spill_threshold_windows: int = 0,
                 spill_target_hbm_frac: float = 0.5,
                 fused_window: str = "off") -> None:
        from ..xla_cache import enable_compilation_cache
        from .wire import CELL_DTYPES, cell_promote_threshold

        enable_compilation_cache()
        if cell_dtype not in CELL_DTYPES:
            raise ValueError(
                f"cell_dtype must be one of {sorted(CELL_DTYPES)}, got "
                f"{cell_dtype!r}")
        if wire_format not in ("raw", "packed"):
            raise ValueError(
                f"wire_format must be raw or packed, got {wire_format!r}")
        self.cell_dtype = cell_dtype
        self._cnt_dtype = CELL_DTYPES[cell_dtype]
        # Narrow-cell promotion bound (None for int32): a row whose sum
        # reaches it moves to the wide int32 side-table BEFORE this
        # window's deltas apply, so narrow cells can never saturate and
        # scores stay bit-identical to an int32 slab.
        self.promote_threshold = cell_promote_threshold(cell_dtype)
        self.wire_packed = wire_format == "packed"
        self.top_k = top_k
        # Bucket-ladder base for the scoring dispatches (see score_buckets).
        # Env-tunable so high-latency links can trade padding for fewer
        # round trips without a config/API change.
        self.score_ladder = int(score_ladder if score_ladder is not None
                                else tuning.env_read(
                                    "TPU_COOC_SCORE_LADDER", 4))
        ladder_bits(self.score_ladder)  # validate at construction
        self.counters = counters if counters is not None else Counters()
        self.development_mode = development_mode
        self.index = make_slab_index(rows_capacity=items_capacity)
        self.items_cap = int(items_capacity)
        self.row_sums_host = np.zeros(self.items_cap, dtype=np.int64)
        self.compact_min_heap = int(compact_min_heap)
        self.capacity = int(capacity)
        self.cnt = jnp.zeros(self.capacity, dtype=self._cnt_dtype)
        self.dst = jnp.zeros(self.capacity, dtype=jnp.int32)
        self.row_sums = jnp.zeros(self.items_cap, dtype=jnp.int32)
        self.observed = 0
        # Exact live-cell count (dead promoted index entries excluded) —
        # feeds cooc_slab_live_cells and the bench's cells-per-byte.
        self.live_cells = 0
        # Wide int32 side-table (narrow cell dtypes only): its own
        # SlabIndex over the same row-id space plus a private slab pair.
        # Rows promote in whole — a row is entirely narrow or entirely
        # wide — so scoring stays per-row and the shared kernels run
        # unchanged over whichever slab pair holds the row.
        if self.promote_threshold is not None:
            self.index_w = make_slab_index(rows_capacity=items_capacity)
            self.capacity_w = 1 << 10
            self.cnt_w = jnp.zeros(self.capacity_w, dtype=jnp.int32)
            self.dst_w = jnp.zeros(self.capacity_w, dtype=jnp.int32)
            self.wide_rows = np.zeros(self.items_cap, dtype=bool)
        else:
            self.index_w = None
        self._plan_buckets_w = {}
        # One-window-deep result pipeline (see ops/device_scorer.py).
        self._pending: Optional[List] = None
        self.last_dispatched_rows = 0
        # scorer_breaker fault-site ordinal (see ops/device_scorer.py).
        self._breaker_seq = 0
        # Deferred-results mode: each score dispatch scatters its top-K
        # into a device-resident [2, items_cap, K] table instead of
        # returning it; ``flush()`` fetches the table's touched rows once.
        # This is the final-state consumption mode (no --emit-updates):
        # per-window result transfer drops to zero, which on a tunneled
        # chip / DCN link is most of a large window's wall time. The
        # reference has no analogue (its sink is a no-op, results ride the
        # accumulator dump — FlinkCooccurrences.java:169-181).
        self.defer_results = bool(defer_results)
        self._results = (DeferredResultsTable(top_k, self.items_cap)
                         if self.defer_results else None)
        # Fixed-shape scoring: pad every bucket's meta to a constant
        # per-bucket row cap so each window re-dispatches the SAME
        # compiled programs — one compile per bucket ever, steady ~1
        # dispatch per occupied bucket, no pow-4 shape ladder. The padded
        # rows are dead device compute (bounded by FIXED_BUDGET) and a
        # bounded meta upload; the win is dispatch/compile-count, which
        # is what a high-latency tunnel and a freshly-started process
        # actually pay for. Default: on for real TPUs, off elsewhere
        # (CPU tests would crawl through the padding); env
        # TPU_COOC_FIXED_SCORE=0/1 overrides.
        self.fixed_shapes = resolve_fixed_shapes(fixed_shapes,
                                                 self.defer_results)
        # bucket -> high-water chunk count (monotone plan: the fused
        # program's static plan only ever grows, so compile count stays
        # bounded even when a bucket occasionally overflows s_block).
        self._plan_buckets = {}
        # Fused-kernel routing for wide rectangles (--pallas): see
        # ops/pallas_score.resolve_sparse_pallas_flag (the measured
        # rationale lives there, once, for both sparse scorers).
        from ..ops.pallas_score import resolve_sparse_pallas_flag

        self.use_pallas = resolve_sparse_pallas_flag(use_pallas)
        self._pallas_interpret = jax.default_backend() != "tpu"
        # Fused one-dispatch window (--fused-window on the SPARSE
        # backend): steady-state windows run wire decode + update
        # scatter + registry sync + rescore + results scatter as ONE
        # program (_fused_sparse_window_*). Deferred results only — the
        # whole point is that nothing returns per window; config rejects
        # an explicit 'on' with --emit-updates, 'auto' degrades to
        # chained. Relocation / promotion / spill-re-promotion windows
        # route chained per window (same bit-identical results: the
        # fused body IS the chained body, fused).
        from ..ops.device_scorer import resolve_fused_flag

        self.use_fused = self.defer_results and resolve_fused_flag(
            fused_window)
        # The sparse fused path consumes aggregated deltas (the host
        # fold owns slot allocation); it never wants basket uplinks.
        self.wants_baskets = False
        # Which path the LAST process_window dispatch took — the job's
        # fused-vs-chained wall-time split and journal field read it.
        self.last_dispatch_fused = False
        # Tracing plane: per-window stage-seconds (uplink-encode /
        # rescore) the job carves into journal span tuples; the
        # unattributed remainder of score_seconds becomes "dispatch".
        self.stage_clock = StageClock()
        self._fused_dispatches = REGISTRY.gauge(
            "cooc_fused_dispatches_total",
            help="windows dispatched through the fused one-dispatch "
                 "window program")
        self._chained_dispatches = REGISTRY.gauge(
            "cooc_chained_dispatches_total",
            help="windows dispatched through the chained "
                 "scatter+score path")
        self._bucket_compiles = REGISTRY.gauge(
            "cooc_fused_bucket_compilations_total",
            help="distinct fused-window program shapes dispatched "
                 "(per-bucket shape-specialization compile churn)")
        # Static-shape keys the fused path has dispatched: each is one
        # XLA compile (pow2/pow4 ladders bound the set).
        self._fused_shapes = set()
        if self.use_fused:
            # Host side of the device registry mirror: every registry
            # mutation logs its rows; each fused dispatch uplinks the
            # dirty rows' (start, len) as a delta sync.
            self.index.rows.enable_dirty_log()
            self.reg_start = jnp.zeros(self.items_cap, dtype=jnp.int32)
            self.reg_len = jnp.zeros(self.items_cap, dtype=jnp.int32)
        # Elastic-state placement policy (state/store.py): tiered
        # cold-row spill when --spill-threshold-windows is set, direct
        # (everything device-resident) otherwise. The store owns the
        # checkpoint-blob round trip either way.
        from .store import make_store

        self.store = make_store(self, spill_threshold_windows,
                                spill_target_hbm_frac)

    def _rect_pallas(self, R: int) -> bool:
        """Whether bucket width ``R`` routes through the fused kernel
        (ops/pallas_score.rect_routed — the shared routing rule)."""
        from ..ops.pallas_score import rect_routed

        return rect_routed(self.use_pallas, R, self.top_k, self.items_cap)

    # Back-compat introspection used by tests.
    @property
    def heap_end(self) -> int:
        return self.index.heap_end

    @property
    def compactions(self) -> int:
        return self.index.compactions

    # -- capacity management --------------------------------------------

    def _ensure_items(self, max_id: int) -> None:
        if max_id >= (1 << 31) - 1:
            raise ValueError("sparse backend supports item ids < 2^31 - 1")
        if max_id < self.items_cap:
            return
        new_cap = int(_pow2ceil(np.asarray([max_id + 1]), 1024)[0])
        grown = np.zeros(new_cap, dtype=np.int64)
        grown[: len(self.row_sums_host)] = self.row_sums_host
        self.row_sums_host = grown
        self.row_sums = _grow(self.row_sums, n=new_cap)
        if self.index_w is not None:
            wide = np.zeros(new_cap, dtype=bool)
            wide[: len(self.wide_rows)] = self.wide_rows
            self.wide_rows = wide
        if self.use_fused:
            # Zero-extension preserves the synced (start, len) entries;
            # new rows read len 0 until their first registry sync.
            self.reg_start = _grow(self.reg_start, n=new_cap)
            self.reg_len = _grow(self.reg_len, n=new_cap)
        self.items_cap = new_cap
        if self._results is not None:
            self._results.resize(new_cap)

    def _ensure_heap(self, need_end: int) -> None:
        if need_end <= self.capacity:
            return
        new_cap = self.capacity
        while new_cap < need_end:
            new_cap *= 2
        self.cnt = _grow(self.cnt, n=new_cap)
        self.dst = _grow(self.dst, n=new_cap)
        self.capacity = new_cap

    def _ensure_heap_w(self, need_end: int) -> None:
        if need_end <= self.capacity_w:
            return
        new_cap = self.capacity_w
        while new_cap < need_end:
            new_cap *= 2
        self.cnt_w = _grow(self.cnt_w, n=new_cap)
        self.dst_w = _grow(self.dst_w, n=new_cap)
        self.capacity_w = new_cap

    # -- the window step --------------------------------------------------

    def process_window(self, ts: int, pairs: PairDeltaBatch):
        self._breaker_seq += 1
        if faults.PLAN is not None:
            # The breaker's trip input (see ops/device_scorer.py).
            faults.PLAN.fire("scorer_breaker", seq=self._breaker_seq)
        self.last_dispatched_rows = 0
        self.last_dispatch_fused = False
        self.stage_clock.reset()
        if len(pairs) == 0:
            if self.defer_results:
                # Idle window: results are intentionally held on device for
                # the end-of-stream/checkpoint flush (the drain itself is
                # incremental — dirty rows only — but draining on every
                # idle window would still cost a dispatch + downlink for
                # rows nobody asked for yet).
                return TopKBatch.empty(self.top_k)
            # No new dispatch — drain any completed in-flight results now.
            return self.flush()
        # Tiered-state spill step (state/store.py; no-op for the direct
        # store): advance the window clock and move rows that went cold
        # to the host arena, BEFORE any index op — the freed regions
        # become garbage the compaction below can reclaim this window.
        self.store.tick()
        # Reclaim freed slab regions once they dominate the heap. Runs
        # between windows only: mid-window the move/update instructions
        # already carry concrete slab addresses.
        if self.index.needs_compaction(self.compact_min_heap):
            gmap = self.index.compact()
            gmap_pad = np.zeros(min(pad_pow2(len(gmap), minimum=1 << 10),
                                    self.capacity), dtype=np.int32)
            gmap_pad[: len(gmap)] = gmap
            LEDGER.up("compact-gather", gmap_pad)
            self.cnt, self.dst = _compact_gather(self.cnt, self.dst,
                                                 gmap_pad, cap=self.capacity)
        if (self.index_w is not None
                and self.index_w.needs_compaction(self.compact_min_heap)):
            gmap = self.index_w.compact()
            gmap_pad = np.zeros(min(pad_pow2(len(gmap), minimum=1 << 10),
                                    self.capacity_w), dtype=np.int32)
            gmap_pad[: len(gmap)] = gmap
            LEDGER.up("compact-gather-wide", gmap_pad)
            self.cnt_w, self.dst_w = _compact_gather(
                self.cnt_w, self.dst_w, gmap_pad, cap=self.capacity_w)
        self._ensure_items(int(max(pairs.src.max(), pairs.dst.max())))
        if isinstance(pairs, AggregatedPairs):
            src_d, d_val, d_key = pairs.src, pairs.delta, pairs.key
        else:
            src_d, _, d_val, d_key = aggregate_window_coo(
                pairs.src, pairs.dst, pairs.delta.astype(np.int64),
                return_key=True)
        d_val32 = narrow_deltas_int32(d_val)

        # Row sums first (watermark ordering, reference
        # ItemRowRescorerTwoInputStreamOperator.java:116-142). The host
        # mirror is exact (int64); the device copy feeds the k21 gathers.
        rows = distinct_sorted(src_d)
        row_ends = np.searchsorted(src_d, rows, side="right")
        cum = np.concatenate([[0], np.cumsum(d_val)])
        rs_delta = cum[row_ends] - cum[np.searchsorted(src_d, rows)]
        self.row_sums_host[rows] += rs_delta
        if self.row_sums_host[rows].max(initial=0) >= 2**31:
            raise ValueError("row sum exceeds int32 range")
        # Fold-invariant: the per-cell aggregated deltas sum to exactly the
        # raw per-pair deltas (both int64), so either input form works.
        window_sum = int(d_val.sum())
        self.observed += window_sum
        self.counters.add(ROW_SUM_PROCESS_WINDOW, window_sum)

        # Spill-tier re-promotion FIRST (before the narrow->wide check
        # and before any delta applies): touched rows resident in the
        # host arena re-enter the slab index with their within-row order
        # preserved; their cell values ride this window's update upload
        # as extra new-cell + delta entries — no extra dispatch.
        promo_n, promo_w = self.store.promote_touched(rows)
        # Incremental-checkpoint dirty feed (state/delta.py): the SAME
        # touched-rows set the recency clock stamps — one dirty source,
        # two consumers. No-op unless --checkpoint-incremental armed it.
        self.store.note_touched(rows)
        # Narrow-cell promotion, then the per-slab split: a cell routes by
        # its row's residency, decided BEFORE this window's deltas apply.
        if self.index_w is not None:
            self._promote_rows(rows)
            cell_wide = self.wide_rows[src_d]
        else:
            cell_wide = None
        # Fused routing gate: steady-state all-narrow windows with no
        # spill re-promotion take the one-dispatch program; promotion /
        # wide-touching / re-promotion windows (and, inside
        # _fused_window, relocation windows and explicit upload-split
        # requests) route chained — per window, bit-identically.
        pre_plan = None
        fused_done = False
        if (self.use_fused and promo_n is None and promo_w is None
                and (cell_wide is None or not cell_wide.any())):
            fused_done, pre_plan = self._fused_window(d_key, d_val32,
                                                      rows, rs_delta)
        if fused_done:
            if self.development_mode:
                self._check_row_sums(rows)
            self.counters.add(RESCORED_ITEMS, len(rows))
            self.last_dispatched_rows = len(rows)
            self.last_dispatch_fused = True
            self._fused_dispatches.add(1)
            self._record_state_gauges()
            # Deferred results only: this window's top-K was scattered
            # into the device table inside the fused program.
            return TopKBatch.empty(self.top_k)

        self._chained_dispatches.add(1)
        with self.stage_clock.stage("uplink-encode"):
            if cell_wide is not None and (cell_wide.any()
                                          or promo_w is not None):
                self._window_update(d_key[~cell_wide], d_val32[~cell_wide],
                                    rows, rs_delta, wide=False, promo=promo_n)
                self._window_update(d_key[cell_wide], d_val32[cell_wide],
                                    rows[:0], rs_delta[:0], wide=True,
                                    promo=promo_w)
            else:
                self._window_update(d_key, d_val32, rows, rs_delta,
                                    wide=False, promo=promo_n, plan=pre_plan)

        if self.development_mode:
            self._check_row_sums(rows)

        # Score every updated row, length-bucketed (padding is device-only).
        self.counters.add(RESCORED_ITEMS, len(rows))
        self.last_dispatched_rows = len(rows)
        with self.stage_clock.stage("rescore"):
            if self.index_w is not None and self.wide_rows[rows].any():
                wmask = self.wide_rows[rows]
                chunks = self._dispatch_scoring(rows[~wmask], wide=False)
                chunks += self._dispatch_scoring(rows[wmask], wide=True)
            else:
                chunks = self._dispatch_scoring(rows)
        self._record_state_gauges()

        prev, self._pending = self._pending, chunks
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _promote_rows(self, rows: np.ndarray) -> None:
        """Promote rows whose (already-updated) sum crossed the narrow
        bound: move their cells to the wide side-table before this
        window's deltas touch them — saturation can never be observed."""
        thr = self.promote_threshold
        sel = (self.row_sums_host[rows] >= thr) & ~self.wide_rows[rows]
        if not sel.any():
            return
        newly = rows[sel]
        self.wide_rows[newly] = True
        keys, slots = self.index.row_cells(newly)
        self.index.free_rows(newly)
        if not len(keys):
            return  # first-ever window already past the bound: no cells yet
        order = np.argsort(keys, kind="stable")
        keys = keys[order]
        slots = slots[order].astype(np.int32)
        plan_w = self.index_w.apply(keys)
        self._ensure_heap_w(self.index_w.heap_end)
        m = len(keys)
        m_pad = pad_pow2(m, minimum=64)
        src = np.zeros(m_pad, dtype=np.int32)
        src[:m] = slots
        dsts = np.full(m_pad, _SENT, dtype=np.int32)
        dsts[:m] = plan_w.slots
        LEDGER.up("promote-cells", src, dsts)
        self.cnt_w, self.dst_w = _promote_cells(
            self.cnt, self.dst, self.cnt_w, self.dst_w, src, dsts)

    def _window_update(self, d_key: np.ndarray, d_val32: np.ndarray,
                       rows: np.ndarray, rs_delta: np.ndarray,
                       wide: bool = False, promo=None,
                       plan: Optional[AllocPlan] = None) -> None:
        """Allocate slots and dispatch one slab's window update. The
        narrow dispatch also carries the shared row-sum section (row
        sums are slab-independent); the wide dispatch's is empty.

        ``promo`` — tiered-store re-promotion extras ``(cell_keys,
        dst_vals, cnt_vals)``: each promoted cell rides the SAME upload
        as one new-cell entry (sets its partner id, zeroes the slot)
        plus one delta entry (adds its spilled count back) — exact
        movement with no extra dispatch. Slots are resolved AFTER
        ``apply`` (a promoted row gaining a new cell this window may be
        relocated by it); they are disjoint from apply's new-cell slots,
        and a promoted slot also receiving a window delta is fine: the
        delta section's scatter-adds commute."""
        index = self.index_w if wide else self.index
        if plan is None:
            # A non-None plan comes from a fused-window attempt that
            # bailed AFTER allocation (relocation window / explicit
            # upload-split request): apply already ran, re-running it
            # would double-insert.
            plan = index.apply(d_key)
        if wide:
            self._ensure_heap_w(index.heap_end)
            cnt_t, dst_t = self.cnt_w, self.dst_w
        else:
            self._ensure_heap(index.heap_end)
            cnt_t, dst_t = self.cnt, self.dst
        self.live_cells += plan.n_new

        upd, bounds, n = self._pack_update(index, plan, d_key, d_val32,
                                           rows, rs_delta, promo)
        n_pad = upd.shape[1]
        lbl = "update-wide" if wide else "update"

        # An explicit upload-split request (TPU_COOC_UPLOAD_CHUNKS /
        # _CHUNK_KB) pins the raw chunked path — the two wire levers are
        # alternatives, and an operator A/B-ing chunk sizes must not
        # silently measure the packed encoder instead.
        parts = split_upload_auto(upd) if not wide else None
        if parts is None and self.wire_packed:
            from .wire import encode_update

            words_i, words_v, header = encode_update(upd, bounds, n)
            wi = _pad_words(words_i)
            wv = _pad_words(words_v)
            if plan.mv is not None:
                LEDGER.up("update-moves", plan.mv)
                LEDGER.up_encoded(lbl + "-packed",
                                  upd.nbytes + bounds.nbytes, wi, wv, header)
                cnt_t, dst_t, self.row_sums = _apply_moves_update_packed(
                    cnt_t, dst_t, self.row_sums, plan.mv, wi, wv, header,
                    n_pad=n_pad, L=plan.mv_len)
            else:
                LEDGER.up_encoded(lbl + "-packed",
                                  upd.nbytes + bounds.nbytes, wi, wv, header)
                cnt_t, dst_t, self.row_sums = _apply_update_packed(
                    cnt_t, dst_t, self.row_sums, wi, wv, header, n_pad=n_pad)
        else:
            if parts is not None:
                # Ledger mirrors the actual transfer pattern: one event
                # per chunk plus the small metadata buffers (same byte
                # total as the monolithic event).
                for p in parts:
                    LEDGER.up("update-chunk", p)
            if plan.mv is not None:
                if parts is not None:
                    LEDGER.up("update-meta", bounds, plan.mv)
                    cnt_t, dst_t, self.row_sums = _apply_moves_update_chunked(
                        cnt_t, dst_t, self.row_sums, plan.mv,
                        parts, bounds, L=plan.mv_len)
                else:
                    LEDGER.up(lbl, upd, bounds, plan.mv)
                    cnt_t, dst_t, self.row_sums = _apply_moves_update(
                        cnt_t, dst_t, self.row_sums, plan.mv, upd,
                        bounds, L=plan.mv_len)
            else:
                if parts is not None:
                    LEDGER.up("update-meta", bounds)
                    cnt_t, dst_t, self.row_sums = _apply_update_chunked(
                        cnt_t, dst_t, self.row_sums, parts, bounds)
                else:
                    LEDGER.up(lbl, upd, bounds)
                    cnt_t, dst_t, self.row_sums = _apply_update(
                        cnt_t, dst_t, self.row_sums, upd, bounds)
        if wide:
            self.cnt_w, self.dst_w = cnt_t, dst_t
        else:
            self.cnt, self.dst = cnt_t, dst_t

    def _pack_update(self, index, plan: AllocPlan, d_key: np.ndarray,
                     d_val32: np.ndarray, rows: np.ndarray,
                     rs_delta: np.ndarray, promo):
        """THE window update-buffer layout (new cells | deltas | row
        sums, sentinel padding, pow4 transfer bucket) — single owner,
        shared by the chained and fused dispatch forms so the wire
        layout cannot drift between them. Returns ``(upd, bounds, n)``.

        ``promo`` as in :meth:`_window_update` (the fused path always
        passes ``None`` — re-promotion windows route chained)."""
        if promo is not None:
            p_keys, p_dst, p_vals = promo
            p_slots = index.lookup(p_keys)
        else:
            p_slots = p_dst = p_vals = np.zeros(0, dtype=np.int32)
        n_pn = plan.n_new
        n_promo = len(p_slots)
        n_new = n_pn + n_promo
        n_d, n_rs = len(d_key) + n_promo, len(rows)
        n = n_new + n_d + n_rs
        n_pad = pad_pow4(n, minimum=1 << 12)
        upd = np.full((2, n_pad), _SENT, dtype=np.int32)
        upd[1] = 0
        if n_pn:
            upd[0, :n_pn] = plan.slots[plan.new_sel]
            upd[1, :n_pn] = (d_key[plan.new_sel]
                             & 0xFFFFFFFF).astype(np.int32)
        if n_promo:
            upd[0, n_pn: n_new] = p_slots
            upd[1, n_pn: n_new] = p_dst
            upd[0, n_new: n_new + n_promo] = p_slots
            upd[1, n_new: n_new + n_promo] = p_vals
        upd[0, n_new + n_promo: n_new + n_d] = plan.slots
        upd[1, n_new + n_promo: n_new + n_d] = d_val32
        upd[0, n_new + n_d: n] = rows
        upd[1, n_new + n_d: n] = rs_delta.astype(np.int32)
        bounds = np.asarray([n_new, n_new + n_d], dtype=np.int32)
        return upd, bounds, n

    def _bump_fixed_plan(self, plan_buckets: dict, bucket: np.ndarray,
                         min_r: int) -> None:
        """Raise the monotone (bucket -> chunk-count) high-water plan to
        cover this window's bucket occupancy — single owner of the
        fixed-shape plan rule, shared by the chained fixed-mode dispatch
        and the fused window so their plans cannot drift."""
        for b, n_rows in zip(*[u.tolist() for u in
                               np.unique(bucket, return_counts=True)]):
            R = bucket_r(b, min_r, self.score_ladder)
            S = fixed_block(R, self.FIXED_BUDGET, self.FIXED_ROW_CAP)
            plan_buckets[b] = max(plan_buckets.get(b, 0), -(-n_rows // S))

    @property
    def fused_compilations(self) -> int:
        """Distinct fused-program static shapes dispatched so far (=
        XLA compiles of the fused window; the journal's per-window
        ``fused_compiles`` field)."""
        return len(self._fused_shapes)

    def _note_fused_shape(self, key) -> None:
        """Track distinct fused-program static shapes (= XLA compiles):
        the per-bucket shape-specialization churn gauge."""
        if key not in self._fused_shapes:
            self._fused_shapes.add(key)
            self._bucket_compiles.set(len(self._fused_shapes))

    def _fused_window(self, d_key: np.ndarray, d_val32: np.ndarray,
                      rows: np.ndarray, rs_delta: np.ndarray):
        """Dispatch one steady-state window through the fused
        one-dispatch program. Returns ``(handled, pre_plan)``:
        ``(True, None)`` when the window ran fused, ``(False, plan)``
        when it must route chained — the allocation already happened, so
        the chained ``_window_update`` receives the plan instead of
        re-applying it.

        Not fused-routable (decided here, after allocation): relocation
        windows (``plan.mv`` — the fused program carries no move
        kernel; moves stay fused with the CHAINED update instead) and
        windows under an explicit upload-split request
        (TPU_COOC_UPLOAD_CHUNKS/_CHUNK_KB pins the raw chunked path —
        an operator A/B-ing chunk sizes must not silently measure the
        fused program). The caller gates promotion / wide-row / spill
        re-promotion windows before allocation.
        """
        plan = self.index.apply(d_key)
        if plan.mv is not None:
            return False, plan
        self._ensure_heap(self.index.heap_end)

        with self.stage_clock.stage("uplink-encode"):
            upd, bounds, n = self._pack_update(self.index, plan, d_key,
                                               d_val32, rows, rs_delta, None)
        n_pad = upd.shape[1]
        if split_upload_auto(upd) is not None:
            return False, plan
        self.live_cells += plan.n_new

        # Registry delta sync: rows whose host (start, len) changed
        # since the device mirror last synced — this window's new-cell
        # rows plus anything a chained window / compaction / spill
        # touched in between. Sentinel-padded, scatter-dropped.
        dirty, all_dirty = self.index.rows.drain_dirty()
        if all_dirty:
            dirty = self.index.rows.occupied().astype(np.int64)
        n_reg = len(dirty)
        reg_pad = pad_pow2(n_reg, minimum=256)
        reg_upd = np.full((3, reg_pad), _SENT, dtype=np.int32)
        if n_reg:
            r_start, r_len, _c = self.index.rows.get(dirty)
            reg_upd[0, :n_reg] = dirty
            reg_upd[1, :n_reg] = r_start
            reg_upd[2, :n_reg] = r_len

        # Monotone scoring plan (the fixed-shape mode's rule, shared
        # _plan_buckets): every (bucket, chunk-rank) ever occupied
        # dispatches — absent ones as all-padding rectangles — so the
        # static plan only grows and compile count stays bounded by the
        # final plan's rectangle count. Per-row independence of
        # _score_rect makes chunking/padding parity-neutral.
        _s, lens_h, _c = self.index.rows.get(rows)
        min_r = max(16, self.top_k)
        bucket, order = score_buckets(lens_h, min_r, self.score_ladder)
        self._bump_fixed_plan(self._plan_buckets, bucket, min_r)
        b_sorted = bucket[order]
        plan_t = []
        segs = []
        off = 0
        for b in sorted(self._plan_buckets):
            R = bucket_r(b, min_r, self.score_ladder)
            S = fixed_block(R, self.FIXED_BUDGET, self.FIXED_ROW_CAP)
            lo = int(np.searchsorted(b_sorted, b))
            hi = int(np.searchsorted(b_sorted, b, side="right"))
            rows_b = rows[order[lo:hi]]
            for c in range(self._plan_buckets[b]):
                chunk = rows_b[c * S: (c + 1) * S]
                seg = np.full(S, _SENT, dtype=np.int32)
                seg[: len(chunk)] = chunk
                segs.append(seg)
                plan_t.append((R, S, off, self._rect_pallas(R)))
                off += S
        rows_all = np.concatenate(segs)
        plan_t = tuple(plan_t)

        self._results.ensure()
        observed = np.float32(self.observed)
        if self.wire_packed:
            from .wire import encode_update

            with self.stage_clock.stage("uplink-encode"):
                words_i, words_v, header = encode_update(upd, bounds, n)
                wi = _pad_words(words_i)
                wv = _pad_words(words_v)
            LEDGER.up_encoded("fused-window-packed",
                              upd.nbytes + bounds.nbytes, wi, wv, header)
            LEDGER.up("fused-window-meta", reg_upd, rows_all)
            self._note_fused_shape(
                ("packed", n_pad, len(wi), len(wv), reg_pad, plan_t))
            (self.cnt, self.dst, self.row_sums, self._results.tbl,
             self.reg_start, self.reg_len) = _fused_sparse_window_packed(
                self.cnt, self.dst, self.row_sums, self._results.tbl,
                self.reg_start, self.reg_len, wi, wv, header, reg_upd,
                rows_all, observed, n_pad=n_pad, top_k=self.top_k,
                plan=plan_t, interpret=self._pallas_interpret)
        else:
            LEDGER.up("fused-window", upd, bounds, reg_upd, rows_all)
            self._note_fused_shape(("raw", n_pad, reg_pad, plan_t))
            (self.cnt, self.dst, self.row_sums, self._results.tbl,
             self.reg_start, self.reg_len) = _fused_sparse_window_raw(
                self.cnt, self.dst, self.row_sums, self._results.tbl,
                self.reg_start, self.reg_len, upd, bounds, reg_upd,
                rows_all, observed, top_k=self.top_k, plan=plan_t,
                interpret=self._pallas_interpret)
        self._results.mark(rows)
        return True, None

    def _record_state_gauges(self) -> None:
        """Per-window state-footprint gauges (the compression layer's
        headline numbers: host index RSS, device slab bytes, live cells)."""
        rss = self.index.nbytes
        slab = self.cnt.nbytes + self.dst.nbytes
        if self.index_w is not None:
            rss += self.index_w.nbytes + self.wide_rows.nbytes
            slab += self.cnt_w.nbytes + self.dst_w.nbytes
        REGISTRY.gauge(
            "cooc_host_index_rss_bytes",
            help="host-side slab index footprint (registry + cell "
                 "index), refreshed per window").set(rss)
        REGISTRY.gauge(
            "cooc_slab_device_bytes",
            help="device slab allocation (cnt + dst, narrow and wide)"
        ).set(slab)
        REGISTRY.gauge(
            "cooc_slab_live_cells",
            help="live matrix cells across narrow and wide slabs"
        ).set(self.live_cells)
        self.store.record_gauges()

    def _dispatch_scoring(self, rows: np.ndarray,
                          wide: bool = False) -> List[Tuple]:
        """Score ``rows`` out of one slab pair (``wide`` routes promoted
        rows through the int32 side-table; the kernels are dtype- and
        buffer-polymorphic, so both residencies share every program)."""
        if wide:
            index, cnt, dst = self.index_w, self.cnt_w, self.dst_w
            plan_buckets = self._plan_buckets_w
        else:
            index, cnt, dst = self.index, self.cnt, self.dst
            plan_buckets = self._plan_buckets
        if len(rows) == 0 and not plan_buckets:
            return []
        # One registry pass (the _RowField views are the compat shim for
        # external callers; this is the per-window hot path).
        starts, lens, _caps = index.rows.get(rows)
        min_r = max(16, self.top_k)  # lax.top_k needs k <= R
        bucket, order = score_buckets(lens, min_r, self.score_ladder)
        b_sorted = bucket[order]
        if self.defer_results:
            self._results.ensure()
        chunks: List[Tuple[np.ndarray, int, object]] = []
        rects: List[Tuple[int, int, np.ndarray]] = []  # fixed: (R, S, chunk)
        if self.fixed_shapes:
            # Monotone plan: dispatch every (bucket, chunk-rank) ever
            # occupied (absent ones as all-padding rectangles), so the
            # fused program's static plan only grows — no churn from
            # per-window bucket subsets OR from a bucket occasionally
            # overflowing its per-dispatch row cap.
            self._bump_fixed_plan(plan_buckets, bucket, min_r)
        pos = 0
        while pos < len(order):
            b = int(b_sorted[pos])
            end = int(np.searchsorted(b_sorted, b, side="right"))
            R = bucket_r(b, min_r, self.score_ladder)
            if self.fixed_shapes:
                s_block = fixed_block(R, self.FIXED_BUDGET,
                                      self.FIXED_ROW_CAP)
            else:
                s_block = max(self.SCORE_BUDGET // R, 16)
            for lo in range(pos, end, s_block):
                chunk = order[lo: min(lo + s_block, end)]
                s = len(chunk)
                if self.fixed_shapes:
                    # Fixed mode: always the full per-bucket rectangle,
                    # collected into ONE window dispatch below.
                    rects.append((R, s_block, chunk))
                    continue
                # pow-4 row padding: each (R, s_pad) combination is one
                # trace + compile per process; a coarse ladder keeps the
                # program count (and per-process retrace time) small.
                s_pad = min(pad_pow4(s, minimum=16), s_block)
                meta = np.zeros((3, s_pad), dtype=np.int32)
                meta[0, :s] = rows[chunk]
                meta[1, :s] = starts[chunk]
                meta[2, :s] = lens[chunk]
                LEDGER.up("bucket-meta", meta)
                if self.defer_results:
                    # Fused: the scatter rides the scoring dispatch (the
                    # table is donated in and reassigned).
                    self._results.tbl = _score_into_table(
                        self._results.tbl, cnt, dst,
                        self.row_sums, meta, np.float32(self.observed),
                        top_k=self.top_k, R=R,
                        pallas=self._rect_pallas(R),
                        interpret=self._pallas_interpret)
                    continue
                score = (_score_slab_pallas if self._rect_pallas(R)
                         else _score_slab)
                kw = ({"interpret": self._pallas_interpret}
                      if self._rect_pallas(R) else {})
                packed = score(cnt, dst, self.row_sums,
                               meta, np.float32(self.observed),
                               top_k=self.top_k, R=R, **kw)
                if hasattr(packed, "copy_to_host_async"):
                    packed.copy_to_host_async()
                chunks.append((rows[chunk], s, packed))
            pos = end
        if self.fixed_shapes:
            # Top up to the high-water plan: every (bucket, chunk-rank)
            # ever seen dispatches, absent ones as all-padding.
            have = {}
            for R, _S, _c in rects:
                have[R] = have.get(R, 0) + 1
            for b, n_chunks in plan_buckets.items():
                R = bucket_r(b, min_r, self.score_ladder)
                S = fixed_block(R, self.FIXED_BUDGET, self.FIXED_ROW_CAP)
                for _ in range(n_chunks - have.get(R, 0)):
                    rects.append((R, S, order[:0]))
        if rects:
            # One packed [3, sum(S)] meta upload + one dispatch for the
            # whole window (fixed mode is defer-only, enforced at
            # construction). Canonical R order keeps the plan identical
            # regardless of which buckets were empty this window.
            rects.sort(key=lambda t: t[0])
            total = sum(S for _R, S, _c in rects)
            meta_all = np.zeros((3, total), dtype=np.int32)
            plan = []
            off = 0
            for R, S, chunk in rects:
                s = len(chunk)
                meta_all[0, off: off + s] = rows[chunk]
                meta_all[1, off: off + s] = starts[chunk]
                meta_all[2, off: off + s] = lens[chunk]
                plan.append((R, S, off, self._rect_pallas(R)))
                off += S
            LEDGER.up("window-meta", meta_all)
            self._results.tbl = _score_window_into_table(
                self._results.tbl, cnt, dst, self.row_sums,
                meta_all, np.float32(self.observed),
                top_k=self.top_k, plan=tuple(plan),
                interpret=self._pallas_interpret)
        if self.defer_results:
            self._results.mark(rows)
        return chunks

    def _check_row_sums(self, rows: np.ndarray) -> None:
        """Dev-mode invariant: slab row contents sum to the tracked row sum
        (reference check, ItemRowRescorerTwoInputStreamOperator.java:183-193)."""
        cnt = np.asarray(self.cnt).astype(np.int64)
        cnt_w = (np.asarray(self.cnt_w) if self.index_w is not None
                 else None)
        for r in rows.tolist():
            if self.index_w is not None and self.wide_rows[r]:
                s, ln = self.index_w.row_start[r], self.index_w.row_len[r]
                actual = int(cnt_w[s: s + ln].sum())
            else:
                s, ln = self.index.row_start[r], self.index.row_len[r]
                actual = int(cnt[s: s + ln].sum())
            if actual != int(self.row_sums_host[r]):
                raise AssertionError(
                    f"Item row {int(self.row_sums_host[r])} does not match "
                    f"actual row sum {actual} (item {r})")

    # -- results ----------------------------------------------------------

    def flush(self) -> TopKBatch:
        if self.defer_results:
            return self._results.drain()
        prev, self._pending = self._pending, None
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _materialize(self, chunks) -> TopKBatch:
        rows_l, idx_l, vals_l = [], [], []
        for rows, s, packed in chunks:
            host = np.asarray(packed)  # single [2, S_pad, K] fetch
            LEDGER.down("results", host)
            rows_l.append(rows)
            vals_l.append(host[0, :s])
            idx_l.append(host[1, :s].view(np.int32))
        return TopKBatch.concatenate(rows_l, idx_l, vals_l, self.top_k)

    # -- checkpoint -------------------------------------------------------

    def checkpoint_state(self) -> dict:
        """Canonical snapshot via the state store (state/store.py): the
        tiered store merges spilled arena cells back into the blob, the
        direct store passes through — either way the format is the
        canonical one and files are interchangeable across stores."""
        return self.store.checkpoint_state()

    def restore_state(self, st: dict) -> None:
        self.store.restore_state(st)

    def _device_checkpoint_state(self) -> dict:
        """Canonical sparse-matrix snapshot of the DEVICE-resident rows —
        same keys as the hybrid backend, so checkpoints are
        interchangeable between the two (and between cell dtypes:
        narrow/wide residency is an in-memory layout, not a checkpoint
        concern)."""
        keys, slots = self.index.keys_and_slots()
        if self.index_w is not None:
            # free_rows deletes promoted rows' narrow entries; the mask
            # filter is defensive belt-and-braces on top of that.
            live = ~self.wide_rows[(keys >> 32).astype(np.int64)]
            keys, slots = keys[live], slots[live]
        if len(slots):
            # Gather live cells ON DEVICE so the fetch is nnz values, not
            # the whole slab (capacity >= 2x nnz from pow-2 slack+garbage).
            # The ledger books the NARROW fetched array — widening to
            # int64 happens host-side and never crosses the wire.
            LEDGER.up("checkpoint-slots", slots)
            fetched = np.asarray(self.cnt[jnp.asarray(slots)])
            LEDGER.down("checkpoint-cells", fetched)
            vals = fetched.astype(np.int64)
        else:
            vals = np.zeros(0, np.int64)
        if self.index_w is not None:
            keys_w, slots_w = self.index_w.keys_and_slots()
            if len(slots_w):
                LEDGER.up("checkpoint-slots", slots_w)
                fetched_w = np.asarray(self.cnt_w[jnp.asarray(slots_w)])
                LEDGER.down("checkpoint-cells", fetched_w)
                vals_w = fetched_w.astype(np.int64)
                keys = np.concatenate([keys, keys_w])
                vals = np.concatenate([vals, vals_w])
                order = np.argsort(keys, kind="stable")
                keys, vals = keys[order], vals[order]
        nz = vals != 0
        return {
            "rows_key": keys[nz],
            "rows_cnt": vals[nz].astype(np.int64),
            "row_sums": self.row_sums_host.copy(),
            "observed": np.asarray([self.observed], dtype=np.int64),
        }

    def _device_restore_state(self, st: dict) -> None:
        from .wire import checked_narrow

        key = st["rows_key"]
        cnt_vals = st["rows_cnt"]
        max_id = int(max((key >> 32).max(initial=0),
                         int((key & 0xFFFFFFFF).max(initial=0))))
        # Size host registries/capacities directly — the device arrays are
        # rebuilt wholesale below, so the _ensure_* grow-copy kernels would
        # only produce buffers we immediately discard.
        if max_id >= self.items_cap:
            new_cap = int(_pow2ceil(np.asarray([max_id + 1]), 1024)[0])
            self.row_sums_host = np.zeros(new_cap, dtype=np.int64)
            self.items_cap = new_cap
        rs = np.asarray(st["row_sums"], dtype=np.int64)
        if len(rs) > self.items_cap and rs[self.items_cap:].any():
            # Row-sum == sum of the row's cells (dev-mode invariant), so a
            # nonzero sum beyond the max cell id is a corrupt checkpoint.
            raise ValueError("checkpoint row sums extend past its cells")
        self.row_sums_host = np.zeros(self.items_cap, dtype=np.int64)
        m = min(len(rs), self.items_cap)
        self.row_sums_host[:m] = rs[:m]
        if self.index_w is not None:
            # Residency from the restored sums: any row at/past the bound
            # goes wide (a once-promoted row whose sum has since dropped
            # back under the bound fits narrow again — every cell is at
            # most the current sum — so the threshold rule is exact).
            self.wide_rows = self.row_sums_host >= self.promote_threshold
            wide_cells = self.wide_rows[(key >> 32).astype(np.int64)]
            key_w, cnt_w_vals = key[wide_cells], cnt_vals[wide_cells]
            key, cnt_vals = key[~wide_cells], cnt_vals[~wide_cells]
            slots_w = self.index_w.rebuild_from_keys(key_w)
            self.capacity_w = 1 << 10
            while self.capacity_w < self.index_w.heap_end:
                self.capacity_w *= 2
            cnt_w_host = np.zeros(self.capacity_w, dtype=np.int32)
            dst_w_host = np.zeros(self.capacity_w, dtype=np.int32)
            cnt_w_host[slots_w] = cnt_w_vals.astype(np.int32)
            dst_w_host[slots_w] = (key_w & 0xFFFFFFFF).astype(np.int32)
            LEDGER.up("restore-slab", cnt_w_host, dst_w_host)
            self.cnt_w = jnp.asarray(cnt_w_host)
            self.dst_w = jnp.asarray(dst_w_host)
        slots = self.index.rebuild_from_keys(key)
        while self.capacity < self.index.heap_end:
            self.capacity *= 2
        cnt_host = np.zeros(self.capacity, dtype=self._cnt_dtype)
        dst_host = np.zeros(self.capacity, dtype=np.int32)
        cnt_host[slots] = checked_narrow(cnt_vals, self._cnt_dtype)
        dst_host[slots] = (key & 0xFFFFFFFF).astype(np.int32)
        LEDGER.up("restore-slab", cnt_host, dst_host)
        self.cnt = jnp.asarray(cnt_host)
        self.dst = jnp.asarray(dst_host)
        self.row_sums = jnp.asarray(self.row_sums_host.astype(np.int32))
        self.observed = int(st["observed"][0])
        self.live_cells = len(st["rows_key"])
        # In-flight results belong to windows after the checkpoint.
        self._pending = None
        if self._results is not None:
            self._results.reset(self.items_cap)
        self._plan_buckets = {}
        self._plan_buckets_w = {}
        if self.use_fused:
            # Fresh device registry mirror for the rebuilt index; the
            # registry reset above marked everything dirty, so the next
            # fused window resyncs every occupied row.
            self.reg_start = jnp.zeros(self.items_cap, dtype=jnp.int32)
            self.reg_len = jnp.zeros(self.items_cap, dtype=jnp.int32)
