"""Compressed sparse state + wire formats (SMASH / FlashSparse playbook).

Three codecs, one module, so every byte-layout decision the sparse
backend makes is in one reviewable place:

* **Narrow cell dtypes** — slab ``cnt`` cells stored as int16 (or int8
  behind ``--cell-dtype``), exactness guaranteed by *promoting a row to
  the wide int32 side-table BEFORE any of its cells could saturate*
  (``cell_promote_threshold``: a row whose sum stays under ``2^(w-1)``
  can never hold a cell at or past the dtype max, because cells are
  non-negative and sum to the row sum). :func:`checked_narrow` is the
  canonical guarded narrowing cast — the ``narrow-cast-guard`` cooclint
  rule (``analysis/rules_wire.py``) rejects bare ``astype(int16/int8)``
  sites elsewhere.

* **Packed uplink** (``encode_update`` / ``decode_update``) — the
  per-window COO update buffer (``[2, n_pad] int32``: new cells |
  cell deltas | row sums, see ``sparse_scorer._update_body``) encoded as
  per-section *sorted delta + zigzag + fixed-width bit-pack*. Each
  section's scatter is order-independent (unique indices per section;
  integer scatter-adds commute), so sorting by index inside a section is
  free, deltas of sorted unique indices are small, and a per-window bit
  width packs them. Fixed-width (not varint) on the wire because the
  decode then needs only gathers, shifts and cumsums — a tiny jit
  prologue feeding the existing scatter unchanged — where varint's
  per-element byte boundaries would serialize an on-device decode.

* **Checkpoint blobs** (``encode_varint`` / ``encode_sorted_u64``) —
  delta + LEB128 varint for the sorted cell-key array and plain varint
  for the count array (host-decoded on restore, so variable-length is
  fine there). Rides inside the existing ``.npz`` generation format;
  ``state/checkpoint.py`` records the codec in the embedded meta and
  restores pre-codec files unchanged.

All encoders are exact (bit-identical round trip) for the full int32 /
nonnegative int64 domains they are applied to; property tests in
``tests/test_wire_format.py`` pin the round trips, and the device decode
is parity-tested against the host decode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# Scatter sentinel: mirrors sparse_scorer._SENT (>= any capacity, dropped
# by mode="drop") without importing it (this module must stay leaf-level).
SENT = np.int32(2**31 - 1)

# -- narrow cell dtypes ------------------------------------------------

#: ``--cell-dtype`` values -> numpy dtype of the slab ``cnt`` cells.
CELL_DTYPES = {"int32": np.int32, "int16": np.int16, "int8": np.int8}


def cell_promote_threshold(cell_dtype: str) -> Optional[int]:
    """Row-sum bound below which every cell of a row provably fits the
    narrow dtype (cells are non-negative and sum to the row sum, so each
    cell <= row sum < 2^(w-1) <= dtype max). A row whose running sum
    reaches this value is promoted to the wide int32 side-table *before*
    the window's deltas are applied — saturation can never occur.
    Returns ``None`` for int32 (nothing ever promotes)."""
    if cell_dtype == "int32":
        return None
    bits = np.iinfo(CELL_DTYPES[cell_dtype]).bits
    return 1 << (bits - 1)


def checked_narrow(arr: np.ndarray, dtype) -> np.ndarray:
    """The canonical guarded narrowing cast: raises instead of wrapping.

    Every host-side cast to a narrower integer dtype must go through
    here (or carry its own visible bounds check) — enforced by the
    ``narrow-cast-guard`` rule in ``analysis/rules_wire.py``.
    """
    info = np.iinfo(dtype)
    if len(arr) and (int(arr.min()) < info.min or int(arr.max()) > info.max):
        raise OverflowError(
            f"value range [{arr.min()}, {arr.max()}] does not fit "
            f"{np.dtype(dtype).name} [{info.min}, {info.max}]")
    return arr.astype(dtype)


def resolve_cell_dtype(flag: str, sparse_single_device: bool) -> str:
    """``--cell-dtype`` resolution: ``auto`` = int16 on the single-device
    sparse backend (the promotion side-table lives there), int32
    everywhere else. Explicit narrow requests on backends that cannot
    honor them are rejected at config time, not here."""
    if flag == "auto":
        return "int16" if sparse_single_device else "int32"
    return flag


def resolve_wire_format(flag: str, sparse_single_device: bool) -> str:
    """``--wire-format`` resolution: ``auto`` = packed uplink on the
    single-device sparse backend (its update buffer is the steady-state
    wire cost), raw elsewhere. The checkpoint codec resolves separately
    (``checkpoint_codec``) — packed checkpoints are host-decoded and
    backend-independent."""
    if flag == "auto":
        return "packed" if sparse_single_device else "raw"
    return flag


def checkpoint_codec(flag: str) -> str:
    """Checkpoint-blob codec from ``--wire-format``: ``auto``/``packed``
    write the delta+varint generation format, ``raw`` writes the
    pre-codec layout (and doubles as the old-format fixture for restore
    tests). Restore auto-detects from the embedded meta either way."""
    return "raw" if flag == "raw" else "packed"


# -- fixed-width bit packing -------------------------------------------


def pack_bits(values: np.ndarray, width: int) -> np.ndarray:
    """Pack ``values`` (< 2^width each) at ``width`` bits into a little-
    endian uint32 word stream. ``1 <= width <= 32``."""
    if not (1 <= width <= 32):
        raise ValueError(f"pack width must be in [1, 32], got {width}")
    n = len(values)
    if n == 0:
        return np.zeros(0, dtype=np.uint32)
    vals = values.astype(np.uint64)
    if int(vals.max()) >> width:
        raise ValueError(f"value {vals.max()} does not fit {width} bits")
    bit0 = np.arange(n, dtype=np.uint64) * np.uint64(width)
    word = (bit0 >> np.uint64(5)).astype(np.int64)
    off = bit0 & np.uint64(31)
    n_words = int((n * width + 31) // 32)
    out = np.zeros(n_words + 1, dtype=np.uint32)  # +1: spill slot
    lo = ((vals << off) & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    # off == 0 shifts by 32: fine on uint64 (values < 2^32 -> 0).
    hi = (vals >> (np.uint64(32) - off)).astype(np.uint32)
    np.bitwise_or.at(out, word, lo)
    np.bitwise_or.at(out, word + 1, hi)
    return out[:n_words]


def unpack_bits(words: np.ndarray, width: int, n: int) -> np.ndarray:
    """Host inverse of :func:`pack_bits` -> uint64 array of length ``n``."""
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    w64 = np.append(words.astype(np.uint64), np.uint64(0))
    bit0 = np.arange(n, dtype=np.uint64) * np.uint64(width)
    word = (bit0 >> np.uint64(5)).astype(np.int64)
    off = bit0 & np.uint64(31)
    combined = w64[word] | (w64[word + 1] << np.uint64(32))
    mask = (np.uint64(1) << np.uint64(width)) - np.uint64(1) \
        if width < 64 else np.uint64(0xFFFFFFFFFFFFFFFF)
    return (combined >> off) & mask


# -- the packed update-buffer wire format ------------------------------
#
# Layout (see docs/ARCHITECTURE.md "Sparse state" wire table):
#
#   header   int32[5]   n, w_idx, w_val, b0, b1
#   words_i  uint32[.]  index column: per-section delta of the section-
#                       sorted indices, w_idx bits each (pow2-padded)
#   words_v  uint32[.]  value column: zigzag(v) at w_val bits each; the
#                       new-cell section's partner ids are additionally
#                       delta-coded (sorted slots => near-sorted ids)
#
# The decode is exact under int32 wraparound: per-section prefix sums may
# exceed 2^31 transiently, so both decoders accumulate in uint32 and the
# final subtraction lands back in the true (< 2^31) value mod 2^32.


def _section_starts(n: int, b0: int, b1: int):
    return (0, b0), (b0, b1), (b1, n)


def encode_update(upd: np.ndarray, bounds: np.ndarray,
                  n: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode the live prefix ``upd[:, :n]`` of a raw update buffer.

    Returns ``(words_i, words_v, header)`` — unpadded word streams; the
    caller pads to its transfer buckets. Each section is sorted by index
    first (scatters inside a section are order-independent: indices are
    unique per section and integer scatter-adds commute), which makes
    the index column piecewise-sorted and delta-friendly.
    """
    b0, b1 = int(bounds[0]), int(bounds[1])
    idx = upd[0, :n].astype(np.int64)
    val = upd[1, :n].astype(np.int64)
    order = np.concatenate([
        lo + np.argsort(idx[lo:hi], kind="stable")
        for lo, hi in _section_starts(n, b0, b1)]) if n else \
        np.zeros(0, dtype=np.int64)
    idx_s = idx[order]
    val_s = val[order]
    d = np.diff(idx_s, prepend=np.int64(0))
    for s, _e in _section_starts(n, b0, b1)[1:]:
        if s < n:
            d[s] = idx_s[s]  # each section restarts from an absolute index
    # New-cell section values (partner ids) ride as deltas too: slots are
    # sorted and same-row slots are dst-ordered, so ids are near-sorted.
    v_enc = val_s.copy()
    if b0:
        v_enc[:b0] = np.diff(val_s[:b0], prepend=np.int64(0))
    zz = ((v_enc << np.int64(1)) ^ (v_enc >> np.int64(63))).astype(np.uint64)
    w_i = max(int(d.max()).bit_length(), 1) if n else 1
    w_v = max(int(zz.max()).bit_length(), 1) if n else 1
    header = np.asarray([n, w_i, w_v, b0, b1], dtype=np.int32)
    return (pack_bits(d.astype(np.uint64), w_i),
            pack_bits(zz, w_v), header)


def decode_update_host(words_i: np.ndarray, words_v: np.ndarray,
                       header: np.ndarray, n_pad: int):
    """Host inverse of :func:`encode_update` (round-trip tests + the
    reference the jit decode is parity-tested against). Returns
    ``(upd[2, n_pad] int32, bounds int32[2])`` with sentinel padding —
    exactly what the raw path would have shipped, modulo the per-section
    index sort."""
    n, w_i, w_v, b0, b1 = (int(x) for x in header)
    d = unpack_bits(words_i, w_i, n).astype(np.int64)
    zz = unpack_bits(words_v, w_v, n)
    v = ((zz >> np.uint64(1)).astype(np.int64)
         ^ -(zz & np.uint64(1)).astype(np.int64))
    idx = np.zeros(n, dtype=np.int64)
    val = np.zeros(n, dtype=np.int64)
    for lo, hi in _section_starts(n, b0, b1):
        idx[lo:hi] = np.cumsum(d[lo:hi])
        val[lo:hi] = v[lo:hi]
    if b0:
        val[:b0] = np.cumsum(v[:b0])
    upd = np.full((2, n_pad), SENT, dtype=np.int32)
    upd[1] = 0
    upd[0, :n] = idx.astype(np.int32)
    upd[1, :n] = val.astype(np.int32)
    return upd, np.asarray([b0, b1], dtype=np.int32)


def decode_update(words_i, words_v, header, n_pad: int):
    """Traceable (jit) decode: gathers, shifts and cumsums only — the
    prologue that feeds ``sparse_scorer._update_body`` unchanged. Also
    runs eagerly for tests. Padding positions carry the scatter sentinel
    (dropped by ``mode="drop"``), mirroring the raw buffer exactly."""
    import jax.numpy as jnp
    from jax import lax

    n = header[0]
    w_i = header[1].astype(jnp.uint32)
    w_v = header[2].astype(jnp.uint32)
    b0, b1 = header[3], header[4]
    i = jnp.arange(n_pad, dtype=jnp.int32)
    live = i < n

    def unpack(words, width):
        bit0 = (i.astype(jnp.uint32) * width)
        word = (bit0 >> jnp.uint32(5)).astype(jnp.int32)
        off = bit0 & jnp.uint32(31)
        lo = words[word] >> off
        hi = jnp.where(off > 0,
                       words[word + 1] << ((jnp.uint32(32) - off)
                                           & jnp.uint32(31)),
                       jnp.uint32(0))
        mask = jnp.where(width >= 32, jnp.uint32(0xFFFFFFFF),
                         (jnp.uint32(1) << (width & jnp.uint32(31)))
                         - jnp.uint32(1))
        return (lo | hi) & mask

    d = jnp.where(live, unpack(words_i, w_i), jnp.uint32(0))
    zz = jnp.where(live, unpack(words_v, w_v), jnp.uint32(0))
    # Zigzag decode in int32 bit arithmetic (logical shift emulated).
    zi = lax.bitcast_convert_type(zz, jnp.int32)
    v = ((zi >> 1) & 0x7FFFFFFF) ^ -(zi & 1)

    # Per-section prefix sums via one global cumsum minus the section
    # base (uint32: transient sums may wrap past 2^31; the subtraction
    # is exact mod 2^32 and true values are < 2^31).
    c = jnp.cumsum(d, dtype=jnp.uint32)

    def base_at(s):
        return jnp.where(s > 0, c[jnp.maximum(s - 1, 0)], jnp.uint32(0))

    base = jnp.where(i >= b1, base_at(b1),
                     jnp.where(i >= b0, base_at(b0), jnp.uint32(0)))
    idx = lax.bitcast_convert_type(c - base, jnp.int32)
    # New-cell section: values are deltas of near-sorted partner ids.
    cv = jnp.cumsum(jnp.where(i < jnp.minimum(b0, n), v, 0),
                    dtype=jnp.int32)
    val = jnp.where(i < b0, cv, v)
    upd = jnp.stack([jnp.where(live, idx, jnp.int32(SENT)),
                     jnp.where(live, val, 0)])
    return upd, jnp.stack([b0, b1])


def packed_nbytes(words_i: np.ndarray, words_v: np.ndarray,
                  header: np.ndarray) -> int:
    return int(words_i.nbytes + words_v.nbytes + header.nbytes)


# -- varint (LEB128) checkpoint blobs ----------------------------------


def encode_varint(values: np.ndarray) -> np.ndarray:
    """LEB128-encode nonnegative int64/uint64 values -> uint8 stream."""
    vals = np.asarray(values)
    if len(vals) and vals.dtype != np.uint64 and int(vals.min()) < 0:
        raise ValueError("varint encodes nonnegative values only")
    vals = vals.astype(np.uint64)
    n = len(vals)
    if n == 0:
        return np.zeros(0, dtype=np.uint8)
    nb = np.ones(n, dtype=np.int64)
    for k in range(1, 10):
        nb += (vals >> np.uint64(7 * k)) != 0
    offsets = np.concatenate([[0], np.cumsum(nb)[:-1]])
    out = np.zeros(int(nb.sum()), dtype=np.uint8)
    for k in range(10):
        sel = nb > k
        if not sel.any():
            break
        byte = ((vals[sel] >> np.uint64(7 * k)) & np.uint64(0x7F)
                ).astype(np.uint8)
        cont = (nb[sel] - 1 > k).astype(np.uint8) << 7
        out[offsets[sel] + k] = byte | cont
    return out


def decode_varint(buf: np.ndarray, count: int) -> np.ndarray:
    """Inverse of :func:`encode_varint` -> uint64 array of ``count``."""
    buf = np.asarray(buf, dtype=np.uint8)
    if count == 0:
        if len(buf):
            raise ValueError("varint blob has trailing bytes")
        return np.zeros(0, dtype=np.uint64)
    term = buf < 128
    if int(term.sum()) != count or not term[-1]:
        raise ValueError(
            f"varint blob holds {int(term.sum())} values, expected {count}")
    gid = np.concatenate([[0], np.cumsum(term)[:-1]]).astype(np.int64)
    starts = np.concatenate([[0], np.flatnonzero(term)[:-1] + 1])
    pos = np.arange(len(buf), dtype=np.int64) - starts[gid]
    if int(pos.max()) > 9:
        raise ValueError("varint run exceeds 10 bytes")
    out = np.zeros(count, dtype=np.uint64)
    np.bitwise_or.at(
        out, gid,
        (buf & np.uint8(0x7F)).astype(np.uint64) << (np.uint64(7) *
                                                     pos.astype(np.uint64)))
    return out


def encode_zigzag_varint(values: np.ndarray) -> np.ndarray:
    """Zigzag + LEB128 for SIGNED int64 values (the delta-log columns
    that carry arbitrary-sign data: cell counts, external ids). Exact
    over the full int64 domain."""
    v = np.asarray(values, dtype=np.int64)
    zz = ((v << np.int64(1)) ^ (v >> np.int64(63))).astype(np.uint64)
    return encode_varint(zz)


def decode_zigzag_varint(buf: np.ndarray, count: int) -> np.ndarray:
    """Inverse of :func:`encode_zigzag_varint` -> int64 array."""
    zz = decode_varint(buf, count)
    return ((zz >> np.uint64(1)).astype(np.int64)
            ^ -(zz & np.uint64(1)).astype(np.int64))


def encode_sorted_u64(keys: np.ndarray) -> np.ndarray:
    """Delta + varint for a sorted nonnegative int64 array (cell keys:
    sorted, unique -> tiny deltas). Raises on unsorted input — the
    caller falls back to the raw layout rather than corrupt a blob."""
    keys = np.asarray(keys, dtype=np.int64)
    if len(keys):
        if int(keys.min()) < 0:
            raise ValueError("sorted-u64 codec needs nonnegative keys")
        d = np.diff(keys.astype(np.uint64), prepend=np.uint64(0))
        if len(keys) > 1 and (np.diff(keys) < 0).any():
            raise ValueError("sorted-u64 codec needs sorted keys")
    else:
        d = np.zeros(0, dtype=np.uint64)
    return encode_varint(d)


def decode_sorted_u64(buf: np.ndarray, count: int) -> np.ndarray:
    d = decode_varint(buf, count)
    return np.cumsum(d.astype(np.uint64)).astype(np.int64)
