"""Checkpoint / resume for the full pipeline.

The reference only checkpoints the file monitor's modification time
(``ContinuousFileMonitoringFunction.java:378-392``); its rescorer matrix and
row sums live in plain Java maps that are *lost* on restart, and the
feedback queue is invisible to checkpoints (SURVEY §5 — a documented
fault-tolerance gap). We close it: a checkpoint captures every piece of
pipeline state — vocabularies, item-cut counters, reservoir state (histories,
totals, draw counters), in-flight window buffers + watermark, the scorer's
matrix/row-sums/observed total, and the source offset — so a restored job
continues bit-identically (validated in ``tests/test_checkpoint.py``).

Format: a single ``.npz`` holding the arrays AND the JSON-encoded scalars
(``meta_json``), committed by one atomic rename; a ``meta.json`` sidecar is
written afterwards for human inspection only and plays no part in restore.
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np

from ..metrics import RESCORED_ITEMS


def exists(job, directory: str) -> bool:
    """True when ``directory`` holds a checkpoint this job could restore
    (same file-naming scheme as :func:`save`, including the per-process
    suffix of multi-host runs)."""
    suffix = getattr(job.scorer, "process_suffix", "")
    return os.path.exists(os.path.join(directory, f"state{suffix}.npz"))


def save(job, directory: str, source=None) -> str:
    """Write a checkpoint of ``job`` (and optionally its file source)."""
    os.makedirs(directory, exist_ok=True)
    arrays = {}
    meta = {
        "seed": job.config.seed,
        "skip_cuts": job.config.skip_cuts,
        "item_cut": job.config.item_cut,
        "user_cut": job.config.user_cut,
        "top_k": job.config.top_k,
        "window_slide": job.config.window_slide,
        "window_millis": job.config.window_millis,
        "windows_fired": job.windows_fired,
        "emissions": job.emissions,
        # A deferred-results scorer materializes each row once from its
        # device table however many windows rescored it, so its emission
        # count is not comparable with the rescored-rows counter. Record
        # the count a PER-WINDOW backend should resume with (the rescored
        # total keeps its drain invariant balanced) alongside the real
        # one, and let restore pick by the restoring scorer's mode.
        "emissions_per_window_resume": (
            job.counters.get(RESCORED_ITEMS)
            if getattr(job.scorer, "defer_results", False)
            else job.emissions),
        "max_ts_seen": job.engine.max_ts_seen,
        "counters": job.counters.as_dict(),
    }

    arrays["item_vocab"] = job.item_vocab.checkpoint_state()
    arrays["user_vocab"] = job.user_vocab.checkpoint_state()
    arrays["item_cut_counts"] = job.item_cut.counts

    s = job.sampler
    if hasattr(s, "checkpoint_state"):  # reservoir samplers (serial or
        # partitioned, both in the serial global-dense-id layout); the
        # sliding sampler is stateless
        arrays.update(s.checkpoint_state(len(job.user_vocab)))

    # In-flight window buffers, flattened.
    starts, users_l, items_l, ts_l = [], [], [], []
    for start, chunks in job.engine._buffers.items():
        for (u, i, t) in chunks:
            starts.append(np.full(len(u), start, dtype=np.int64))
            users_l.append(u)
            items_l.append(i)
            ts_l.append(t)
    if starts:
        arrays["buf_start"] = np.concatenate(starts)
        arrays["buf_users"] = np.concatenate(users_l)
        arrays["buf_items"] = np.concatenate(items_l)
        arrays["buf_ts"] = np.concatenate(ts_l)

    for key, val in job.scorer.checkpoint_state().items():
        arrays[f"scorer_{key}"] = val

    if source is not None:
        meta["source"] = source.checkpoint_state()

    # Latest emitted top-K (the consumable result state).
    lat_items, lat_offsets, lat_others, lat_scores = [], [0], [], []
    for item in sorted(job.latest):
        lat_items.append(item)
        top = job.latest[item]
        lat_others.extend(j for j, _ in top)
        lat_scores.extend(sc for _, sc in top)
        lat_offsets.append(len(lat_others))
    arrays["latest_items"] = np.asarray(lat_items, dtype=np.int64)
    arrays["latest_offsets"] = np.asarray(lat_offsets, dtype=np.int64)
    arrays["latest_others"] = np.asarray(lat_others, dtype=np.int64)
    arrays["latest_scores"] = np.asarray(lat_scores, dtype=np.float64)

    # The meta scalars ride INSIDE the .npz so one atomic rename commits
    # the whole checkpoint — a crash between two file replacements would
    # otherwise leave a mixed-generation (arrays N, meta N-1) state that
    # restores without error and silently double-ingests. The sidecar
    # meta.json is written afterwards purely for human inspection.
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)

    # Multi-host runs checkpoint per process (each host owns a row block
    # and its partition of the results); the scorer supplies the suffix.
    suffix = getattr(job.scorer, "process_suffix", "")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    os.close(fd)
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    npz_path = os.path.join(directory, f"state{suffix}.npz")
    os.replace(tmp, npz_path)
    meta_tmp = os.path.join(directory, f"meta{suffix}.json.tmp")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(meta_tmp, os.path.join(directory, f"meta{suffix}.json"))
    return npz_path


def restore(job, directory: str, source=None) -> None:
    """Restore ``job`` (constructed with the same Config) from a checkpoint."""
    suffix = getattr(job.scorer, "process_suffix", "")
    data = np.load(os.path.join(directory, f"state{suffix}.npz"))
    # Meta comes from inside the npz (the atomic commit point); the
    # meta.json sidecar is informational only and may lag by a crash.
    if "meta_json" not in data:
        raise ValueError(
            f"incompatible checkpoint format in {directory}: no embedded "
            "meta_json (written by a pre-atomic-commit version of this "
            "framework) — re-checkpoint with the current version")
    meta = json.loads(bytes(data["meta_json"]).decode())
    for key in ("seed", "skip_cuts", "item_cut", "user_cut", "top_k",
                "window_slide"):
        if getattr(job.config, key) != meta.get(key):
            raise ValueError(
                f"checkpoint config mismatch for {key}: "
                f"{meta.get(key)} != {getattr(job.config, key)}")

    job.item_vocab.restore_state(data["item_vocab"])
    job.user_vocab.restore_state(data["user_vocab"])
    job.item_cut.counts = data["item_cut_counts"].copy()

    s = job.sampler
    if hasattr(s, "restore_state") and "hist" in data:
        st = {k: data[k] for k in ("hist", "hist_len", "total", "draws")}
        if "sampler_part" in data:
            # Partition-sampled snapshots hold only the writing process's
            # users; a non-partitioned sampler would silently restore
            # zeroed reservoirs for everyone else.
            if not getattr(s, "process_partition", False):
                raise ValueError(
                    "checkpoint was written with --partition-sampling — "
                    "restore with the same flag and process layout")
            st["sampler_part"] = data["sampler_part"]
        s.restore_state(st, len(job.user_vocab))

    job.engine.max_ts_seen = meta["max_ts_seen"]
    job.engine._buffers.clear()
    if "buf_start" in data:
        starts = data["buf_start"]
        for start in np.unique(starts):
            sel = starts == start
            job.engine._buffers[int(start)] = [
                (data["buf_users"][sel], data["buf_items"][sel],
                 data["buf_ts"][sel])]

    job.scorer.restore_state(
        {k[len("scorer_"):]: v for k, v in data.items()
         if k.startswith("scorer_")})

    job.windows_fired = meta["windows_fired"]
    job.emissions = (meta["emissions"]
                     if getattr(job.scorer, "defer_results", False)
                     else meta.get("emissions_per_window_resume",
                                   meta["emissions"]))
    job.counters.replace_all(meta["counters"])

    # The store keeps dense ids; the .npz holds external ids (the public
    # result shape), so map back through the already-restored vocab.
    job.latest.clear()
    items = data["latest_items"]
    offsets = data["latest_offsets"]
    to_dense = job.item_vocab.to_dense
    for pos, item in enumerate(items.tolist()):
        lo, hi = int(offsets[pos]), int(offsets[pos + 1])
        top = list(zip(
            (to_dense(j) for j in data["latest_others"][lo:hi].tolist()),
            data["latest_scores"][lo:hi].tolist()))
        job.latest.set_row(to_dense(item), top)

    if source is not None and "source" in meta:
        source.restore_state(meta["source"])
