"""Checkpoint / resume for the full pipeline.

The reference only checkpoints the file monitor's modification time
(``ContinuousFileMonitoringFunction.java:378-392``); its rescorer matrix and
row sums live in plain Java maps that are *lost* on restart, and the
feedback queue is invisible to checkpoints (SURVEY §5 — a documented
fault-tolerance gap). We close it: a checkpoint captures every piece of
pipeline state — vocabularies, item-cut counters, reservoir state (histories,
totals, draw counters), in-flight window buffers + watermark, the scorer's
matrix/row-sums/observed total, and the source offset — so a restored job
continues bit-identically (validated in ``tests/test_checkpoint.py``).

Format: a single ``.npz`` holding the arrays AND the JSON-encoded scalars
(``meta_json``), committed by one atomic rename; a ``meta.json`` sidecar is
written afterwards for human inspection only and plays no part in restore.

Durability (the recovery-loop contract, ``tests/test_chaos.py``):

* **Integrity digest** — a sha256 over every array's bytes rides inside
  the ``.npz`` (``digest_sha256``). A torn or bit-rotted file — the one
  failure an atomic rename cannot rule out (rename is atomic; the
  preceding writes are only as durable as the filesystem's journaling) —
  fails verification instead of restoring garbage or crash-looping
  ``np.load``.
* **Generations** — each save commits ``state<suffix>.<gen>.npz`` with a
  monotonically increasing generation number and updates an atomic
  ``LATEST<suffix>`` pointer; ``--checkpoint-retain`` newest generations
  are kept. Restore walks newest-to-oldest, quarantines any generation
  that fails verification as ``*.corrupt`` (counted on
  ``cooc_checkpoint_quarantined_total``), and restores the newest one
  that verifies — a corrupt latest checkpoint costs one generation of
  progress, not the job.
* Orphaned ``*.tmp`` files (a crash between ``mkstemp`` and
  ``os.replace``) are swept by the next :func:`save` once they are old
  enough to be provably dead.
* **Directory durability** — after every atomic rename (generation file,
  ``LATEST`` pointer, epoch marker) the *directory* is fsynced too:
  rename alone only orders the pointer change in the page cache, and a
  power loss could resurrect the old directory entry under a new
  ``LATEST`` — a torn-pointer window the digest cannot see because both
  files verify.

Incremental generations (``--checkpoint-incremental``, ISSUE 12): a
full *base* generation plus per-generation *row-delta* files
(``delta<suffix>.<gen>.bin``, ``state/delta.py``) holding only the rows
touched since the previous committed generation — commit bytes scale
with churn, not vocab, so checkpoint intervals can shrink and
restart replay with them. The chain rules:

* A generation is incremental iff its delta file exists (chain
  structure is derivable from a directory listing alone — the gang
  restore vote never opens an npz); a delta generation's predecessor is
  always ``gen - 1``, its *base* is the newest generation at or below
  it without a delta file.
* The delta file is renamed into place BEFORE the generation's npz: the
  npz rename commits the generation (its embedded meta records the
  delta file's sha256, so a swapped or torn delta cannot restore), and
  a delta file without its npz is an orphan the next save sweeps.
* Restore reconstructs ``base + delta[B+1..G]`` into exactly the arrays
  a full generation-``G`` checkpoint would hold — byte-identical in
  every StateStore / cell-dtype / wire-format / topology combination
  (``tests/test_incremental_checkpoint.py``). A corrupt delta is
  quarantined ``*.corrupt`` and the walk falls back exactly like the
  torn-npz path.
* A ratio trigger (``--checkpoint-compact-ratio``: delta-chain bytes vs
  base bytes) rewrites a fresh base at the next window boundary and the
  old chain ages out under ``--checkpoint-retain``; retention never
  deletes a base or intermediate delta some retained generation still
  chains through.
* The same delta files are a consumable, documented **delta log**
  (``state/delta.read_delta_stream``) — ROADMAP #2's read replicas tail
  it for catch-up instead of re-syncing full snapshots.

Multi-host epoch commit (the gang contract, ``robustness/gang.py``):
each process of a multi-controller run checkpoints its own row block as
``state.p<i>.<gen>.npz``, which makes "the checkpoint" a *set* of files
whose partial existence is a torn global state. :func:`save` therefore
commits per-host files in two phases: after its own rename + directory
fsync every process enters a window-aligned ``gang_barrier`` (all
processes checkpoint at the same fired-window ordinal, so the barrier
is deterministic), and only once every host's file is durable does each
process write its own ``EPOCH.p<i>.<gen>`` marker. A generation is
*committed* on a host iff its marker exists; restore walks committed
generations only, and the gang supervisor's restore vote
(:func:`gang.agree_restore_generation`) allgathers each host's newest
committed generation and quarantines anything newer as ``*.partial`` —
so a crash anywhere between the first per-host rename and the last
marker write falls back exactly one generation on every host instead of
restoring a torn mix. Single-process runs write no markers and restore
exactly as before.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import tempfile
import time

import numpy as np

from ..metrics import RESCORED_ITEMS
from ..observability.registry import REGISTRY
from ..robustness import faults
from . import delta as deltalog

LOG = logging.getLogger("tpu_cooccurrence.checkpoint")

#: Orphaned ``*.tmp`` snapshots younger than this are left alone by the
#: sweep: they may belong to a live writer (another process of a
#: multi-host run saving into the same directory).
TMP_SWEEP_AGE_S = 900.0

#: Quarantine counter (metrics plane): checkpoint files that failed
#: verification and were renamed ``*.corrupt``.
QUARANTINE_GAUGE = "cooc_checkpoint_quarantined_total"

#: Generation-in-use gauge: set by :func:`save` (generation written) and
#: :func:`restore` (generation restored).
GENERATION_GAUGE = "cooc_checkpoint_generation"

#: Multi-host epoch gauge: the newest generation whose ``EPOCH`` marker
#: this process has written (save) or restored from. Stays 0 on
#: single-process runs (no epoch plane).
EPOCH_GAUGE = "cooc_epoch_committed"

#: Partial-generation quarantine counter: per-host generation files
#: newer than the gang's agreed committed epoch, moved aside as
#: ``*.partial`` before restore.
PARTIAL_GAUGE = "cooc_checkpoint_partial_total"

#: Last commit's total bytes (npz + delta file) — the headline the
#: incremental plane exists to shrink.
COMMIT_BYTES_GAUGE = "cooc_checkpoint_commit_bytes"

#: Last commit's wall seconds (arrays snapshot to durable rename).
COMMIT_SECONDS_GAUGE = "cooc_checkpoint_commit_seconds"

#: Delta generations between the last written generation and its base
#: (0 = the last commit was a full base).
CHAIN_LEN_GAUGE = "cooc_checkpoint_delta_chain_len"

#: Ratio-triggered base rewrites (--checkpoint-compact-ratio).
COMPACTIONS_GAUGE = "cooc_checkpoint_compactions_total"

#: Ingest offset sections committed with checkpoint generations (the
#: wire side of the exactly-once boundary; incremented at the
#: ``offset_commit`` fault site).
OFFSET_COMMITS_GAUGE = "cooc_ingest_offset_commits_total"

#: Stats of this process's most recent :func:`save` — the journal
#: checkpoint record's source (read by ``job.checkpoint`` right after
#: the save returns; single writer thread per process).
LAST_COMMIT: "dict | None" = None


class CheckpointCorrupt(ValueError):
    """A checkpoint file failed to load or verify its digest."""


# -- naming ------------------------------------------------------------


def _legacy_path(directory: str, suffix: str) -> str:
    return os.path.join(directory, f"state{suffix}.npz")


def _gen_path(directory: str, suffix: str, gen: int) -> str:
    return os.path.join(directory, f"state{suffix}.{gen}.npz")


def _latest_path(directory: str, suffix: str) -> str:
    return os.path.join(directory, f"LATEST{suffix}")


def _epoch_path(directory: str, suffix: str, gen: int) -> str:
    return os.path.join(directory, f"EPOCH{suffix}.{gen}")


def _fsync_dir(directory: str) -> None:
    """fsync the directory itself so a just-committed rename survives
    power loss — ``os.replace`` alone only updates the in-cache
    directory entry; the journal flush that makes it durable needs an
    explicit fsync on the directory fd. Best-effort: a filesystem
    without directory fds (or a permission quirk) must not fail the
    checkpoint it is trying to harden."""
    try:
        fd = os.open(directory, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def chain_of(directory: str, suffix: str,
             gen: int) -> "tuple[int, list[int]]":
    """``(base_gen, delta_gens_ascending)`` for ``gen``, derived purely
    from the directory listing: a generation is incremental iff its
    ``delta<suffix>.<gen>.bin`` exists, and a delta generation's
    predecessor is always ``gen - 1`` (save only extends the chain when
    the newest on-disk generation is the dirty log's anchor)."""
    dset = set(deltalog.delta_generations(directory, suffix))
    chain = []
    g = gen
    while g in dset:
        chain.append(g)
        g -= 1
    chain.reverse()
    return g, chain


def chain_bytes(directory: str, suffix: str, base: int,
                chain: "list[int]") -> "tuple[int, int]":
    """``(base_bytes, delta_chain_bytes)`` for an already-derived chain
    (:func:`chain_of` — passed in so the caller's directory listing is
    not walked twice). Missing files count as 0 (the ratio then errs
    toward compaction, which is the safe direction)."""
    try:
        base_b = os.path.getsize(_gen_path(directory, suffix, base))
    except OSError:
        base_b = 0
    total = 0
    for g in chain:
        try:
            total += os.path.getsize(
                deltalog.delta_path(directory, suffix, g))
        except OSError:
            continue
    return base_b, total


def epoch_markers(directory: str, suffix: str) -> "list[int]":
    """Committed-epoch markers for this process suffix, newest first."""
    pat = re.compile(rf"^EPOCH{re.escape(suffix)}\.(\d+)$")
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted((int(m.group(1)) for m in map(pat.match, names) if m),
                  reverse=True)


def committed_generations(directory: str,
                          suffix: str) -> "list[tuple[int, str]]":
    """Restorable generations *committed* for this process suffix,
    newest first.

    Multi-host only (``suffix`` non-empty callers): a generation counts
    as committed iff its ``EPOCH<suffix>.<gen>`` marker exists — the
    marker is written only after the whole gang's files were durable,
    so a generation without one may be a torn global state. Directories
    with generation files but NO markers at all are legacy (pre-epoch)
    checkpoints and restore as before, with a warning.
    """
    gens = generations(directory, suffix)
    marked = set(epoch_markers(directory, suffix))
    if not marked:
        if gens:
            LOG.warning(
                "checkpoint dir %s has generations for suffix %r but no "
                "EPOCH markers (written by a pre-epoch-commit version); "
                "restoring without global-commit protection", directory,
                suffix)
        return gens
    return [(g, p) for g, p in gens if g in marked]


def newest_committed(directory: str, suffix: str) -> int:
    """Newest committed generation for this suffix, or -1 when none —
    the per-process input to the gang's restore vote.

    Chain-aware (ISSUE 12): an incremental generation only counts when
    its FULL delta chain is committed here — every generation from its
    base up must be present and epoch-marked, because a delta whose
    predecessor is a torn global state is itself unrestorable. Derived
    from directory listings alone (the vote must not open npz files)."""
    gens = committed_generations(directory, suffix)
    if not gens:
        return -1
    present = {g for g, _p in gens}
    dset = set(deltalog.delta_generations(directory, suffix))
    for g, _path in gens:
        cur = g
        while cur in dset and (cur - 1) in present:
            cur -= 1
        if cur not in dset:
            return g
        LOG.warning(
            "committed generation %d (suffix %r) has an incomplete "
            "delta chain (broken at %d) — not counting it for the "
            "restore vote", g, suffix, cur)
    return -1


def quarantine_uncommitted(directory: str, suffix: str,
                           above_gen: int) -> "list[int]":
    """Move this suffix's generation files newer than ``above_gen``
    aside as ``*.partial`` (and drop their markers, if any): the gang's
    restore vote agreed on ``above_gen``, so anything newer on this
    host is part of a torn global commit no host may restore. Returns
    the quarantined generation numbers."""
    out = []
    for gen, path in generations(directory, suffix):
        if gen <= above_gen:
            continue
        try:
            os.replace(path, path + ".partial")
        except OSError as exc:
            LOG.error("could not quarantine uncommitted generation %d "
                      "(%s): %s", gen, path, exc)
            continue
        dpath = deltalog.delta_path(directory, suffix, gen)
        if os.path.exists(dpath):
            # The generation's delta file is part of the same torn
            # global commit; quarantining it also detaches it from any
            # chain a directory listing would derive.
            try:
                os.replace(dpath, dpath + ".partial")
            except OSError:
                pass
        try:
            os.remove(_epoch_path(directory, suffix, gen))
        except OSError:
            pass
        out.append(gen)
        REGISTRY.gauge(
            PARTIAL_GAUGE,
            help="per-host checkpoint generations newer than the gang's "
                 "agreed epoch, moved aside as *.partial").add(1)
        LOG.warning("quarantined uncommitted checkpoint generation %d "
                    "(%s -> *.partial): the gang's committed epoch is %d",
                    gen, path, above_gen)
    if out:
        _update_latest(directory, suffix)
        _fsync_dir(directory)
    return out


def generations(directory: str, suffix: str) -> "list[tuple[int, str]]":
    """Restorable generations in ``directory`` for this process suffix,
    newest first, as ``(gen, path)``. A legacy un-numbered
    ``state<suffix>.npz`` (pre-generation format) appears as generation
    0, so old checkpoints keep restoring."""
    pat = re.compile(
        rf"^state{re.escape(suffix)}\.(\d+)\.npz$")
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = pat.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    legacy = _legacy_path(directory, suffix)
    if os.path.exists(legacy):
        out.append((0, legacy))
    out.sort(reverse=True)
    return out


def exists(job, directory: str) -> bool:
    """True when ``directory`` holds a checkpoint this job could restore
    (any generation, or the legacy un-numbered file; same per-process
    suffix scheme as :func:`save`)."""
    suffix = getattr(job.scorer, "process_suffix", "")
    return bool(generations(directory, suffix))


# -- integrity ---------------------------------------------------------


def compute_digest(arrays: "dict[str, np.ndarray]") -> str:
    """sha256 over every array's name, dtype, shape and bytes, in sorted
    name order — the payload the atomic rename commits, independent of
    zip-container details."""
    h = hashlib.sha256()
    for key in sorted(arrays):
        a = np.ascontiguousarray(arrays[key])
        h.update(key.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _load_verified(path: str) -> "dict[str, np.ndarray]":
    """Load ``path`` and verify its embedded digest.

    Raises :class:`CheckpointCorrupt` on any read failure (torn zip,
    truncated member) or digest mismatch. A file without a digest
    (written by a pre-digest version) loads with a warning — corruption
    detection is best-effort for legacy snapshots, not a restore veto.
    """
    try:
        with np.load(path) as data:
            arrays = {k: data[k] for k in data.files}
    except (MemoryError, OSError):
        # Environmental, not corruption: a transient EIO / fd exhaustion
        # / tight-memory load must not get a good snapshot quarantined —
        # propagate and let the supervisor's restart retry it.
        raise
    except Exception as exc:  # BadZipFile / zlib.error / ValueError ...
        raise CheckpointCorrupt(f"unreadable checkpoint {path}: {exc}")
    stored = arrays.pop("digest_sha256", None)
    if stored is None:
        LOG.warning("checkpoint %s predates integrity digests; restoring "
                    "unverified", path)
        return arrays
    expected = bytes(stored).decode()
    actual = compute_digest(arrays)
    if actual != expected:
        raise CheckpointCorrupt(
            f"checkpoint digest mismatch in {path}: stored {expected[:12]}…, "
            f"recomputed {actual[:12]}…")
    return arrays


def _update_latest(directory: str, suffix: str) -> None:
    """Point ``LATEST<suffix>`` at the newest surviving generation (or
    remove it when none survive) — kept fresh across quarantine and
    step-back so the operator breadcrumb never names a gone file."""
    gens = generations(directory, suffix)
    latest = _latest_path(directory, suffix)
    try:
        if not gens:
            os.remove(latest)
            return
        tmp = latest + ".tmp"
        with open(tmp, "w") as f:
            f.write(os.path.basename(gens[0][1]) + "\n")
        os.replace(tmp, latest)
    except OSError:
        pass  # the pointer is advisory; never fail recovery over it


def _quarantine(path: str, directory: str, suffix: str) -> None:
    """Move a failed-verification file aside as ``<path>.corrupt`` so the
    crash-restart loop cannot hit it again, and count it."""
    target = path + ".corrupt"
    try:
        os.replace(path, target)
    except OSError as exc:
        LOG.error("could not quarantine corrupt checkpoint %s: %s",
                  path, exc)
        return
    _update_latest(directory, suffix)
    REGISTRY.gauge(
        QUARANTINE_GAUGE,
        help="checkpoint files that failed verification, moved aside "
             "as *.corrupt").add(1)
    LOG.error("quarantined corrupt checkpoint %s -> %s", path, target)


def _quarantine_delta(dpath: str) -> None:
    """Move a failed-verification delta file aside as ``*.corrupt`` —
    same contract as the torn-npz path: the crash-restart loop cannot
    hit it again, the walk falls back one committed generation, and the
    quarantine is counted."""
    try:
        os.replace(dpath, dpath + ".corrupt")
    except OSError as exc:
        LOG.error("could not quarantine corrupt delta %s: %s", dpath, exc)
        return
    REGISTRY.gauge(
        QUARANTINE_GAUGE,
        help="checkpoint files that failed verification, moved aside "
             "as *.corrupt").add(1)
    LOG.error("quarantined corrupt checkpoint delta %s -> *.corrupt",
              dpath)


def _decode_codec(data: "dict[str, np.ndarray]", meta: dict) -> None:
    """Decode ``ckpt_codec``-packed blobs back to canonical arrays in
    place (state/wire.py delta+varint generation format). Absent record
    = pre-codec file, restored through the raw path unchanged."""
    codec = meta.get("ckpt_codec")
    if not codec:
        return
    from .wire import decode_sorted_u64, decode_varint

    if codec.get("v") != 1:
        raise ValueError(
            f"unknown checkpoint codec version {codec.get('v')!r} "
            f"(written by a newer framework?)")
    for name, (spec, count) in codec["arrays"].items():
        blob = data.pop(name + "__packed")
        if spec == "sdv":
            data[name] = decode_sorted_u64(blob, count)
        elif spec == "v":
            data[name] = decode_varint(blob, count).astype(np.int64)
        else:
            raise ValueError(
                f"unknown checkpoint array codec {spec!r} for {name}")


#: Canonical big-blob keys an incremental generation omits from its npz
#: (reconstructed from base + delta replay instead).
_BLOB_KEYS = ("rows_key", "rows_cnt", "mh_rows_key", "mh_local_cnt",
              "row_sums", "observed", "mh_local_shards")
_LATEST_KEYS = ("latest_items", "latest_offsets", "latest_others",
                "latest_scores")


def _resolve_chain(directory: str, suffix: str, top_gen: int,
                   top_meta: dict,
                   quarantine: bool = True) -> "tuple[dict, tuple, dict]":
    """Reconstruct an incremental generation's big arrays: walk the
    delta files down to the full base, then replay them oldest-first
    over the base blob.

    Verification chain: the top npz's digest was already checked and
    its meta commits the top delta's sha256; every delta file carries
    its own sha256 trailer plus ``gen``/``prev``/``base`` cross-links
    (a delta generation's predecessor is always ``gen - 1`` and every
    chain member records the same base), and the base npz verifies its
    own digest. Intermediate npzs are deliberately NOT opened — their
    arrays are superseded by the top generation's, and under the
    commit protocol a delta file at a chain position can only be the
    one its generation's npz committed (orphans are overwritten or
    removed by the next save, quarantine/step-back move npz and delta
    together), so re-reading each one's meta would cost a full
    inflate+digest per generation for no additional integrity.

    Raises :class:`CheckpointCorrupt` on any broken link; provably
    corrupt files are quarantined (``*.corrupt``) so the restart loop
    cannot hit them again, while MISSING links quarantine nothing (the
    walk simply falls back past the gap). ``quarantine=False`` makes
    the whole resolve READ-ONLY (corrupt files are skipped, never
    renamed) — the serving-replica bootstrap path, which must not
    mutate a live writer's directory.
    """
    deltas = []
    rec = top_meta["ckpt_delta"]
    base_gen = int(rec["base"])
    top_sha = rec.get("sha256")
    cur_gen = top_gen
    while cur_gen > base_gen:
        dpath = deltalog.delta_path(directory, suffix, cur_gen)
        try:
            with open(dpath, "rb") as f:
                raw = f.read()
        except FileNotFoundError as exc:
            # Missing = broken link (fall back past the gap); any other
            # OSError is environmental and propagates for the
            # supervisor's restart to retry (same policy as
            # _load_verified).
            raise CheckpointCorrupt(
                f"chain broken at generation {cur_gen}: missing delta "
                f"file ({exc})")
        if cur_gen == top_gen \
                and hashlib.sha256(raw).hexdigest() != top_sha:
            if quarantine:
                _quarantine_delta(dpath)
            raise CheckpointCorrupt(
                f"delta for generation {cur_gen} does not match the "
                f"sha256 its generation meta committed")
        try:
            d = deltalog.decode_delta(raw)
        except deltalog.DeltaCorrupt as exc:
            if quarantine:
                _quarantine_delta(dpath)
            raise CheckpointCorrupt(
                f"corrupt delta for generation {cur_gen}: {exc}")
        if d.gen != cur_gen or d.prev != cur_gen - 1 \
                or d.base != base_gen:
            if quarantine:
                _quarantine_delta(dpath)
            raise CheckpointCorrupt(
                f"delta header ({d.gen}/{d.prev}/{d.base}) does not "
                f"link generation {cur_gen} to base {base_gen}")
        deltas.append(d)
        cur_gen -= 1
    ppath = _gen_path(directory, suffix, base_gen)
    try:
        base_data = _load_verified(ppath)
    except CheckpointCorrupt:
        if quarantine:
            _quarantine(ppath, directory, suffix)
        raise
    except FileNotFoundError as exc:
        # Missing link: fall back past it. Other OSErrors are
        # environmental and propagate (supervisor retries).
        raise CheckpointCorrupt(
            f"chain broken at generation {base_gen}: {exc}")
    if "meta_json" not in base_data:
        raise CheckpointCorrupt(
            f"chain base generation {base_gen} has no embedded meta")
    pmeta = json.loads(bytes(base_data["meta_json"]).decode())
    if pmeta.get("ckpt_delta") is not None:
        raise CheckpointCorrupt(
            f"chain base generation {base_gen} is itself incremental "
            f"— the chain structure is inconsistent")
    _decode_codec(base_data, pmeta)
    blob = {k: base_data[f"scorer_{k}"] for k in _BLOB_KEYS
            if f"scorer_{k}" in base_data}
    latest = tuple(base_data[k] for k in _LATEST_KEYS)
    aux = {k: base_data[k] for k in ("item_vocab", "user_vocab")}
    if "hist" in base_data:
        aux.update({k: base_data[k]
                    for k in ("hist", "hist_len", "total", "draws")})
    state = deltalog.ChainState(blob, latest,
                                n_shards=deltas[0].n_shards, aux=aux)
    try:
        state.replay(list(reversed(deltas)))  # oldest first, one pass
    except deltalog.DeltaCorrupt as exc:
        raise CheckpointCorrupt(f"delta replay failed: {exc}")
    return state.close()


def step_back(directory: str, suffix: str = "") -> "int | None":
    """Retire the newest generation (crash-loop breaker: the supervisor
    calls this when restarts keep dying post-restore, so the next
    attempt restores the previous generation). The file is kept as
    ``*.rolledback`` for forensics. Returns the retired generation, or
    ``None`` when there is no older generation to fall back to."""
    gens = generations(directory, suffix)
    if len(gens) < 2:
        return None
    gen, path = gens[0]
    os.replace(path, path + ".rolledback")
    dpath = deltalog.delta_path(directory, suffix, gen)
    if os.path.exists(dpath):
        # Retire the generation's delta with it: the remaining chain
        # (base .. gen-1) stays intact, so stepping back from a delta
        # generation lands on a restorable prefix.
        try:
            os.replace(dpath, dpath + ".rolledback")
        except OSError:
            pass
    _update_latest(directory, suffix)
    LOG.warning("crash-loop breaker: stepped back checkpoint generation "
                "%d (%s -> *.rolledback); next restore uses generation %d",
                gen, path, gens[1][0])
    return gen


def _sweep_aged_quarantine(directory: str, suffix: str,
                           oldest_kept: int) -> None:
    """Delete quarantine files (``*.corrupt`` digest failures and
    ``*.partial`` uncommitted-epoch fallout) whose generation has aged
    out of the retain window (generation < ``oldest_kept``). The legacy
    un-numbered ``state<suffix>.npz.corrupt`` counts as generation 0.
    Called by :func:`save` alongside generation retention so the two
    windows can never drift apart."""
    pat = re.compile(
        rf"^(?:state{re.escape(suffix)}\.(\d+)\.npz"
        rf"|delta{re.escape(suffix)}\.(\d+)\.bin)"
        rf"\.(?:corrupt|partial)$")
    legacy = os.path.basename(_legacy_path(directory, suffix)) + ".corrupt"
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        m = pat.match(name)
        gen = (int(m.group(1) or m.group(2)) if m
               else (0 if name == legacy else None))
        if gen is None or gen >= oldest_kept:
            continue
        try:
            os.remove(os.path.join(directory, name))
            LOG.info("aged out quarantined checkpoint %s (retain window "
                     "starts at generation %d)", name, oldest_kept)
        except OSError:
            continue


def _sweep_orphan_tmps(directory: str) -> None:
    """Delete ``*.tmp`` snapshots abandoned by a crash between
    ``mkstemp`` and ``os.replace``. Age-gated: a fresh tmp may be a
    live writer's (multi-host processes share the directory)."""
    now = time.time()
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        if not name.endswith(".tmp"):
            continue
        p = os.path.join(directory, name)
        try:
            if now - os.path.getmtime(p) > TMP_SWEEP_AGE_S:
                os.remove(p)
                LOG.info("swept orphaned checkpoint tmp %s", p)
        except OSError:
            continue  # raced with another sweeper or the owner's rename


# -- save / restore ----------------------------------------------------


def save(job, directory: str, source=None) -> str:
    """Write a checkpoint of ``job`` (and optionally its file source)."""
    t0 = time.monotonic()
    os.makedirs(directory, exist_ok=True)
    _sweep_orphan_tmps(directory)
    arrays = {}
    meta = {
        "seed": job.config.seed,
        "skip_cuts": job.config.skip_cuts,
        "item_cut": job.config.item_cut,
        "user_cut": job.config.user_cut,
        "top_k": job.config.top_k,
        "window_slide": job.config.window_slide,
        "window_millis": job.config.window_millis,
        "windows_fired": job.windows_fired,
        "emissions": job.emissions,
        # A deferred-results scorer materializes each row once from its
        # device table however many windows rescored it, so its emission
        # count is not comparable with the rescored-rows counter. Record
        # the count a PER-WINDOW backend should resume with (the rescored
        # total keeps its drain invariant balanced) alongside the real
        # one, and let restore pick by the restoring scorer's mode.
        "emissions_per_window_resume": (
            job.counters.get(RESCORED_ITEMS)
            if getattr(job.scorer, "defer_results", False)
            else job.emissions),
        "max_ts_seen": job.engine.max_ts_seen,
        "counters": job.counters.as_dict(),
    }

    arrays["item_vocab"] = job.item_vocab.checkpoint_state()
    arrays["user_vocab"] = job.user_vocab.checkpoint_state()
    arrays["item_cut_counts"] = job.item_cut.counts

    s = job.sampler
    if hasattr(s, "checkpoint_state"):  # reservoir samplers (serial or
        # partitioned, both in the serial global-dense-id layout); the
        # sliding sampler is stateless
        arrays.update(s.checkpoint_state(len(job.user_vocab)))

    # In-flight window buffers, flattened.
    starts, users_l, items_l, ts_l = [], [], [], []
    for start, chunks in job.engine._buffers.items():
        for (u, i, t) in chunks:
            starts.append(np.full(len(u), start, dtype=np.int64))
            users_l.append(u)
            items_l.append(i)
            ts_l.append(t)
    if starts:
        arrays["buf_start"] = np.concatenate(starts)
        arrays["buf_users"] = np.concatenate(users_l)
        arrays["buf_items"] = np.concatenate(items_l)
        arrays["buf_ts"] = np.concatenate(ts_l)

    for key, val in job.scorer.checkpoint_state().items():
        arrays[f"scorer_{key}"] = val

    if source is not None:
        meta["source"] = source.checkpoint_state()
        # First-class ingest-offset section (io/source.Source
        # .offsets_state): per-partition byte/record offsets plus the
        # rewrite guards, committed atomically with the state under the
        # same epoch protocol — the wire and the state recover from the
        # SAME boundary (the reference's core exactly-once guarantee).
        meta["ingest_offsets"] = source.offsets_state()

    # Latest emitted top-K (the consumable result state).
    lat_items, lat_offsets, lat_others, lat_scores = [], [0], [], []
    for item in sorted(job.latest):
        lat_items.append(item)
        top = job.latest[item]
        lat_others.extend(j for j, _ in top)
        lat_scores.extend(sc for _, sc in top)
        lat_offsets.append(len(lat_others))
    arrays["latest_items"] = np.asarray(lat_items, dtype=np.int64)
    arrays["latest_offsets"] = np.asarray(lat_offsets, dtype=np.int64)
    arrays["latest_others"] = np.asarray(lat_others, dtype=np.int64)
    arrays["latest_scores"] = np.asarray(lat_scores, dtype=np.float64)

    # Multi-host runs checkpoint per process (each host owns a row block
    # and its partition of the results); the scorer supplies the suffix.
    suffix = getattr(job.scorer, "process_suffix", "")
    gens = generations(directory, suffix)
    # Generation numbering continues past a gang rescale: a worker slot
    # that did not exist in the previous topology has no files under
    # its own suffix, but its first save must still land ABOVE the
    # restored generation — the epoch barrier's name is the generation
    # number, so diverging per-suffix counters would wedge the gang.
    # restore()/restore_rescaled() leave the floor on the job.
    newest = gens[0][0] if gens else 0
    newest = max(newest, int(getattr(job, "_ckpt_gen_floor", 0)))
    gen = newest + 1
    prev = gens[0][0] if gens else None
    if suffix:
        # Rescale-tagged generation meta (robustness/autoscale.py): the
        # topology that WROTE this generation, so forensics (and the
        # meta.json sidecar) can tell which process layout a mixed
        # directory's files belong to. The restore-side source of truth
        # stays the epoch markers (the vote must not open npz files).
        meta["gang_topology"] = {
            "processes": int(job.config.num_processes or 1),
            "shards": int(getattr(job.scorer, "n_shards", 1)),
        }
        if getattr(job, "_rescaled_from", None):
            meta["rescaled_from"] = int(job._rescaled_from)

    # Incremental generation decision (--checkpoint-incremental): write
    # a row-delta file instead of the full slab when (a) the store's
    # dirty log is armed and anchored at the newest on-disk generation
    # (anything else — fresh store, foreign files — forces a base), (b)
    # the log did not overflow to all-dirty, and (c) the existing chain
    # is still under the compaction ratio. The big arrays are popped
    # from the npz BEFORE the blob codec runs, so an incremental npz
    # carries only the small state (vocabs, cuts, sampler, buffers).
    delta_bytes = None
    delta_file = deltalog.delta_path(directory, suffix, gen)
    chain_len = 0
    store = getattr(job.scorer, "store", None)
    log = getattr(store, "ckpt_dirty", None) if store is not None else None
    tracker = getattr(job, "_ckpt_dirty", None)
    if (log is not None and tracker is not None
            and getattr(job.config, "checkpoint_incremental", False)
            and prev is not None and log.anchor_gen == prev
            and tracker.users.anchor_gen == prev):
        dirty, all_dirty = log.peek()
        dirty_users, all_dirty_u = tracker.users.peek()
        base, chain = chain_of(directory, suffix, prev)
        base_b, chain_b = chain_bytes(directory, suffix, base, chain)
        ratio = float(getattr(job.config, "checkpoint_compact_ratio",
                              0.5))
        if all_dirty or all_dirty_u:
            LOG.info("incremental checkpoint: dirty log overflowed — "
                     "writing a full base at generation %d", gen)
        elif base_b <= 0 or not os.path.exists(
                _gen_path(directory, suffix, base)):
            LOG.warning("incremental checkpoint: base generation %d is "
                        "missing — writing a full base at generation %d",
                        base, gen)
        elif chain_b > ratio * base_b:
            # Ratio-triggered compaction: rewrite a fresh base; the old
            # chain ages out under --checkpoint-retain.
            REGISTRY.gauge(
                COMPACTIONS_GAUGE,
                help="ratio-triggered full-base rewrites "
                     "(--checkpoint-compact-ratio)").add(1)
            LOG.info("incremental checkpoint: delta chain %d B vs base "
                     "%d B exceeded --checkpoint-compact-ratio %.3g — "
                     "compacting to a full base at generation %d",
                     chain_b, base_b, ratio, gen)
        else:
            blob = {}
            for k in ("rows_key", "rows_cnt", "mh_rows_key",
                      "mh_local_cnt", "row_sums"):
                kk = f"scorer_{k}"
                if kk in arrays:
                    blob[k] = arrays.pop(kk)
            blob["observed"] = arrays["scorer_observed"]
            if "scorer_mh_local_shards" in arrays:
                blob["mh_local_shards"] = arrays["scorer_mh_local_shards"]
            latest_cols = (arrays.pop("latest_items"),
                           arrays.pop("latest_offsets"),
                           arrays.pop("latest_others"),
                           arrays.pop("latest_scores"))
            # Job-level row-indexed state rides the delta too: the
            # reservoir table (dirty users) and the vocab appends.
            aux = {"item_vocab": arrays.pop("item_vocab"),
                   "user_vocab": arrays.pop("user_vocab"),
                   "prev_item_len": tracker.item_vocab_len,
                   "prev_user_len": tracker.user_vocab_len}
            if "hist" in arrays:
                aux.update(dirty_users=dirty_users,
                           hist=arrays.pop("hist"),
                           hist_len=arrays.pop("hist_len"),
                           total=arrays.pop("total"),
                           draws=arrays.pop("draws"))
            rec = deltalog.extract_delta(
                blob, latest_cols, dirty,
                job.item_vocab.to_external_batch(dirty),
                gen=gen, prev=prev, base=base,
                n_shards=getattr(job.scorer, "n_shards", 0), aux=aux)
            # The ingest-offset section rides the delta header too: a
            # consumer tailing the delta log (read_delta_stream) sees
            # the wire position each generation committed, without
            # opening the npz meta.
            rec.ingest_offsets = meta.get("ingest_offsets")
            delta_bytes = deltalog.encode_delta(rec)
            chain_len = len(chain) + 1
            meta["ckpt_delta"] = {
                "v": 1, "base": base, "prev": prev,
                "sha256": hashlib.sha256(delta_bytes).hexdigest(),
                "bytes": len(delta_bytes), "rows": int(len(dirty)),
            }

    # Checkpoint blob codec (state/wire.py): the sorted cell-key array
    # delta+varint-encodes to a fraction of its raw bytes (sorted unique
    # keys -> tiny deltas, before the npz's own deflate even runs), and
    # the count arrays varint-pack the same way. The codec is recorded in
    # the embedded meta, so restore self-describes; a file without the
    # record (pre-codec generations, or --wire-format raw) restores
    # through the unchanged raw path.
    from .wire import checkpoint_codec, encode_sorted_u64, encode_varint

    if checkpoint_codec(
            getattr(job.config, "wire_format", "raw")) == "packed":
        packed = {}
        for name, arr in arrays.items():
            arr = np.asarray(arr)
            if arr.ndim != 1 or arr.dtype != np.int64 or not len(arr):
                continue
            if name.endswith("rows_key") or name.endswith("tier_rows"):
                # Sorted nonnegative id arrays: cell keys and the
                # tiered store's stamped-row ids (the latter is
                # O(touched-ever rows) and rides EVERY incremental npz,
                # so raw int64 would put a vocab-scale floor under the
                # per-generation commit bytes).
                try:
                    packed[name] = ("sdv", len(arr), encode_sorted_u64(arr))
                except ValueError:
                    continue  # not sorted/nonnegative: stays raw
            elif name.endswith("_cnt") and int(arr.min()) >= 0:
                packed[name] = ("v", len(arr), encode_varint(arr))
        if packed:
            meta["ckpt_codec"] = {
                "v": 1,
                "arrays": {name: [spec, count]
                           for name, (spec, count, _b) in packed.items()}}
            for name, (_spec, _count, blob) in packed.items():
                del arrays[name]
                arrays[name + "__packed"] = blob

    # The meta scalars ride INSIDE the .npz so one atomic rename commits
    # the whole checkpoint — a crash between two file replacements would
    # otherwise leave a mixed-generation (arrays N, meta N-1) state that
    # restores without error and silently double-ingests. The sidecar
    # meta.json is written afterwards purely for human inspection.
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)

    # Normalize before digesting: the digest must hash exactly the
    # arrays savez will store (asarray-converted), not pre-conversion
    # Python objects.
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    arrays["digest_sha256"] = np.frombuffer(
        compute_digest(arrays).encode(), dtype=np.uint8)

    if faults.PLAN is not None:
        faults.PLAN.fire("checkpoint_pre_write", seq=job.windows_fired)
    if delta_bytes is not None:
        # Delta file first, npz second: the npz rename is THE commit
        # point (its meta records the delta's sha256), so a crash here
        # leaves an orphan delta the next save overwrites or sweeps —
        # never a generation that references a missing delta.
        fd, dtmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        os.close(fd)
        with open(dtmp, "wb") as f:
            f.write(delta_bytes)
        os.replace(dtmp, delta_file)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    os.close(fd)
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
    npz_path = _gen_path(directory, suffix, gen)
    if faults.PLAN is not None:
        faults.PLAN.fire("checkpoint_post_write", seq=job.windows_fired,
                         path=tmp, rename_to=npz_path)
    os.replace(tmp, npz_path)
    if delta_bytes is None and os.path.exists(delta_file):
        # A full generation re-using a crashed predecessor's number must
        # not leave that stale delta around: chain structure is derived
        # from delta-file presence alone.
        try:
            os.remove(delta_file)
        except OSError:
            pass
    if log is not None:
        # The generation is renamed into place: rows accumulated so far
        # are durable (full or delta either way); restart the dirty log
        # anchored here. A crash before this line only widens the next
        # delta — never narrows it.
        log.commit(gen)
    if tracker is not None:
        tracker.commit(gen, len(job.item_vocab), len(job.user_vocab))
    # Atomic LATEST pointer: an operator breadcrumb only — restore
    # always directory-scans (ordering by generation number), so the
    # pointer is advisory, never load-bearing. Quarantine and step-back
    # refresh it so it never names a gone file.
    _update_latest(directory, suffix)
    # The offset_commit site marks the wire side of the same boundary:
    # the generation (ingest offsets included) is renamed into place —
    # a crash here must replay the wire and the state from the SAME
    # point, which the chaos capstone pins bit-identically.
    if source is not None:
        if faults.PLAN is not None:
            faults.PLAN.fire("offset_commit", seq=gen)
        REGISTRY.gauge(
            OFFSET_COMMITS_GAUGE,
            help="ingest offset sections committed with checkpoint "
                 "generations this run").add(1)
    # The ckpt_commit site sits exactly inside the torn-pointer window:
    # the generation file is renamed into place but neither the
    # directory entry nor the gang's epoch marker is durable yet — a
    # crash here is the power-loss shape the directory fsync below (and,
    # multi-host, the epoch commit) exists to contain. seq = generation,
    # so chaos specs address "the generation-N commit", not a window.
    if faults.PLAN is not None:
        faults.PLAN.fire("ckpt_commit", seq=gen)
    _fsync_dir(directory)
    if suffix:
        # Multi-host epoch commit: my generation file is durable; wait
        # until EVERY host's is (all processes checkpoint at the same
        # fired-window ordinal, so this barrier is deterministic), then
        # mark the generation committed on this host. A crash anywhere
        # before the marker rename leaves the generation uncommitted
        # here — the gang's restore vote then drags every host back to
        # the previous epoch (gang.agree_restore_generation).
        from ..parallel.distributed import gang_barrier

        gang_barrier(f"ckpt/{gen}")
        epoch_tmp = _epoch_path(directory, suffix, gen) + ".tmp"
        with open(epoch_tmp, "w") as f:
            # "<gen> <processes>": the writing topology rides in the
            # marker so the autoscaler's topology-aware restore vote
            # (gang.agree_restore_topology) can tell how many markers a
            # globally-committed generation needs — without opening any
            # npz. Pre-autoscale readers only split the first token.
            f.write(f"{gen} {int(job.config.num_processes or 1)}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(epoch_tmp, _epoch_path(directory, suffix, gen))
        _fsync_dir(directory)
        REGISTRY.gauge(
            EPOCH_GAUGE,
            help="newest checkpoint generation whose gang epoch marker "
                 "this process committed (multi-host only)").set(gen)
    # Retention: keep the newest N generations (quarantined/rolled-back
    # files keep their renamed forms and are not counted) — and, chain-
    # aware, everything the oldest kept generation still chains
    # through: deleting a base (or an intermediate delta) would orphan
    # every retained generation built on it. Epoch markers age out with
    # their generation files.
    retain = max(1, getattr(job.config, "checkpoint_retain", 3))
    survivors = generations(directory, suffix)
    kept = survivors[:retain]
    floor = kept[-1][0] if kept else 0
    if kept:
        base_floor, _chain = chain_of(directory, suffix, floor)
        floor = min(floor, base_floor)
    for old_gen, old_path in survivors[retain:]:
        if old_gen >= floor:
            continue  # a retained generation's chain passes through it
        try:
            os.remove(old_path)
        except OSError:
            pass
        try:
            os.remove(deltalog.delta_path(directory, suffix, old_gen))
        except OSError:
            pass
        if suffix:
            try:
                os.remove(_epoch_path(directory, suffix, old_gen))
            except OSError:
                pass
    # Quarantined *.corrupt files beyond the retain window age out too:
    # they exist for operator forensics on RECENT generations, and
    # without a sweep a long-running crashy job accumulates them
    # forever. A corrupt generation still inside the window is kept —
    # its forensics are still current.
    _sweep_aged_quarantine(directory, suffix, oldest_kept=floor)
    REGISTRY.gauge(
        GENERATION_GAUGE,
        help="checkpoint generation last written or restored").set(gen)
    # Commit accounting (the headline the incremental plane shrinks):
    # total committed bytes, wall seconds, and the chain depth behind
    # the written generation — gauges, the journal checkpoint record
    # and /healthz all read these.
    commit_bytes = 0
    try:
        commit_bytes = os.path.getsize(npz_path)
    except OSError:
        pass
    if delta_bytes is not None:
        commit_bytes += len(delta_bytes)
    commit_seconds = time.monotonic() - t0
    REGISTRY.gauge(
        COMMIT_BYTES_GAUGE,
        help="bytes committed by the last checkpoint generation "
             "(npz + delta file)").set(commit_bytes)
    REGISTRY.gauge(
        COMMIT_SECONDS_GAUGE,
        help="wall seconds of the last checkpoint commit").set(
            commit_seconds)
    REGISTRY.gauge(
        CHAIN_LEN_GAUGE,
        help="delta generations between the last written checkpoint "
             "and its full base (0 = full)").set(chain_len)
    global LAST_COMMIT
    LAST_COMMIT = {
        "gen": gen,
        "kind": "delta" if delta_bytes is not None else "full",
        "bytes": commit_bytes,
        "seconds": commit_seconds,
        "chain_len": chain_len,
    }
    meta_tmp = os.path.join(directory, f"meta{suffix}.json.tmp")
    with open(meta_tmp, "w") as f:
        json.dump(meta, f)
    os.replace(meta_tmp, os.path.join(directory, f"meta{suffix}.json"))
    return npz_path


def restore(job, directory: str, source=None) -> None:
    """Restore ``job`` (constructed with the same Config) from the newest
    checkpoint generation that verifies.

    Fallback walk: generations newest-to-oldest, ordered by the
    generation number in the filename (the ``LATEST`` pointer is an
    operator breadcrumb, not an input). A generation that fails
    to load or verify is quarantined as ``*.corrupt`` and the walk
    continues — a torn latest checkpoint costs one generation, not a
    crash loop. Incremental generations verify their WHOLE chain (base
    npz + every delta, digests and header cross-links); a corrupt delta
    is quarantined like a torn npz and the walk falls back exactly one
    committed generation. Config mismatches and legacy-format errors
    are operator errors, not corruption: they raise immediately without
    quarantining.
    """
    suffix = getattr(job.scorer, "process_suffix", "")
    gens = generations(directory, suffix)
    if not gens:
        raise FileNotFoundError(
            f"no checkpoint for suffix {suffix!r} in {directory}")
    data = None
    restored_gen = None
    for gen, path in gens:
        try:
            data = _load_verified(path)
        except FileNotFoundError:
            # An earlier chain walk may have quarantined this very
            # generation (the gens list is a snapshot): skip the stale
            # entry rather than crash the whole restore over it.
            LOG.warning("checkpoint generation %d vanished mid-walk "
                        "(quarantined by a chain verification?); "
                        "skipping", gen)
            continue
        except CheckpointCorrupt as exc:
            LOG.error("checkpoint generation %d failed verification: %s",
                      gen, exc)
            _quarantine(path, directory, suffix)
            continue
        if "meta_json" in data:
            probe = json.loads(bytes(data["meta_json"]).decode())
            if probe.get("ckpt_delta"):
                # Incremental generation: reconstruct the big arrays
                # from base + delta replay; the merged dict is exactly
                # what a full generation would have held, so everything
                # downstream is format-agnostic.
                try:
                    blob, latest, aux = _resolve_chain(
                        directory, suffix, gen, probe)
                except CheckpointCorrupt as exc:
                    LOG.error("checkpoint generation %d delta chain "
                              "failed: %s", gen, exc)
                    data = None
                    continue
                for k, v in blob.items():
                    data[f"scorer_{k}"] = v
                for k, v in zip(_LATEST_KEYS, latest):
                    data[k] = v
                data.update(aux)
        restored_gen = gen
        break
    if data is None:
        raise CheckpointCorrupt(
            f"no checkpoint generation in {directory} verifies "
            f"(walked all {len(gens)})")
    _apply_restored(job, data, restored_gen, source=source)
    if restored_gen != gens[0][0]:
        LOG.warning("restored checkpoint generation %d (newest was %d; "
                    "newer generations failed verification)",
                    restored_gen, gens[0][0])


def _apply_restored(job, data: "dict[str, np.ndarray]", restored_gen: int,
                    source=None, own_rows_only: bool = False,
                    anchor_dirty: bool = True) -> None:
    """Land a fully-resolved checkpoint ``data`` dict (codec decoded,
    delta chains replayed) in ``job``.

    ``own_rows_only`` filters the restored ``latest`` table down to the
    rows this process's shards own under the CURRENT topology — the
    cross-topology (gang rescale) path, where the merged table holds
    every writer's partition and the multi-host emission contract says
    each process may only ever print its own. ``anchor_dirty=False``
    leaves the incremental dirty log un-anchored so the next save
    writes a full base (a delta against another topology's chain would
    be key-aligned to the wrong shard layout).
    """
    # Meta comes from inside the npz (the atomic commit point); the
    # meta.json sidecar is informational only and may lag by a crash.
    if "meta_json" not in data:
        raise ValueError(
            f"incompatible checkpoint format: no embedded meta_json "
            "(written by a pre-atomic-commit version of this framework) "
            "— re-checkpoint with the current version")
    meta = json.loads(bytes(data["meta_json"]).decode())
    # Decode the ckpt_codec-packed blobs back to the canonical arrays
    # before any consumer sees them (no-op for incremental generations:
    # their big arrays were reconstructed above, and nothing else packs).
    _decode_codec(data, meta)
    # window_millis included (a real gap the ckpt-format-roundtrip rule
    # surfaced): restoring buffered in-flight events into a job with a
    # different window size would silently re-window them.
    for key in ("seed", "skip_cuts", "item_cut", "user_cut", "top_k",
                "window_slide", "window_millis"):
        if getattr(job.config, key) != meta.get(key):
            raise ValueError(
                f"checkpoint config mismatch for {key}: "
                f"{meta.get(key)} != {getattr(job.config, key)}")

    job.item_vocab.restore_state(data["item_vocab"])
    job.user_vocab.restore_state(data["user_vocab"])
    job.item_cut.counts = data["item_cut_counts"].copy()

    s = job.sampler
    if hasattr(s, "restore_state") and "hist" in data:
        st = {k: data[k] for k in ("hist", "hist_len", "total", "draws")}
        if "sampler_part" in data:
            # Partition-sampled snapshots hold only the writing process's
            # users; a non-partitioned sampler would silently restore
            # zeroed reservoirs for everyone else.
            if not getattr(s, "process_partition", False):
                raise ValueError(
                    "checkpoint was written with --partition-sampling — "
                    "restore with the same flag and process layout")
            st["sampler_part"] = data["sampler_part"]
        s.restore_state(st, len(job.user_vocab))

    job.engine.max_ts_seen = meta["max_ts_seen"]
    job.engine._buffers.clear()
    if "buf_start" in data:
        starts = data["buf_start"]
        for start in np.unique(starts):
            sel = starts == start
            job.engine._buffers[int(start)] = [
                (data["buf_users"][sel], data["buf_items"][sel],
                 data["buf_ts"][sel])]

    job.scorer.restore_state(
        {k[len("scorer_"):]: v for k, v in data.items()
         if k.startswith("scorer_")})

    job.windows_fired = meta["windows_fired"]
    job.emissions = (meta["emissions"]
                     if getattr(job.scorer, "defer_results", False)
                     else meta.get("emissions_per_window_resume",
                                   meta["emissions"]))
    job.counters.replace_all(meta["counters"])

    # The store keeps dense ids; the .npz holds external ids (the public
    # result shape), so map back through the already-restored vocab.
    # Cross-topology restores filter by NEW ownership: the merged table
    # holds every old writer's partition, and each process may only
    # ever emit the rows its shards own.
    owned = None
    if own_rows_only:
        local = getattr(job.scorer, "local_shard_ids", None)
        if local is not None:
            owned = (set(local), int(job.scorer.n_shards))
    job.latest.clear()
    items = data["latest_items"]
    offsets = data["latest_offsets"]
    to_dense = job.item_vocab.to_dense
    for pos, item in enumerate(items.tolist()):
        dense = to_dense(item)
        if owned is not None and dense % owned[1] not in owned[0]:
            continue
        lo, hi = int(offsets[pos]), int(offsets[pos + 1])
        top = list(zip(
            (to_dense(j) for j in data["latest_others"][lo:hi].tolist()),
            data["latest_scores"][lo:hi].tolist()))
        job.latest.set_row(dense, top)

    if source is not None:
        # Offsets first: the section's format tag is the cross-format
        # guard, and a checkpoint written by the other --source-format
        # must fail with the clean launch error before the legacy
        # marker restore trips over the foreign marker shape.
        if "ingest_offsets" in meta:
            # The wire resumes from the same committed boundary as the
            # state: per-partition offsets, rewrite guards and the
            # rotation cursor (io/source.Source.restore_offsets).
            source.restore_offsets(meta["ingest_offsets"])
        else:
            LOG.warning(
                "checkpoint generation %d predates the ingest-offset "
                "section: offsets absent, replaying from source markers "
                "(resume is marker-exact but unguarded against in-flight "
                "file rewrites)", restored_gen)
        if "source" in meta:
            source.restore_state(meta["source"])
    # Anchor the incremental dirty log at the restored generation: the
    # in-memory state now equals that generation exactly, so rows
    # touched from here on are precisely "dirty since restored_gen" and
    # the next save may extend its chain. Cross-topology restores skip
    # the anchor on purpose — the first post-rescale save must write a
    # FULL base (a delta would be key-aligned per the OLD shard layout).
    if anchor_dirty:
        store = getattr(job.scorer, "store", None)
        log = (getattr(store, "ckpt_dirty", None)
               if store is not None else None)
        if log is not None:
            log.commit(restored_gen)
        tracker = getattr(job, "_ckpt_dirty", None)
        if tracker is not None:
            tracker.commit(restored_gen, len(job.item_vocab),
                           len(job.user_vocab))
    # Generation floor for save(): a rescaled-in worker slot has no
    # files under its own suffix, but its first save must still number
    # past the restored generation (the epoch barrier is named by it).
    job._ckpt_gen_floor = int(restored_gen)
    REGISTRY.gauge(
        GENERATION_GAUGE,
        help="checkpoint generation last written or restored").set(
            restored_gen)


def merge_ingest_offsets(sections: "list", writers: int) -> "dict | None":
    """Merge per-writer ``ingest_offsets`` sections across a rescale —
    the wire-plane analogue of :func:`~.store.merge_mh_cells`: each
    partition's authoritative copy comes from its OWNING writer under
    the old topology (``index % writers``, the ``parallel/`` modular
    ownership idiom), and every other writer's replicated copy is
    cross-checked against it. Ingest is deterministic and replicated,
    so agreement is the invariant; on disagreement the conservative
    minimum entry wins (re-reading a suffix beats skipping one) with a
    loud warning. The round-robin cursor is replicated too — a cursor
    disagreement resets the rotation alongside the same warning."""
    sections = [s for s in sections if s]
    if not sections:
        return None
    merged = dict(sections[0])
    if merged.get("format") != "partitioned":
        # Files-format (or unknown) sections are replicated whole;
        # writer 0's copy stands for the gang.
        return merged
    all_names = sorted(set().union(
        *[set(s.get("partitions") or {}) for s in sections]))
    partitions = {}
    for idx, name in enumerate(all_names):
        entries = [e for e in ((s.get("partitions") or {}).get(name)
                               for s in sections) if e is not None]
        owner = idx % max(1, writers)
        chosen = ((sections[owner].get("partitions") or {}).get(name)
                  if owner < len(sections) else None) or entries[0]
        if any(int(e.get("byte_offset", 0)) != int(
                chosen.get("byte_offset", 0))
               or int(e.get("records", 0)) != int(chosen.get("records", 0))
               for e in entries):
            chosen = min(entries,
                         key=lambda e: int(e.get("byte_offset", 0)))
            LOG.warning(
                "rescale restore: ingest offset sections disagree for "
                "partition %r — replicated ingest should have kept them "
                "identical; taking the conservative minimum "
                "(%d bytes, %d records)", name,
                int(chosen.get("byte_offset", 0)),
                int(chosen.get("records", 0)))
        partitions[name] = chosen
    merged["partitions"] = partitions
    if any(s.get("rr_part") != merged.get("rr_part")
           or s.get("rr_remaining") != merged.get("rr_remaining")
           for s in sections[1:]):
        LOG.warning("rescale restore: round-robin ingest cursors "
                    "disagree across writers — resetting the rotation")
        merged["rr_part"] = None
        merged["rr_remaining"] = 0
    return merged


def restore_rescaled(job, directory: str, gen: int, writers: int,
                     source=None) -> None:
    """Cross-topology gang restore (the autoscaler's N→M seam): land
    generation ``gen``, written by a ``writers``-process gang, in a job
    running a DIFFERENT process count.

    Every old per-process file is loaded and verified (incremental
    chains resolved per suffix); the per-shard slab counts merge back
    into the canonical GLOBAL key space (``state/store.merge_mh_cells``
    — the key union is host-replicated, so any file supplies it) and
    the scorer's ordinary global-blob restore re-buckets onto THIS
    run's shard count, exactly like a single-process rescale. The
    replicated job state (vocabularies, cuts, sampler, window buffers,
    counters, source offset) comes from writer 0's file — ingest is
    deterministic and replicated, so every writer held the identical
    copy. The emitted-top-K table is merged across writers and then
    filtered down to the rows THIS process owns under the new topology.

    Corruption here raises :class:`CheckpointCorrupt` without walking
    older generations: the caller (the topology-aware restore vote)
    already agreed gang-wide on ``gen``, and silently restoring an
    older epoch on one host only would be exactly the torn global
    state the vote exists to prevent.
    """
    from .store import merge_mh_cells

    datas = []
    metas = []
    for p in range(writers):
        suffix = f".p{p}"
        path = _gen_path(directory, suffix, gen)
        data = _load_verified(path)
        if "meta_json" not in data:
            raise CheckpointCorrupt(
                f"rescale restore: {path} has no embedded meta")
        meta = json.loads(bytes(data["meta_json"]).decode())
        # The rescale-tagged meta is the belt to the epoch markers'
        # braces: the file itself records which process layout wrote
        # it, so a marker/file mismatch cannot silently merge the
        # wrong number of blobs.
        topo = meta.get("gang_topology")
        if topo is not None and int(topo.get("processes", 0)) != writers:
            raise CheckpointCorrupt(
                f"rescale restore: {path} records topology "
                f"{topo.get('processes')} processes but the restore "
                f"vote agreed on {writers} writers")
        if meta.get("rescaled_from"):
            LOG.info("rescale restore: generation %d was itself the "
                     "first commit after a rescale from %d workers",
                     gen, int(meta["rescaled_from"]))
        if meta.get("ckpt_delta"):
            blob, latest, aux = _resolve_chain(directory, suffix, gen,
                                               meta, quarantine=False)
            for k, v in blob.items():
                data[f"scorer_{k}"] = v
            for k, v in zip(_LATEST_KEYS, latest):
                data[k] = v
            data.update(aux)
        else:
            _decode_codec(data, meta)
        datas.append(data)
        metas.append(meta)
    if not datas:
        raise CheckpointCorrupt(
            f"rescale restore: generation {gen} has no writer files")
    # Merge the per-process slab blobs into one canonical global blob.
    merged = merge_mh_cells([
        {k[len("scorer_"):]: v for k, v in d.items()
         if k.startswith("scorer_")} for d in datas])
    base = dict(datas[0])
    for k in list(base):
        if k.startswith("scorer_"):
            del base[k]
    for k, v in merged.items():
        base[f"scorer_{k}"] = v
    # The per-file arrays are already codec-decoded and chain-resolved;
    # rewrite the merged meta without the codec/delta records so the
    # common applier does not decode (or chain-walk) a second time.
    meta0 = dict(metas[0])
    meta0.pop("ckpt_codec", None)
    meta0.pop("ckpt_delta", None)
    # Partition reassignment (the wire side of the seam): merge the
    # per-writer ingest offset sections under the OLD topology's
    # ownership, then let the relaunched topology re-derive ownership
    # from the same modular formula — the drain checkpoint carried the
    # offsets, so every partition resumes exactly once at M workers.
    ing_offsets = merge_ingest_offsets(
        [m.get("ingest_offsets") for m in metas], writers)
    if ing_offsets is not None:
        meta0["ingest_offsets"] = ing_offsets
    if faults.PLAN is not None:
        faults.PLAN.fire("partition_reassign", seq=int(gen))
    if ing_offsets is not None \
            and ing_offsets.get("format") == "partitioned" \
            and getattr(job, "journal", None) is not None:
        job._journal_ingest_event(
            f"ingest/partition-reassign:{int(writers)}->"
            f"{int(job.config.num_processes or 1)}")
    base["meta_json"] = np.frombuffer(
        json.dumps(meta0).encode(), dtype=np.uint8)
    # Merge the emitted top-K across writers (disjoint partitions),
    # item-sorted so the rebuild below is deterministic.
    rows = []
    for d in datas:
        items = d["latest_items"]
        offsets = d["latest_offsets"]
        for pos, item in enumerate(items.tolist()):
            lo, hi = int(offsets[pos]), int(offsets[pos + 1])
            rows.append((int(item), d["latest_others"][lo:hi],
                         d["latest_scores"][lo:hi]))
    rows.sort(key=lambda r: r[0])
    base["latest_items"] = np.asarray([r[0] for r in rows],
                                      dtype=np.int64)
    base["latest_offsets"] = np.concatenate(
        [[0], np.cumsum([len(r[1]) for r in rows])]).astype(np.int64)
    base["latest_others"] = (np.concatenate([r[1] for r in rows])
                             if rows else np.zeros(0, dtype=np.int64))
    base["latest_scores"] = (np.concatenate([r[2] for r in rows])
                             if rows else np.zeros(0, dtype=np.float64))
    _apply_restored(job, base, gen, source=source, own_rows_only=True,
                    anchor_dirty=False)
    job._rescaled_from = int(writers)
    LOG.info("rescale restore: generation %d (written by %d processes) "
             "re-bucketed onto %d shards", gen, writers,
             int(getattr(job.scorer, "n_shards", 1)))


def topology_committed_generations(directory: str
                                   ) -> "list[tuple[int, int]]":
    """``(gen, writers)`` for every generation committed by its WHOLE
    writing topology, newest first — the autoscaler's restore-vote
    input, derived from epoch markers and directory listings alone.

    A generation qualifies when its markers record a topology ``P``
    (autoscale-era markers carry ``"<gen> <P>"``), markers exist for
    every pid in ``range(P)``, and each suffix's delta chain at that
    generation is fully present (``_chain_restorable``). Legacy markers
    without a topology token never qualify — the fixed-topology vote
    (:func:`~tpu_cooccurrence.robustness.gang.agree_restore_generation`)
    owns those directories.
    """
    pat = re.compile(r"^EPOCH\.p(\d+)\.(\d+)$")
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    by_gen: "dict[int, dict[int, int | None]]" = {}
    for m in filter(None, map(pat.match, names)):
        pid, gen = int(m.group(1)), int(m.group(2))
        declared: "int | None" = None
        try:
            with open(os.path.join(directory, m.group(0))) as f:
                parts = f.read().split()
            if len(parts) >= 2:
                declared = int(parts[1])
        except (OSError, ValueError):
            declared = None
        by_gen.setdefault(gen, {})[pid] = declared
    out = []
    for gen in sorted(by_gen, reverse=True):
        markers = by_gen[gen]
        topo = {p for p in markers.values() if p is not None}
        if len(topo) != 1:
            continue  # legacy or self-disagreeing markers
        writers = topo.pop()
        if set(markers) != set(range(writers)):
            continue  # torn global commit: some writer never marked
        if all(_chain_restorable(directory, f".p{i}", gen)
               for i in range(writers)):
            out.append((gen, writers))
    return out


def has_epoch_markers(directory: str) -> bool:
    """True when the directory holds ANY per-process epoch marker —
    the topology-aware vote's tell between "a gang with commit history
    (some of it possibly torn)" and "per-process files with no epoch
    plane at all" (pre-epoch legacy, which must not be quarantined)."""
    pat = re.compile(r"^EPOCH\.p\d+\.\d+$")
    try:
        names = os.listdir(directory)
    except OSError:
        return False
    return any(map(pat.match, names))


def has_legacy_epoch_markers(directory: str) -> bool:
    """True when the directory holds epoch markers WITHOUT a recorded
    topology (written before the autoscaler existed). The topology-
    aware restore vote refuses to run over them: guessing the writing
    process count from the marker COUNT would qualify a torn legacy
    commit as a smaller gang's complete one."""
    pat = re.compile(r"^EPOCH\.p\d+\.\d+$")
    try:
        names = os.listdir(directory)
    except OSError:
        return False
    for name in filter(pat.match, names):
        try:
            with open(os.path.join(directory, name)) as f:
                if len(f.read().split()) < 2:
                    return True
        except OSError:
            continue
    return False


def _chain_restorable(directory: str, suffix: str, gen: int) -> bool:
    """``gen`` is restorable for ``suffix`` from directory listings
    alone: its npz exists and, when incremental, every delta down to a
    present full base exists too (mirrors ``newest_committed``'s chain
    walk, pinned at one generation)."""
    present = {g for g, _p in generations(directory, suffix)}
    if gen not in present:
        return False
    dset = set(deltalog.delta_generations(directory, suffix))
    cur = gen
    while cur in dset and (cur - 1) in present:
        cur -= 1
    return cur not in dset


def process_suffixes(directory: str) -> "list[str]":
    """Every per-process checkpoint suffix with files in ``directory``
    (``.p0``, ``.p1``, …) — the quarantine sweep of the topology-aware
    restore vote walks all of them, current and retired topologies
    alike."""
    pat = re.compile(r"^(?:state|delta)(\.p(\d+))\.\d+\.(?:npz|bin)$")
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted({m.group(1) for m in filter(None, map(pat.match, names))},
                  key=lambda s: int(s[2:]))


def load_serving_state(directory: str, suffix: str = "") -> dict:
    """Read-only bootstrap loader for serving replicas
    (``serving/replica.py``): the newest verifying generation's
    *consumable* state — the emitted top-K table, the append-only
    vocabularies and (when the writer runs a reservoir sampler) the
    per-user history arrays — WITHOUT constructing a job and WITHOUT
    ever renaming a file. A replica shares the directory with a live
    writer (and with its sibling replicas), so corrupt or vanished
    generations are skipped, never quarantined; the writer's own
    restore walk owns quarantine.

    Returns ``{"gen", "windows_fired", "latest": (items, offsets,
    others, scores), "item_vocab", "user_vocab"[, "hist", "hist_len"]}``
    — ``latest`` in the exact external-id arrays :func:`save` writes.
    Raises :class:`FileNotFoundError` when the directory holds no
    generation at all and :class:`CheckpointCorrupt` when none
    verifies.
    """
    gens = generations(directory, suffix)
    if not gens:
        raise FileNotFoundError(
            f"no checkpoint for suffix {suffix!r} in {directory}")
    for gen, path in gens:
        try:
            data = _load_verified(path)
        except FileNotFoundError:
            continue  # the writer's retention raced the listing
        except CheckpointCorrupt as exc:
            LOG.warning("replica bootstrap: generation %d failed "
                        "verification (%s); trying older", gen, exc)
            continue
        if "meta_json" not in data:
            LOG.warning("replica bootstrap: generation %d has no "
                        "embedded meta; trying older", gen)
            continue
        meta = json.loads(bytes(data["meta_json"]).decode())
        if meta.get("ckpt_delta"):
            try:
                _blob, latest, aux = _resolve_chain(
                    directory, suffix, gen, meta, quarantine=False)
            except CheckpointCorrupt as exc:
                LOG.warning("replica bootstrap: generation %d delta "
                            "chain failed (%s); trying older", gen, exc)
                continue
            data.update(aux)
        else:
            _decode_codec(data, meta)
            latest = tuple(data[k] for k in _LATEST_KEYS)
        out = {
            "gen": gen,
            "windows_fired": int(meta.get("windows_fired", 0)),
            "latest": tuple(np.asarray(a) for a in latest),
            "item_vocab": np.asarray(data["item_vocab"], dtype=np.int64),
            "user_vocab": np.asarray(data["user_vocab"], dtype=np.int64),
        }
        if "hist" in data:
            out["hist"] = np.asarray(data["hist"])
            out["hist_len"] = np.asarray(data["hist_len"], dtype=np.int64)
        return out
    raise CheckpointCorrupt(
        f"no checkpoint generation in {directory} verifies for the "
        f"replica bootstrap (walked all {len(gens)})")
