"""Dirty-row delta log: the incremental-checkpoint record format.

The PR-9 recency clock already knows exactly which rows changed each
window, yet every checkpoint generation used to rewrite the whole
``rows_key`` / ``rows_cnt`` / ``row_sums`` blob — at unbounded vocab the
full rewrite dominates the epoch-commit window (the Flink lineage solves
this with incremental RocksDB checkpoints; PAPER.md). This module is
that story rebuilt on our own wire codec: one **delta generation file**
(``delta<suffix>.<gen>.bin``) per incremental checkpoint, holding ONLY
the rows touched since the previous committed generation, coded with the
PR-7 primitives (delta + zigzag + LEB128 varint, ``state/wire.py``).

The same file doubles as the **continuous delta log** a read replica can
tail (ROADMAP #2's catch-up feed): each record carries the row's full
current cell state *and* its current emitted top-K, so one format serves
two consumers — checkpoint restore replays cells, a replica replays
top-K rows (:meth:`DeltaGeneration.iter_topk`).

File layout (stable; version bumps on breaking change)::

    magic     b"COOCDLT1"                      8 bytes
    hlen      uint32 LE                        4 bytes
    header    JSON (ascii), hlen bytes — {"v", "gen", "prev", "base",
              "kind" ("sp" | "mh"), "observed", "row_sums_len",
              "n_rows", "n_shards", "local_shards", "hist_k",
              "item_vocab_len", "user_vocab_len",
              "payload": [codec, nbytes]  (codec: "zlib" | "none"),
              "sections": [[name, enc, count, nbytes], ...]}
    payload   the concatenated sections (header order; per-section
              nbytes are pre-compression), as one zlib stream
    digest    sha256 hexdigest (64 ascii bytes) over everything above

Section encodings (``enc``):

===========  ===========================================================
``sdv``      sorted nonnegative int64: delta + LEB128 varint
             (``wire.encode_sorted_u64``)
``v``        nonnegative int64: LEB128 varint (``wire.encode_varint``)
``zv``       signed int64: zigzag + varint (``wire.encode_zigzag_varint``)
``zdv``      sorted signed int64: zigzag + varint of the first
             differences (external ids may be negative)
``f64``      raw little-endian float64 (scores are carried verbatim —
             bit-exact restore is the whole contract)
===========  ===========================================================

Sections, in order (counts per the header; every section present):

=============  ========================================================
``rows``       sorted dirty dense row ids (``sdv``) — the row-removal
               set replay applies before re-inserting the records
``row_sums``   the dirty rows' CURRENT row sums (``zv``), aligned with
               ``rows``
``cell_lens``  cells per dirty row (``v``), aligned with ``rows``
``cell_keys``  all dirty rows' cell keys ``row<<32|dst`` in global sort
               order (``sdv``)
``cell_cnts``  cell counts (``zv``): one per cell (``sp``), or one per
               *locally-owned* row's cell (``mh`` — remote shards'
               counts live in the owning process's file)
``lat_rows``   sorted EXTERNAL item ids of dirty rows present in the
               emitted top-K table (``zdv``)
``lat_lens``   top-K entries per ``lat_rows`` row (``v``)
``lat_others`` external partner ids (``zv``), row-major
``lat_scores`` scores (``f64``), row-major
``usr_rows``   sorted dirty dense USER ids (``sdv``) — the reservoir
               sampler's per-user state is row-indexed too
``usr_lens``   live hist length per dirty user (``v``)
``usr_total``  reservoir totals (``v``), aligned with ``usr_rows``
``usr_draws``  reservoir draw counters (``v``)
``usr_hist``   concatenated live hist prefixes (``v``), row-major
``voc_items``  external item ids appended to the vocab since the
               previous generation (``zv``; IdMap is append-only)
``voc_users``  external user ids appended since the previous
               generation (``zv``)
=============  ========================================================

A record is a ROW SNAPSHOT, not an arithmetic diff: replaying a delta
replaces each dirty row's cells / sum / top-K with the recorded state,
so replay of ``base + delta[B+1..G]`` reconstructs generation ``G``'s
canonical arrays byte-identically (pinned by
``tests/test_incremental_checkpoint.py`` across every StateStore x
cell-dtype x wire-format x topology combination).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import zlib
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .wire import (
    decode_sorted_u64,
    decode_varint,
    decode_zigzag_varint,
    encode_sorted_u64,
    encode_varint,
    encode_zigzag_varint,
)

#: Delta-file magic + format version (the trailing byte).
MAGIC = b"COOCDLT1"

#: Header format version.
VERSION = 1

#: Section name -> encoding tag, in file order. The writer emits exactly
#: these sections; the reader rejects anything else — the two ends of
#: the format cannot drift silently (also enforced statically by the
#: ``ckpt-format-roundtrip`` cooclint rule).
SECTIONS = (
    ("rows", "sdv"),
    ("row_sums", "zv"),
    ("cell_lens", "v"),
    ("cell_keys", "sdv"),
    ("cell_cnts", "zv"),
    ("lat_rows", "zdv"),
    ("lat_lens", "v"),
    ("lat_others", "zv"),
    ("lat_scores", "f64"),
    # User-reservoir table (dirty USERS — the sampler's per-user state
    # is row-indexed too, and on cohort-churn streams it would other-
    # wise dominate the small-state npz): per dirty user the live hist
    # prefix + the three scalars.
    ("usr_rows", "sdv"),
    ("usr_lens", "v"),
    ("usr_total", "v"),
    ("usr_draws", "v"),
    ("usr_hist", "v"),
    # Vocab appends (IdMap is append-only: dense ids are assigned in
    # first-appearance order and never mutate, so a delta carries just
    # the new external ids since the previous generation).
    ("voc_items", "zv"),
    ("voc_users", "zv"),
)


class DeltaCorrupt(ValueError):
    """A delta file failed to parse or verify its digest."""


def delta_path(directory: str, suffix: str, gen: int) -> str:
    """Filename scheme beside ``state<suffix>.<gen>.npz``: a generation
    is incremental iff its delta file exists (chain structure is
    derivable from a directory listing alone — the gang restore vote
    must not open npz files to count committed chains)."""
    return os.path.join(directory, f"delta{suffix}.{gen}.bin")


def delta_generations(directory: str, suffix: str) -> "list[int]":
    """Generations with a delta file in ``directory``, ascending."""
    pat = re.compile(rf"^delta{re.escape(suffix)}\.(\d+)\.bin$")
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(int(m.group(1)) for m in map(pat.match, names) if m)


# -- dirty-row tracking -------------------------------------------------


class DirtyRowLog:
    """Rows touched since the last committed checkpoint generation.

    One dirty source, two consumers (ISSUE 12): the scorer feeds this
    from the same per-window touched-rows set the TieredSlabStore
    recency clock stamps (``StateStore.note_touched``), and the
    checkpoint writer drains it per generation. Disabled (no memory
    cost) unless ``--checkpoint-incremental`` enables it.

    ``anchor_gen`` is the generation the accumulated rows are dirty
    *since* — set by save (generation written) and restore (generation
    restored). A save only writes a delta when the newest on-disk
    generation still equals the anchor; anything else (foreign files,
    an unanchored fresh store) forces a full base.
    """

    #: Past this many logged row entries the log collapses to the
    #: all-dirty flag (the next checkpoint writes a full base) — bounds
    #: memory on arbitrarily long checkpoint intervals.
    CAP = 1 << 22

    def __init__(self) -> None:
        self._parts: List[np.ndarray] = []
        self._count = 0
        self._all = False
        self.anchor_gen = -1

    def note(self, rows: np.ndarray) -> None:
        if self._all or not len(rows):
            return
        self._parts.append(np.asarray(rows, dtype=np.int64))
        self._count += len(rows)
        if self._count > self.CAP:
            # The entry count includes duplicates (a hot working set
            # re-touched every window); consolidate to the unique set
            # first and only give up (all-dirty -> full base) when the
            # TRUE dirty set exceeds the cap.
            rows = np.unique(np.concatenate(self._parts))
            if len(rows) > self.CAP:
                self.mark_all()
            else:
                self._parts = [rows]
                self._count = len(rows)

    def mark_all(self) -> None:
        """Everything dirty: the next save must write a full base."""
        self._all = True
        self._parts.clear()
        self._count = 0

    def peek(self) -> "Tuple[np.ndarray, bool]":
        """``(sorted unique rows, all_dirty)`` — non-destructive: the
        log clears only on :meth:`commit`, after the generation's rename
        landed, so a save that dies mid-write loses no dirtiness."""
        if self._all:
            return np.zeros(0, dtype=np.int64), True
        if not self._parts:
            return np.zeros(0, dtype=np.int64), False
        rows = (np.unique(self._parts[0]) if len(self._parts) == 1
                else np.unique(np.concatenate(self._parts)))
        return rows, False

    def commit(self, gen: int) -> None:
        """The generation commit landed: rows accumulated so far are
        durable, the log restarts anchored at ``gen``."""
        self._parts.clear()
        self._count = 0
        self._all = False
        self.anchor_gen = gen


class JobDirtyTracker:
    """Job-side dirty domains for incremental checkpoints: the USERS
    touched per fired window (the reservoir sampler's state is
    row-indexed by user) plus the vocab lengths at the last committed
    generation (IdMap is append-only, so a length is a complete delta
    cursor). Lifecycle mirrors the store's :class:`DirtyRowLog`:
    committed by save after the rename, re-anchored by restore."""

    def __init__(self) -> None:
        self.users = DirtyRowLog()
        self.item_vocab_len = 0
        self.user_vocab_len = 0

    def commit(self, gen: int, item_len: int, user_len: int) -> None:
        self.users.commit(gen)
        self.item_vocab_len = int(item_len)
        self.user_vocab_len = int(user_len)


# -- vectorized range gather --------------------------------------------


def _range_indices(starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    """Concatenated ``[starts[i], ends[i])`` index ranges, no Python
    loop (the per-dirty-row cell gather)."""
    lens = (ends - starts).astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    # repeat/cumsum trick: position j of range i = starts[i] + (j -
    # exclusive-cumsum(lens)[i]), fully vectorized.
    excl = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
    return (np.repeat(starts.astype(np.int64), lens)
            + np.arange(total, dtype=np.int64) - np.repeat(excl, lens))


# -- the delta generation record ----------------------------------------


@dataclasses.dataclass
class DeltaGeneration:
    """One decoded ``delta.<gen>.bin``: the dirty-row snapshot records.

    ``kind``: ``"sp"`` — single-file cell blobs (``rows_key`` /
    ``rows_cnt``); ``"mh"`` — multi-host per-process blobs
    (``mh_rows_key`` with the key union replicated and counts only for
    ``local_shards``).
    """

    gen: int
    prev: int
    base: int
    kind: str
    observed: int
    row_sums_len: int
    rows: np.ndarray        # sorted dirty dense rows [R]
    row_sums: np.ndarray    # int64 [R]
    cell_lens: np.ndarray   # int64 [R]
    cell_keys: np.ndarray   # sorted int64 global keys
    cell_cnts: np.ndarray   # int64 (sp: per cell; mh: local cells only)
    lat_rows: np.ndarray    # sorted int64 external ids [L]
    lat_lens: np.ndarray    # int64 [L]
    lat_others: np.ndarray  # int64, row-major
    lat_scores: np.ndarray  # float64, row-major
    usr_rows: np.ndarray    # sorted dirty dense user ids [D]
    usr_lens: np.ndarray    # int64 [D] (hist_len per user)
    usr_total: np.ndarray   # int64 [D]
    usr_draws: np.ndarray   # int64 [D]
    usr_hist: np.ndarray    # int64, row-major live hist prefixes
    voc_items: np.ndarray   # external item ids appended since prev
    voc_users: np.ndarray   # external user ids appended since prev
    n_shards: int = 0
    local_shards: Tuple[int, ...] = ()
    hist_k: int = 0         # reservoir kMax (hist columns; 0 = the run
    #                         has no per-user reservoir state)
    item_vocab_len: int = 0  # len(item_vocab) at this generation
    user_vocab_len: int = 0  # len(user_vocab) at this generation
    ingest_offsets: Optional[dict] = None  # the generation's committed
    #                         ingest-offset section (io/source.Source
    #                         .offsets_state) — the wire position a
    #                         delta-log consumer sees without opening
    #                         the npz meta; None on pre-ingest files

    def iter_rows(self) -> Iterator[dict]:
        """Per-row state records (dense-id domain): ``{"gen", "row",
        "row_sum", "dsts", "cnts"}`` — ``cnts`` is ``None`` for a row a
        multi-host file does not own (its counts are in the owning
        process's delta)."""
        cell_off = np.concatenate(
            [[0], np.cumsum(self.cell_lens)]).astype(np.int64)
        local = self._local_row_mask()
        cnt_off = np.concatenate(
            [[0], np.cumsum(np.where(local, self.cell_lens, 0))]
        ).astype(np.int64)
        for i, row in enumerate(self.rows.tolist()):
            lo, hi = int(cell_off[i]), int(cell_off[i + 1])
            cnts: Optional[np.ndarray] = None
            if local[i]:
                clo = int(cnt_off[i])
                cnts = self.cell_cnts[clo: clo + (hi - lo)]
            yield {
                "gen": self.gen, "row": row,
                "row_sum": int(self.row_sums[i]),
                "dsts": (self.cell_keys[lo:hi]
                         & 0xFFFFFFFF).astype(np.int64),
                "cnts": cnts,
            }

    def iter_topk(self) -> Iterator[dict]:
        """Per-row emitted-top-K records (EXTERNAL-id domain — no vocab
        needed): ``{"gen", "item", "top": [(other, score), ...]}``.
        This is the replica catch-up feed shape (ROADMAP #2): replaying
        these over a snapshot reproduces the writer's top-K table."""
        off = np.concatenate(
            [[0], np.cumsum(self.lat_lens)]).astype(np.int64)
        for i, item in enumerate(self.lat_rows.tolist()):
            lo, hi = int(off[i]), int(off[i + 1])
            yield {
                "gen": self.gen, "item": item,
                "top": list(zip(self.lat_others[lo:hi].tolist(),
                                self.lat_scores[lo:hi].tolist())),
            }

    def _local_row_mask(self) -> np.ndarray:
        if self.kind != "mh":
            return np.ones(len(self.rows), dtype=bool)
        owner = self.rows % max(self.n_shards, 1)
        return np.isin(owner, np.asarray(self.local_shards,
                                         dtype=np.int64))

    @property
    def nbytes_payload(self) -> int:
        """Approximate decoded payload size (bench bookkeeping)."""
        return int(sum(getattr(self, n).nbytes for n, _e in SECTIONS))


def _enc_section(enc: str, arr: np.ndarray) -> bytes:
    if enc == "sdv":
        return encode_sorted_u64(np.asarray(arr, dtype=np.int64)).tobytes()
    if enc == "v":
        return encode_varint(np.asarray(arr, dtype=np.int64)).tobytes()
    if enc == "zv":
        return encode_zigzag_varint(
            np.asarray(arr, dtype=np.int64)).tobytes()
    if enc == "zdv":
        v = np.asarray(arr, dtype=np.int64)
        d = np.diff(v, prepend=np.int64(0))
        return encode_zigzag_varint(d).tobytes()
    if enc == "f64":
        return np.asarray(arr, dtype="<f8").tobytes()
    raise ValueError(f"unknown delta section encoding {enc!r}")


def _dec_section(enc: str, buf: bytes, count: int) -> np.ndarray:
    b = np.frombuffer(buf, dtype=np.uint8)
    if enc == "sdv":
        return decode_sorted_u64(b, count)
    if enc == "v":
        return decode_varint(b, count).astype(np.int64)
    if enc == "zv":
        return decode_zigzag_varint(b, count)
    if enc == "zdv":
        return np.cumsum(decode_zigzag_varint(b, count)).astype(np.int64)
    if enc == "f64":
        if len(buf) != 8 * count:
            raise DeltaCorrupt(
                f"f64 section holds {len(buf)} bytes, expected {8 * count}")
        return np.frombuffer(buf, dtype="<f8").copy()
    raise DeltaCorrupt(f"unknown delta section encoding {enc!r}")


def encode_delta(d: DeltaGeneration) -> bytes:
    """Serialize one generation's dirty-row records (see the module
    docstring for the byte layout). The concatenated sections ride one
    zlib stream (``payload`` header slot): the sibling npz is deflated
    by the zip container, and the raw-f64 score column deflates ~2.5x
    (f32-origin values carry four zero mantissa bytes each)."""
    blobs = []
    sections = []
    for name, enc in SECTIONS:
        arr = getattr(d, name)
        blob = _enc_section(enc, arr)
        sections.append([name, enc, int(len(arr)), len(blob)])
        blobs.append(blob)
    payload = zlib.compress(b"".join(blobs), 6)
    header = {
        "v": VERSION, "gen": d.gen, "prev": d.prev, "base": d.base,
        "kind": d.kind, "observed": int(d.observed),
        "row_sums_len": int(d.row_sums_len),
        "n_rows": int(len(d.rows)),
        "n_shards": int(d.n_shards),
        "local_shards": [int(s) for s in d.local_shards],
        "hist_k": int(d.hist_k),
        "item_vocab_len": int(d.item_vocab_len),
        "user_vocab_len": int(d.user_vocab_len),
        "ingest_offsets": d.ingest_offsets,
        "payload": ["zlib", len(payload)],
        "sections": sections,
    }
    hjson = json.dumps(header, sort_keys=True).encode("ascii")
    out = bytearray()
    out += MAGIC
    out += np.uint32(len(hjson)).tobytes()
    out += hjson
    out += payload
    out += hashlib.sha256(bytes(out)).hexdigest().encode("ascii")
    return bytes(out)


def decode_delta(data: bytes) -> DeltaGeneration:
    """Parse + verify one delta file's bytes; raises
    :class:`DeltaCorrupt` on any framing, digest or count mismatch."""
    if len(data) < len(MAGIC) + 4 + 64 or data[: len(MAGIC)] != MAGIC:
        raise DeltaCorrupt("not a delta file (bad magic or truncated)")
    digest = data[-64:]
    body = data[:-64]
    actual = hashlib.sha256(body).hexdigest().encode("ascii")
    if digest != actual:
        raise DeltaCorrupt(
            f"delta digest mismatch: stored {digest[:12]!r}…, "
            f"recomputed {actual[:12]!r}…")
    hlen = int(np.frombuffer(
        data[len(MAGIC): len(MAGIC) + 4], dtype=np.uint32)[0])
    hstart = len(MAGIC) + 4
    try:
        header = json.loads(data[hstart: hstart + hlen].decode("ascii"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise DeltaCorrupt(f"unreadable delta header: {exc}")
    if header.get("v") != VERSION:
        raise DeltaCorrupt(
            f"unknown delta format version {header.get('v')!r} "
            f"(written by a newer framework?)")
    listed = [(s[0], s[1]) for s in header["sections"]]
    if listed != list(SECTIONS):
        raise DeltaCorrupt(
            f"delta section registry mismatch: file has {listed}")
    codec, pnbytes = header.get("payload", ["none", None])
    raw = body[hstart + hlen:]
    if pnbytes is not None and len(raw) != int(pnbytes):
        raise DeltaCorrupt(
            f"delta payload holds {len(raw)} bytes, header says "
            f"{pnbytes}")
    if codec == "zlib":
        try:
            raw = zlib.decompress(raw)
        except zlib.error as exc:
            raise DeltaCorrupt(f"delta payload inflate failed: {exc}")
    elif codec != "none":
        raise DeltaCorrupt(f"unknown delta payload codec {codec!r}")
    pos = 0
    fields = {}
    for name, enc, count, nbytes in header["sections"]:
        blob = raw[pos: pos + nbytes]
        if len(blob) != nbytes:
            raise DeltaCorrupt(f"delta section {name!r} truncated")
        try:
            fields[name] = _dec_section(enc, blob, int(count))
        except ValueError as exc:
            raise DeltaCorrupt(f"delta section {name!r} corrupt: {exc}")
        pos += nbytes
    if pos != len(raw):
        raise DeltaCorrupt(
            f"delta file has {len(raw) - pos} trailing bytes")
    d = DeltaGeneration(
        gen=int(header["gen"]), prev=int(header["prev"]),
        base=int(header["base"]), kind=str(header["kind"]),
        observed=int(header["observed"]),
        row_sums_len=int(header["row_sums_len"]),
        n_shards=int(header.get("n_shards", 0)),
        local_shards=tuple(header.get("local_shards", [])),
        hist_k=int(header.get("hist_k", 0)),
        item_vocab_len=int(header.get("item_vocab_len", 0)),
        user_vocab_len=int(header.get("user_vocab_len", 0)),
        ingest_offsets=header.get("ingest_offsets"),
        **fields)
    if not (len(d.rows) == len(d.row_sums) == len(d.cell_lens)
            == int(header["n_rows"])):
        raise DeltaCorrupt("delta row sections disagree on row count")
    if len(d.lat_rows) != len(d.lat_lens):
        raise DeltaCorrupt("delta latest sections disagree on row count")
    if not (len(d.usr_rows) == len(d.usr_lens) == len(d.usr_total)
            == len(d.usr_draws)):
        raise DeltaCorrupt("delta user sections disagree on row count")
    return d


def read_delta_file(path: str) -> DeltaGeneration:
    """Decode + verify one on-disk delta file."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as exc:
        raise DeltaCorrupt(f"unreadable delta file {path}: {exc}")
    return decode_delta(data)


def read_delta_stream(directory: str, suffix: str = "",
                      start_gen: int = 0) -> Iterator[DeltaGeneration]:
    """Tail the delta log: yield every COMMITTED generation's decoded
    delta in ascending order, skipping generations at or below
    ``start_gen``.

    The consumable feed (one format, two consumers): a read replica
    that holds state as of generation ``G`` calls
    ``read_delta_stream(dir, start_gen=G)`` after each epoch commit and
    replays :meth:`DeltaGeneration.iter_topk` records into its snapshot
    table — no full-table resync. Corrupt files raise
    :class:`DeltaCorrupt` (the consumer falls back to a checkpoint
    resync, exactly like restore falls back a generation).

    Commit gate: a delta file without its generation npz is an ORPHAN
    of a crashed save (the npz rename is the commit point) and may be
    rewritten with different content on restart — replaying it would
    permanently diverge the consumer, so it is never yielded."""
    for gen in delta_generations(directory, suffix):
        if gen <= start_gen:
            continue
        # Naming coupled to state/checkpoint._gen_path (checkpoint
        # imports this module, so the literal lives here).
        if not os.path.exists(
                os.path.join(directory, f"state{suffix}.{gen}.npz")):
            continue
        yield read_delta_file(delta_path(directory, suffix, gen))


# -- extraction (checkpoint writer side) --------------------------------


def _aligned_mh_counts(keys: np.ndarray, local_cnt: np.ndarray,
                       n_shards: int,
                       local_shards) -> "Tuple[np.ndarray, np.ndarray]":
    """Expand a multi-host blob's (shard-asc, key-order) count packing
    to key-aligned form. Returns ``(cnt_aligned, local_cell_mask)`` —
    ``cnt_aligned`` is meaningful only where the mask is True. The
    inverse of :func:`_pack_mh_counts`."""
    owner = ((keys >> 32) % max(n_shards, 1)).astype(np.int64)
    cnt_aligned = np.zeros(len(keys), dtype=np.int64)
    mask = np.zeros(len(keys), dtype=bool)
    lo = 0
    for d in sorted(int(s) for s in local_shards):
        sel = owner == d
        n = int(sel.sum())
        cnt_aligned[sel] = local_cnt[lo: lo + n]
        mask |= sel
        lo += n
    if lo != len(local_cnt):
        raise ValueError(
            f"mh count blob holds {len(local_cnt)} cells but local "
            f"shards {sorted(local_shards)} own {lo} keys")
    return cnt_aligned, mask


def _pack_mh_counts(keys: np.ndarray, cnt_aligned: np.ndarray,
                    n_shards: int, local_shards) -> np.ndarray:
    """Key-aligned counts -> the blob's (shard-asc, key-order) packing.
    Filtering the globally-sorted key array to one shard preserves that
    shard's local-key order (for fixed ``d``, the global key is
    monotone in the local key), so this reproduces
    ``mh_local_cnt`` byte-identically."""
    owner = ((keys >> 32) % max(n_shards, 1)).astype(np.int64)
    parts = [cnt_aligned[owner == d]
             for d in sorted(int(s) for s in local_shards)]
    return (np.concatenate(parts).astype(np.int64) if parts
            else np.zeros(0, dtype=np.int64))


def extract_delta(blob: dict, latest: "Tuple[np.ndarray, np.ndarray, "
                  "np.ndarray, np.ndarray]",
                  dirty: np.ndarray, ext_dirty: np.ndarray,
                  gen: int, prev: int, base: int,
                  n_shards: int = 0,
                  aux: Optional[dict] = None) -> DeltaGeneration:
    """Build one generation's delta records from the canonical blob the
    scorer just snapshotted (``blob``: the UNPREFIXED scorer checkpoint
    dict) plus the emitted-top-K arrays ``latest = (items, offsets,
    others, scores)`` in the exact form ``checkpoint.save`` writes.

    ``dirty``: sorted unique dense rows touched since ``prev``;
    ``ext_dirty``: their external ids (same order as ``dirty``);
    ``n_shards``: the writing run's shard count (multi-host blobs only
    — it defines cell ownership, ``row % n_shards``).

    ``aux`` carries the job-level row-indexed state: ``item_vocab`` /
    ``user_vocab`` (full append-only rev arrays) with
    ``prev_item_len`` / ``prev_user_len`` (lengths at ``prev``, so the
    delta stores just the appends), and — when the run has a reservoir
    sampler — ``dirty_users`` plus the ``hist`` / ``hist_len`` /
    ``total`` / ``draws`` arrays.
    """
    mh = "mh_rows_key" in blob
    if mh:
        keys = np.asarray(blob["mh_rows_key"], dtype=np.int64)
        local_shards = tuple(
            int(s) for s in np.asarray(blob["mh_local_shards"]).tolist())
        cnt_aligned, local_mask = _aligned_mh_counts(
            keys, np.asarray(blob["mh_local_cnt"], dtype=np.int64),
            n_shards, local_shards)
    else:
        keys = np.asarray(blob["rows_key"], dtype=np.int64)
        n_shards = 0
        local_shards = ()
        cnt_aligned = np.asarray(blob["rows_cnt"], dtype=np.int64)
        local_mask = np.ones(len(keys), dtype=bool)
    rs = np.asarray(blob["row_sums"], dtype=np.int64)
    dirty = np.asarray(dirty, dtype=np.int64)

    rowcol = (keys >> 32).astype(np.int64)
    starts = np.searchsorted(rowcol, dirty, side="left")
    ends = np.searchsorted(rowcol, dirty, side="right")
    sel = _range_indices(starts, ends)
    cell_keys = keys[sel]
    cell_sel_local = local_mask[sel]
    cell_cnts = cnt_aligned[sel][cell_sel_local]

    # Emitted-top-K records for dirty rows currently in the table (a
    # dirty row absent from the table now was never in it: the latest
    # store only ever replaces rows, so replace-on-replay is complete).
    items, offsets, others, scores = latest
    ext_sorted = np.sort(np.asarray(ext_dirty, dtype=np.int64))
    pos = np.searchsorted(items, ext_sorted)
    safe = np.minimum(pos, max(len(items) - 1, 0))
    present = ((pos < len(items)) & (items[safe] == ext_sorted)
               if len(items) else np.zeros(len(ext_sorted), dtype=bool))
    lat_rows = ext_sorted[present]
    lpos = pos[present]
    lstarts = np.asarray(offsets, dtype=np.int64)[lpos]
    lends = np.asarray(offsets, dtype=np.int64)[lpos + 1]
    lsel = _range_indices(lstarts, lends)

    # Row sums index within bounds by construction (a touched row's sum
    # was written before it could be noted dirty); guard anyway so a
    # foreign dirty set cannot read garbage.
    if len(dirty) and int(dirty.max()) >= len(rs):
        raise ValueError(
            f"dirty row {int(dirty.max())} outside row_sums[{len(rs)}]")

    aux = aux or {}
    z = np.zeros(0, dtype=np.int64)
    usr_rows = usr_lens = usr_total = usr_draws = usr_hist = z
    hist_k = 0
    if "hist" in aux:
        hist = np.asarray(aux["hist"])
        hist_k = hist.shape[1]
        du = np.asarray(aux["dirty_users"], dtype=np.int64)
        du = du[du < len(hist)]
        usr_rows = du
        hlen = np.asarray(aux["hist_len"], dtype=np.int64)
        usr_lens = hlen[du]
        usr_total = np.asarray(aux["total"], dtype=np.int64)[du]
        usr_draws = np.asarray(aux["draws"], dtype=np.int64)[du]
        flat = hist.reshape(-1)
        hsel = _range_indices(du * hist_k, du * hist_k + usr_lens)
        usr_hist = flat[hsel].astype(np.int64)
    voc_i = np.asarray(aux.get("item_vocab", z), dtype=np.int64)
    voc_u = np.asarray(aux.get("user_vocab", z), dtype=np.int64)
    prev_i = int(aux.get("prev_item_len", len(voc_i)))
    prev_u = int(aux.get("prev_user_len", len(voc_u)))

    return DeltaGeneration(
        gen=gen, prev=prev, base=base, kind="mh" if mh else "sp",
        observed=int(np.asarray(blob["observed"]).reshape(-1)[0]),
        row_sums_len=len(rs),
        rows=dirty,
        row_sums=rs[dirty] if len(dirty) else np.zeros(0, dtype=np.int64),
        cell_lens=(ends - starts).astype(np.int64),
        cell_keys=cell_keys,
        cell_cnts=cell_cnts,
        lat_rows=lat_rows,
        lat_lens=(lends - lstarts).astype(np.int64),
        lat_others=np.asarray(others, dtype=np.int64)[lsel],
        lat_scores=np.asarray(scores, dtype=np.float64)[lsel],
        usr_rows=usr_rows, usr_lens=usr_lens, usr_total=usr_total,
        usr_draws=usr_draws, usr_hist=usr_hist,
        voc_items=voc_i[prev_i:], voc_users=voc_u[prev_u:],
        n_shards=n_shards, local_shards=local_shards,
        hist_k=hist_k,
        item_vocab_len=len(voc_i), user_vocab_len=len(voc_u))


# -- replay (checkpoint restore side) -----------------------------------


class ChainState:
    """Mutable reconstruction state: open with the base generation's
    canonical arrays, :meth:`replay` the chain's deltas (oldest first),
    then :meth:`close` back to the exact arrays a full checkpoint at
    the top generation would have written."""

    def __init__(self, blob: dict, latest, n_shards: int = 0,
                 aux: Optional[dict] = None) -> None:
        self.mh = "mh_rows_key" in blob
        if self.mh:
            self.keys = np.asarray(blob["mh_rows_key"], dtype=np.int64)
            self.n_shards = int(n_shards)
            self.local_shards = tuple(
                int(s)
                for s in np.asarray(blob["mh_local_shards"]).tolist())
            self.cnts, self._local_mask = _aligned_mh_counts(
                self.keys,
                np.asarray(blob["mh_local_cnt"], dtype=np.int64),
                self.n_shards, self.local_shards)
        else:
            self.keys = np.asarray(blob["rows_key"], dtype=np.int64)
            self.cnts = np.asarray(blob["rows_cnt"], dtype=np.int64)
        self.row_sums = np.asarray(blob["row_sums"], dtype=np.int64)
        self.observed = int(np.asarray(blob["observed"]).reshape(-1)[0])
        items, offsets, others, scores = latest
        self.lat_items = np.asarray(items, dtype=np.int64)
        self.lat_lens = np.diff(
            np.asarray(offsets, dtype=np.int64))
        self.lat_others = np.asarray(others, dtype=np.int64)
        self.lat_scores = np.asarray(scores, dtype=np.float64)
        aux = aux or {}
        self.item_vocab = np.asarray(aux.get(
            "item_vocab", np.zeros(0, dtype=np.int64)), dtype=np.int64)
        self.user_vocab = np.asarray(aux.get(
            "user_vocab", np.zeros(0, dtype=np.int64)), dtype=np.int64)
        # Reservoir table (absent for stateless samplers).
        self.hist = (np.asarray(aux["hist"]) if "hist" in aux else None)
        if self.hist is not None:
            self.hist = self.hist.copy()
            self.hist_len = np.asarray(aux["hist_len"],
                                       dtype=np.int64).copy()
            self.total = np.asarray(aux["total"], dtype=np.int64).copy()
            self.draws = np.asarray(aux["draws"], dtype=np.int64).copy()

    def _check(self, d: DeltaGeneration) -> None:
        if d.kind != ("mh" if self.mh else "sp"):
            raise DeltaCorrupt(
                f"delta generation {d.gen} kind {d.kind!r} does not "
                f"match the base blob")
        if self.mh and (d.n_shards != self.n_shards
                        or tuple(d.local_shards) != self.local_shards):
            raise DeltaCorrupt(
                f"delta generation {d.gen} was written by shard layout "
                f"{d.n_shards}/{list(d.local_shards)}; the chain base "
                f"has {self.n_shards}/{list(self.local_shards)}")
        if d.row_sums_len < len(self.row_sums):
            raise DeltaCorrupt(
                f"delta generation {d.gen} shrinks row_sums "
                f"({d.row_sums_len} < {len(self.row_sums)})")

    def replay(self, deltas: "List[DeltaGeneration]") -> None:
        """Apply a chain (oldest first) in ONE merge pass.

        Replace-on-replay means only each row's LAST record matters, so
        the cells / top-K structures merge once: per delta, keep the
        rows no later delta supersedes; drop all superseded rows from
        the base; concatenate and sort. Restore cost is
        O(total cells log) regardless of chain depth — the per-delta
        rebuild would pay the full-array cost chain-length times. The
        small dense overlays (row sums, vocab appends, reservoir rows)
        stay sequential: they are cheap and order-sensitive.
        """
        # Per-delta keep masks (a row's record survives iff no LATER
        # delta touches the row), walking newest -> oldest.
        seen = np.zeros(0, dtype=np.int64)
        seen_lat = np.zeros(0, dtype=np.int64)
        keep_rows: List[np.ndarray] = [None] * len(deltas)
        keep_lat: List[np.ndarray] = [None] * len(deltas)
        for i in range(len(deltas) - 1, -1, -1):
            d = deltas[i]
            self._check(d)
            keep_rows[i] = (~np.isin(d.rows, seen) if len(seen)
                            else np.ones(len(d.rows), dtype=bool))
            keep_lat[i] = (~np.isin(d.lat_rows, seen_lat) if len(seen_lat)
                           else np.ones(len(d.lat_rows), dtype=bool))
            seen = np.union1d(seen, d.rows)
            seen_lat = np.union1d(seen_lat, d.lat_rows)

        # Cells: base minus every superseded row + each delta's
        # surviving rows' cells, one concatenate + one stable sort (row
        # sets are disjoint across parts, so key order is total).
        base_keep = ~np.isin((self.keys >> 32).astype(np.int64), seen)
        key_parts = [self.keys[base_keep]]
        cnt_parts = [self.cnts[base_keep]]
        for i, d in enumerate(deltas):
            cell_keep = np.repeat(keep_rows[i], d.cell_lens)
            key_parts.append(d.cell_keys[cell_keep])
            if self.mh:
                # Key-aligned counts: remote cells carry a zero
                # placeholder (never read back out).
                local = d._local_row_mask()
                cell_local = np.repeat(local, d.cell_lens)
                aligned = np.zeros(len(d.cell_keys), dtype=np.int64)
                aligned[cell_local] = d.cell_cnts
                cnt_parts.append(aligned[cell_keep])
            else:
                cnt_parts.append(d.cell_cnts[cell_keep])
        keys = np.concatenate(key_parts)
        cnts = np.concatenate(cnt_parts)
        order = np.argsort(keys, kind="stable")
        self.keys = keys[order]
        self.cnts = cnts[order]

        # Latest: same keep-last merge, row-major cells gathered once.
        lb_keep = ~np.isin(self.lat_items, seen_lat)
        lb_cell = np.repeat(lb_keep, self.lat_lens)
        items_parts = [self.lat_items[lb_keep]]
        lens_parts = [self.lat_lens[lb_keep]]
        others_parts = [self.lat_others[lb_cell]]
        scores_parts = [self.lat_scores[lb_cell]]
        for i, d in enumerate(deltas):
            cell_keep = np.repeat(keep_lat[i], d.lat_lens)
            items_parts.append(d.lat_rows[keep_lat[i]])
            lens_parts.append(d.lat_lens[keep_lat[i]])
            others_parts.append(d.lat_others[cell_keep])
            scores_parts.append(d.lat_scores[cell_keep])
        items = np.concatenate(items_parts)
        lens = np.concatenate(lens_parts)
        others = np.concatenate(others_parts)
        scores = np.concatenate(scores_parts)
        starts = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(
            np.int64)
        lorder = np.argsort(items, kind="stable")
        csel = _range_indices(starts[lorder], starts[lorder]
                              + lens[lorder])
        self.lat_items = items[lorder]
        self.lat_lens = lens[lorder]
        self.lat_others = others[csel]
        self.lat_scores = scores[csel]

        # Sequential dense overlays (cheap, order matters).
        for d in deltas:
            rs = np.zeros(d.row_sums_len, dtype=np.int64)
            rs[: len(self.row_sums)] = self.row_sums
            rs[d.rows] = d.row_sums
            self.row_sums = rs
            self.observed = d.observed
            # Vocab appends (append-only: lengths must agree exactly —
            # the anchor protocol guarantees contiguity, so a mismatch
            # is a torn or foreign chain).
            if len(self.item_vocab) + len(d.voc_items) \
                    != d.item_vocab_len:
                raise DeltaCorrupt(
                    f"delta generation {d.gen} item-vocab appends do "
                    f"not extend the chain ({len(self.item_vocab)} + "
                    f"{len(d.voc_items)} != {d.item_vocab_len})")
            if len(self.user_vocab) + len(d.voc_users) \
                    != d.user_vocab_len:
                raise DeltaCorrupt(
                    f"delta generation {d.gen} user-vocab appends do "
                    f"not extend the chain")
            self.item_vocab = np.concatenate([self.item_vocab,
                                              d.voc_items])
            self.user_vocab = np.concatenate([self.user_vocab,
                                              d.voc_users])
            # Reservoir overlay.
            if self.hist is not None:
                if d.hist_k != self.hist.shape[1]:
                    raise DeltaCorrupt(
                        f"delta generation {d.gen} reservoir width "
                        f"{d.hist_k} != chain's {self.hist.shape[1]}")
                u = d.user_vocab_len
                if u > len(self.hist):
                    k = self.hist.shape[1]
                    grown = np.zeros((u, k), dtype=self.hist.dtype)
                    grown[: len(self.hist)] = self.hist
                    self.hist = grown
                    for name in ("hist_len", "total", "draws"):
                        old = getattr(self, name)
                        g = np.zeros(u, dtype=np.int64)
                        g[: len(old)] = old
                        setattr(self, name, g)
                du = d.usr_rows
                self.hist[du] = 0
                hsel = _range_indices(
                    du * self.hist.shape[1],
                    du * self.hist.shape[1] + d.usr_lens)
                self.hist.reshape(-1)[hsel] = d.usr_hist.astype(
                    self.hist.dtype)
                self.hist_len[du] = d.usr_lens
                self.total[du] = d.usr_total
                self.draws[du] = d.usr_draws

    def close(self) -> "Tuple[dict, tuple, dict]":
        """Canonical arrays at the top generation: ``(blob, latest,
        aux)`` in the exact dtypes/layout ``checkpoint.save`` writes."""
        if self.mh:
            blob = {
                "mh_rows_key": self.keys,
                "mh_local_cnt": _pack_mh_counts(
                    self.keys, self.cnts, self.n_shards,
                    self.local_shards),
            }
        else:
            blob = {"rows_key": self.keys, "rows_cnt": self.cnts}
        blob["row_sums"] = self.row_sums
        blob["observed"] = np.asarray([self.observed], dtype=np.int64)
        offsets = np.concatenate(
            [[0], np.cumsum(self.lat_lens)]).astype(np.int64)
        latest = (self.lat_items.astype(np.int64), offsets,
                  self.lat_others.astype(np.int64),
                  self.lat_scores.astype(np.float64))
        aux = {"item_vocab": self.item_vocab,
               "user_vocab": self.user_vocab}
        if self.hist is not None:
            aux.update(hist=self.hist, hist_len=self.hist_len,
                       total=self.total, draws=self.draws)
        return blob, latest, aux
