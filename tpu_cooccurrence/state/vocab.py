"""External-id <-> dense-index mapping.

The reference keys operators by raw integer ids via hash partitioning; the
TPU path needs *dense* indices to address device arrays (the co-occurrence
matrix row/col space). Ids are assigned in first-appearance order, which is
deterministic for a fixed stream — this also makes the dense index a stable
RNG key for the reservoir sampler.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class IdMap:
    """Grow-only external->dense id mapping with batch lookup."""

    def __init__(self) -> None:
        self._fwd: Dict[int, int] = {}
        self._rev: list = []
        self._rev_arr: np.ndarray = np.zeros(0, dtype=np.int64)  # cache

    def __len__(self) -> int:
        return len(self._rev)

    def map_batch(self, ids: np.ndarray) -> np.ndarray:
        """Map a batch of external ids, assigning new dense ids as needed.

        Dense ids are assigned in first-appearance order. Only the batch's
        *unique* ids touch the Python dict; the expansion back to the full
        batch is a vectorized take.
        """
        fwd = self._fwd
        rev = self._rev
        uniq, inverse = np.unique(ids, return_inverse=True)
        dense_uniq = np.empty(len(uniq), dtype=np.int64)
        missing = []
        for pos, ext in enumerate(uniq.tolist()):
            dense = fwd.get(ext)
            if dense is None:
                missing.append(pos)
            else:
                dense_uniq[pos] = dense
        if missing:
            # np.unique sorts, but first-appearance order must win for
            # determinism: assign new ids by first position in the batch.
            first_pos = np.full(len(uniq), np.iinfo(np.int64).max, dtype=np.int64)
            np.minimum.at(first_pos, inverse, np.arange(len(inverse), dtype=np.int64))
            missing.sort(key=lambda u_idx: int(first_pos[u_idx]))
            for u_idx in missing:
                ext = int(uniq[u_idx])
                dense = len(rev)
                fwd[ext] = dense
                rev.append(ext)
                dense_uniq[u_idx] = dense
        return dense_uniq[inverse]

    def to_external(self, dense: int) -> int:
        return self._rev[dense]

    def to_dense(self, ext):
        """Dense id for an external id, or ``None`` if never seen."""
        return self._fwd.get(ext)

    def to_external_batch(self, dense: np.ndarray) -> np.ndarray:
        # Rebuilt only when the vocab has grown since the last call (result
        # materialization calls this per row — it must not be O(vocab)).
        if len(self._rev_arr) != len(self._rev):
            self._rev_arr = np.asarray(self._rev, dtype=np.int64)
        return self._rev_arr[dense]

    # -- checkpoint ------------------------------------------------------

    def checkpoint_state(self) -> np.ndarray:
        return np.asarray(self._rev, dtype=np.int64)

    def restore_state(self, rev: np.ndarray) -> None:
        self._rev = [int(x) for x in rev]
        self._fwd = {ext: i for i, ext in enumerate(self._rev)}
        self._rev_arr = np.zeros(0, dtype=np.int64)  # length check is not
        # enough here: a same-length restore must still drop the cache
