"""External-id <-> dense-index mapping.

The reference keys operators by raw integer ids via hash partitioning; the
TPU path needs *dense* indices to address device arrays (the co-occurrence
matrix row/col space). Ids are assigned in first-appearance order, which is
deterministic for a fixed stream — this also makes the dense index a stable
RNG key for the reservoir sampler.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..ops.aggregate import merge_sorted_insert


class IdMap:
    """Grow-only external->dense id mapping with batch lookup.

    Two regimes, switched automatically:

    * **table** (fast path): while every external id is a small
      non-negative int (true of every benchmark dataset — MovieLens /
      Instacart ids and the synthetic streams are bounded), lookups are a
      single fancy-index into a dense ``ext -> dense+1`` table — O(n),
      no sort. The table grows to the max id seen, capped at
      ``_TABLE_CAP`` entries (128 MB).
    * **sorted** (general path): first batch with a negative or
      too-large id permanently switches to a sorted (external, dense)
      array pair — fully vectorized ``searchsorted``. The per-batch
      ``np.unique`` sort this pays was the vocab-mapping hot spot at the
      25M-event shape, which is why the table path exists.

    A lazy dict mirror serves the scalar :meth:`to_dense` API.
    """

    _TABLE_CAP = 1 << 24

    def __init__(self) -> None:
        self._keys = np.zeros(0, dtype=np.int64)   # sorted external ids
        self._vals = np.zeros(0, dtype=np.int64)   # dense id per key
        self._rev: list = []
        self._rev_arr: np.ndarray = np.zeros(0, dtype=np.int64)  # cache
        self._fwd: Dict[int, int] = {}  # lazy mirror for to_dense()
        self._fwd_n = 0  # how many dense ids the mirror covers
        self._table: Optional[np.ndarray] = np.zeros(1024, dtype=np.int64)

    def __len__(self) -> int:
        return len(self._rev)

    def map_batch(self, ids: np.ndarray) -> np.ndarray:
        """Map a batch of external ids, assigning new dense ids as needed.

        Dense ids are assigned in first-appearance order (deterministic for
        a fixed stream). No per-id Python loop in either regime.
        """
        ids = np.asarray(ids, dtype=np.int64)
        if self._table is not None and len(ids):
            mx = int(ids.max())
            if int(ids.min()) >= 0 and mx < self._TABLE_CAP:
                return self._map_table(ids, mx)
            self._leave_table_mode()
        return self._map_sorted(ids)

    def _map_table(self, ids: np.ndarray, mx: int) -> np.ndarray:
        table = self._table
        if mx >= len(table):
            grown = np.zeros(max(2 * len(table), mx + 1), dtype=np.int64)
            grown[: len(table)] = table
            self._table = table = grown
        dense1 = table[ids]  # dense id + 1; 0 = unseen
        miss = dense1 == 0
        if miss.any():
            miss_ids = ids[miss]
            # First-appearance dedup WITHOUT sorting (np.unique sorts —
            # measured as the mapping's dominant cost on vocab-heavy
            # streams): scatter descending markers over the reversed
            # array (last write wins => the first occurrence's marker
            # survives), then keep exactly the positions whose marker
            # reads back as their own. The temp markers only touch miss
            # slots, every one of which is finalized just below.
            n = len(miss_ids)
            table[miss_ids[::-1]] = np.arange(n, 0, -1, dtype=np.int64)
            is_first = table[miss_ids] == np.arange(1, n + 1)
            new_ext = miss_ids[is_first]  # in first-appearance order
            base = len(self._rev)
            table[new_ext] = base + 1 + np.arange(len(new_ext),
                                                  dtype=np.int64)
            self._rev.extend(new_ext.tolist())
            dense1 = table[ids]
        return dense1 - 1

    def _leave_table_mode(self) -> None:
        """Materialize the sorted arrays from ``_rev`` and switch for good
        (an id outside the table regime was seen)."""
        rev = np.asarray(self._rev, dtype=np.int64)
        order = np.argsort(rev, kind="stable")
        self._keys = rev[order]
        self._vals = order.astype(np.int64)
        self._table = None

    def _map_sorted(self, ids: np.ndarray) -> np.ndarray:
        uniq, inverse = np.unique(ids, return_inverse=True)
        dense_uniq = np.empty(len(uniq), dtype=np.int64)
        if len(self._keys):
            pos = np.searchsorted(self._keys, uniq)
            safe = np.minimum(pos, len(self._keys) - 1)
            hit = self._keys[safe] == uniq
        else:
            pos = np.zeros(len(uniq), dtype=np.int64)
            hit = np.zeros(len(uniq), dtype=bool)
        dense_uniq[hit] = self._vals[pos[hit]]
        miss = np.flatnonzero(~hit)
        if len(miss):
            # np.unique sorts, but first-appearance order must win for
            # determinism: assign new ids by first position in the batch.
            first_pos = np.full(len(uniq), np.iinfo(np.int64).max,
                                dtype=np.int64)
            np.minimum.at(first_pos, inverse,
                          np.arange(len(inverse), dtype=np.int64))
            order = miss[np.argsort(first_pos[miss], kind="stable")]
            new_ext = uniq[order]
            new_dense = len(self._rev) + np.arange(len(order), dtype=np.int64)
            dense_uniq[order] = new_dense
            self._rev.extend(new_ext.tolist())
            # Merge the (sorted) new keys into the sorted lookup arrays.
            ins = pos[miss]  # miss is sorted, so uniq[miss] is sorted too
            self._keys, self._vals = merge_sorted_insert(
                self._keys, self._vals, ins, uniq[miss], dense_uniq[miss])
        return dense_uniq[inverse]

    def to_external(self, dense: int) -> int:
        return self._rev[dense]

    def to_dense(self, ext):
        """Dense id for an external id, or ``None`` if never seen.

        Safe under concurrent growth (serving query threads call this
        while the ingest thread appends): the catch-up bound is captured
        ONCE — re-reading ``len(self._rev)`` after the fill loop could
        mark ids mapped mid-loop as covered without ever filling them,
        silently resolving those users/items to ``None`` forever.
        """
        n = len(self._rev)
        if self._fwd_n != n:
            for dense in range(self._fwd_n, n):
                self._fwd[self._rev[dense]] = dense
            self._fwd_n = n
        return self._fwd.get(ext)

    def external_array(self) -> np.ndarray:
        """The dense -> external id array, refreshed if the vocab grew.

        The returned object is never mutated (growth *replaces* the
        cache), so a caller may hold it across its own reads — the
        serving snapshot captures it at publish and reads it lock-free.
        """
        # Rebuilt only when the vocab has grown since the last call (result
        # materialization calls this per row — it must not be O(vocab)).
        if len(self._rev_arr) != len(self._rev):
            self._rev_arr = np.asarray(self._rev, dtype=np.int64)
        return self._rev_arr

    def to_external_batch(self, dense: np.ndarray) -> np.ndarray:
        return self.external_array()[dense]

    # -- checkpoint ------------------------------------------------------

    def checkpoint_state(self) -> np.ndarray:
        return np.asarray(self._rev, dtype=np.int64)

    def restore_state(self, rev: np.ndarray) -> None:
        self._rev = [int(x) for x in rev]
        rev = np.asarray(rev, dtype=np.int64)
        if len(rev) == 0 or (rev.min() >= 0 and rev.max() < self._TABLE_CAP):
            # Rebuild the fast-path table (mode is part of restored state).
            n = max(1024, int(rev.max(initial=0)) + 1)
            self._table = np.zeros(n, dtype=np.int64)
            self._table[rev] = 1 + np.arange(len(rev), dtype=np.int64)
            self._keys = np.zeros(0, dtype=np.int64)
            self._vals = np.zeros(0, dtype=np.int64)
        else:
            self._leave_table_mode()
        self._fwd = {}
        self._fwd_n = 0
        self._rev_arr = np.zeros(0, dtype=np.int64)  # length check is not
        # enough here: a same-length restore must still drop the cache
