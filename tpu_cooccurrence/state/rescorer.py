"""Host (exact, float64) rescoring backend.

Dict-based materialized rows + global row sums + observed total, mirroring
the reference rescorer's plain-Java-map state
(``ItemRowRescorerTwoInputStreamOperator.java:33-37,59-69``) and its scoring
loop (:158-228). Used as the ``oracle`` production backend and as the exact
baseline the device backends are validated against.

Row-sum updates are derived from the pair stream (segment-sum by source row
— see ``sampling/reservoir.py`` fact 3) and applied *before* scoring the
window's rows, preserving the reference's watermark ordering (:116-142).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metrics import Counters, RESCORED_ITEMS, ROW_SUM_PROCESS_WINDOW
from ..oracle.heap import TopKHeap
from ..ops.llr import llr_np
from ..sampling.reservoir import PairDeltaBatch

# One window's emissions: [(item, [(other, score) desc]), ...]
WindowTopK = List[Tuple[int, List[Tuple[int, float]]]]


class HostRescorer:
    # Pipelined mode (pipeline.py) may hand this scorer pre-folded
    # AggregatedPairs instead of a raw PairDeltaBatch. The math below only
    # touches ``src``/``dst``/``delta`` and is invariant under the fold:
    # per-item row sums, per-cell row updates, and the rescored-item set
    # are identical whether deltas arrive raw or cell-aggregated, so the
    # oracle stays an exact baseline for either execution mode.
    accepts_aggregated = True

    def __init__(self, top_k: int, counters: Optional[Counters] = None,
                 development_mode: bool = False) -> None:
        self.top_k = top_k
        # Degradation plane (robustness/degrade.py): the top-K width
        # actually emitted. Tighten-only; identity at NORMAL. Only the
        # emitted heap narrows — row/row-sum state is untouched, so a
        # later NORMAL window re-emits full-width rows from exact state.
        self.effective_top_k = top_k
        self.counters = counters if counters is not None else Counters()
        self.development_mode = development_mode
        self.item_rows: Dict[int, Dict[int, int]] = {}
        self.global_row_sums: Dict[int, int] = {}
        self.observed: int = 0
        self._heap = TopKHeap(top_k)

    def set_effective_top_k(self, k: int) -> None:
        """Set the emitted top-K width (shedding knob)."""
        k = max(1, min(self.top_k, k))
        if k != self.effective_top_k:
            self.effective_top_k = k
            self._heap = TopKHeap(k)

    def process_window(self, ts: int, pairs: PairDeltaBatch) -> WindowTopK:
        if len(pairs) == 0:
            return []
        src = pairs.src
        dst = pairs.dst
        delta = pairs.delta.astype(np.int64)

        # Row-sum updates first (reference :116-142, :144-156).
        rs_items, rs_inv = np.unique(src, return_inverse=True)
        rs_sums = np.bincount(rs_inv, weights=delta).astype(np.int64)
        for item, s in zip(rs_items.tolist(), rs_sums.tolist()):
            if s != 0:  # zero suppression (RowSumAggregator.java:66-70)
                self.counters.add(ROW_SUM_PROCESS_WINDOW, s)
                self.global_row_sums[item] = self.global_row_sums.get(item, 0) + s
                self.observed += s

        # Aggregate pair deltas into per-row delta maps
        # (ItemRowAggregator.java:26-31) and score each updated row.
        order = np.argsort(src, kind="stable")
        src_s, dst_s, delta_s = src[order], dst[order], delta[order]
        boundaries = np.flatnonzero(src_s[1:] != src_s[:-1]) + 1
        out: WindowTopK = []
        for chunk_idx in np.split(np.arange(len(src_s)), boundaries):
            item = int(src_s[chunk_idx[0]])
            row = self.item_rows.setdefault(item, {})
            for j, d in zip(dst_s[chunk_idx].tolist(), delta_s[chunk_idx].tolist()):
                row[j] = row.get(j, 0) + d
            out.append((item, self._score_row(item, row)))
        return out

    def _score_row(self, item: int, row: Dict[int, int]) -> List[Tuple[int, float]]:
        self.counters.add(RESCORED_ITEMS, 1)
        row_sum = self.global_row_sums.get(item, 0)
        if self.development_mode:
            actual = sum(row.values())
            if actual != row_sum:
                raise AssertionError(
                    f"Item row {row_sum} does not match actual row sum {actual}")
        # Sorted column order: deterministic tie-breaking (lowest index wins
        # among equal scores, matching lax.top_k) that survives
        # checkpoint/restore — unlike the reference, whose tie order floats
        # with hashmap iteration order.
        others = np.array(sorted(j for j, c in row.items() if c != 0),
                          dtype=np.int64)
        if len(others) == 0:
            return []
        k11 = np.fromiter((row[int(j)] for j in others), dtype=np.int64,
                          count=len(others))
        other_sums = np.fromiter(
            (self.global_row_sums.get(int(j), 0) for j in others),
            dtype=np.int64, count=len(others))
        k12 = row_sum - k11
        k21 = other_sums - k11
        k22 = self.observed + k11 - k12 - k21
        scores = llr_np(k11, k12, k21, k22)
        if self.development_mode and np.any(np.isnan(scores)):
            bad = int(np.flatnonzero(np.isnan(scores))[0])
            raise AssertionError(
                f"Score is NaN (item: {item}, otherItem: {int(others[bad])})")
        self._heap.reset()
        for j, s in zip(others.tolist(), scores.tolist()):
            self._heap.offer(j, s)
        return self._heap.sorted_desc()

    # -- checkpoint ------------------------------------------------------

    def checkpoint_state(self) -> dict:
        items = sorted(self.item_rows)
        flat_src, flat_dst, flat_cnt = [], [], []
        for i in items:
            for j, c in self.item_rows[i].items():
                if c != 0:
                    flat_src.append(i)
                    flat_dst.append(j)
                    flat_cnt.append(c)
        rs_items = np.asarray(sorted(self.global_row_sums), dtype=np.int64)
        return {
            "rows_src": np.asarray(flat_src, dtype=np.int64),
            "rows_dst": np.asarray(flat_dst, dtype=np.int64),
            "rows_cnt": np.asarray(flat_cnt, dtype=np.int64),
            "rs_items": rs_items,
            "rs_sums": np.asarray(
                [self.global_row_sums[int(i)] for i in rs_items], dtype=np.int64),
            "observed": np.asarray([self.observed], dtype=np.int64),
        }

    def restore_state(self, st: dict) -> None:
        self.item_rows = {}
        for i, j, c in zip(st["rows_src"].tolist(), st["rows_dst"].tolist(),
                           st["rows_cnt"].tolist()):
            self.item_rows.setdefault(i, {})[j] = c
        self.global_row_sums = dict(
            zip(st["rs_items"].tolist(), st["rs_sums"].tolist()))
        self.observed = int(st["observed"][0])
