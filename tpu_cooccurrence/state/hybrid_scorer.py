"""Hybrid scoring backend: host sparse matrix + device batched LLR/top-K.

For vocabularies where a dense item x item device matrix is infeasible
(benchmark config 4: 1M items — a dense C would be 4 TB), this backend keeps
the co-occurrence matrix as a host-side sorted-COO structure (the sparse
analogue of the reference rescorer's materialized rows,
``ItemRowRescorerTwoInputStreamOperator.java:35,172-177``) and ships each
window's *updated rows only* to the device as padded ``[S, R]`` blocks for
vectorized LLR + ``lax.top_k`` — the compute-hot part of rescoring (hot
loop 4, SURVEY §3.4).

The matrix is three parallel arrays sorted by (row, col); a window update is
one concatenate + lexsort + segment-reduce — no Python-level per-row or
per-entry loops anywhere, so ~1e9-pair streams stay tractable host-side.
Scales to any vocabulary bounded by host memory; device memory is O(S * R)
per window instead of O(I^2).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..metrics import Counters, RESCORED_ITEMS, ROW_SUM_PROCESS_WINDOW
from ..ops.aggregate import (aggregate_window_coo, distinct_sorted,
                             merge_sorted_insert)
from ..ops.llr import llr_stable
from ..ops.device_scorer import pad_pow2
from ..sampling.reservoir import PairDeltaBatch
from .results import TopKBatch


@functools.partial(jax.jit, static_argnames=("top_k",))
def _score_rows_batched(block, row_sums, observed, top_k: int):
    """LLR + top-K over padded row blocks.

    block    [2, S, R] f32 — (k11 counts, rowSum(j)) per row nonzero; padded
             and zero-count slots carry ``k11 == 0`` (the validity mask —
             the reference skips zero cells too, so no separate mask ships)
    row_sums [S] f32 — rowSum(i) per scored row

    One packed input and one packed ``[2, S, K]`` output (scores; slot
    indices bitcast): the tunneled host<->device hop is bandwidth- and
    per-transfer-latency-bound, so both count and bytes matter.
    """
    k11 = block[0]
    other_sums = block[1]
    rsi = row_sums[:, None]
    k12 = rsi - k11
    k21 = other_sums - k11
    k22 = observed + k11 - k12 - k21
    scores = llr_stable(k11, k12, k21, k22)
    scores = jnp.where(k11 != 0, scores, -jnp.inf)
    vals, idx = jax.lax.top_k(scores, top_k)
    return jnp.stack([vals, jax.lax.bitcast_convert_type(idx, jnp.float32)])


class HybridScorer:
    """Host sorted-COO matrix, device-batched scoring.

    Entries are keyed ``src << 32 | dst`` in one sorted int64 array; a
    window merge touches only existing entries in place (searchsorted) and
    inserts new ones with a single O(nnz) memcpy — no global re-sort."""

    def __init__(self, top_k: int, counters: Optional[Counters] = None,
                 development_mode: bool = False,
                 row_sum_capacity: int = 1024) -> None:
        from ..xla_cache import enable_compilation_cache

        enable_compilation_cache()
        self.top_k = top_k
        self.counters = counters if counters is not None else Counters()
        self.development_mode = development_mode
        # Global matrix: (sorted packed keys, counts). Zero counts are kept
        # until compaction (cheaper than re-building every window).
        self.g_key = np.zeros(0, dtype=np.int64)
        self.g_cnt = np.zeros(0, dtype=np.int64)
        self._zeros = 0
        self.row_sums = np.zeros(row_sum_capacity, dtype=np.int64)
        self.observed = 0
        # One-window-deep result pipeline (see ops/device_scorer.py): the
        # latency-bound device->host fetch of window N's top-K overlaps
        # window N+1's host merge and dispatch; ``flush()`` drains the tail.
        self._pending: Optional[List] = None
        self.last_dispatched_rows = 0
        # Introspection: lifetime chunk counts per scoring path, so tests
        # can assert a stream actually exercised both host and device paths.
        self.dispatched_host_chunks = 0
        self.dispatched_device_chunks = 0

    def _ensure(self, max_id: int) -> None:
        # Strict bound: id 2^31 - 1 would overflow the (rows + 1) << 32
        # row-end search probe in int64.
        if max_id >= (1 << 31) - 1:
            raise ValueError("hybrid backend supports item ids < 2^31 - 1")
        if max_id >= len(self.row_sums):
            grown = np.zeros(max(2 * len(self.row_sums), max_id + 1),
                             dtype=np.int64)
            grown[: len(self.row_sums)] = self.row_sums
            self.row_sums = grown

    def process_window(self, ts: int, pairs: PairDeltaBatch):
        self.last_dispatched_rows = 0
        if len(pairs) == 0:
            # No new dispatch this window — drain any completed in-flight
            # results now instead of withholding them behind idle windows.
            return self.flush()
        delta64 = pairs.delta.astype(np.int64)
        self._ensure(int(max(pairs.src.max(), pairs.dst.max())))

        # Row sums first (watermark ordering, reference :116-142).
        np.add.at(self.row_sums, pairs.src, delta64)
        window_sum = int(delta64.sum())
        self.observed += window_sum
        self.counters.add(ROW_SUM_PROCESS_WINDOW, window_sum)

        # Aggregate the window's COO to unique sorted keys (shared helper,
        # ops/aggregate.py; key order matches the matrix's packed-key sort).
        _, _, d_val, d_key = aggregate_window_coo(
            pairs.src, pairs.dst, delta64, return_key=True)

        # Merge: in-place update for existing keys, single insert for new.
        if len(self.g_key):
            idx = np.searchsorted(self.g_key, d_key)
            safe = np.minimum(idx, len(self.g_key) - 1)
            exists = self.g_key[safe] == d_key
            hit = idx[exists]
            old = self.g_cnt[hit]
            new = old + d_val[exists]
            self._zeros += (int(((old != 0) & (new == 0)).sum())
                            - int(((old == 0) & (new != 0)).sum()))
            self.g_cnt[hit] = new
            if not exists.all():
                miss = ~exists
                # Keys inserted with a net-zero window delta (e.g. +1 then
                # -1 within one window) are zero entries from birth.
                self._zeros += int((d_val[miss] == 0).sum())
                self.g_key, self.g_cnt = merge_sorted_insert(
                    self.g_key, self.g_cnt, idx[miss], d_key[miss],
                    d_val[miss])
        else:
            self.g_key = d_key
            self.g_cnt = d_val
            self._zeros = int((d_val == 0).sum())
        # Compact lazily once zero entries exceed 10% of storage.
        if self._zeros * 10 > len(self.g_cnt):
            keep = self.g_cnt != 0
            self.g_key = self.g_key[keep]
            self.g_cnt = self.g_cnt[keep]
            self._zeros = 0

        # Rows to score: every row that received any delta (even net-zero,
        # matching the reference's bufferedItemRowDeltas keying, :87-91).
        # d_key is sorted, so distinct srcs fall out without a re-sort.
        rows = distinct_sorted((d_key >> 32))
        self.counters.add(RESCORED_ITEMS, len(rows))
        self.last_dispatched_rows = len(rows)

        starts = np.searchsorted(self.g_key, rows << 32, side="left")
        ends = np.searchsorted(self.g_key, (rows + 1) << 32, side="left")
        lens = ends - starts

        if self.development_mode:
            # Row-sum consistency (reference dev check, :183-193), as
            # segment sums over the sorted storage (empty storage included:
            # every scored row must then sum to zero).
            cs = np.concatenate([[0], np.cumsum(self.g_cnt)])
            sums = cs[ends] - cs[starts]
            expect = self.row_sums[rows]
            if not np.array_equal(sums, expect):
                bad = int(np.flatnonzero(sums != expect)[0])
                raise AssertionError(
                    f"Item row {int(expect[bad])} does not match actual row "
                    f"sum {int(sums[bad])} (item {int(rows[bad])})")

        chunks: List[Tuple[np.ndarray, np.ndarray, object]] = []
        if len(self.g_cnt):
            # Split by row length. Short rows (the long-tail mass at big
            # vocabularies — typically >95% of rows but a sliver of the
            # cells) are scored ON HOST in float64: shipping them padded to
            # device rectangles cost ~20x their content in transfer on the
            # ~100 MB/s tunneled link, while host numpy scores them in
            # milliseconds. Long rows (head items, most of the cells) go to
            # the device in length-bucketed [S_pad, R] blocks where padding
            # is tight.
            short = lens <= self.HOST_ROW_MAX
            if short.any():
                self.dispatched_host_chunks += 1
                chunks.append(self._score_short_rows_host(
                    rows[short], starts[short], lens[short]))
            long_idx = np.flatnonzero(~short)
            # Length-bucketed device blocks over a bounded two-dimensional
            # shape ladder — R is the pow-2 row-length bucket, S_pad =
            # min(pad_pow2(S), budget // R) — so at most O(log R x log S)
            # programs ever compile (a free per-chunk S_pad walks an
            # unbounded shape space on a growing stream, and every new
            # combination is a multi-second XLA compile on the tunneled
            # chip). Dispatches are async (one packed buffer each); the
            # fetch happens one window later (see flush/_materialize).
            by_len = long_idx[np.argsort(lens[long_idx], kind="stable")]
            budget = 1 << 20
            pos = 0
            min_r = max(16, self.top_k)  # lax.top_k needs k <= R
            while pos < len(by_len):
                R = pad_pow2(int(lens[by_len[pos]]) or 1, minimum=min_r)
                s_block = max(budget // R, 16)
                chunk = by_len[pos: pos + s_block]
                # Extend R to cover the chunk's longest row (sorted
                # ascending, so it's the last element), then trim the chunk
                # if R grew.
                R = pad_pow2(int(lens[chunk[-1]]) or 1, minimum=min_r)
                s_block = max(budget // R, 16)
                chunk = chunk[:s_block]
                pos += len(chunk)
                s_pad = min(pad_pow2(len(chunk), minimum=16), s_block)
                self.dispatched_device_chunks += 1
                chunks.append(self._dispatch_chunk(
                    rows[chunk], starts[chunk], lens[chunk], R, s_pad))
        else:
            # Entire matrix cancelled to zero: every scored row is empty
            # (all -inf batch; ids are filtered at materialization).
            chunks.append((rows.astype(np.int32),
                           np.zeros((len(rows), 1), np.int32), None))

        prev, self._pending = self._pending, chunks
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    # Rows at or below this length are scored on host (float64, exact);
    # above it, on device. Sized so host LLR work stays in the single-digit
    # milliseconds per window while the padded-rectangle transfer the host
    # path replaces would have dwarfed the content.
    HOST_ROW_MAX = 32

    def _score_short_rows_host(self, rows, starts, lens):
        """Score rows of <= HOST_ROW_MAX nonzeros on host; returns a chunk
        in already-materialized form (ids final, payload == 'host')."""
        from ..ops.llr import llr_np

        S = len(rows)
        R = max(int(lens.max()) if S else 1, 1)
        col_idx = np.arange(R, dtype=np.int64)[None, :]
        valid = col_idx < lens[:, None]
        flat_idx = np.minimum(starts[:, None] + col_idx, len(self.g_cnt) - 1)
        k11 = np.where(valid, self.g_cnt[flat_idx], 0).astype(np.float64)
        valid &= k11 != 0  # zero entries (pending compaction) unscored
        cols = np.where(valid, self.g_key[flat_idx] & 0xFFFFFFFF,
                        0).astype(np.int64)
        rsj = np.where(valid, self.row_sums[cols], 0).astype(np.float64)
        rsi = self.row_sums[rows].astype(np.float64)[:, None]
        k12 = rsi - k11
        k21 = rsj - k11
        k22 = float(self.observed) + k11 - k12 - k21
        scores = llr_np(k11, k12, k21, k22)
        scores[~valid] = -np.inf
        # Stable argsort of -scores: descending scores, ties broken by the
        # lower column (matches the device lax.top_k tie-break).
        order = np.argsort(-scores, axis=1, kind="stable")[:, : self.top_k]
        vals = np.take_along_axis(scores, order, axis=1).astype(np.float32)
        idx = np.take_along_axis(cols, order, axis=1).astype(np.int32)
        if vals.shape[1] < self.top_k:  # every row shorter than K
            pad = self.top_k - vals.shape[1]
            vals = np.pad(vals, ((0, 0), (0, pad)),
                          constant_values=-np.inf)
            idx = np.pad(idx, ((0, 0), (0, pad)))
        return rows.astype(np.int32), idx, (("host", vals))

    def _dispatch_chunk(self, rows, starts, lens, R, S_pad):
        """Async-dispatch one [S_pad, R] block; returns (rows, col ids, buf)."""
        S = len(rows)
        col_idx = np.arange(R, dtype=np.int64)[None, :]
        valid = np.zeros((S_pad, R), dtype=bool)
        valid[:S] = col_idx < lens[:, None]
        flat_idx = np.zeros((S_pad, R), dtype=np.int64)
        flat_idx[:S] = np.minimum(starts[:, None] + col_idx,
                                  len(self.g_cnt) - 1)
        block = np.zeros((2, S_pad, R), dtype=np.float32)
        k11 = block[0]
        np.copyto(k11, np.where(valid, self.g_cnt[flat_idx], 0))
        valid &= k11 != 0  # zero entries (pending compaction) are not scored
        cols_padded = np.where(valid, self.g_key[flat_idx] & 0xFFFFFFFF,
                               0).astype(np.int32)
        np.copyto(block[1], np.where(valid, self.row_sums[cols_padded], 0))
        rsums = np.zeros(S_pad, dtype=np.float32)
        rsums[:S] = self.row_sums[rows]

        packed = _score_rows_batched(
            block, rsums, np.float32(self.observed), top_k=self.top_k)
        if hasattr(packed, "copy_to_host_async"):
            packed.copy_to_host_async()
        return rows.astype(np.int32), cols_padded[:S], packed

    def flush(self) -> TopKBatch:
        """Emit the final in-flight window's results (end of pipeline)."""
        prev, self._pending = self._pending, None
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _materialize(self, chunks) -> TopKBatch:
        rows_l, idx_l, vals_l = [], [], []
        for rows, cols_padded, packed in chunks:
            S = len(rows)
            if packed is None:  # zero-matrix window: all-empty rows
                rows_l.append(rows)
                vals_l.append(np.full((S, self.top_k), -np.inf, np.float32))
                idx_l.append(np.zeros((S, self.top_k), np.int32))
                continue
            if isinstance(packed, tuple) and packed[0] == "host":
                # Host-scored chunk (_score_short_rows_host): ids and values
                # are already final — cols_padded IS the [S, K] id matrix.
                rows_l.append(rows)
                idx_l.append(cols_padded)
                vals_l.append(packed[1])
                continue
            host = np.asarray(packed)  # single [2, S_pad, K] fetch
            vals = host[0, :S]
            slot = host[1, :S].view(np.int32)
            # Map top-K slot indices back to dense item ids. -inf rows carry
            # garbage slots (in-range by top_k's contract); their ids are
            # filtered at materialization (TopKBatch contract).
            idx_l.append(np.take_along_axis(cols_padded, slot, axis=1))
            vals_l.append(vals)
            rows_l.append(rows)
        return TopKBatch.concatenate(rows_l, idx_l, vals_l, self.top_k)

    # -- checkpoint ------------------------------------------------------

    def checkpoint_state(self) -> dict:
        nz = self.g_cnt != 0
        return {
            "rows_key": self.g_key[nz],
            "rows_cnt": self.g_cnt[nz],
            "row_sums": self.row_sums,
            "observed": np.asarray([self.observed], dtype=np.int64),
        }

    def restore_state(self, st: dict) -> None:
        self.g_key = st["rows_key"].copy()
        self.g_cnt = st["rows_cnt"].copy()
        self._zeros = 0
        self.row_sums = st["row_sums"].copy()
        self.observed = int(st["observed"][0])
        # In-flight results belong to windows after the checkpoint; a
        # restore that rolls back must not emit them.
        self._pending = None
