"""Elastic state: the :class:`StateStore` interface + tiered spill cache.

The reference gets elastic state for free from Flink — savepoints can be
rescaled onto a different parallelism, and RocksDB tiers cold state out
of memory (SURVEY L0). This backend's sparse slab had neither: every
live row held HBM cells for the whole run, on a topology fixed at
launch. This module closes both gaps behind one interface:

* **StateStore** — the contract over today's canonical checkpoint blobs
  (``rows_key`` / ``rows_cnt`` / ``row_sums`` / ``observed``, the format
  every sparse-family backend has shared since round 3). A scorer
  delegates ``checkpoint_state`` / ``restore_state`` to its store; the
  store decides *placement* (device slab, host arena, shard bucket)
  while the blob stays backend- and topology-neutral. Checkpoints
  therefore remain interchangeable across stores: any store restores
  any store's blob.

* **DirectSlabStore** — today's behavior: every row device-resident,
  checkpoint/restore pass through to the scorer's device snapshot.

* **TieredSlabStore** — HBM as a managed hot cache over host memory.
  A window-granularity recency clock (one vectorized stamp per window,
  zero per-touch device cost) drives an LRU spill of cold rows into a
  host-side packed arena (:class:`SpillArena`); their index keys are
  *really freed* (``SlabIndex.free_rows`` → the PR-7 registry drops
  them, compaction reclaims the slab region), so hot rows reuse the
  capacity and the device slab stops growing with the long tail.
  A spilled row touched again is **re-promoted before the window's
  deltas apply**: its cells re-enter the index with their within-row
  slab order preserved (``SlabIndex.adopt_rows`` — top-K tie-breaking
  is slot-ordered, so order is part of bit-identity) and the cell
  values ride the window's existing update upload as extra
  new-cell + delta section entries — steady state stays ONE dispatch
  per window (PR 6). Spill/promote is exact movement, never
  approximation: a spill-enabled run is bit-identical to spill-off,
  and its checkpoints are byte-identical (the arena merges back into
  the canonical blob at save).

* **ShardedRescaleStore** — rescale-on-restore for the sharded-sparse
  backend (Flink savepoint semantics): the single-process checkpoint
  blob is written in the GLOBAL key space, so ``restore`` re-buckets
  every cell key onto the *current* mesh via :func:`rebucket_cells`
  (``row % D``) — a checkpoint taken at ``--num-shards N`` restores
  onto M shards bit-identically, N→M in both directions. Multi-host
  (per-process) snapshots still require the writing layout — they
  shard the slab *values* across files, not just the keys.

Residency rules the tiered store shares with the narrow-cell side-table
(``state/wire.cell_promote_threshold``): a spilled row re-promotes to
the wide int32 table when it was wide at spill time OR its
(already-updated) row sum has crossed the promotion bound — exactly the
residency an unspilled run would have (once wide, always wide), so
placement can never diverge from the spill-off run.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..observability import LEDGER


class StateStore:
    """Placement-policy interface over the canonical checkpoint blob.

    ``checkpoint_state`` / ``restore_state`` own the scorer's matrix
    state round trip; ``tick`` / ``promote_touched`` are the per-window
    hooks a tiering policy uses (no-ops for non-tiered stores, so the
    steady-state hot path pays nothing for the indirection).
    """

    kind = "abstract"
    #: True when the store may hold rows outside the device slab.
    tiered = False
    #: Checkpoint dirty-row log (state/delta.DirtyRowLog), lazily
    #: created by :meth:`enable_ckpt_dirty` — class-level ``None``
    #: default so subclasses need no ``__init__`` cooperation and a
    #: run without ``--checkpoint-incremental`` pays nothing.
    _ckpt_log = None

    def checkpoint_state(self) -> dict:
        raise NotImplementedError

    def restore_state(self, st: dict) -> None:
        raise NotImplementedError

    # -- incremental-checkpoint dirty feed ------------------------------
    #
    # One dirty source, two consumers (ISSUE 12): the scorer calls
    # note_touched with the SAME per-window touched-rows set the tiered
    # store's recency clock stamps; the checkpoint writer drains it per
    # generation (state/checkpoint.save) to emit delta files whose
    # bytes scale with churn, not vocab.

    def enable_ckpt_dirty(self):
        """Arm dirty-row tracking (``--checkpoint-incremental``).
        Returns the log."""
        if self._ckpt_log is None:
            from .delta import DirtyRowLog

            self._ckpt_log = DirtyRowLog()
        return self._ckpt_log

    @property
    def ckpt_dirty(self):
        """The dirty log, or ``None`` when incremental checkpoints are
        off."""
        return self._ckpt_log

    def note_touched(self, rows: np.ndarray) -> None:
        """Record this window's touched rows for the checkpoint delta
        (no-op unless :meth:`enable_ckpt_dirty` armed the log)."""
        if self._ckpt_log is not None:
            self._ckpt_log.note(rows)

    def tick(self) -> None:
        """Advance the window clock; spill whatever went cold."""

    def promote_touched(self, rows: np.ndarray):
        """Re-promote spilled rows among ``rows`` (sorted unique dense
        ids, row sums already updated for this window). Returns
        ``(promo_narrow, promo_wide)`` — per-slab extra update-section
        triples ``(cell_keys, dst_vals, cnt_vals)`` or ``None``; the
        scorer resolves keys to slots AFTER the window's ``apply`` (it
        may relocate a just-adopted row)."""
        return None, None

    def record_gauges(self) -> None:
        """Refresh the store's registry gauges (tiering counters)."""


class DirectSlabStore(StateStore):
    """Every row device-resident — the pre-elastic behavior, unchanged.

    Round-trip evidence: ``tests/test_state_store.py`` pins blob
    equivalence against :class:`TieredSlabStore` and the existing
    checkpoint suite exercises it on every sparse resume test.
    """

    kind = "direct"

    def __init__(self, scorer) -> None:
        self.scorer = scorer

    def checkpoint_state(self) -> dict:
        return self.scorer._device_checkpoint_state()

    def restore_state(self, st: dict) -> None:
        self.scorer._device_restore_state(st)


class SpillArena:
    """Host-side packed arena for spilled rows' cells.

    One append-only (keys, counts) array pair plus a ``row -> (offset,
    length, was_wide)`` directory; cells are stored in their within-row
    SLAB order (the order ``adopt_rows`` must reproduce). Popped rows
    leave garbage that a ratio-triggered compaction sweeps — same
    1/3-garbage rule as the device slab's heap.
    """

    def __init__(self) -> None:
        self.keys = np.zeros(0, dtype=np.int64)
        self.cnt = np.zeros(0, dtype=np.int32)
        self.tail = 0
        self.garbage = 0
        self.dir: Dict[int, Tuple[int, int, bool]] = {}

    def __contains__(self, row: int) -> bool:
        return row in self.dir

    def __len__(self) -> int:
        return len(self.dir)

    @property
    def live_cells(self) -> int:
        return self.tail - self.garbage

    @property
    def nbytes(self) -> int:
        return self.keys.nbytes + self.cnt.nbytes + 48 * len(self.dir)

    def _ensure(self, need: int) -> None:
        if need <= len(self.keys):
            return
        cap = max(len(self.keys), 1024)
        while cap < need:
            cap *= 2
        keys = np.zeros(cap, dtype=np.int64)
        cnt = np.zeros(cap, dtype=np.int32)
        keys[: self.tail] = self.keys[: self.tail]
        cnt[: self.tail] = self.cnt[: self.tail]
        self.keys, self.cnt = keys, cnt

    def put_rows(self, rows: np.ndarray, lens: np.ndarray,
                 keys: np.ndarray, cnt: np.ndarray,
                 was_wide: np.ndarray) -> None:
        """Append ``rows`` (cells concatenated in slab order)."""
        n = len(keys)
        self._ensure(self.tail + n)
        self.keys[self.tail: self.tail + n] = keys
        self.cnt[self.tail: self.tail + n] = cnt
        off = self.tail + np.concatenate(
            [[0], np.cumsum(lens)[:-1]]).astype(np.int64)
        for r, o, ln, w in zip(rows.tolist(), off.tolist(), lens.tolist(),
                               was_wide.tolist()):
            self.dir[int(r)] = (int(o), int(ln), bool(w))
        self.tail += n

    def pop_rows(self, rows: np.ndarray):
        """Remove ``rows`` and return ``(lens, keys, cnt, was_wide)``
        with cells concatenated in ``rows`` order (slab order within
        each row)."""
        lens = np.empty(len(rows), dtype=np.int64)
        wide = np.empty(len(rows), dtype=bool)
        keys_l, cnt_l = [], []
        for i, r in enumerate(rows.tolist()):
            off, ln, w = self.dir.pop(int(r))
            lens[i] = ln
            wide[i] = w
            keys_l.append(self.keys[off: off + ln])
            cnt_l.append(self.cnt[off: off + ln])
            self.garbage += ln
        # np.concatenate always allocates (even for one input), so the
        # returned arrays are already detached from the backing store
        # the compaction below may replace — no defensive copy needed.
        keys = (np.concatenate(keys_l) if keys_l
                else np.zeros(0, dtype=np.int64))
        cnt = (np.concatenate(cnt_l) if cnt_l
               else np.zeros(0, dtype=np.int32))
        if self.garbage * 3 > self.tail and self.tail > 4096:
            self._compact()
        return lens, keys, cnt, wide

    def _compact(self) -> None:
        live = sum(ln for _o, ln, _w in self.dir.values())
        keys = np.zeros(max(live, 1024), dtype=np.int64)
        cnt = np.zeros(max(live, 1024), dtype=np.int32)
        pos = 0
        for r in sorted(self.dir):
            off, ln, w = self.dir[r]
            keys[pos: pos + ln] = self.keys[off: off + ln]
            cnt[pos: pos + ln] = self.cnt[off: off + ln]
            self.dir[r] = (pos, ln, w)
            pos += ln
        self.keys, self.cnt = keys, cnt
        self.tail = pos
        self.garbage = 0

    def all_cells(self):
        """Every spilled cell as ``(keys, counts)``, row order by id —
        the checkpoint merge input."""
        keys_l, cnt_l = [], []
        for r in sorted(self.dir):
            off, ln, _w = self.dir[r]
            keys_l.append(self.keys[off: off + ln])
            cnt_l.append(self.cnt[off: off + ln])
        if not keys_l:
            return (np.zeros(0, dtype=np.int64),
                    np.zeros(0, dtype=np.int32))
        return np.concatenate(keys_l), np.concatenate(cnt_l)

    def reset(self) -> None:
        self.keys = np.zeros(0, dtype=np.int64)
        self.cnt = np.zeros(0, dtype=np.int32)
        self.tail = 0
        self.garbage = 0
        self.dir.clear()


class TieredSlabStore(StateStore):
    """LRU cold-row spill over :class:`SpillArena` + exact re-promotion.

    ``threshold_windows`` — rows untouched for this many fired windows
    become spill-eligible. ``target_hbm_frac`` — spilling engages only
    while live device cells exceed this fraction of the allocated slab
    capacity (0.0 = spill every eligible row unconditionally; 1.0 =
    only under a full slab). Eligible rows spill coldest-bucket-first.

    Bit-identity contract (pinned by ``tests/test_state_store.py`` and
    the spill arm of the chaos suite): scores, emitted top-K and
    checkpoint blobs are identical to a spill-off run — the store only
    ever moves exact cell values between tiers, preserves within-row
    slab order across the round trip, and re-promotes *before* the
    window's deltas apply.
    """

    kind = "tiered"
    tiered = True

    def __init__(self, scorer, threshold_windows: int,
                 target_hbm_frac: float = 0.5) -> None:
        if threshold_windows < 1:
            raise ValueError(
                f"spill threshold must be >= 1 window, got "
                f"{threshold_windows}")
        if not (0.0 <= target_hbm_frac <= 1.0):
            raise ValueError(
                f"spill target HBM fraction must be in [0, 1], got "
                f"{target_hbm_frac}")
        self.scorer = scorer
        self.threshold = int(threshold_windows)
        self.frac = float(target_hbm_frac)
        self.clock = 0
        self.last_touch = np.full(scorer.items_cap, -1, dtype=np.int64)
        # Arena residency as a flat bool array (kept in lockstep with
        # arena.dir): the per-window touched-rows membership test must
        # be one vectorized index, not a Python loop over the window.
        self._resident = np.zeros(scorer.items_cap, dtype=bool)
        # clock -> rows stamped then (stale entries — rows re-touched
        # later — are filtered by last_touch equality at spill time).
        self._buckets: Dict[int, np.ndarray] = {}
        self.arena = SpillArena()
        self.evictions = 0
        self.promotions = 0
        self.touches = 0

    # -- bookkeeping ----------------------------------------------------

    def _ensure(self, n: int) -> None:
        if n <= len(self.last_touch):
            return
        grown = np.full(n, -1, dtype=np.int64)
        grown[: len(self.last_touch)] = self.last_touch
        self.last_touch = grown
        res = np.zeros(n, dtype=bool)
        res[: len(self._resident)] = self._resident
        self._resident = res

    def _over_target(self) -> bool:
        sc = self.scorer
        cap = sc.capacity + (sc.capacity_w if sc.index_w is not None else 0)
        return sc.live_cells > self.frac * cap

    # -- the spill step (between windows) -------------------------------

    def tick(self) -> None:
        self.clock += 1
        self._ensure(self.scorer.items_cap)
        limit = self.clock - self.threshold
        if (not self._over_target()
                and len(self._buckets) <= max(4 * self.threshold, 64)):
            # Under the HBM target with a small bucket directory:
            # nothing to spill and nothing worth consolidating — the
            # steady-state tick stays O(1).
            return
        sc = self.scorer
        cap = sc.capacity + (sc.capacity_w if sc.index_w is not None else 0)
        projected = sc.live_cells
        spill_parts = []
        for c in sorted(k for k in self._buckets if k <= limit):
            rows = self._buckets.pop(c)
            rows = rows[self.last_touch[rows] == c]
            if not len(rows):
                continue
            if projected > self.frac * cap:
                # Coldest-bucket-first selection against a host-side
                # projection of live cells; the actual movement is
                # batched into ONE _spill below so the index pays one
                # free_rows (a full table rebuild under the hash
                # layout) per tick, not one per bucket.
                rows = np.unique(rows)
                projected -= self._cells_held(rows)
                spill_parts.append(rows)
                continue
            # Under the HBM target: keep the rows eligible but
            # consolidate them into one bucket at the eligibility
            # horizon, so the bucket directory stays bounded (~threshold
            # entries) on arbitrarily long streams instead of growing
            # one entry per window. Relative coldness among
            # already-eligible rows is deliberately collapsed — they
            # are all past the threshold.
            self.last_touch[rows] = limit
            b = self._buckets.get(limit)
            self._buckets[limit] = (rows if b is None
                                    else np.concatenate([b, rows]))
        if spill_parts:
            # Buckets are disjoint (a row has exactly one last_touch
            # stamp), so unique == merge-sort of the parts.
            self._spill(np.unique(np.concatenate(spill_parts)))

    def _cells_held(self, rows: np.ndarray) -> int:
        """Device cells currently held by ``rows`` across both slabs —
        the spill-selection projection (host registry reads only,
        matches exactly what :meth:`_spill` will remove)."""
        sc = self.scorer
        wmask = (sc.wide_rows[rows] if sc.index_w is not None
                 else np.zeros(len(rows), dtype=bool))
        total = 0
        for wide in (False, True):
            r = rows[wmask] if wide else rows[~wmask]
            if len(r):
                index = sc.index_w if wide else sc.index
                total += int(index.rows.get(r)[1].sum())
        return total

    def _spill(self, rows: np.ndarray) -> None:
        """Move ``rows`` (sorted unique, device-resident) to the arena:
        fetch their cells in slab order, record residency, free the
        index keys (the slab region becomes compactible garbage)."""
        import jax.numpy as jnp

        sc = self.scorer
        wmask = (sc.wide_rows[rows] if sc.index_w is not None
                 else np.zeros(len(rows), dtype=bool))
        for wide in (False, True):
            r = rows[wmask] if wide else rows[~wmask]
            if not len(r):
                continue
            index = sc.index_w if wide else sc.index
            cnt_dev = sc.cnt_w if wide else sc.cnt
            keys, slots = index.row_cells(r)
            _s, lens, _c = index.rows.get(r)
            if len(keys):
                # Slab (slot) order within each row: tie-breaking among
                # equal scores is slot-ordered, so the arena must
                # preserve it for the promotion to be exact.
                seg = np.repeat(np.arange(len(r)), lens)
                order = np.lexsort((slots, seg))
                keys_o = keys[order]
                slots_o = np.ascontiguousarray(slots[order])
                LEDGER.up("spill-slots", slots_o)
                fetched = np.asarray(cnt_dev[jnp.asarray(slots_o)])
                LEDGER.down("spill-cells", fetched)
                vals = fetched.astype(np.int32)
            else:
                keys_o = np.zeros(0, dtype=np.int64)
                vals = np.zeros(0, dtype=np.int32)
            self.arena.put_rows(r, lens, keys_o, vals,
                                np.full(len(r), wide, dtype=bool))
            self._resident[r] = True
            index.free_rows(r)
            sc.live_cells -= len(keys_o)
            if wide:
                sc.wide_rows[r] = False
            self.evictions += len(r)

    # -- the promote step (inside the window, before deltas) ------------

    def promote_touched(self, rows: np.ndarray):
        sc = self.scorer
        self._ensure(sc.items_cap)
        self.touches += len(rows)
        promo = (None, None)
        if len(self.arena.dir) and len(rows):
            spilled = np.asarray(rows, dtype=np.int64)
            spilled = spilled[self._resident[spilled]]
            if len(spilled):
                promo = self._promote(spilled)
        if len(rows):
            r64 = np.asarray(rows, dtype=np.int64)
            self.last_touch[r64] = self.clock
            b = self._buckets.get(self.clock)
            self._buckets[self.clock] = (
                r64.copy() if b is None else np.concatenate([b, r64]))
        return promo

    def _promote(self, spilled: np.ndarray):
        """Re-insert ``spilled`` rows' cells (slab order preserved) and
        return per-slab update-section extras. Residency: wide iff the
        row was wide at spill time or its updated sum crossed the
        promotion bound — identical to the unspilled run's once-wide-
        always-wide rule, so placement never diverges."""
        sc = self.scorer
        lens, keys, vals, was_wide = self.arena.pop_rows(spilled)
        self._resident[spilled] = False
        if sc.index_w is not None:
            wmask = was_wide | (
                sc.row_sums_host[spilled] >= sc.promote_threshold)
        else:
            wmask = np.zeros(len(spilled), dtype=bool)
        seg = np.repeat(np.arange(len(spilled)), lens)
        out = [None, None]
        for wide in (False, True):
            sel = wmask if wide else ~wmask
            if not sel.any():
                continue
            r = spilled[sel]
            cell_sel = sel[seg]
            k = keys[cell_sel]
            v = vals[cell_sel]
            ln = lens[sel].astype(np.int32)
            if wide:
                crossing = ~was_wide[sel]
                if crossing.any():
                    # A row crossing the wide bound ON its promotion
                    # window must adopt in KEY order, not arena (narrow
                    # slab) order: the spill-off reference path is
                    # _promote_rows, whose wide insert is key-sorted —
                    # arena order here would flip slot-ordered tie
                    # breaks against it. Rows already wide at spill
                    # keep their preserved slab order (identity key).
                    seg_w = np.repeat(np.arange(len(r)), ln)
                    order = np.lexsort((
                        np.where(np.repeat(crossing, ln), k,
                                 np.arange(len(k), dtype=np.int64)),
                        seg_w))
                    k, v = k[order], v[order]
            index = sc.index_w if wide else sc.index
            index.adopt_rows(r, k, ln)
            if wide:
                sc.wide_rows[r] = True
            sc.live_cells += len(k)
            # Keys, not slots: the window's apply may still relocate a
            # just-adopted row, so the scorer re-resolves slots after it
            # (SlabIndex.lookup).
            out[int(wide)] = (k,
                              (k & 0xFFFFFFFF).astype(np.int32),
                              v.astype(np.int32))
        self.promotions += len(spilled)
        return out[0], out[1]

    # -- checkpoint blobs ------------------------------------------------

    def checkpoint_state(self) -> dict:
        """The canonical blob, arena cells merged back in — the CELL
        arrays stay byte-identical to a spill-off run's (placement is
        not a checkpoint concern). The spill clock rides alongside as
        supplemental ``tier_*`` arrays (ages relative to the clock, so
        the values are resume-position-free): a restore resumes the
        same residency trajectory instead of starting every row hot and
        waiting ``threshold`` windows to re-spill the cold tail. Other
        stores ignore the keys — blobs stay interchangeable."""
        st = self.scorer._device_checkpoint_state()
        keys_a, cnt_a = self.arena.all_cells()
        if len(keys_a):
            keys = np.concatenate([st["rows_key"], keys_a])
            vals = np.concatenate([st["rows_cnt"],
                                   cnt_a.astype(np.int64)])
            order = np.argsort(keys, kind="stable")
            keys, vals = keys[order], vals[order]
            nz = vals != 0
            st["rows_key"] = keys[nz]
            st["rows_cnt"] = vals[nz]
        stamped = np.flatnonzero(self.last_touch >= 0).astype(np.int64)
        st["tier_clock"] = np.asarray([self.clock], dtype=np.int64)
        st["tier_rows"] = stamped
        # Ages clipped at the eligibility threshold: relative coldness
        # among already-eligible rows is deliberately collapsed — the
        # exact collapse the tick's bucket consolidation applies — so
        # the rider stays a tiny-alphabet array (deflates to almost
        # nothing at vocab scale) while eligibility round-trips
        # exactly.
        st["tier_ages"] = np.minimum(
            self.clock - self.last_touch[stamped],
            self.threshold).astype(np.int32)
        return st

    def restore_state(self, st: dict) -> None:
        """Restore everything hot. With ``tier_*`` arrays in the blob
        the recency clock resumes where the writer left it (same
        residency trajectory — untouched cold rows re-spill at the next
        tick, pinned by the spill-parity-across-restore test); a legacy
        blob without them restores with every row freshly stamped and
        the cold tail re-spills ``threshold`` windows in."""
        self.scorer._device_restore_state(st)
        self.arena.reset()
        self._buckets.clear()
        self.last_touch = np.full(self.scorer.items_cap, -1,
                                  dtype=np.int64)
        self._resident = np.zeros(self.scorer.items_cap, dtype=bool)
        if "tier_rows" in st:
            self.clock = int(np.asarray(st["tier_clock"]).reshape(-1)[0])
            rows = np.asarray(st["tier_rows"], dtype=np.int64)
            ages = np.asarray(st["tier_ages"], dtype=np.int64)
            # A stamped row whose cells all decayed to zero may sit past
            # the restored capacity (restore sizes from cell keys).
            ok = rows < self.scorer.items_cap
            rows, ages = rows[ok], ages[ok]
            stamps = self.clock - ages
            self.last_touch[rows] = stamps
            # One argsort + split (not a per-stamp scan: distinct
            # stamps x rows would be quadratic-ish on long runs).
            order = np.argsort(stamps, kind="stable")
            uniq, starts = np.unique(stamps[order], return_index=True)
            for s, part in zip(uniq.tolist(),
                               np.split(rows[order], starts[1:])):
                self._buckets[int(s)] = part
            return
        self.clock = 0
        rows = np.unique(
            (np.asarray(st["rows_key"]) >> 32).astype(np.int64))
        if len(rows):
            self.last_touch[rows] = 0
            self._buckets[0] = rows

    # -- observability ---------------------------------------------------

    def record_gauges(self) -> None:
        from ..observability.registry import REGISTRY

        REGISTRY.gauge(
            "cooc_spill_evictions_total",
            help="rows spilled from the HBM slab to the host arena"
        ).set(self.evictions)
        REGISTRY.gauge(
            "cooc_spill_promotions_total",
            help="spilled rows re-promoted to the HBM slab on touch"
        ).set(self.promotions)
        REGISTRY.gauge(
            "cooc_spill_resident_rows",
            help="rows currently held in the host spill arena"
        ).set(len(self.arena))
        REGISTRY.gauge(
            "cooc_spill_arena_bytes",
            help="host spill-arena footprint (packed cells + directory)"
        ).set(self.arena.nbytes)
        REGISTRY.gauge(
            "cooc_spill_row_touches_total",
            help="row touches observed by the tiered store (hit rate = "
                 "1 - promotions/touches)").set(self.touches)


def rebucket_cells(keys: np.ndarray, vals: Optional[np.ndarray],
                   n_shards: int):
    """Re-partition a GLOBAL-key-space cell blob onto ``n_shards``.

    The rescale-on-restore core: global row ``r`` owns shard ``r % D``
    and shard-local row ``r // D`` (the modulo sharding rule), so a
    checkpoint taken at any shard count re-buckets exactly onto any
    other. Returns a list of per-shard ``(local_keys, vals, dst)``
    with local keys sorted (global keys are sorted and ``r // D`` is
    monotone within a residue class). ``vals=None`` (a keys-only
    caller, e.g. the multihost index restore) yields ``None`` in the
    vals slot instead of partitioning a throwaway array.
    """
    src = (keys >> 32).astype(np.int64)
    dst = (keys & 0xFFFFFFFF).astype(np.int64)
    owner = (src % n_shards).astype(np.int64)
    out = []
    for d in range(n_shards):
        sel = owner == d
        lk = ((src[sel] // n_shards) << 32) | dst[sel]
        out.append((lk, vals[sel] if vals is not None else None,
                    dst[sel]))
    return out


def merge_mh_cells(blobs: "list[dict]") -> dict:
    """Merge the per-process multi-host slab blobs of ONE generation
    back into the canonical GLOBAL key-space blob — the gang rescale's
    N→M bridge (``checkpoint.restore_rescaled``).

    Every per-process file carries the identical host-replicated key
    union (``mh_rows_key``, sorted global keys) and the counts of the
    shards its chips owned (``mh_local_cnt``, laid out per shard in
    ascending ``mh_local_shards`` order, within a shard in sorted
    local-key order — which is the same relative order as the sorted
    global union restricted to that shard, because the global key
    ``(local_row * D + d) << 32 | dst`` is monotone in the local key
    within a residue class). So each file's count segments scatter
    straight into the union by ownership mask. Zero-count cells are
    KEPT, exactly like the same-topology mh restore keeps them: a
    zeroed cell still owns its slot, and dropping it would shift the
    slot-ordered top-K tie-breaks of every later re-insertion — the
    cross-topology restore must canonicalize to the same within-row
    layout a fixed-topology recovery at the same boundary would. The
    result restores through the ordinary ``rebucket_cells`` path onto
    ANY shard count.
    """
    if not blobs:
        raise ValueError("merge_mh_cells needs at least one blob")
    keys = np.asarray(blobs[0]["mh_rows_key"], dtype=np.int64)
    shard_ids = sorted({int(s) for b in blobs
                        for s in np.asarray(b["mh_local_shards"]).tolist()})
    d_old = (shard_ids[-1] + 1) if shard_ids else 1
    if shard_ids != list(range(d_old)):
        raise ValueError(
            f"multi-host blobs cover shards {shard_ids}, expected the "
            f"full range 0..{d_old - 1} — a writer's file is missing")
    owner = ((keys >> 32) % d_old).astype(np.int64)
    cnt = np.zeros(len(keys), dtype=np.int64)
    for b in blobs:
        if len(np.asarray(b["mh_rows_key"])) != len(keys):
            raise ValueError(
                "multi-host blobs disagree on the replicated key union "
                "— files from different generations?")
        local_cnt = np.asarray(b["mh_local_cnt"], dtype=np.int64)
        lo = 0
        for d in np.asarray(b["mh_local_shards"]).tolist():
            sel = owner == int(d)
            n = int(sel.sum())
            cnt[sel] = local_cnt[lo: lo + n]
            lo += n
        if lo != len(local_cnt):
            raise ValueError(
                "multi-host blob count segments do not cover its "
                "declared shards")
    return {
        "rows_key": keys.copy(),
        "rows_cnt": cnt,
        "row_sums": np.asarray(blobs[0]["row_sums"], dtype=np.int64),
        "observed": np.asarray(blobs[0]["observed"], dtype=np.int64),
    }


class ShardedRescaleStore(StateStore):
    """Rescale-on-restore for the sharded-sparse backend.

    Single-process checkpoints are written in the global key space
    (the scorer's ``_global_key``), so ``restore_state`` re-buckets
    through :func:`rebucket_cells` onto however many shards THIS run
    has — N→M in both directions, proven bit-identical by the rescale
    chaos test. Multi-host per-process snapshots shard the slab values
    across files and still require the writing layout (the scorer's
    ``_restore_multihost`` path, reached through here).
    """

    kind = "rescale"

    def __init__(self, scorer) -> None:
        self.scorer = scorer

    def checkpoint_state(self) -> dict:
        return self.scorer._device_checkpoint_state()

    def restore_state(self, st: dict) -> None:
        self.scorer._device_restore_state(st)


def make_store(scorer, spill_threshold_windows: int = 0,
               spill_target_hbm_frac: float = 0.5) -> StateStore:
    """Store factory for the single-device sparse scorer: tiered when a
    spill threshold is set, direct otherwise."""
    if spill_threshold_windows > 0:
        return TieredSlabStore(scorer, spill_threshold_windows,
                               spill_target_hbm_frac)
    return DirectSlabStore(scorer)
