"""Process supervisor: restart-on-failure for the CLI job.

The reference delegates failure recovery entirely to Flink's restart
strategies — the JobManager respawns the job graph on task failure
(SURVEY §5 "Failure detection / elastic recovery: delegated entirely to
Flink restarts"). This is the standalone analogue: a parent process
respawns the job child on abnormal exit (crash, OOM-kill, SIGKILL), and
the child resumes from the latest checkpoint on its own
(``state/checkpoint.py`` restores all state including the source's
mid-file position), so recovery needs zero operator action.

Hardened recovery loop (proven by injected faults, ``tests/test_chaos.py``):

* **Backoff** — restart delays use exponential backoff with
  decorrelated jitter (``--restart-backoff-base-ms`` /
  ``--restart-backoff-max-ms``) so a flapping job does not hammer a
  shared resource in lockstep; the legacy fixed ``--restart-delay-ms``
  remains the default.
* **Crash-loop breaker** — ``--crash-loop-threshold`` failures inside a
  ``--crash-loop-window-s`` sliding window mean restarting alone is not
  working (the classic cause: a poisoned latest checkpoint). The
  breaker steps the checkpoint back one generation
  (``state/checkpoint.step_back``) and grants one more round; if the
  loop re-trips, it gives up instead of burning attempts forever.
* **Permanent failures** — usage/config exit codes
  (:data:`PERMANENT_EXIT_CODES`) are never retried: a bad flag does not
  get better with restarts.
* **Hang watchdog** — a child whose run journal has gone stale past
  ``--watchdog-stale-after-s`` (same liveness signal as ``/healthz``:
  "no window fired") is SIGTERM→SIGKILLed and counted as a failed
  attempt, so a wedged device dispatch costs one restart, not the whole
  ``timeout_s``.

Output discipline: each attempt's stdout is spooled to an anonymous
temp file and only forwarded when that attempt exits cleanly, so a
crashed attempt's partial output is discarded and the supervised run's
total stdout is identical to an uninterrupted run's. Spooling to disk
(not a PIPE buffer) keeps supervisor RSS independent of the stream
size — a 25M-event ``--emit-updates`` dump is GBs that must not live in
the parent's memory. (In ``--emit-updates`` mode the resumed child
replays restored rows itself — ``cli.py`` — so the successful attempt's
stream alone is complete.) stderr streams through live: it carries the
operator-facing logs.
"""

from __future__ import annotations

import io
import json
import logging
import os
import random
import shutil
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Sequence

from . import tuning

LOG = logging.getLogger("tpu_cooccurrence.supervisor")

#: Flags the supervisor strips from the child's argv (the child must run
#: the job directly, not recurse into supervision; the watchdog/backoff/
#: breaker flags are supervisor-side policy the child has no use for —
#: and ``--watchdog-stale-after-s`` would fail the child's config
#: validation once ``--restart-on-failure`` is stripped).
_SUPERVISOR_FLAGS = ("--restart-on-failure", "--restart-delay-ms",
                     "--restart-backoff-base-ms", "--restart-backoff-max-ms",
                     "--crash-loop-threshold", "--crash-loop-window-s",
                     "--watchdog-stale-after-s",
                     # Gang-supervisor policy (robustness/gang.py): a
                     # gang worker must run the job directly, not spawn
                     # a nested gang.
                     "--gang-workers")

#: ``EX_CONFIG`` from sysexits(3): the CLI exits with it on a
#: configuration ValueError, and argparse exits 2 on usage errors.
EX_CONFIG = 78

#: Child exit codes that mean "retrying cannot help" (usage / config
#: errors): the supervisor returns them immediately without burning a
#: restart attempt.
PERMANENT_EXIT_CODES = frozenset({2, EX_CONFIG})

#: Environment variable carrying supervisor state into the child, which
#: surfaces it on ``/metrics`` (restart/backoff gauges) and ``/healthz``
#: (last-restart info) — the scrape plane runs in the child, not here.
SUPERVISOR_STATE_ENV = "TPU_COOC_SUPERVISOR_STATE"

#: Watchdog: before the child's first journal growth, staleness is
#: measured against ``max(stale_after, this)`` — interpreter + jax
#: startup must not read as a hang.
WATCHDOG_START_GRACE_S = 30.0

#: Watchdog/timeout poll period while the child runs.
_POLL_S = 0.2

#: SIGTERM-to-SIGKILL escalation grace for a hung child.
_TERM_GRACE_S = 5.0


def child_argv(argv: Sequence[str]) -> List[str]:
    """``argv`` minus the supervisor's own flags (both ``--flag value``
    and ``--flag=value`` spellings)."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in _SUPERVISOR_FLAGS:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in _SUPERVISOR_FLAGS):
            continue
        out.append(a)
    return out


def _journal_size(journal_path: Optional[str]) -> int:
    if not journal_path:
        return 0
    try:
        return os.path.getsize(journal_path)
    except OSError:
        return 0


def _quote_journal_tail(journal_path: str, size_before: int,
                        n: int = 5) -> None:
    """Surface the dead child's last fired windows in the restart log.

    The spooled stdout is discarded by design (exactly-once output), but
    the run journal (``observability/journal.py``) survives the crash —
    its tail is the flight-recorder readout: what the child was doing
    when it died, without any Flink-UI equivalent to consult.

    ``size_before`` is the journal size when this attempt was spawned:
    only records written past it are quoted, so an attempt that died
    before recording anything (startup crash, bad restore) — or one that
    wrote fewer than ``n`` records — can never have an earlier attempt's
    (or an earlier run's) windows quoted as its own last act.

    Forensics must never kill the patient: any failure reading or
    parsing the journal (unreadable file, binary garbage) is logged and
    swallowed — the restart proceeds without the quote.
    """
    try:
        from .observability.journal import tail

        records = tail(journal_path, n=n, start_offset=size_before)
    except Exception as exc:
        LOG.warning("could not read dead child's journal %s for "
                    "forensics (%s: %s); restarting without the quote",
                    journal_path, type(exc).__name__, exc)
        return
    if not records:
        LOG.warning("dead child wrote no journal records this attempt "
                    "(%s); it died before its first window fired",
                    journal_path)
        return
    LOG.warning("dead child's journal tail (%d record(s) from %s):",
                len(records), journal_path)
    for rec in records:
        LOG.warning("  journal: %s", json.dumps(rec, sort_keys=True))


def _kill_child(proc: "subprocess.Popen") -> None:
    """SIGTERM, a short grace, then SIGKILL — and reap."""
    proc.terminate()
    try:
        proc.wait(timeout=_TERM_GRACE_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def _run_attempt(cmd: Sequence[str], spool, timeout_s: Optional[float],
                 watchdog_stale_after_s: Optional[float],
                 journal_path: Optional[str], env: dict) -> int:
    """Spawn one child attempt and wait for it, enforcing the overall
    ``timeout_s`` and the journal-staleness watchdog. Returns the exit
    code (124 for a timeout or watchdog kill, matching timeout(1))."""
    proc = subprocess.Popen(list(cmd), stdout=spool, env=env)
    start = time.monotonic()
    last_activity = start
    last_size = _journal_size(journal_path)
    seen_growth = False
    while True:
        rc = proc.poll()
        if rc is not None:
            return rc
        now = time.monotonic()
        if timeout_s is not None and now - start > timeout_s:
            LOG.error("job attempt exceeded timeout_s=%.1f; killing",
                      timeout_s)
            _kill_child(proc)
            return 124
        if watchdog_stale_after_s and journal_path:
            size = _journal_size(journal_path)
            # First growth must exceed 1 byte: a restarted child seals a
            # predecessor's torn final line with a single "\n" the moment
            # it opens the journal — before restore/replay — and that
            # seal must not collapse the startup grace down to the
            # steady-state threshold (a real record is far larger).
            if size > last_size + (0 if seen_growth else 1):
                last_size = size
                last_activity = now
                seen_growth = True
            # Same liveness signal as /healthz: "no window fired for N
            # seconds" — with a startup grace before the first record
            # (imports + restore are not a hang).
            threshold = (watchdog_stale_after_s if seen_growth
                         else max(watchdog_stale_after_s,
                                  WATCHDOG_START_GRACE_S))
            if now - last_activity > threshold:
                LOG.error(
                    "hang watchdog: journal %s stale for %.1fs "
                    "(> %.1fs); SIGTERM then SIGKILL, counting a "
                    "failed attempt", journal_path, now - last_activity,
                    threshold)
                _kill_child(proc)
                return 124
        time.sleep(_POLL_S)


def supervise(cmd: Sequence[str], attempts: int, delay_s: float = 1.0,
              stdout=None, timeout_s: Optional[float] = None,
              journal_path: Optional[str] = None,
              backoff_base_s: Optional[float] = None,
              backoff_max_s: float = 30.0,
              crash_loop_threshold: int = 3,
              crash_loop_window_s: float = 60.0,
              watchdog_stale_after_s: Optional[float] = None,
              checkpoint_dir: Optional[str] = None) -> int:
    """Run ``cmd`` to successful completion, restarting up to ``attempts``
    times on abnormal exit. Returns the final exit code (0 on success,
    the last failure's code once attempts are exhausted, or immediately
    on a permanent failure code).

    ``stdout`` (default ``sys.stdout``) receives the successful attempt's
    spooled output; failed attempts' partial output is discarded with a
    log line so at-least-once execution still yields exactly-once output.
    Each attempt spools to an anonymous temp file (deleted on close
    regardless of outcome), so supervisor memory stays O(1) in the
    child's output size.

    ``journal_path`` (the child's ``--journal`` file, when configured):
    on every abnormal exit the last few journal records are quoted into
    the restart log, and (with ``watchdog_stale_after_s``) its growth is
    the liveness signal the hang watchdog polls.

    ``backoff_base_s=None`` keeps the legacy fixed ``delay_s`` between
    attempts; a value enables exponential backoff with decorrelated
    jitter capped at ``backoff_max_s``. ``checkpoint_dir`` arms the
    crash-loop breaker's generation step-back.
    """
    from .observability.journal import ATTEMPT_ENV, RUN_ID_ENV, mint_run_id

    sink = stdout if stdout is not None else sys.stdout
    restarts = 0
    stepped_back = False
    breaker_warned = False
    failure_times: List[float] = []
    prev_delay = backoff_base_s if backoff_base_s is not None else delay_s
    last_rc = 0
    # Tracing correlation: mint the fleet run id ONCE, before the first
    # attempt, and hand every attempt the same id plus its restart
    # ordinal — a post-crash child's journal records then stitch to the
    # prior attempt's instead of starting an unrelated stream. An
    # already-present env id (outer supervisor, operator) is inherited.
    run_id = tuning.env_read(RUN_ID_ENV) or mint_run_id()
    while True:
        # Journal size at spawn: the crash-forensics quote below must only
        # fire for records THIS attempt wrote (append mode keeps earlier
        # attempts' records in the same file).
        journal_size_before = _journal_size(journal_path)
        env = dict(os.environ)
        env[RUN_ID_ENV] = run_id
        env[ATTEMPT_ENV] = str(restarts)
        env[SUPERVISOR_STATE_ENV] = json.dumps({
            "restarts": restarts,
            "last_rc": last_rc,
            "backoff_ms": int(prev_delay * 1000) if restarts else 0,
            "last_restart_unix": round(time.time(), 3) if restarts else 0,
            "stepped_back": stepped_back,
            "run_id": run_id,
            "attempt": restarts,
        })
        # One anonymous spool per attempt: auto-deleted on close, so a
        # failed attempt's partial output vanishes without cleanup code.
        with tempfile.TemporaryFile() as spool:
            rc = _run_attempt(cmd, spool, timeout_s,
                              watchdog_stale_after_s, journal_path, env)
            # The child wrote through the shared fd; our handle's position
            # never moved, so size comes from the file, not tell().
            out_bytes = os.fstat(spool.fileno()).st_size
            if rc == 0:
                spool.seek(0)
                if hasattr(sink, "buffer"):
                    shutil.copyfileobj(spool, sink.buffer)
                    sink.flush()
                else:
                    # Text sink: incremental decode (TextIOWrapper keeps
                    # multi-byte sequences intact across chunk reads).
                    # newline="" disables universal-newline translation —
                    # the byte-identical-output contract includes \r\n.
                    reader = io.TextIOWrapper(spool, encoding="utf-8",
                                              errors="replace", newline="")
                    try:
                        shutil.copyfileobj(reader, sink)
                    finally:
                        reader.detach()  # the with-block owns the close
                if restarts:
                    LOG.info("job completed after %d restart(s)", restarts)
                return 0
        last_rc = rc
        if rc in PERMANENT_EXIT_CODES:
            LOG.error("job failed with rc=%d (usage/config error — "
                      "permanent); not restarting", rc)
            return rc
        restarts += 1
        if journal_path:
            _quote_journal_tail(journal_path, journal_size_before)
        if restarts > attempts:
            LOG.error("job failed with rc=%d; restart attempts exhausted "
                      "(%d)", rc, attempts)
            return rc
        now = time.monotonic()
        failure_times.append(now)
        failure_times[:] = [t for t in failure_times
                            if now - t <= crash_loop_window_s]
        if (crash_loop_threshold > 0
                and len(failure_times) >= crash_loop_threshold):
            # Restarting alone is not working. Step the checkpoint back a
            # generation once (the poisoned-latest-snapshot hypothesis);
            # a RE-trip after the step-back means the failure is not
            # checkpoint-shaped — give up rather than crash-loop through
            # every attempt. A run with nothing to step back (no
            # --checkpoint-dir, or a single generation; supervised runs
            # are single-process by config, so the default suffix is the
            # right namespace) keeps its full --restart-on-failure
            # budget: the breaker only ever trades attempts for a
            # recovery action it actually performed.
            if stepped_back:
                LOG.error(
                    "crash-loop breaker open: %d failures within %.0fs "
                    "after stepping back a generation; giving up with "
                    "rc=%d", len(failure_times), crash_loop_window_s, rc)
                return rc
            retired = None
            if checkpoint_dir:
                from .state.checkpoint import step_back

                retired = step_back(checkpoint_dir)
            if retired is not None:
                stepped_back = True
                failure_times.clear()
            elif checkpoint_dir and not breaker_warned:
                breaker_warned = True
                LOG.warning(
                    "crash-loop detected (%d failures within %.0fs) but "
                    "no older checkpoint generation to step back to; "
                    "continuing with plain restarts",
                    len(failure_times), crash_loop_window_s)
        if backoff_base_s is not None:
            # Decorrelated jitter (AWS architecture-blog shape): each
            # delay is uniform on [base, prev*3], capped — restarts
            # spread out instead of synchronizing on the failure period.
            prev_delay = min(backoff_max_s,
                             random.uniform(backoff_base_s,
                                            max(backoff_base_s,
                                                prev_delay * 3)))
        else:
            prev_delay = delay_s
        LOG.warning(
            "job attempt %d failed with rc=%d; discarding %d bytes of "
            "partial output and restarting in %.1fs (%d attempt(s) left)",
            restarts, rc, out_bytes, prev_delay,
            attempts - restarts)
        if prev_delay > 0:
            time.sleep(prev_delay)
