"""Process supervisor: restart-on-failure for the CLI job.

The reference delegates failure recovery entirely to Flink's restart
strategies — the JobManager respawns the job graph on task failure
(SURVEY §5 "Failure detection / elastic recovery: delegated entirely to
Flink restarts"). This is the standalone analogue: a parent process
respawns the job child on abnormal exit (crash, OOM-kill, SIGKILL), and
the child resumes from the latest checkpoint on its own
(``state/checkpoint.py`` restores all state including the source's
mid-file position), so recovery needs zero operator action.

Output discipline: each attempt's stdout is spooled to an anonymous
temp file and only forwarded when that attempt exits cleanly, so a
crashed attempt's partial output is discarded and the supervised run's
total stdout is identical to an uninterrupted run's. Spooling to disk
(not a PIPE buffer) keeps supervisor RSS independent of the stream
size — a 25M-event ``--emit-updates`` dump is GBs that must not live in
the parent's memory. (In ``--emit-updates`` mode the resumed child
replays restored rows itself — ``cli.py`` — so the successful attempt's
stream alone is complete.) stderr streams through live: it carries the
operator-facing logs.
"""

from __future__ import annotations

import io
import json
import logging
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Sequence

LOG = logging.getLogger("tpu_cooccurrence.supervisor")

#: Flags the supervisor strips from the child's argv (the child must run
#: the job directly, not recurse into supervision).
_SUPERVISOR_FLAGS = ("--restart-on-failure", "--restart-delay-ms")


def child_argv(argv: Sequence[str]) -> List[str]:
    """``argv`` minus the supervisor's own flags (both ``--flag value``
    and ``--flag=value`` spellings)."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in _SUPERVISOR_FLAGS:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in _SUPERVISOR_FLAGS):
            continue
        out.append(a)
    return out


def _journal_size(journal_path: Optional[str]) -> int:
    if not journal_path:
        return 0
    try:
        return os.path.getsize(journal_path)
    except OSError:
        return 0


def _quote_journal_tail(journal_path: str, size_before: int,
                        n: int = 5) -> None:
    """Surface the dead child's last fired windows in the restart log.

    The spooled stdout is discarded by design (exactly-once output), but
    the run journal (``observability/journal.py``) survives the crash —
    its tail is the flight-recorder readout: what the child was doing
    when it died, without any Flink-UI equivalent to consult.

    ``size_before`` is the journal size when this attempt was spawned:
    only records written past it are quoted, so an attempt that died
    before recording anything (startup crash, bad restore) — or one that
    wrote fewer than ``n`` records — can never have an earlier attempt's
    (or an earlier run's) windows quoted as its own last act.
    """
    from .observability.journal import tail

    records = tail(journal_path, n=n, start_offset=size_before)
    if not records:
        LOG.warning("dead child wrote no journal records this attempt "
                    "(%s); it died before its first window fired",
                    journal_path)
        return
    LOG.warning("dead child's journal tail (%d record(s) from %s):",
                len(records), journal_path)
    for rec in records:
        LOG.warning("  journal: %s", json.dumps(rec, sort_keys=True))


def supervise(cmd: Sequence[str], attempts: int, delay_s: float = 1.0,
              stdout=None, timeout_s: Optional[float] = None,
              journal_path: Optional[str] = None) -> int:
    """Run ``cmd`` to successful completion, restarting up to ``attempts``
    times on abnormal exit. Returns the final exit code (0 on success,
    the last failure's code once attempts are exhausted).

    ``stdout`` (default ``sys.stdout``) receives the successful attempt's
    spooled output; failed attempts' partial output is discarded with a
    log line so at-least-once execution still yields exactly-once output.
    Each attempt spools to an anonymous temp file (deleted on close
    regardless of outcome), so supervisor memory stays O(1) in the
    child's output size.

    ``journal_path`` (the child's ``--journal`` file, when configured):
    on every abnormal exit the last few journal records are quoted into
    the restart log — the crashed attempt's final fired windows, which
    would otherwise vanish with its discarded stdout.
    """
    sink = stdout if stdout is not None else sys.stdout
    restarts = 0
    while True:
        # Journal size at spawn: the crash-forensics quote below must only
        # fire for records THIS attempt wrote (append mode keeps earlier
        # attempts' records in the same file).
        journal_size_before = _journal_size(journal_path)
        # One anonymous spool per attempt: auto-deleted on close, so a
        # failed attempt's partial output vanishes without cleanup code.
        with tempfile.TemporaryFile() as spool:
            try:
                proc = subprocess.run(list(cmd), stdout=spool,
                                      timeout=timeout_s)
                rc = proc.returncode
            except subprocess.TimeoutExpired:
                # A hung attempt counts as a failed one (subprocess.run
                # has already killed the child); 124 matches timeout(1).
                rc = 124
            # The child wrote through the shared fd; our handle's position
            # never moved, so size comes from the file, not tell().
            out_bytes = os.fstat(spool.fileno()).st_size
            if rc == 0:
                spool.seek(0)
                if hasattr(sink, "buffer"):
                    shutil.copyfileobj(spool, sink.buffer)
                    sink.flush()
                else:
                    # Text sink: incremental decode (TextIOWrapper keeps
                    # multi-byte sequences intact across chunk reads).
                    # newline="" disables universal-newline translation —
                    # the byte-identical-output contract includes \r\n.
                    reader = io.TextIOWrapper(spool, encoding="utf-8",
                                              errors="replace", newline="")
                    try:
                        shutil.copyfileobj(reader, sink)
                    finally:
                        reader.detach()  # the with-block owns the close
                if restarts:
                    LOG.info("job completed after %d restart(s)", restarts)
                return 0
        restarts += 1
        if journal_path:
            _quote_journal_tail(journal_path, journal_size_before)
        if restarts > attempts:
            LOG.error("job failed with rc=%d; restart attempts exhausted "
                      "(%d)", rc, attempts)
            return rc
        LOG.warning(
            "job attempt %d failed with rc=%d; discarding %d bytes of "
            "partial output and restarting in %.1fs (%d attempt(s) left)",
            restarts, rc, out_bytes, delay_s,
            attempts - restarts)
        if delay_s > 0:
            time.sleep(delay_s)
