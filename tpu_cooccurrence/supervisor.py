"""Process supervisor: restart-on-failure for the CLI job.

The reference delegates failure recovery entirely to Flink's restart
strategies — the JobManager respawns the job graph on task failure
(SURVEY §5 "Failure detection / elastic recovery: delegated entirely to
Flink restarts"). This is the standalone analogue: a parent process
respawns the job child on abnormal exit (crash, OOM-kill, SIGKILL), and
the child resumes from the latest checkpoint on its own
(``state/checkpoint.py`` restores all state including the source's
mid-file position), so recovery needs zero operator action.

Output discipline: each attempt's stdout is spooled to an anonymous
temp file and only forwarded when that attempt exits cleanly, so a
crashed attempt's partial output is discarded and the supervised run's
total stdout is identical to an uninterrupted run's. Spooling to disk
(not a PIPE buffer) keeps supervisor RSS independent of the stream
size — a 25M-event ``--emit-updates`` dump is GBs that must not live in
the parent's memory. (In ``--emit-updates`` mode the resumed child
replays restored rows itself — ``cli.py`` — so the successful attempt's
stream alone is complete.) stderr streams through live: it carries the
operator-facing logs.
"""

from __future__ import annotations

import io
import logging
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Sequence

LOG = logging.getLogger("tpu_cooccurrence.supervisor")

#: Flags the supervisor strips from the child's argv (the child must run
#: the job directly, not recurse into supervision).
_SUPERVISOR_FLAGS = ("--restart-on-failure", "--restart-delay-ms")


def child_argv(argv: Sequence[str]) -> List[str]:
    """``argv`` minus the supervisor's own flags (both ``--flag value``
    and ``--flag=value`` spellings)."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in _SUPERVISOR_FLAGS:
            skip = True
            continue
        if any(a.startswith(f + "=") for f in _SUPERVISOR_FLAGS):
            continue
        out.append(a)
    return out


def supervise(cmd: Sequence[str], attempts: int, delay_s: float = 1.0,
              stdout=None, timeout_s: Optional[float] = None) -> int:
    """Run ``cmd`` to successful completion, restarting up to ``attempts``
    times on abnormal exit. Returns the final exit code (0 on success,
    the last failure's code once attempts are exhausted).

    ``stdout`` (default ``sys.stdout``) receives the successful attempt's
    spooled output; failed attempts' partial output is discarded with a
    log line so at-least-once execution still yields exactly-once output.
    Each attempt spools to an anonymous temp file (deleted on close
    regardless of outcome), so supervisor memory stays O(1) in the
    child's output size.
    """
    sink = stdout if stdout is not None else sys.stdout
    restarts = 0
    while True:
        # One anonymous spool per attempt: auto-deleted on close, so a
        # failed attempt's partial output vanishes without cleanup code.
        with tempfile.TemporaryFile() as spool:
            try:
                proc = subprocess.run(list(cmd), stdout=spool,
                                      timeout=timeout_s)
                rc = proc.returncode
            except subprocess.TimeoutExpired:
                # A hung attempt counts as a failed one (subprocess.run
                # has already killed the child); 124 matches timeout(1).
                rc = 124
            # The child wrote through the shared fd; our handle's position
            # never moved, so size comes from the file, not tell().
            out_bytes = os.fstat(spool.fileno()).st_size
            if rc == 0:
                spool.seek(0)
                if hasattr(sink, "buffer"):
                    shutil.copyfileobj(spool, sink.buffer)
                    sink.flush()
                else:
                    # Text sink: incremental decode (TextIOWrapper keeps
                    # multi-byte sequences intact across chunk reads).
                    # newline="" disables universal-newline translation —
                    # the byte-identical-output contract includes \r\n.
                    reader = io.TextIOWrapper(spool, encoding="utf-8",
                                              errors="replace", newline="")
                    try:
                        shutil.copyfileobj(reader, sink)
                    finally:
                        reader.detach()  # the with-block owns the close
                if restarts:
                    LOG.info("job completed after %d restart(s)", restarts)
                return 0
        restarts += 1
        if restarts > attempts:
            LOG.error("job failed with rc=%d; restart attempts exhausted "
                      "(%d)", rc, attempts)
            return rc
        LOG.warning(
            "job attempt %d failed with rc=%d; discarding %d bytes of "
            "partial output and restarting in %.1fs (%d attempt(s) left)",
            restarts, rc, out_bytes, delay_s,
            attempts - restarts)
        if delay_s > 0:
            time.sleep(delay_s)
