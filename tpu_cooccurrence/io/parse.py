"""Interaction line parsing.

The reference parses ``user,item,timestamp`` CSV lines with boxed
``String.split`` per record (``FlinkCooccurrences.java:207-219``,
``InteractionLineSplitter``). Here parsing is batched into NumPy int64
arrays — the framework's record unit is a *batch*, not a record.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple

import numpy as np

# Structured batch: parallel arrays (users, items, timestamps).
InteractionBatch = Tuple[np.ndarray, np.ndarray, np.ndarray]


def parse_lines(lines: Iterable[str]) -> InteractionBatch:
    """Parse an iterable of ``user,item,ts`` lines into an interaction batch."""
    users: List[int] = []
    items: List[int] = []
    tss: List[int] = []
    for line in lines:
        u, i, t = line.split(",")
        users.append(int(u))
        items.append(int(i))
        tss.append(int(t))
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(tss, dtype=np.int64),
    )


def batched_lines(lines: Iterable[str], batch_size: int = 65536) -> Iterator[InteractionBatch]:
    """Group a line stream into fixed-size parsed batches."""
    buf: List[str] = []
    for line in lines:
        buf.append(line)
        if len(buf) >= batch_size:
            yield parse_lines(buf)
            buf.clear()
    if buf:
        yield parse_lines(buf)
