"""Interaction line parsing.

The reference parses ``user,item,timestamp`` CSV lines with boxed
``String.split`` per record (``FlinkCooccurrences.java:207-219``,
``InteractionLineSplitter``). Here parsing is batched into NumPy int64
arrays — the framework's record unit is a *batch*, not a record.
"""

from __future__ import annotations

import time
import warnings
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

# Structured batch: parallel arrays (users, items, timestamps).
InteractionBatch = Tuple[np.ndarray, np.ndarray, np.ndarray]


def parse_lines(lines: Iterable[str]) -> InteractionBatch:
    """Parse an iterable of ``user,item,ts`` lines into an interaction batch.

    Fast path: numpy's C CSV parser (~7x the Python loop — at the 25M-line
    scale parsing is otherwise a visible slice of wall-clock). Any parse
    failure re-runs the Python loop so the raised error keeps the
    reference's per-line ``String.split`` semantics
    (``FlinkCooccurrences.java:213-218``), which tests pin.
    """
    if not isinstance(lines, list):
        lines = list(lines)
    if lines:
        try:
            with warnings.catch_warnings():
                # numpy's parser accepts "1.9"/"1e3"/out-of-range values
                # for an int dtype via a deprecated float parse (silent
                # truncation/wraparound); promoting its warning to an
                # error routes those lines to the strict fallback.
                warnings.simplefilter("error", DeprecationWarning)
                arr = np.atleast_2d(np.loadtxt(
                    lines, delimiter=",", dtype=np.int64, comments=None))
            # Shape checks: a wrong field count or silently-skipped blank
            # lines mean the fast parse is not faithful — reject.
            if arr.shape[1] == 3 and arr.shape[0] == len(lines):
                return (arr[:, 0].copy(), arr[:, 1].copy(),
                        arr[:, 2].copy())
        except (ValueError, DeprecationWarning):
            pass  # fall through for the parity error (or reject)
    users: List[int] = []
    items: List[int] = []
    tss: List[int] = []
    for line in lines:
        u, i, t = line.split(",")
        users.append(int(u))
        items.append(int(i))
        tss.append(int(t))
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(tss, dtype=np.int64),
    )


def batched_lines(lines: Iterable[str], batch_size: int = 65536,
                  max_latency_s: Optional[float] = None
                  ) -> Iterator[InteractionBatch]:
    """Group a line stream into parsed batches.

    Batches flush at ``batch_size`` lines, or — when ``max_latency_s`` is
    set (the ``--buffer-timeout`` analogue of the reference's record-flush
    bound, ``FlinkCooccurrences.java:46``) — once the oldest buffered line
    has waited that long. A continuous-mode source interleaves ``None``
    heartbeats while idle so an aged partial batch flushes even when no
    further lines arrive.
    """
    buf: List[str] = []
    oldest = 0.0
    for line in lines:
        if line is None:  # idle heartbeat (continuous sources only)
            if buf and max_latency_s is not None \
                    and time.monotonic() - oldest >= max_latency_s:
                yield parse_lines(buf)
                buf.clear()
            continue
        if not buf:
            oldest = time.monotonic()
        buf.append(line)
        if len(buf) >= batch_size or (
                max_latency_s is not None
                and time.monotonic() - oldest >= max_latency_s):
            yield parse_lines(buf)
            buf.clear()
    if buf:
        yield parse_lines(buf)
