"""Interaction line parsing.

The reference parses ``user,item,timestamp`` CSV lines with boxed
``String.split`` per record (``FlinkCooccurrences.java:207-219``,
``InteractionLineSplitter``). Here parsing is batched into NumPy int64
arrays — the framework's record unit is a *batch*, not a record.

Error handling (robustness plane): every rejected line is reported with
``path:lineno`` provenance and the offending raw text via
:class:`ParseError` — a crash report naming the poisoned line, not just
"invalid literal". With a :class:`~..robustness.quarantine.Quarantine`
attached, rejected lines are diverted to the dead-letter file instead
of raised and the remaining lines of the batch still parse (bounded by
the quarantine's own rate breaker).
"""

from __future__ import annotations

import time
import warnings
from typing import Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..robustness import faults
from ..robustness.quarantine import RAW_TRUNCATE

# Structured batch: parallel arrays (users, items, timestamps).
InteractionBatch = Tuple[np.ndarray, np.ndarray, np.ndarray]

_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


class ParseError(ValueError):
    """A rejected interaction line, with full provenance.

    ``ValueError`` subclass so callers pinned to the reference's
    per-line ``Integer.parseInt`` failure shape keep working; the extra
    attributes (``source_path``, ``lineno``, ``raw``) carry what those
    callers previously lost — *which* line, *where*.
    """

    def __init__(self, source_path: str, lineno: int, raw: str,
                 reason: object) -> None:
        self.source_path = source_path
        self.lineno = lineno
        self.raw = raw
        super().__init__(
            f"{source_path}:{lineno}: {reason} — offending line: "
            f"{raw[:RAW_TRUNCATE]!r}")


def _parse_one(line: str) -> Tuple[int, int, int]:
    """Strict single-line parse (the reference's split semantics), with
    an int64 range check so an out-of-range id fails *here* with the
    line in hand, not later as an opaque array-conversion overflow."""
    u, i, t = line.split(",")
    out = (int(u), int(i), int(t))
    for v in out:
        if not (_INT64_MIN <= v <= _INT64_MAX):
            raise ValueError(f"value {v} out of int64 range")
    return out


def parse_lines(lines: Iterable[str],
                provenance: Optional[List[Tuple[str, int]]] = None,
                quarantine=None) -> InteractionBatch:
    """Parse an iterable of ``user,item,ts`` lines into an interaction batch.

    Fast path: numpy's C CSV parser (~7x the Python loop — at the 25M-line
    scale parsing is otherwise a visible slice of wall-clock). Any parse
    failure re-runs the Python loop so the raised error keeps the
    reference's per-line ``String.split`` semantics
    (``FlinkCooccurrences.java:213-218``), which tests pin — now wrapped
    as :class:`ParseError` with ``path:lineno`` provenance.

    ``provenance`` (optional, parallel to ``lines``) supplies each
    line's ``(path, lineno)`` origin; without it, errors report the
    1-based position within this batch against ``"<stream>"``.
    ``quarantine`` (a :class:`~..robustness.quarantine.Quarantine`)
    diverts rejected lines to the dead-letter file instead of raising.
    """
    if not isinstance(lines, list):
        lines = list(lines)
    if lines:
        try:
            with warnings.catch_warnings():
                # numpy's parser accepts "1.9"/"1e3"/out-of-range values
                # for an int dtype via a deprecated float parse (silent
                # truncation/wraparound); promoting its warning to an
                # error routes those lines to the strict fallback.
                warnings.simplefilter("error", DeprecationWarning)
                arr = np.atleast_2d(np.loadtxt(
                    lines, delimiter=",", dtype=np.int64, comments=None))
            # Shape checks: a wrong field count or silently-skipped blank
            # lines mean the fast parse is not faithful — reject.
            if arr.shape[1] == 3 and arr.shape[0] == len(lines):
                return (arr[:, 0].copy(), arr[:, 1].copy(),
                        arr[:, 2].copy())
        except (ValueError, DeprecationWarning, OverflowError):
            pass  # fall through for the per-line verdict (or quarantine)
    users: List[int] = []
    items: List[int] = []
    tss: List[int] = []
    for idx, line in enumerate(lines):
        try:
            u, i, t = _parse_one(line)
        except (ValueError, OverflowError) as exc:
            if provenance is not None and idx < len(provenance):
                src, lineno = provenance[idx]
            else:
                src, lineno = "<stream>", idx + 1
            if quarantine is not None:
                quarantine.quarantine(src, lineno, line, exc)
                continue
            raise ParseError(src, lineno, line, exc) from exc
        users.append(u)
        items.append(i)
        tss.append(t)
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(tss, dtype=np.int64),
    )


def batched_lines(lines: Iterable[str], batch_size: int = 65536,
                  max_latency_s: Optional[float] = None,
                  origin: Optional[Callable[[], Tuple[str, int]]] = None,
                  quarantine=None) -> Iterator[InteractionBatch]:
    """Group a line stream into parsed batches.

    Batches flush at ``batch_size`` lines, or — when ``max_latency_s`` is
    set (the ``--buffer-timeout`` analogue of the reference's record-flush
    bound, ``FlinkCooccurrences.java:46``) — once the oldest buffered line
    has waited that long. A continuous-mode source interleaves ``None``
    heartbeats while idle so an aged partial batch flushes even when no
    further lines arrive.

    ``origin`` (e.g. ``FileMonitorSource.origin``) is called once per
    buffered line to capture its ``(path, lineno)`` provenance for parse
    errors and the quarantine; ``quarantine`` flows through to
    :func:`parse_lines`. The per-line capture is a deliberate cost
    (~one bound call + tuple per line, on a loop that already appends
    per line): exact provenance must exist *before* a failure is known,
    and blank-line skips / file boundaries make positions within a
    batch non-reconstructable after the fact.
    """
    buf: List[str] = []
    prov: Optional[List[Tuple[str, int]]] = [] if origin is not None else None
    oldest = 0.0
    batches = 0

    def flush() -> InteractionBatch:
        nonlocal batches
        batches += 1
        if faults.PLAN is not None:
            faults.PLAN.fire("parse_record", seq=batches)
        if quarantine is not None:
            quarantine.note_lines(len(buf))
        out = parse_lines(buf, provenance=prov, quarantine=quarantine)
        buf.clear()
        if prov is not None:
            prov.clear()
        return out

    for line in lines:
        if line is None:  # idle heartbeat (continuous sources only)
            if buf and max_latency_s is not None \
                    and time.monotonic() - oldest >= max_latency_s:
                yield flush()
            continue
        if not buf:
            oldest = time.monotonic()
        buf.append(line)
        if prov is not None:
            prov.append(origin())
        if len(buf) >= batch_size or (
                max_latency_s is not None
                and time.monotonic() - oldest >= max_latency_s):
            yield flush()
    if buf:
        yield flush()
