"""Interaction line parsing.

The reference parses ``user,item,timestamp`` CSV lines with boxed
``String.split`` per record (``FlinkCooccurrences.java:207-219``,
``InteractionLineSplitter``). Here parsing is batched into NumPy int64
arrays — the framework's record unit is a *batch*, not a record.
"""

from __future__ import annotations

import time
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

# Structured batch: parallel arrays (users, items, timestamps).
InteractionBatch = Tuple[np.ndarray, np.ndarray, np.ndarray]


def parse_lines(lines: Iterable[str]) -> InteractionBatch:
    """Parse an iterable of ``user,item,ts`` lines into an interaction batch."""
    users: List[int] = []
    items: List[int] = []
    tss: List[int] = []
    for line in lines:
        u, i, t = line.split(",")
        users.append(int(u))
        items.append(int(i))
        tss.append(int(t))
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(tss, dtype=np.int64),
    )


def batched_lines(lines: Iterable[str], batch_size: int = 65536,
                  max_latency_s: Optional[float] = None
                  ) -> Iterator[InteractionBatch]:
    """Group a line stream into parsed batches.

    Batches flush at ``batch_size`` lines, or — when ``max_latency_s`` is
    set (the ``--buffer-timeout`` analogue of the reference's record-flush
    bound, ``FlinkCooccurrences.java:46``) — once the oldest buffered line
    has waited that long. A continuous-mode source interleaves ``None``
    heartbeats while idle so an aged partial batch flushes even when no
    further lines arrive.
    """
    buf: List[str] = []
    oldest = 0.0
    for line in lines:
        if line is None:  # idle heartbeat (continuous sources only)
            if buf and max_latency_s is not None \
                    and time.monotonic() - oldest >= max_latency_s:
                yield parse_lines(buf)
                buf.clear()
            continue
        if not buf:
            oldest = time.monotonic()
        buf.append(line)
        if len(buf) >= batch_size or (
                max_latency_s is not None
                and time.monotonic() - oldest >= max_latency_s):
            yield parse_lines(buf)
            buf.clear()
    if buf:
        yield parse_lines(buf)
