"""Partitioned-log source: Kafka's shape without the dependency.

The Flink reference's exactly-once story (PAPER.md L0/L1) rests on the
source being a *replayable partitioned log* whose per-partition offsets
commit atomically with operator state. :class:`PartitionedLogSource`
reproduces that shape on plain files: a directory of ``part-*`` files,
each an independent append-only partition, consumed in a deterministic
chunked round-robin whose cursor — together with every partition's
(byte offset, record count, head-prefix hash) — is the first-class
``ingest_offsets`` section of the checkpoint/delta codec
(``state/checkpoint.py`` / ``state/delta.py``). Recovery therefore
resumes each partition exactly once: no byte is re-read, no record is
dropped, across crash, gang restart and the autoscale rescale seam.

Invariants:

  * **Partition order** is the lexicographic sort of the ``part-*``
    names — stable across listings, processes and restores; the
    partition COUNT is fixed at first discovery (``--ingest-partitions``
    pins it up front; a mismatch is a configuration error, exactly like
    a Kafka topic changing partition count under a consumer group).
  * **Replicated ingest**: every gang worker reads every partition in
    the same order (the same contract the sharded backends assume for
    the line stream — ingest is deterministic and replicated; ownership
    masks carve the *state*, not the wire). Partition OWNERSHIP
    (``parallel/``'s modular ownership idiom, ``index % processes``)
    governs which worker is authoritative for a partition's offsets in
    the rescaled-restore merge and for its lag in journal/healthz
    reporting — re-derived from the same formula at the new topology on
    the rescale seam.
  * **Append-only enforcement**: a partition whose file shrank below
    the committed offset, or whose consumed head-prefix hash changed,
    was rewritten — it is quarantined (dead-letter record + journaled
    ``ingest/partition-quarantined`` event) and skipped while healthy
    partitions keep flowing; the admission ladder
    (``robustness/degrade.py``) gates each partition's turn the same
    way it gates file splits.
  * **Record framing** is newline-delimited; in continuous mode a
    torn tail (no trailing newline yet) is deferred until the writer
    completes it, so offsets never split a record.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..metrics import Counters, SPLIT_READER_NUM_SPLITS
from ..robustness import degrade, faults
from .source import ADMIT_EVERY_LINES, Source, head_hash

LOG = logging.getLogger("tpu_cooccurrence.io.partitioned")

#: Records consumed from one partition before rotating to the next —
#: the interleave grain. Small enough that windows mix partitions,
#: large enough that the per-turn bookkeeping stays off the hot path.
TURN_RECORDS = 256

#: Partition files must match this prefix (everything else in the
#: directory — manifests, dead-letter files, tmp writes — is ignored).
PARTITION_PREFIX = "part-"


class _Partition:
    """One append-only partition file and its committed position."""

    __slots__ = ("name", "path", "byte_offset", "records", "quarantined",
                 "_handle")

    def __init__(self, name: str, path: str) -> None:
        self.name = name
        self.path = path
        self.byte_offset = 0
        self.records = 0
        self.quarantined = False
        self._handle = None


class PartitionedLogSource(Source):
    """Streams records from N append-only partition files, exactly once."""

    def __init__(
        self,
        path: str,
        counters: Optional[Counters] = None,
        process_continuously: bool = False,
        poll_interval_s: float = 1.0,
        expected_partitions: int = 0,
        process_id: int = 0,
        num_processes: int = 1,
        turn_records: int = TURN_RECORDS,
    ) -> None:
        self.path = path
        self.counters = counters or Counters()
        self.process_continuously = process_continuously
        self.poll_interval_s = poll_interval_s
        self.expected_partitions = int(expected_partitions)
        self.process_id = int(process_id)
        self.num_processes = max(1, int(num_processes))
        self.turn_records = int(turn_records)
        self._parts: Dict[str, _Partition] = {}
        self._order: List[str] = []
        self._discovered = False
        self._rr_pos = 0
        self._rr_remaining = self.turn_records
        self._restored_offsets: Optional[dict] = None
        self._current_name: Optional[str] = None
        self._opens = 0

    # -- discovery -------------------------------------------------------

    def _discover(self) -> None:
        """Fix the partition set: lexicographically sorted ``part-*``
        files under the directory (a single plain file is one-partition
        degenerate). Validated against --ingest-partitions when set."""
        if self._discovered and self._order:
            return
        if os.path.isdir(self.path):
            names = sorted(
                n for n in os.listdir(self.path)
                if n.startswith(PARTITION_PREFIX)
                and os.path.isfile(os.path.join(self.path, n)))
            parts = [(n, os.path.join(self.path, n)) for n in names]
        elif os.path.isfile(self.path):
            parts = [(os.path.basename(self.path), self.path)]
        else:
            parts = []
        if self.expected_partitions and parts and \
                len(parts) != self.expected_partitions:
            raise ValueError(
                f"--ingest-partitions {self.expected_partitions} but "
                f"{len(parts)} part-* files found under {self.path} — "
                f"the partition count is part of the offset contract "
                f"and cannot drift")
        for name, p in parts:
            if name not in self._parts:
                self._parts[name] = _Partition(name, p)
        self._order = sorted(self._parts)
        self._discovered = bool(parts)
        if self._discovered and self._restored_offsets is not None:
            self._apply_restored_offsets()

    # -- checkpoint hooks ------------------------------------------------

    def checkpoint_state(self) -> dict:
        # The cursor markers ride the offsets section (offsets_state) —
        # this legacy hook carries only the format tag so a pre-offset
        # restore path has something well-formed to hand back.
        return {"format": "partitioned"}

    def restore_state(self, state: dict) -> None:
        # Nothing to restore here: without an ingest_offsets section a
        # partitioned log can only replay from the start (the restore
        # path warns "offsets absent, replaying from source markers").
        return None

    def offsets_state(self) -> dict:
        """The first-class ingest-offset section: per-partition (byte
        offset, record count, consumed head-prefix hash, quarantine
        flag) plus the round-robin cursor — everything a restore needs
        to resume each partition exactly once."""
        partitions: Dict[str, dict] = {}
        for name in self._order:
            p = self._parts[name]
            try:
                digest = head_hash(p.path, p.byte_offset)
            except OSError:
                digest = None
            partitions[name] = {
                "byte_offset": int(p.byte_offset),
                "records": int(p.records),
                "head_hash": digest,
                "quarantined": bool(p.quarantined),
            }
        offsets = {
            "v": 1,
            "format": "partitioned",
            "partitions": partitions,
            "rr_part": self._order[self._rr_pos] if self._order else None,
            "rr_remaining": int(self._rr_remaining),
        }
        return offsets

    def restore_offsets(self, state: dict) -> None:
        self._restored_offsets = state
        if self._discovered:
            self._apply_restored_offsets()

    def _apply_restored_offsets(self) -> None:
        """Apply (and verify) a restored offsets section against the
        discovered partition set: an append-only grown partition resumes
        at its committed offset; a shrunk/rewritten one is quarantined
        and lags alone while healthy partitions keep flowing."""
        state, self._restored_offsets = self._restored_offsets, None
        if not state:
            return
        if int(state.get("v", 1)) != 1:
            LOG.warning("ingest offset section v=%s is newer than this "
                        "reader (v=1): applying best-effort",
                        state.get("v"))
        fmt = state.get("format", "partitioned")
        if fmt != "partitioned":
            raise ValueError(
                f"checkpoint ingest offsets carry format {fmt!r} but "
                f"the job was launched with --source-format partitioned")
        restored = state.get("partitions") or {}
        for name, entry in sorted(restored.items()):
            part = self._parts.get(name)
            if part is None:
                LOG.warning(
                    "checkpointed partition %r is gone from %s — its "
                    "committed offset (%d bytes, %d records) cannot be "
                    "resumed", name, self.path,
                    int(entry.get("byte_offset", 0)),
                    int(entry.get("records", 0)))
                continue
            part.byte_offset = int(entry.get("byte_offset", 0))
            part.records = int(entry.get("records", 0))
            if entry.get("quarantined"):
                part.quarantined = True
                continue
            if not self._verify_append_only(part, entry.get("head_hash")):
                self._quarantine_partition(
                    part, "rewritten under a checkpoint (shrunk or "
                          "head-prefix mismatch)")
        for name in self._order:
            if name not in restored:
                LOG.warning("partition %r has no checkpointed offset — "
                            "reading it from the start", name)
        rr_part = state.get("rr_part")
        if rr_part in self._parts:
            self._rr_pos = self._order.index(rr_part)
            self._rr_remaining = int(
                state.get("rr_remaining", self.turn_records))
            if self._rr_remaining <= 0:
                # Committed exactly at a turn boundary: the live reader
                # would have rotated before reading again, so resume at
                # the NEXT partition's fresh turn. Restoring the spent
                # turn verbatim would read as an idle turn and could
                # end a process-once drain before the rotation came
                # back around.
                self._rr_pos = (self._rr_pos + 1) % len(self._order)
                self._rr_remaining = self.turn_records
        else:
            self._rr_pos = 0
            self._rr_remaining = self.turn_records

    def _verify_append_only(self, part: _Partition,
                            digest: Optional[str]) -> bool:
        """True when the partition file still starts with the consumed
        prefix the checkpoint committed (size and head-prefix hash)."""
        try:
            if os.stat(part.path).st_size < part.byte_offset:
                return False
            if digest is not None and \
                    head_hash(part.path, part.byte_offset) != digest:
                return False
        except OSError:
            return False
        return True

    def _quarantine_partition(self, part: _Partition, reason: str) -> None:
        """Dead-letter a poisoned partition and journal the event; the
        partition is skipped from here on (it dead-letters and lags
        alone — healthy partitions keep flowing)."""
        part.quarantined = True
        if part._handle is not None:
            part._handle.close()
            part._handle = None
        LOG.warning("partition %s %s — quarantined (healthy partitions "
                    "keep flowing)", part.name, reason)
        if self._quarantine is not None:
            self._quarantine.quarantine(part.path, part.records, "",
                                        f"partition {reason}")
        if self._on_event is not None:
            self._on_event(f"ingest/partition-quarantined:{part.name}")

    # -- ownership -------------------------------------------------------

    def partition_owner(self, index: int) -> int:
        """Deterministic partition ownership across the gang — the
        ``parallel/`` modular ownership idiom (``(keys >> 32) % shards``
        for state rows) applied to partition indices. Re-evaluating this
        at a new topology IS the reassignment on the rescale seam."""
        return index % self.num_processes

    # -- health ----------------------------------------------------------

    def ingest_health(self) -> Optional[dict]:
        """Per-partition offset/lag/owner snapshot for /healthz, the
        journal's per-window ingest fields and the lag gauge."""
        if not self._order:
            return None
        # Deliberately NOT named ``partitions``: this dict is a health
        # snapshot (lag/owner are derived, not committed state), not the
        # offset codec the ingest-offset-registry lint watches.
        snapshot: Dict[str, dict] = {}
        quarantined = 0
        for idx, name in enumerate(self._order):
            p = self._parts[name]
            try:
                size = os.stat(p.path).st_size
            except OSError:
                size = p.byte_offset
            quarantined += int(p.quarantined)
            snapshot[name] = {
                "byte_offset": int(p.byte_offset),
                "records": int(p.records),
                "lag": max(0, int(size) - int(p.byte_offset)),
                "quarantined": bool(p.quarantined),
                "owner": self.partition_owner(idx),
            }
        return {
            "format": "partitioned",
            "partitions": snapshot,
            "quarantined_partitions": quarantined,
        }

    # -- provenance ------------------------------------------------------

    def origin(self) -> Tuple[str, int]:
        """``(partition path, record number)`` of the record most
        recently yielded — per-line provenance for parse errors and the
        dead-letter file."""
        if self._current_name is not None:
            p = self._parts[self._current_name]
            return (p.path, p.records)
        return (self.path, 0)

    # -- reading ---------------------------------------------------------

    def _open(self, part: _Partition):
        if part._handle is None:
            part._handle = open(part.path, "rb")
            part._handle.seek(part.byte_offset)
        return part._handle

    def _read_record(self, part: _Partition) -> Optional[bytes]:
        """One framed record (raw bytes incl. newline) or None when the
        partition has no complete record to offer right now."""
        try:
            f = self._open(part)
            raw = f.readline()
        except OSError:
            self._quarantine_partition(part, "unreadable")
            return None
        if not raw:
            return None
        if not raw.endswith(b"\n") and self.process_continuously:
            # Torn tail: the writer is mid-append. Defer until the
            # newline lands so a committed offset never splits a record.
            f.seek(part.byte_offset)
            return None
        return raw

    def lines(self) -> Iterator[Optional[str]]:
        """Yield records across partitions in deterministic chunked
        round-robin order.

        Offsets advance BEFORE each yield, so a checkpoint taken at any
        batch boundary snapshots exactly the records delivered — the
        same contract ``FileMonitorSource`` keeps for its line cursor.
        The rotation cursor (partition index + records left in the
        current turn) is part of the offsets section, so a restored run
        continues the interleave mid-turn, bit-identically.
        """
        self._discover()
        since_gate = 0
        while True:
            idle_turns = 0
            while self._order and idle_turns < len(self._order):
                name = self._order[self._rr_pos]
                part = self._parts[name]
                took = 0
                if not part.quarantined:
                    if self._rr_remaining == self.turn_records:
                        # Fresh turn on this partition: the chaos hook
                        # and the admission gate sit at the same grain
                        # as FileMonitorSource's split boundary.
                        self._opens += 1
                        if faults.PLAN is not None:
                            faults.PLAN.fire("source_read",
                                             seq=self._opens)
                        if degrade.CONTROLLER is not None:
                            degrade.CONTROLLER.admit()
                        self.counters.add(SPLIT_READER_NUM_SPLITS, 1)
                    while self._rr_remaining > 0:
                        raw = self._read_record(part)
                        if raw is None:
                            break
                        self._rr_remaining -= 1
                        took += 1
                        part.byte_offset += len(raw)
                        part.records += 1
                        self._current_name = name
                        line = raw.rstrip(b"\r\n").decode(
                            "utf-8", "replace")
                        if line:
                            if degrade.CONTROLLER is not None:
                                since_gate += 1
                                if since_gate >= ADMIT_EVERY_LINES:
                                    since_gate = 0
                                    degrade.CONTROLLER.admit()
                            yield line
                # Turn over (quota spent or nothing to read): rotate.
                self._rr_pos = (self._rr_pos + 1) % len(self._order)
                self._rr_remaining = self.turn_records
                idle_turns = 0 if took else idle_turns + 1
            if not self.process_continuously:
                self._close_handles()
                return
            # Idle heartbeat: lets the downstream batcher flush an aged
            # partial batch while no partition has a complete record.
            yield None
            time.sleep(self.poll_interval_s)
            if not self._discovered:
                self._discover()
            self._check_append_only()

    def _check_append_only(self) -> None:
        """Continuous-mode poll-time guard: a partition whose file
        shrank below the committed offset was rewritten — quarantine it
        (the head-prefix check is restore-time only; mid-run the open
        handle pins the inode, so shrink is the observable violation)."""
        for name in self._order:
            part = self._parts[name]
            if part.quarantined:
                continue
            try:
                if os.stat(part.path).st_size < part.byte_offset:
                    self._quarantine_partition(
                        part, "shrank below the committed offset")
            except OSError:
                self._quarantine_partition(part, "unreadable")

    def _close_handles(self) -> None:
        for part in self._parts.values():
            if part._handle is not None:
                part._handle.close()
                part._handle = None
