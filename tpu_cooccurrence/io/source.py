"""File ingestion: monitor a file or directory and stream interaction batches.

TPU-native replacement for the reference's forked file-monitoring source
(``ContinuousFileMonitoringFunction.java``) + unsplittable text format
(``UnsplittableTextInputFormat.java``):

  * a path (file or directory) is listed; files are forwarded **sorted by
    modification time** (reference :239-257),
  * each file is read whole, in line order — never split — preserving the
    ascending-timestamp contract (``UnsplittableTextInputFormat.java:12-20``),
  * ``PROCESS_ONCE`` reads the current snapshot and stops;
    ``PROCESS_CONTINUOUSLY`` re-lists and forwards files whose modification
    time is newer than the max seen (reference :204-236),
  * the max modification time is checkpointable so a restored job does not
    re-ingest (reference :380-392).

No existence pre-check is done before listing — the reference deliberately
removed it for object-store compatibility (:196-201); we surface listing
errors directly instead.

Every source implements the :class:`Source` interface: cursor markers ride
``meta["source"]`` (:meth:`Source.checkpoint_state`), while the first-class
ingest-offset section rides ``meta["ingest_offsets"]``
(:meth:`Source.offsets_state`) and commits atomically with the state under
the epoch protocol — the checkpoint plane's exactly-once guarantee extended
to the wire (see ``io/partitioned.py`` for the partitioned-log shape).
"""

from __future__ import annotations

import hashlib
import logging
import os
import time
from typing import Callable, Iterator, List, Optional, Tuple

from ..metrics import Counters, SPLIT_READER_NUM_SPLITS
from ..robustness import degrade, faults

LOG = logging.getLogger("tpu_cooccurrence.io.source")

#: Lines between admission-gate checks while a degradation controller is
#: installed: cheap enough to bound burst admission at sub-batch
#: granularity, coarse enough to stay off the per-line hot path.
ADMIT_EVERY_LINES = 4096

#: Cap on the head-prefix hash that guards a checkpointed in-flight file
#: (and a partitioned log's consumed prefix): enough bytes to make an
#: accidental rewrite collision implausible, small enough that restore
#: verification never re-reads a large log.
HEAD_HASH_BYTES = 65536


def head_hash(path: str, nbytes: int) -> str:
    """SHA-256 hex digest of the first ``min(nbytes, HEAD_HASH_BYTES)``
    bytes of ``path`` — the rewrite guard both sides of a checkpoint
    compute over the same prefix length (append-only growth beyond the
    checkpointed length never changes it)."""
    limit = min(int(nbytes), HEAD_HASH_BYTES)
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        digest.update(f.read(limit))
    return digest.hexdigest()


class Source:
    """Interface every ingest source implements.

    Two checkpoint hooks with distinct contracts:

    * :meth:`checkpoint_state` / :meth:`restore_state` — the legacy
      cursor markers (``meta["source"]``), enough to resume an
      unmodified input;
    * :meth:`offsets_state` / :meth:`restore_offsets` — the first-class
      ingest-offset section (``meta["ingest_offsets"]``), carrying the
      rewrite guards (sizes + head-prefix hashes) and, for partitioned
      logs, the per-partition byte/record offsets that make recovery
      exactly-once end-to-end.

    :meth:`attach` hands the source the dead-letter quarantine and the
    journal event callback; both are optional and default inert.
    """

    _quarantine = None
    _on_event: Optional[Callable[[str], None]] = None

    def attach(self, quarantine=None,
               on_event: Optional[Callable[[str], None]] = None) -> None:
        """Arm the dead-letter path and the journal event hook (called
        by the CLI after quarantine construction, before :meth:`lines`)."""
        self._quarantine = quarantine
        self._on_event = on_event

    def checkpoint_state(self) -> dict:
        raise NotImplementedError

    def restore_state(self, state: dict) -> None:
        raise NotImplementedError

    def offsets_state(self) -> dict:
        raise NotImplementedError

    def restore_offsets(self, state: dict) -> None:
        raise NotImplementedError

    def ingest_health(self) -> Optional[dict]:
        """Per-partition offset/lag/quarantine health for the /healthz
        ingest block and the journal's per-window ingest fields — None
        when the source has no partition structure to report."""
        return None

    def origin(self) -> Tuple[str, int]:
        raise NotImplementedError

    def lines(self) -> Iterator[Optional[str]]:
        raise NotImplementedError


class FileMonitorSource(Source):
    """Streams lines from a file or directory in modification-time order."""

    def __init__(
        self,
        path: str,
        counters: Optional[Counters] = None,
        process_continuously: bool = False,
        poll_interval_s: float = 1.0,
    ) -> None:
        self.path = path
        self.counters = counters or Counters()
        self.process_continuously = process_continuously
        self.poll_interval_s = poll_interval_s
        # Checkpointed monotone progress marker (reference:
        # ContinuousFileMonitoringFunction.java:380-392). Advanced only when
        # a file has been fully consumed; a mid-file position is carried
        # separately so a checkpoint taken mid-file resumes exactly (the
        # reference cannot: its marker covers whole splits only).
        self.global_modification_time: int = -1
        self._current_file: Optional[str] = None
        self._current_mtime: int = -1
        self._current_line: int = 0
        # Restored in-flight rewrite guard (offsets_state's "in_flight"
        # section), consumed once by lines(); files it condemns land here
        # and are never re-listed.
        self._in_flight_guard: Optional[dict] = None
        self._dropped_paths: set = set()

    # -- checkpoint hooks ------------------------------------------------

    def checkpoint_state(self) -> dict:
        return {
            "global_modification_time": self.global_modification_time,
            "current_file": self._current_file,
            "current_mtime": self._current_mtime,
            "current_line": self._current_line,
        }

    def restore_state(self, state: dict) -> None:
        self.global_modification_time = int(state["global_modification_time"])
        self._current_file = state.get("current_file")
        self._current_mtime = int(state.get("current_mtime", -1))
        self._current_line = int(state.get("current_line", 0))

    def offsets_state(self) -> dict:
        offsets = {
            "v": 1,
            "format": "files",
            "in_flight": self._in_flight_state(),
        }
        return offsets

    def restore_offsets(self, state: dict) -> None:
        state = state or {}
        if int(state.get("v", 1)) != 1:
            LOG.warning("ingest offset section v=%s is newer than this "
                        "reader (v=1): applying best-effort",
                        state.get("v"))
        fmt = state.get("format", "files")
        if fmt != "files":
            raise ValueError(
                f"checkpoint ingest offsets carry format {fmt!r} but "
                f"the job was launched with --source-format files")
        self._in_flight_guard = state.get("in_flight")

    def _in_flight_state(self) -> Optional[dict]:
        """Rewrite guard for the file a mid-file checkpoint is inside:
        (mtime, size, head-prefix hash) — enough for a restore to tell
        an append-only grown file (resume exactly) from a rewritten one
        (dead-letter, never silently re-read whole)."""
        if self._current_file is None:
            return None
        try:
            st = os.stat(self._current_file)
            digest = head_hash(self._current_file, st.st_size)
        except OSError:
            return None
        in_flight = {
            "path": self._current_file,
            "mtime": int(st.st_mtime_ns),
            "size": int(st.st_size),
            "head_hash": digest,
        }
        return in_flight

    def _verify_in_flight(self, guard: dict) -> str:
        """``"ok"`` (unchanged or append-only grown), ``"rewritten"``
        (shrunk or head-prefix mismatch) or ``"missing"`` for the
        checkpointed in-flight file."""
        path = guard.get("path")
        size = int(guard.get("size", 0))
        try:
            st = os.stat(path)
            if (st.st_size == size
                    and int(st.st_mtime_ns) == int(guard.get("mtime",
                                                             -1))):
                # Untouched since the checkpoint — skip the hash read.
                return "ok"
            if st.st_size < size:
                return "rewritten"
            if head_hash(path, size) != guard.get("head_hash"):
                return "rewritten"
        except OSError:
            return "missing"
        return "ok"

    def _dead_letter_file(self, path: str, reason: str) -> None:
        """Divert a condemned in-flight file to the dead-letter path and
        journal the event — the file is skipped, never re-read whole."""
        LOG.warning("in-flight input file %s %s — dead-lettering, "
                    "skipping (events it held beyond the checkpoint are "
                    "not recoverable)", path, reason)
        if self._quarantine is not None:
            self._quarantine.quarantine(path, self._current_line, "",
                                        f"in-flight file {reason}")
        if self._on_event is not None:
            self._on_event(
                f"ingest/file-rewritten:{os.path.basename(path)}")

    # -- listing ---------------------------------------------------------

    def _list_splits(self) -> List[Tuple[int, str]]:
        """New files as (mtime_ns, path), sorted by modification time then
        path (deterministic tiebreak), filtered to mtime > max seen."""
        if os.path.isdir(self.path):
            candidates = [
                os.path.join(self.path, name)
                for name in os.listdir(self.path)
                if not name.startswith((".", "_"))
            ]
        else:
            candidates = [self.path]
        splits = []
        for p in candidates:
            if not os.path.isfile(p) or p in self._dropped_paths:
                continue
            mtime = os.stat(p).st_mtime_ns
            if mtime > self.global_modification_time:
                splits.append((mtime, p))
        splits.sort()
        return splits

    # -- provenance ------------------------------------------------------

    def origin(self) -> Tuple[str, int]:
        """``(path, lineno)`` of the line most recently yielded by
        :meth:`lines` — the per-line provenance hook ``batched_lines``
        captures for parse errors and the quarantine dead-letter file."""
        return (self._current_file or self.path, self._current_line)

    # -- reading ---------------------------------------------------------

    def lines(self) -> Iterator[Optional[str]]:
        """Yield all input lines, file by file, in order.

        The progress marker advances only once a file is exhausted; while a
        file is open, (path, mtime, lines yielded) track the exact position
        so a checkpoint taken between batches loses nothing. A restored
        source skips the already-consumed prefix of the in-flight file (if
        it still exists unmodified) and continues.
        """
        # Restored mid-file position (if any). With the checkpoint's
        # in_flight guard (offsets_state) the resume is verified: an
        # unchanged or append-only grown file resumes at the exact line
        # even when its mtime moved, while a shrunk/rewritten file is
        # dead-lettered and skipped instead of silently re-read whole
        # (the pre-guard exposure: prefix events in still-open windows
        # were double-counted, matching the reference re-forwarding a
        # modified file as a whole new split,
        # ContinuousFileMonitoringFunction.java:239-257). A legacy
        # checkpoint with no guard keeps the old rule — resume only on
        # an unchanged mtime, re-read whole otherwise.
        skip_file = self._current_file
        skip_mtime = self._current_mtime
        skip_lines = self._current_line
        resume_any_mtime = False
        guard, self._in_flight_guard = self._in_flight_guard, None
        if (skip_file is not None and guard is not None
                and guard.get("path") == skip_file):
            verdict = self._verify_in_flight(guard)
            if verdict == "ok":
                resume_any_mtime = True
            elif verdict == "rewritten":
                self._dead_letter_file(skip_file, "rewritten under a "
                                       "checkpoint (shrunk or head-prefix "
                                       "mismatch)")
                self._dropped_paths.add(skip_file)
                # The (mtime, path) floor below still hides the consumed
                # same-mtime siblings; only the condemned file is dropped.
        files_opened = 0
        since_gate = 0
        while True:
            splits = self._list_splits()
            if skip_file is not None:
                # Consumption order is the deterministic (mtime, path) sort,
                # so files ordered before the in-flight one were fully
                # consumed even when they share its mtime (the > marker
                # filter alone cannot know that).
                splits = [s for s in splits if s >= (skip_mtime, skip_file)]
            for pos, (mtime, p) in enumerate(splits):
                files_opened += 1
                if faults.PLAN is not None:
                    faults.PLAN.fire("source_read", seq=files_opened)
                if degrade.CONTROLLER is not None:
                    # Admission control (bounded delay) at the split
                    # boundary: a burst of small files is gated too.
                    degrade.CONTROLLER.admit()
                self.counters.add(SPLIT_READER_NUM_SPLITS, 1)
                to_skip = skip_lines if (p == skip_file
                                         and (mtime == skip_mtime
                                              or resume_any_mtime)) else 0
                self._current_file = p
                self._current_mtime = mtime
                self._current_line = to_skip
                with open(p, "r") as f:
                    for line in f:
                        if to_skip:  # raw-line count, blank lines included
                            to_skip -= 1
                            continue
                        self._current_line += 1
                        line = line.rstrip("\n")
                        if line:
                            if degrade.CONTROLLER is not None:
                                # Source-side admission gate (degrade.py
                                # PAUSE_INGEST): at most pause_ms delay
                                # per check — bounded, never a stall.
                                since_gate += 1
                                if since_gate >= ADMIT_EVERY_LINES:
                                    since_gate = 0
                                    degrade.CONTROLLER.admit()
                            yield line
                # Advance the marker only once the LAST file sharing this
                # mtime completes: the marker's invariant is "everything at
                # or below is fully consumed", and _list_splits filters with
                # a strict >, so advancing early would hide same-mtime
                # siblings from a restored run.
                last_of_mtime = (pos + 1 == len(splits)
                                 or splits[pos + 1][0] > mtime)
                if last_of_mtime and mtime > self.global_modification_time:
                    self.global_modification_time = mtime
                self._current_file = None
                self._current_mtime = -1
                self._current_line = 0
            skip_file = None  # the restored position applies only once
            if not self.process_continuously:
                return
            # Idle heartbeat: lets the downstream batcher flush an aged
            # partial batch (--buffer-timeout) while no new lines arrive.
            yield None
            time.sleep(self.poll_interval_s)
