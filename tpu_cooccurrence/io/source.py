"""File ingestion: monitor a file or directory and stream interaction batches.

TPU-native replacement for the reference's forked file-monitoring source
(``ContinuousFileMonitoringFunction.java``) + unsplittable text format
(``UnsplittableTextInputFormat.java``):

  * a path (file or directory) is listed; files are forwarded **sorted by
    modification time** (reference :239-257),
  * each file is read whole, in line order — never split — preserving the
    ascending-timestamp contract (``UnsplittableTextInputFormat.java:12-20``),
  * ``PROCESS_ONCE`` reads the current snapshot and stops;
    ``PROCESS_CONTINUOUSLY`` re-lists and forwards files whose modification
    time is newer than the max seen (reference :204-236),
  * the max modification time is checkpointable so a restored job does not
    re-ingest (reference :380-392).

No existence pre-check is done before listing — the reference deliberately
removed it for object-store compatibility (:196-201); we surface listing
errors directly instead.
"""

from __future__ import annotations

import os
import time
from typing import Iterator, List, Optional, Tuple

from ..metrics import Counters, SPLIT_READER_NUM_SPLITS


class FileMonitorSource:
    """Streams lines from a file or directory in modification-time order."""

    def __init__(
        self,
        path: str,
        counters: Optional[Counters] = None,
        process_continuously: bool = False,
        poll_interval_s: float = 1.0,
    ) -> None:
        self.path = path
        self.counters = counters or Counters()
        self.process_continuously = process_continuously
        self.poll_interval_s = poll_interval_s
        # Checkpointed monotone progress marker (reference:
        # ContinuousFileMonitoringFunction.java:380-392).
        self.global_modification_time: int = -1

    # -- checkpoint hooks ------------------------------------------------

    def checkpoint_state(self) -> dict:
        return {"global_modification_time": self.global_modification_time}

    def restore_state(self, state: dict) -> None:
        self.global_modification_time = int(state["global_modification_time"])

    # -- listing ---------------------------------------------------------

    def _list_splits(self) -> List[Tuple[int, str]]:
        """New files as (mtime_ns, path), sorted by modification time then
        path (deterministic tiebreak), filtered to mtime > max seen."""
        if os.path.isdir(self.path):
            candidates = [
                os.path.join(self.path, name)
                for name in os.listdir(self.path)
                if not name.startswith((".", "_"))
            ]
        else:
            candidates = [self.path]
        splits = []
        for p in candidates:
            if not os.path.isfile(p):
                continue
            mtime = os.stat(p).st_mtime_ns
            if mtime > self.global_modification_time:
                splits.append((mtime, p))
        splits.sort()
        return splits

    # -- reading ---------------------------------------------------------

    def lines(self) -> Iterator[str]:
        """Yield all input lines, file by file, in order."""
        while True:
            splits = self._list_splits()
            for mtime, p in splits:
                self.counters.add(SPLIT_READER_NUM_SPLITS, 1)
                if mtime > self.global_modification_time:
                    self.global_modification_time = mtime
                with open(p, "r") as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if line:
                            yield line
            if not self.process_continuously:
                return
            time.sleep(self.poll_interval_s)
