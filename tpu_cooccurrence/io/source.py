"""File ingestion: monitor a file or directory and stream interaction batches.

TPU-native replacement for the reference's forked file-monitoring source
(``ContinuousFileMonitoringFunction.java``) + unsplittable text format
(``UnsplittableTextInputFormat.java``):

  * a path (file or directory) is listed; files are forwarded **sorted by
    modification time** (reference :239-257),
  * each file is read whole, in line order — never split — preserving the
    ascending-timestamp contract (``UnsplittableTextInputFormat.java:12-20``),
  * ``PROCESS_ONCE`` reads the current snapshot and stops;
    ``PROCESS_CONTINUOUSLY`` re-lists and forwards files whose modification
    time is newer than the max seen (reference :204-236),
  * the max modification time is checkpointable so a restored job does not
    re-ingest (reference :380-392).

No existence pre-check is done before listing — the reference deliberately
removed it for object-store compatibility (:196-201); we surface listing
errors directly instead.
"""

from __future__ import annotations

import os
import time
from typing import Iterator, List, Optional, Tuple

from ..metrics import Counters, SPLIT_READER_NUM_SPLITS
from ..robustness import degrade, faults

#: Lines between admission-gate checks while a degradation controller is
#: installed: cheap enough to bound burst admission at sub-batch
#: granularity, coarse enough to stay off the per-line hot path.
ADMIT_EVERY_LINES = 4096


class FileMonitorSource:
    """Streams lines from a file or directory in modification-time order."""

    def __init__(
        self,
        path: str,
        counters: Optional[Counters] = None,
        process_continuously: bool = False,
        poll_interval_s: float = 1.0,
    ) -> None:
        self.path = path
        self.counters = counters or Counters()
        self.process_continuously = process_continuously
        self.poll_interval_s = poll_interval_s
        # Checkpointed monotone progress marker (reference:
        # ContinuousFileMonitoringFunction.java:380-392). Advanced only when
        # a file has been fully consumed; a mid-file position is carried
        # separately so a checkpoint taken mid-file resumes exactly (the
        # reference cannot: its marker covers whole splits only).
        self.global_modification_time: int = -1
        self._current_file: Optional[str] = None
        self._current_mtime: int = -1
        self._current_line: int = 0

    # -- checkpoint hooks ------------------------------------------------

    def checkpoint_state(self) -> dict:
        return {
            "global_modification_time": self.global_modification_time,
            "current_file": self._current_file,
            "current_mtime": self._current_mtime,
            "current_line": self._current_line,
        }

    def restore_state(self, state: dict) -> None:
        self.global_modification_time = int(state["global_modification_time"])
        self._current_file = state.get("current_file")
        self._current_mtime = int(state.get("current_mtime", -1))
        self._current_line = int(state.get("current_line", 0))

    # -- listing ---------------------------------------------------------

    def _list_splits(self) -> List[Tuple[int, str]]:
        """New files as (mtime_ns, path), sorted by modification time then
        path (deterministic tiebreak), filtered to mtime > max seen."""
        if os.path.isdir(self.path):
            candidates = [
                os.path.join(self.path, name)
                for name in os.listdir(self.path)
                if not name.startswith((".", "_"))
            ]
        else:
            candidates = [self.path]
        splits = []
        for p in candidates:
            if not os.path.isfile(p):
                continue
            mtime = os.stat(p).st_mtime_ns
            if mtime > self.global_modification_time:
                splits.append((mtime, p))
        splits.sort()
        return splits

    # -- provenance ------------------------------------------------------

    def origin(self) -> Tuple[str, int]:
        """``(path, lineno)`` of the line most recently yielded by
        :meth:`lines` — the per-line provenance hook ``batched_lines``
        captures for parse errors and the quarantine dead-letter file."""
        return (self._current_file or self.path, self._current_line)

    # -- reading ---------------------------------------------------------

    def lines(self) -> Iterator[str]:
        """Yield all input lines, file by file, in order.

        The progress marker advances only once a file is exhausted; while a
        file is open, (path, mtime, lines yielded) track the exact position
        so a checkpoint taken between batches loses nothing. A restored
        source skips the already-consumed prefix of the in-flight file (if
        it still exists unmodified) and continues.
        """
        # Restored mid-file position (if any): resume only when the same
        # file is re-listed with an unchanged mtime; a file modified since
        # the checkpoint is re-read whole. Prefix events behind the
        # restored watermark are then dropped as late, but prefix events in
        # still-open (checkpointed, unfired) windows are NOT late and are
        # double-counted — same exposure as the reference, which re-forwards
        # a modified file as a whole new split
        # (ContinuousFileMonitoringFunction.java:239-257). Don't modify an
        # in-flight input file concurrently with a checkpointed run.
        skip_file = self._current_file
        skip_mtime = self._current_mtime
        skip_lines = self._current_line
        files_opened = 0
        since_gate = 0
        while True:
            splits = self._list_splits()
            if skip_file is not None:
                # Consumption order is the deterministic (mtime, path) sort,
                # so files ordered before the in-flight one were fully
                # consumed even when they share its mtime (the > marker
                # filter alone cannot know that).
                splits = [s for s in splits if s >= (skip_mtime, skip_file)]
            for pos, (mtime, p) in enumerate(splits):
                files_opened += 1
                if faults.PLAN is not None:
                    faults.PLAN.fire("source_read", seq=files_opened)
                if degrade.CONTROLLER is not None:
                    # Admission control (bounded delay) at the split
                    # boundary: a burst of small files is gated too.
                    degrade.CONTROLLER.admit()
                self.counters.add(SPLIT_READER_NUM_SPLITS, 1)
                to_skip = skip_lines if (p == skip_file
                                         and mtime == skip_mtime) else 0
                skip_file = None
                self._current_file = p
                self._current_mtime = mtime
                self._current_line = to_skip
                with open(p, "r") as f:
                    for line in f:
                        if to_skip:  # raw-line count, blank lines included
                            to_skip -= 1
                            continue
                        self._current_line += 1
                        line = line.rstrip("\n")
                        if line:
                            if degrade.CONTROLLER is not None:
                                # Source-side admission gate (degrade.py
                                # PAUSE_INGEST): at most pause_ms delay
                                # per check — bounded, never a stall.
                                since_gate += 1
                                if since_gate >= ADMIT_EVERY_LINES:
                                    since_gate = 0
                                    degrade.CONTROLLER.admit()
                            yield line
                # Advance the marker only once the LAST file sharing this
                # mtime completes: the marker's invariant is "everything at
                # or below is fully consumed", and _list_splits filters with
                # a strict >, so advancing early would hide same-mtime
                # siblings from a restored run.
                last_of_mtime = (pos + 1 == len(splits)
                                 or splits[pos + 1][0] > mtime)
                if last_of_mtime and mtime > self.global_modification_time:
                    self.global_modification_time = mtime
                self._current_file = None
                self._current_mtime = -1
                self._current_line = 0
            skip_file = None  # the restored position applies only once
            if not self.process_continuously:
                return
            # Idle heartbeat: lets the downstream batcher flush an aged
            # partial batch (--buffer-timeout) while no new lines arrive.
            yield None
            time.sleep(self.poll_interval_s)
