"""Synthetic and public-dataset interaction streams for benchmarks.

Provides the five BASELINE.md benchmark inputs: tiny text batch, the
MovieLens / Instacart adapters (CSV on disk), and the Zipfian basket
generator (1M items, alpha=1.1) — see SURVEY.md §6.
"""

from __future__ import annotations

import os
from typing import Iterator, Tuple

import numpy as np


def zipfian_interactions(
    n_events: int,
    n_items: int = 1_000_000,
    n_users: int = 100_000,
    alpha: float = 1.1,
    seed: int = 0,
    events_per_ms: int = 100,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Zipfian basket stream: item popularity ~ Zipf(alpha), users uniform,
    timestamps ascending at ``events_per_ms`` events per millisecond.

    Returns (users, items, timestamps) int64 arrays.
    """
    rng = np.random.default_rng(seed)
    # Bounded Zipf via inverse-CDF over a precomputed table (np.random.zipf
    # is unbounded and slow for alpha near 1).
    ranks = np.arange(1, n_items + 1, dtype=np.float64)
    weights = ranks ** (-alpha)
    cdf = np.cumsum(weights)
    cdf /= cdf[-1]
    u = rng.random(n_events)
    items = np.searchsorted(cdf, u).astype(np.int64)
    users = rng.integers(0, n_users, n_events, dtype=np.int64)
    timestamps = (np.arange(n_events, dtype=np.int64) // events_per_ms)
    return users, items, timestamps


def word_cooccurrence_stream(
    text: str,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batch word co-occurrence on a text: each line is a 'user' (basket),
    each token an 'item', timestamps = line index (benchmark config 1)."""
    vocab = {}
    users, items, tss = [], [], []
    for line_no, line in enumerate(text.splitlines()):
        for tok in line.split():
            idx = vocab.setdefault(tok, len(vocab))
            users.append(line_no)
            items.append(idx)
            tss.append(line_no)
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(tss, dtype=np.int64),
    )


def movielens_interactions(
    ratings_csv: str,
    min_rating: float = 0.0,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Adapter for MovieLens ``ratings.csv`` (userId,movieId,rating,timestamp).

    Yields sorted-by-timestamp chunks as interaction batches (benchmark
    configs 2 and 3). Handles both the 100K tab format (u.data) and the
    25M CSV format.
    """
    is_udata = ratings_csv.endswith(".data")
    delim = "\t" if is_udata else ","
    skip = 0 if is_udata else 1
    data = np.loadtxt(ratings_csv, delimiter=delim, skiprows=skip,
                      dtype=np.float64)
    users = data[:, 0].astype(np.int64)
    items = data[:, 1].astype(np.int64)
    ratings = data[:, 2]
    ts = data[:, 3].astype(np.int64) * 1000  # seconds -> ms
    keep = ratings >= min_rating
    users, items, ts = users[keep], items[keep], ts[keep]
    order = np.argsort(ts, kind="stable")
    yield users[order], items[order], ts[order]


def instacart_interactions(
    orders_csv: str,
    order_products_csv: str,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Adapter for Instacart order-product baskets (benchmark config 5):
    user = order's user_id, item = product_id, ts = order_number ordering."""
    orders = np.loadtxt(orders_csv, delimiter=",", skiprows=1,
                        usecols=(0, 1, 3), dtype=np.int64)  # order_id,user_id,order_number
    order_user = {int(o): int(u) for o, u, _n in orders}
    order_ts = {int(o): int(n) for o, _u, n in orders}
    op = np.loadtxt(order_products_csv, delimiter=",", skiprows=1,
                    usecols=(0, 1), dtype=np.int64)  # order_id,product_id
    users = np.asarray([order_user[int(o)] for o in op[:, 0]], dtype=np.int64)
    ts = np.asarray([order_ts[int(o)] for o in op[:, 0]], dtype=np.int64)
    items = op[:, 1]
    order = np.argsort(ts, kind="stable")
    yield users[order], items[order], ts[order]


def write_interactions_csv(path: str, users, items, timestamps) -> None:
    """Write interactions in the reference's input format ``user,item,ts``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arr = np.stack([users, items, timestamps], axis=1)
    np.savetxt(path, arr, fmt="%d", delimiter=",")
