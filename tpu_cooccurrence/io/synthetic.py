"""Synthetic and public-dataset interaction streams for benchmarks.

Provides the five BASELINE.md benchmark inputs: tiny text batch, the
MovieLens / Instacart adapters (CSV on disk), and the Zipfian basket
generator (1M items, alpha=1.1) — see SURVEY.md §6.
"""

from __future__ import annotations

import os
from typing import Iterator, Tuple

import numpy as np


def zipfian_interactions(
    n_events: int,
    n_items: int = 1_000_000,
    n_users: int = 100_000,
    alpha: float = 1.1,
    seed: int = 0,
    events_per_ms: int = 100,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Zipfian basket stream: item popularity ~ Zipf(alpha), users uniform,
    timestamps ascending at ``events_per_ms`` events per millisecond.

    Returns (users, items, timestamps) int64 arrays.
    """
    rng = np.random.default_rng(seed)
    # Bounded Zipf via inverse-CDF over a precomputed table (np.random.zipf
    # is unbounded and slow for alpha near 1).
    ranks = np.arange(1, n_items + 1, dtype=np.float64)
    weights = ranks ** (-alpha)
    items = sample_items(weights / weights.sum(), n_events, rng)
    users = rng.integers(0, n_users, n_events, dtype=np.int64)
    timestamps = (np.arange(n_events, dtype=np.int64) // events_per_ms)
    return users, items, timestamps


def sample_items(weights: np.ndarray, n: int,
                 rng: np.random.Generator) -> np.ndarray:
    """``n`` iid draws from a normalized weight vector via inverse-CDF
    (single shared implementation: the cdf[-1] pinning guards the
    round-off case where cumsum tops out just under 1.0 and a uniform
    draw above it would index out of range)."""
    cdf = np.cumsum(weights)
    cdf[-1] = 1.0
    return np.searchsorted(cdf, rng.random(n)).astype(np.int64)


def zipf_mandelbrot_weights(n_items: int, s: float, q: float) -> np.ndarray:
    """Normalized Zipf-Mandelbrot law ``w(r) ∝ (r + q)^-s`` over ranks
    1..n_items. Unlike pure Zipf, the offset ``q`` flattens the head —
    real popularity spectra (MovieLens, Instacart) have near-tied top
    items (e.g. ML-25M's top-2 movies within 0.01% of each other),
    which no pure power law reproduces."""
    r = np.arange(1, n_items + 1, dtype=np.float64)
    w = (r + q) ** (-s)
    return w / w.sum()


def truncated_lognormal_activity(n: int, mu: float, sigma: float,
                                 lo: float, hi: float,
                                 rng: np.random.Generator) -> np.ndarray:
    """Per-entity activity weights ~ LogNormal(mu, sigma) clipped to
    [lo, hi] — the user-activity model for the calibrated stand-ins
    (e.g. ML-25M: every user has >= 20 ratings by construction of the
    dataset, median ~71, mean 153.8; a clipped log-normal hits all
    three where a power law cannot)."""
    a = np.exp(rng.normal(mu, sigma, n))
    return np.clip(a, lo, hi)


def _exact_multiplicities(weights: np.ndarray, total: int) -> np.ndarray:
    """Integer counts summing to ``total``, proportional to ``weights``
    (largest-remainder rounding): the generated stream then carries the
    target per-entity marginal EXACTLY, not merely in expectation."""
    expected = total * (weights / weights.sum())
    base = np.floor(expected).astype(np.int64)
    rem = total - int(base.sum())
    if rem > 0:
        frac = expected - base
        base[np.argsort(-frac)[:rem]] += 1
    return base


def calibrated_interactions(
    n_events: int,
    *,
    n_users: int,
    n_items: int,
    item_s: float,
    item_q: float,
    user_mu: float,
    user_sigma: float,
    user_lo: float,
    user_hi: float,
    seed: int = 0,
    events_per_ms: int = 50,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Interaction stream with marginals calibrated to a real dataset.

    Item popularity follows a Zipf-Mandelbrot law fitted to published
    head anchors; per-user activity follows a clipped log-normal fitted
    to the dataset's documented minimum/median/mean. User ids are
    assigned by exact multiplicity (largest remainder) and shuffled
    uniformly over the stream; items are drawn iid from the item law.

    Deliberate simplifications vs real data (docs/calibrated_standins.md
    quantifies them): user/item independence (no taste structure),
    sessionless user activity (a user's events spread uniformly over
    the stream instead of bursting), synthetic ascending timestamps at
    ``events_per_ms`` (window cadence comparable across benchmark
    rounds), and ``n_events`` below the dataset's full size behaves as
    uniform thinning, not a time-prefix.
    """
    rng = np.random.default_rng(seed)
    items = sample_items(zipf_mandelbrot_weights(n_items, item_s, item_q),
                         n_events, rng)
    activity = truncated_lognormal_activity(n_users, user_mu, user_sigma,
                                            user_lo, user_hi, rng)
    counts = _exact_multiplicities(activity, n_events)
    users = np.repeat(np.arange(n_users, dtype=np.int64), counts)
    rng.shuffle(users)
    timestamps = np.arange(n_events, dtype=np.int64) // events_per_ms
    return users, items, timestamps


#: Calibration constants. Hard anchors come from the datasets' own
#: documentation (total ratings/users/movies; the >=20-ratings-per-user
#: floor); head anchors (top-3 item counts) and medians are the widely
#: reported empirical values. Parameters (s, q, sigma) were fitted by
#: bisection so the generated law reproduces the anchors exactly; the
#: fit script and the residual deltas vs the real spectra are in
#: docs/calibrated_standins.md.
ML25M_CALIBRATION = dict(
    # 25,000,095 ratings, 162,541 users, 59,047 movies (README);
    # top-3 ≈ 81,491 / 80,573(fit) / 79,672; user median ≈ 71.
    n_users=162_541, n_items=59_047,
    item_s=1.335659, item_q=116.337,
    user_mu=4.2627, user_sigma=1.1346, user_lo=20.0, user_hi=32_202.0,
)
ML25M_EVENTS = 25_000_095

ML100K_CALIBRATION = dict(
    # 100,000 ratings, 943 users, 1,682 movies; top-3 = 583/509/508
    # (Star Wars / Contact / Fargo); >=20 ratings per user.
    n_users=943, n_items=1_682,
    item_s=0.5444, item_q=5.949,
    user_mu=4.1744, user_sigma=0.9373, user_lo=20.0, user_hi=737.0,
)
ML100K_EVENTS = 100_000


def ml25m_calibrated(n_events: int = ML25M_EVENTS, seed: int = 25,
                     events_per_ms: int = 50):
    """ML-25M-shaped stream (see ML25M_CALIBRATION)."""
    return calibrated_interactions(n_events, seed=seed,
                                   events_per_ms=events_per_ms,
                                   **ML25M_CALIBRATION)


def ml100k_calibrated(n_events: int = ML100K_EVENTS, seed: int = 100,
                      events_per_ms: int = 5):
    """ML-100K-shaped stream (see ML100K_CALIBRATION)."""
    return calibrated_interactions(n_events, seed=seed,
                                   events_per_ms=events_per_ms,
                                   **ML100K_CALIBRATION)


#: Instacart: 3,421,083 orders, 206,209 users (4..100 orders each,
#: mean 16.6), 49,688 products over 33,819,106 order-products
#: (prior+train); top-3 products Banana 491,291 / Bag of Organic
#: Bananas 394,930 / Organic Strawberries 275,577; basket mean ~10.1,
#: median ~8.
INSTACART_CALIBRATION = dict(
    n_orders=3_421_083,
    n_products=49_688, item_s=0.7845, item_q=0.836,
    orders_mu=2.3026, orders_sigma=0.9079, orders_lo=4.0, orders_hi=100.0,
    basket_mu=2.0794, basket_sigma=0.6822, basket_lo=1.0, basket_hi=145.0,
    n_users=206_209,
)


def instacart_calibrated(n_baskets: int, seed: int = 55,
                         ms_per_basket: int = 10):
    """Instacart-shaped basket stream: per-user order counts and basket
    sizes from clipped log-normals, product popularity Zipf-Mandelbrot
    (all fitted to the published marginals above). Each basket is one
    (user, timestamp) group, like the real order->products join."""
    c = INSTACART_CALIBRATION
    rng = np.random.default_rng(seed)
    # Scale the user population with the basket budget so orders/user
    # keeps its real mean at any size; full size = exactly all users.
    n_users = max(1, min(c["n_users"], int(round(
        n_baskets * c["n_users"] / c["n_orders"]))))
    orders = truncated_lognormal_activity(
        n_users, c["orders_mu"], c["orders_sigma"],
        c["orders_lo"], c["orders_hi"], rng)
    basket_users = np.repeat(
        np.arange(n_users, dtype=np.int64),
        _exact_multiplicities(orders, n_baskets))
    rng.shuffle(basket_users)
    sizes = np.rint(truncated_lognormal_activity(
        n_baskets, c["basket_mu"], c["basket_sigma"],
        c["basket_lo"], c["basket_hi"], rng)).astype(np.int64)
    users = np.repeat(basket_users, sizes)
    ts = np.repeat(np.arange(n_baskets, dtype=np.int64) * ms_per_basket,
                   sizes)
    items = sample_items(
        zipf_mandelbrot_weights(c["n_products"], c["item_s"], c["item_q"]),
        int(sizes.sum()), rng)
    return users, items, ts


def word_cooccurrence_stream(
    text: str,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batch word co-occurrence on a text: each line is a 'user' (basket),
    each token an 'item', timestamps = line index (benchmark config 1)."""
    vocab = {}
    users, items, tss = [], [], []
    for line_no, line in enumerate(text.splitlines()):
        for tok in line.split():
            idx = vocab.setdefault(tok, len(vocab))
            users.append(line_no)
            items.append(idx)
            tss.append(line_no)
    return (
        np.asarray(users, dtype=np.int64),
        np.asarray(items, dtype=np.int64),
        np.asarray(tss, dtype=np.int64),
    )


def movielens_interactions(
    ratings_csv: str,
    min_rating: float = 0.0,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Adapter for MovieLens ``ratings.csv`` (userId,movieId,rating,timestamp).

    Yields sorted-by-timestamp chunks as interaction batches (benchmark
    configs 2 and 3). Handles both the 100K tab format (u.data) and the
    25M CSV format.
    """
    is_udata = ratings_csv.endswith(".data")
    delim = "\t" if is_udata else ","
    skip = 0 if is_udata else 1
    data = np.loadtxt(ratings_csv, delimiter=delim, skiprows=skip,
                      dtype=np.float64)
    users = data[:, 0].astype(np.int64)
    items = data[:, 1].astype(np.int64)
    ratings = data[:, 2]
    ts = data[:, 3].astype(np.int64) * 1000  # seconds -> ms
    keep = ratings >= min_rating
    users, items, ts = users[keep], items[keep], ts[keep]
    order = np.argsort(ts, kind="stable")
    yield users[order], items[order], ts[order]


def instacart_interactions(
    orders_csv: str,
    order_products_csv: str,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Adapter for Instacart order-product baskets (benchmark config 5):
    user = order's user_id, item = product_id, ts = order_number ordering."""
    orders = np.loadtxt(orders_csv, delimiter=",", skiprows=1,
                        usecols=(0, 1, 3), dtype=np.int64)  # order_id,user_id,order_number
    order_user = {int(o): int(u) for o, u, _n in orders}
    order_ts = {int(o): int(n) for o, _u, n in orders}
    op = np.loadtxt(order_products_csv, delimiter=",", skiprows=1,
                    usecols=(0, 1), dtype=np.int64)  # order_id,product_id
    users = np.asarray([order_user[int(o)] for o in op[:, 0]], dtype=np.int64)
    ts = np.asarray([order_ts[int(o)] for o in op[:, 0]], dtype=np.int64)
    items = op[:, 1]
    order = np.argsort(ts, kind="stable")
    yield users[order], items[order], ts[order]


def write_interactions_csv(path: str, users, items, timestamps) -> None:
    """Write interactions in the reference's input format ``user,item,ts``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arr = np.stack([users, items, timestamps], axis=1)
    np.savetxt(path, arr, fmt="%d", delimiter=",")
