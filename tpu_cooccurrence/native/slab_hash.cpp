// Open-addressing int64 -> int32 hash table for the sparse backends'
// cell index (key = row << 32 | dst, value = device slab slot).
//
// Why native: the sorted-array SlabIndex pays an O(total cells) merge
// per window (measured 90 s of a 463 s full ML-25M CPU run at 14M
// cells); hashing makes the per-window cost O(window cells). Batched
// flat-array API so Python holds the storage (NumPy arrays) and ctypes
// passes pointers — no ownership crosses the boundary.
//
// Table contract: capacity is a power of two (mask = cap - 1); empty
// buckets hold key -1 (packed keys are non-negative: row and dst are
// < 2^31). Linear probing; the caller keeps the load factor below the
// grow threshold, so probes terminate.

#include <cstdint>

namespace {
inline uint64_t mix(uint64_t x) {
  // splitmix64 finalizer: full-avalanche over the packed key's bits
  // (row ids cluster in the high word; identity hashing would chain).
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}
}  // namespace

extern "C" {

// All probe loops are bounded at capacity (mask + 1) steps so a violated
// contract (key absent where presence is promised, or a 100%-full table)
// fails loudly instead of spinning forever on a corrupted reverse map.
// Each function returns the number of keys whose probe exhausted the
// table; callers raise on any nonzero return.

// Probe each key: out_slots[i] = value when present (out_new[i] = 0),
// otherwise out_new[i] = 1 (out_slots[i] untouched).
int64_t slab_hash_lookup(const int64_t* tkeys, const int32_t* tvals,
                         int64_t mask, const int64_t* keys, int64_t n,
                         int32_t* out_slots, uint8_t* out_new) {
  int64_t exhausted = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t key = keys[i];
    uint64_t h = mix((uint64_t)key) & (uint64_t)mask;
    int64_t left = mask + 1;
    for (; left > 0; --left) {
      const int64_t k = tkeys[h];
      if (k == key) {
        out_slots[i] = tvals[h];
        out_new[i] = 0;
        break;
      }
      if (k == -1) {
        out_new[i] = 1;
        break;
      }
      h = (h + 1) & (uint64_t)mask;
    }
    if (left == 0) {
      out_new[i] = 1;
      ++exhausted;  // table 100% full and key absent: contract violation
    }
  }
  return exhausted;
}

// Insert (key, slot) pairs known to be absent (fresh from a lookup miss,
// or a rebuild). The caller has already grown the table if needed.
int64_t slab_hash_insert(int64_t* tkeys, int32_t* tvals, int64_t mask,
                         const int64_t* keys, const int32_t* slots,
                         int64_t n) {
  int64_t exhausted = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t key = keys[i];
    uint64_t h = mix((uint64_t)key) & (uint64_t)mask;
    int64_t left = mask + 1;
    while (left > 0 && tkeys[h] != -1) {
      h = (h + 1) & (uint64_t)mask;
      --left;
    }
    if (left == 0) {
      ++exhausted;  // no empty bucket: caller failed to grow the table
      continue;
    }
    tkeys[h] = key;
    tvals[h] = slots[i];
  }
  return exhausted;
}

// One-pass row relocation for DISJOINT moves (every new region lies
// beyond the old heap end — the _allocate growth case): for each moved
// row, copy its reverse-map keys old->new and re-point the table's
// slot values, without materializing the ragged index/gather arrays
// the NumPy path builds per window. NOT safe for compaction's
// overlapping re-lay — the caller keeps the gather-first path there.
int64_t slab_shift_rows(int64_t* tkeys, int32_t* tvals, int64_t mask,
                        int64_t* slot_key, const int32_t* old_starts,
                        const int32_t* new_starts, const int32_t* lens,
                        int64_t n_rows) {
  int64_t exhausted = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t os = old_starts[r];
    const int64_t ns = new_starts[r];
    const int64_t len = lens[r];
    for (int64_t j = 0; j < len; ++j) {
      const int64_t key = slot_key[os + j];
      slot_key[ns + j] = key;
      uint64_t h = mix((uint64_t)key) & (uint64_t)mask;
      int64_t left = mask + 1;
      while (left > 0 && tkeys[h] != key) {
        h = (h + 1) & (uint64_t)mask;
        --left;
      }
      if (left == 0) {
        ++exhausted;  // key absent: promised-present contract violated
        continue;
      }
      tvals[h] = (int32_t)(ns + j);
    }
  }
  return exhausted;
}

// Overwrite the slot of keys known to be present (row relocations and
// compaction re-laying).
int64_t slab_hash_update(int64_t* tkeys, int32_t* tvals, int64_t mask,
                         const int64_t* keys, const int32_t* slots,
                         int64_t n) {
  int64_t exhausted = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t key = keys[i];
    uint64_t h = mix((uint64_t)key) & (uint64_t)mask;
    int64_t left = mask + 1;
    while (left > 0 && tkeys[h] != key) {
      h = (h + 1) & (uint64_t)mask;
      --left;
    }
    if (left == 0) {
      ++exhausted;  // key absent: promised-present contract violated
      continue;
    }
    tvals[h] = slots[i];
  }
  return exhausted;
}

}  // extern "C"
