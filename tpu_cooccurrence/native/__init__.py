"""Native (C++) host kernels, loaded via ctypes with NumPy fallbacks.

Build happens lazily on first import (g++ is assumed present, as in the
target image); failures degrade gracefully to the pure-NumPy paths, so the
framework never hard-depends on a compiler at runtime.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

LOG = logging.getLogger("tpu_cooccurrence.native")

_HERE = os.path.dirname(__file__)
_SRCS = [os.path.join(_HERE, "reservoir_expand.cpp"),
         os.path.join(_HERE, "sliding_expand.cpp"),
         os.path.join(_HERE, "slab_hash.cpp"),
         os.path.join(_HERE, "grouped_rank.cpp"),
         os.path.join(_HERE, "coo_aggregate.cpp")]
_LIB = os.path.join(_HERE, "libreservoir_expand.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()  # partitioned sampler threads race the first call


def _build() -> bool:
    try:
        # Build to a temp name + atomic rename: a concurrent *process*
        # (e.g. two CLI runs) must never observe a half-written .so whose
        # mtime passes the staleness check.
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, *_SRCS],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except Exception as exc:  # pragma: no cover - environment-dependent
        LOG.info("native build unavailable (%s); using NumPy fallback", exc)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first call; None if unavailable.

    Thread-safe: worker threads of the partitioned sampler may all reach
    the first call together."""
    with _lock:
        return _get_lib_locked()


def _get_lib_locked() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB) or (
            os.path.getmtime(_LIB) < max(os.path.getmtime(s)
                                         for s in _SRCS)):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError as exc:  # pragma: no cover
        LOG.info("native load failed (%s); using NumPy fallback", exc)
        return None
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    try:
        _bind_prototypes(lib, i64p, i32p)
    except AttributeError:
        # The .so on disk passed the staleness check but predates a newer
        # symbol set (e.g. installed by a concurrent older-version build
        # winning the atomic-rename race). Rebuild once; degrade to the
        # NumPy fallback if the fresh build still lacks the symbols.
        # dlopen caches handles BY PATHNAME, so re-CDLL'ing the replaced
        # canonical path would return the stale handle — load the fresh
        # build through a unique path instead (the unlink below is safe:
        # the handle keeps the inode alive).
        if not _build():
            return None
        reload_path = f"{_LIB}.{os.getpid()}.reload.so"
        try:
            import shutil

            shutil.copy2(_LIB, reload_path)
            lib = ctypes.CDLL(reload_path)
            _bind_prototypes(lib, i64p, i32p)
        except (OSError, AttributeError) as exc:
            LOG.info("native symbols unavailable (%s); using NumPy "
                     "fallback", exc)
            return None
        finally:
            try:
                os.unlink(reload_path)
            except OSError:
                pass
    _lib = lib
    return _lib


def _bind_prototypes(lib, i64p, i32p) -> None:
    lib.expand_replacements.restype = ctypes.c_int64
    lib.expand_replacements.argtypes = [
        i32p, ctypes.c_int64, i64p, i64p, i64p, ctypes.c_int64,
        i64p, i64p, i32p]
    lib.expand_appends.restype = ctypes.c_int64
    lib.expand_appends.argtypes = [
        i32p, ctypes.c_int64, i64p, i64p, i64p, ctypes.c_int64,
        i64p, i64p, i32p]
    lib.sliding_prepare.restype = ctypes.c_int64
    lib.sliding_prepare.argtypes = [
        i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, i32p, i32p, i64p, i64p, i64p, i64p, i64p]
    lib.sliding_emit.restype = None
    lib.sliding_emit.argtypes = [
        i64p, i64p, ctypes.c_int64, i32p, i64p, ctypes.c_int64,
        i64p, i64p, i64p, i64p]
    lib.sliding_cut_mask.restype = None
    lib.sliding_cut_mask.argtypes = [
        i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        i32p, i32p, ctypes.POINTER(ctypes.c_uint8)]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    # The slab-hash entry points return the number of keys whose bounded
    # probe exhausted the table (contract violation); callers raise on it.
    lib.slab_hash_lookup.restype = ctypes.c_int64
    lib.slab_hash_lookup.argtypes = [
        i64p, i32p, ctypes.c_int64, i64p, ctypes.c_int64, i32p, u8p]
    lib.slab_hash_insert.restype = ctypes.c_int64
    lib.slab_hash_insert.argtypes = [
        i64p, i32p, ctypes.c_int64, i64p, i32p, ctypes.c_int64]
    lib.slab_hash_update.restype = ctypes.c_int64
    lib.slab_hash_update.argtypes = [
        i64p, i32p, ctypes.c_int64, i64p, i32p, ctypes.c_int64]
    lib.slab_shift_rows.restype = ctypes.c_int64
    lib.slab_shift_rows.argtypes = [
        i64p, i32p, ctypes.c_int64, i64p, i32p, i32p, i32p,
        ctypes.c_int64]
    lib.grouped_rank_dense.restype = None
    lib.grouped_rank_dense.argtypes = [i64p, ctypes.c_int64, i32p, i32p]
    lib.coo_aggregate.restype = ctypes.c_int64
    lib.coo_aggregate.argtypes = [i64p, i64p, ctypes.c_int64]


def _ptr64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _ptr32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _ptr8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def coo_aggregate(key: np.ndarray, delta: np.ndarray,
                  clobber_key: bool = False):
    """Native fold of duplicate packed cell keys; returns
    ``(unique_sorted_keys, int64 summed deltas)`` or None (no lib).

    The C routine folds in place; this wrapper hands it the caller's
    buffer only when that is safe — ``clobber_key=True`` says the key
    array is throwaway (the hot path hands a freshly-packed local, and
    an 8B*n defensive memcpy is exactly the cost class the native fold
    exists to remove); deltas are only reused when the dtype conversion
    already produced a fresh array. Callers see their inputs unchanged
    unless they opted in.
    """
    lib = get_lib()
    if lib is None:
        return None
    n = len(key)
    if len(delta) != n:
        # The numpy path's bincount(weights=...) raised on this; the C
        # loop would read past the buffer instead.
        raise ValueError(
            f"coo_aggregate: delta length {len(delta)} != key length {n}")
    if not np.issubdtype(np.asarray(delta).dtype, np.integer):
        # The int64 conversion below would silently truncate fractional
        # deltas, diverging from the float64 bincount fallback (which
        # sums them exactly). No caller ships non-integer deltas today;
        # a future one must not fold differently by buffer size.
        raise TypeError(
            f"coo_aggregate: delta dtype must be integer, got "
            f"{np.asarray(delta).dtype} (the native fold sums int64)")
    if n == 0:
        return (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64))
    keys = np.ascontiguousarray(key, dtype=np.int64)
    if keys is key and not clobber_key:
        keys = keys.copy()
    deltas = np.ascontiguousarray(delta, dtype=np.int64)
    if deltas is delta:
        deltas = deltas.copy()
    m = int(lib.coo_aggregate(_ptr64(keys), _ptr64(deltas), n))
    return keys[:m], deltas[:m]


def expand_appends(hist: np.ndarray, users: np.ndarray, items: np.ndarray,
                   slots: np.ndarray):
    """Native append-pair expansion; returns (src, dst, delta) or None.

    ``slots[e]`` is both the slot event ``e`` wrote and its partner count;
    the caller must have written the new items into ``hist`` already (see
    sampling/reservoir.py fact 1).
    """
    lib = get_lib()
    if lib is None or len(users) == 0:
        return None
    n = len(users)
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    cap = int(2 * slots.sum())
    if cap == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z, np.zeros(0, dtype=np.int32)
    src = np.empty(cap, dtype=np.int64)
    dst = np.empty(cap, dtype=np.int64)
    delta = np.empty(cap, dtype=np.int32)
    users = np.ascontiguousarray(users, dtype=np.int64)
    items = np.ascontiguousarray(items, dtype=np.int64)
    assert hist.flags.c_contiguous and hist.dtype == np.int32
    written = lib.expand_appends(
        _ptr32(hist), hist.shape[1], _ptr64(users), _ptr64(items),
        _ptr64(slots), n, _ptr64(src), _ptr64(dst), _ptr32(delta))
    return src[:written], dst[:written], delta[:written]


def expand_replacements(hist: np.ndarray, users: np.ndarray,
                        items: np.ndarray, slots: np.ndarray):
    """Native replacement expansion; returns (src, dst, delta) or None.

    ``hist`` is the [U, k_max] int32 reservoir storage and is MUTATED
    (slots written in event order), matching the NumPy path's semantics.
    """
    lib = get_lib()
    if lib is None or len(users) == 0:
        return None
    k_max = hist.shape[1]
    n = len(users)
    cap = n * 4 * (k_max - 1)
    src = np.empty(cap, dtype=np.int64)
    dst = np.empty(cap, dtype=np.int64)
    delta = np.empty(cap, dtype=np.int32)
    users = np.ascontiguousarray(users, dtype=np.int64)
    items = np.ascontiguousarray(items, dtype=np.int64)
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    assert hist.flags.c_contiguous and hist.dtype == np.int32
    written = lib.expand_replacements(
        _ptr32(hist), k_max, _ptr64(users), _ptr64(items), _ptr64(slots),
        n, _ptr64(src), _ptr64(dst), _ptr32(delta))
    return src[:written], dst[:written], delta[:written]


class SlidingScratch:
    """Persistent dense scratch for the native sliding expansion.

    One instance per sampler: the dense count arrays are grown to the
    largest ids seen and re-zeroed (used prefix only) between windows —
    a memset, vs the NumPy path's per-window argsorts.
    """

    def __init__(self) -> None:
        self.item_count = np.zeros(1024, dtype=np.int32)
        self.user_count = np.zeros(1024, dtype=np.int32)
        self.user_start = np.zeros(1024, dtype=np.int64)

    def _ensure(self, max_item: int, max_user: int) -> None:
        if max_item >= len(self.item_count):
            self.item_count = np.zeros(
                max(2 * len(self.item_count), max_item + 1), dtype=np.int32)
        if max_user >= len(self.user_count):
            n = max(2 * len(self.user_count), max_user + 1)
            self.user_count = np.zeros(n, dtype=np.int32)
            self.user_start = np.zeros(n, dtype=np.int64)


def sliding_expand(users: np.ndarray, items: np.ndarray, f_max: int,
                   k_max: int, skip_cuts: bool,
                   scratch: SlidingScratch):
    """Native sliding basket expansion; returns (src, dst) or None.

    Byte-identical output to the NumPy path in ``sampling/sliding.py``
    (groups ascending by user id, arrival order within groups, partners
    by ascending basket position skipping self).
    """
    lib = get_lib()
    if lib is None:
        return None
    n = len(users)
    users = np.ascontiguousarray(users, dtype=np.int64)
    items = np.ascontiguousarray(items, dtype=np.int64)
    max_item = int(items.max())
    max_user = int(users.max())
    scratch._ensure(max_item, max_user)
    # Scratch buffers cross the ctypes boundary below; their dtypes are
    # fixed at allocation in SlidingScratch but that is invisible here —
    # assert at the boundary so a scratch refactor cannot silently hand
    # the C loops mis-sized cells.
    assert (scratch.item_count.dtype == np.int32
            and scratch.user_count.dtype == np.int32
            and scratch.user_start.dtype == np.int64)
    # Zero the used prefixes (phase 1 contract). user_start needs none:
    # only touched entries are written-then-read.
    scratch.item_count[: max_item + 1].fill(0)
    scratch.user_count[: max_user + 1].fill(0)
    kept_users = np.empty(n, dtype=np.int64)
    kept_items = np.empty(n, dtype=np.int64)
    touched = np.empty(n, dtype=np.int64)
    n_touched = np.zeros(1, dtype=np.int64)
    total_pairs = np.zeros(1, dtype=np.int64)
    n_kept = lib.sliding_prepare(
        _ptr64(users), _ptr64(items), n, f_max, k_max,
        1 if skip_cuts else 0, _ptr32(scratch.item_count),
        _ptr32(scratch.user_count), _ptr64(kept_users), _ptr64(kept_items),
        _ptr64(touched), _ptr64(n_touched), _ptr64(total_pairs))
    nt = int(n_touched[0])
    total = int(total_pairs[0])
    if total == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z
    # Ascending user-id group order — matches argsort(users) grouping.
    touched_sorted = np.sort(touched[:nt])
    assert touched_sorted.dtype == np.int64  # np.sort preserves int64
    grouped = np.empty(n_kept, dtype=np.int64)
    src = np.empty(total, dtype=np.int64)
    dst = np.empty(total, dtype=np.int64)
    lib.sliding_emit(
        _ptr64(kept_users), _ptr64(kept_items), n_kept,
        _ptr32(scratch.user_count), _ptr64(touched_sorted), nt,
        _ptr64(scratch.user_start), _ptr64(grouped), _ptr64(src),
        _ptr64(dst))
    return src, dst


def sliding_cut_mask(users: np.ndarray, items: np.ndarray, f_max: int,
                     k_max: int, scratch: SlidingScratch):
    """Native grouped-rank cut mask (one O(n) counting pass); None if the
    library is unavailable (callers fall back to argsort grouped_rank)."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(users)
    users = np.ascontiguousarray(users, dtype=np.int64)
    items = np.ascontiguousarray(items, dtype=np.int64)
    max_item = int(items.max())
    max_user = int(users.max())
    scratch._ensure(max_item, max_user)
    # Boundary dtype assert — see sliding_expand.
    assert (scratch.item_count.dtype == np.int32
            and scratch.user_count.dtype == np.int32)
    scratch.item_count[: max_item + 1].fill(0)
    scratch.user_count[: max_user + 1].fill(0)
    keep = np.empty(n, dtype=np.uint8)
    lib.sliding_cut_mask(
        _ptr64(users), _ptr64(items), n, f_max, k_max,
        _ptr32(scratch.item_count), _ptr32(scratch.user_count),
        keep.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return keep.view(np.bool_)


def grouped_rank_dense(keys: np.ndarray, max_key: int):
    """Native stable grouped rank for dense non-negative int64 keys.

    ``max_key`` is an inclusive bound on ``keys`` (callers track it —
    vocab size / user count); returns int64 ranks, or None when the
    native library is unavailable (callers fall back to the argsort
    form in sampling/item_cut.py).
    """
    lib = get_lib()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    scratch = np.zeros(max_key + 1, dtype=np.int32)
    out = np.empty(len(keys), dtype=np.int32)
    lib.grouped_rank_dense(_ptr64(keys), len(keys), _ptr32(scratch),
                           _ptr32(out))
    return out.astype(np.int64)
