// Native pair-expansion kernels for the reservoir sampler hot path.
//
// The reference has no native layer (SURVEY §2.6: 100% Java; fastutil +
// object reuse are its "fast path"), but its per-record emission loop
// (UserInteractionCounterOneInputStreamOperator.java:206-245) is the
// framework's host-side bottleneck once reservoirs are full: each
// replacement emits 4*(kMax-1) pair deltas. This kernel performs the
// sequential slot mutations and pair emission in C++ at memory speed;
// Python falls back to a NumPy loop when the shared library is missing.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libreservoir_expand.so
//        reservoir_expand.cpp   (see native/build.py)

#include <cstdint>

extern "C" {

// Expand replacement events into pair deltas.
//
// hist        [n_users_cap * k_max] row-major int32 reservoir storage (mutated!)
// users/items/slots [n_repl] replacement events in processing order
// out_src/out_dst/out_delta [n_repl * 4 * (k_max - 1)] preallocated outputs
//
// Emission order per event matches the vectorized spec: (item->others +1),
// (prev->others -1), (others->item +1), (others->prev -1), `others` being
// the k_max-1 slots excluding the replaced one, read *at event time*.
// Returns the number of emitted entries.
int64_t expand_replacements(
    int32_t* hist, int64_t k_max,
    const int64_t* users, const int64_t* items, const int64_t* slots,
    int64_t n_repl,
    int64_t* out_src, int64_t* out_dst, int32_t* out_delta) {
  int64_t pos = 0;
  const int64_t m = k_max - 1;
  for (int64_t e = 0; e < n_repl; ++e) {
    int32_t* row = hist + users[e] * k_max;
    const int64_t item = items[e];
    const int64_t slot = slots[e];
    const int64_t prev = row[slot];

    int64_t* src0 = out_src + pos;        // item -> others
    int64_t* dst0 = out_dst + pos;
    int32_t* del0 = out_delta + pos;
    int64_t* src1 = src0 + m;             // prev -> others
    int64_t* dst1 = dst0 + m;
    int32_t* del1 = del0 + m;
    int64_t* src2 = src1 + m;             // others -> item
    int64_t* dst2 = dst1 + m;
    int32_t* del2 = del1 + m;
    int64_t* src3 = src2 + m;             // others -> prev
    int64_t* dst3 = dst2 + m;
    int32_t* del3 = del2 + m;

    int64_t w = 0;
    for (int64_t i = 0; i < k_max; ++i) {
      if (i == slot) continue;
      const int64_t other = row[i];
      src0[w] = item;  dst0[w] = other; del0[w] = 1;
      src1[w] = prev;  dst1[w] = other; del1[w] = -1;
      src2[w] = other; dst2[w] = item;  del2[w] = 1;
      src3[w] = other; dst3[w] = prev;  del3[w] = -1;
      ++w;
    }
    row[slot] = static_cast<int32_t>(item);
    pos += 4 * m;
  }
  return pos;
}

// Expand append events into pair deltas (both directions).
//
// For append event e writing slot `slot_e`, partners are hist[u][0:slot_e]
// *after* all appends are written (equivalent to event-time state; see
// sampling/reservoir.py fact 1). Caller must have already written the new
// items into their slots. Returns entries written.
int64_t expand_appends(
    const int32_t* hist, int64_t hist_cols,
    const int64_t* users, const int64_t* items, const int64_t* slots,
    int64_t n_app,
    int64_t* out_src, int64_t* out_dst, int32_t* out_delta) {
  int64_t pos = 0;
  for (int64_t e = 0; e < n_app; ++e) {
    const int32_t* row = hist + users[e] * hist_cols;
    const int64_t item = items[e];
    const int64_t n = slots[e];  // number of partners
    int64_t* srcA = out_src + pos;
    int64_t* dstA = out_dst + pos;
    int32_t* delA = out_delta + pos;
    int64_t* srcB = srcA + n;
    int64_t* dstB = dstA + n;
    int32_t* delB = delA + n;
    for (int64_t i = 0; i < n; ++i) {
      const int64_t other = row[i];
      srcA[i] = item;  dstA[i] = other; delA[i] = 1;
      srcB[i] = other; dstB[i] = item;  delB[i] = 1;
    }
    pos += 2 * n;
  }
  return pos;
}

}  // extern "C"
