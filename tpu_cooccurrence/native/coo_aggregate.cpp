// Per-window COO cell aggregation: fold duplicate packed (src, dst)
// keys and sum their deltas, returning the unique cells sorted by key.
//
// Why native: the NumPy path (ops/aggregate.aggregate_window_coo) is
// np.unique — an indirect argsort over every raw pair delta plus a
// bincount over the inverse, ~40% of the dense carrier's host floor at
// the calibrated ML-25M workload (435M pair deltas across 503
// windows). One std::sort over (key, delta) records followed by an
// in-place fold is both cache-friendlier (16-byte records, no
// permutation gather) and sorts each record once.
//
// In-place contract: the caller passes COPIES of the packed key array
// and an int64 delta array; both are overwritten, the fold's results
// occupying the first `return value` entries sorted ascending by key.
// Exactness matches the NumPy path: deltas are small ints, int64
// summation is exact (the NumPy path's float64 bincount is exact below
// 2^53 the same way).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {
struct Cell {
  int64_t key;
  int64_t delta;
};
}  // namespace

extern "C" {

int64_t coo_aggregate(int64_t* keys, int64_t* deltas, int64_t n) {
  if (n <= 0) return 0;
  std::vector<Cell> cells;
  cells.reserve((size_t)n);
  for (int64_t i = 0; i < n; ++i) cells.push_back({keys[i], deltas[i]});
  std::sort(cells.begin(), cells.end(),
            [](const Cell& a, const Cell& b) { return a.key < b.key; });
  int64_t m = 0;
  keys[0] = cells[0].key;
  deltas[0] = cells[0].delta;
  for (int64_t i = 1; i < n; ++i) {
    if (cells[i].key == keys[m]) {
      deltas[m] += cells[i].delta;
    } else {
      ++m;
      keys[m] = cells[i].key;
      deltas[m] = cells[i].delta;
    }
  }
  return m + 1;
}

}  // extern "C"
