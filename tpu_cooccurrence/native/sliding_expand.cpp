// Native sliding-window basket expansion (host hot path, config 3 shape).
//
// The NumPy sliding path (sampling/sliding.py) is dominated by
// comparison sorts: argsort(users) for grouping plus two grouped_rank
// argsorts for the per-window cuts — O(n log n) each, ~60% of host time
// at the ML-25M shape. Ids here are dense vocab ids, so every one of
// those sorts is a counting pass in C: this kernel applies both cuts,
// groups kept events by user (stable, arrival order), and emits all
// ordered distinct-position basket pairs in O(n + pairs) with no
// temporaries beyond the caller's dense scratch arrays.
//
// Two-call protocol (the caller cannot size the pair output up front):
//   1) sliding_prepare: cuts + kept compaction + per-user kept counts +
//      touched-user list; returns n_kept and writes total_pairs.
//   2) sliding_emit: counting-sort scatter into grouped order + pair
//      emission. Emission order matches the NumPy path exactly: events
//      in (user-stable, arrival) order, partners by ascending basket
//      position with the event's own position skipped.
//
// Scratch ownership: Python owns and zeroes the dense arrays between
// windows (item_count/user_count sized to the window's max id + 1).
//
// The reference has no sliding mode at all (FlinkCooccurrences.java:
// 139,153 wires tumbling only); this supports the framework's sliding
// extension (benchmark config 3).
//
// Build: via native/__init__.py (g++ -O3 -shared -fPIC).

#include <cstdint>

extern "C" {

// Phase 1: cuts + compaction. All counts are per-window ranks over ALL
// arrivals (kept or not) — grouped_rank semantics (item_cut.py:20).
//
// users/items [n]: dense ids, arrival order.
// item_count [max_item+1], user_count [max_user+1]: zeroed by caller;
//   on return user_count[u] holds u's KEPT count (reused by phase 2).
// kept_users/kept_items [n]: compacted kept events (arrival order).
// touched [n]: unique kept users in first-kept order; *n_touched set.
// *total_pairs: sum over users of m*(m-1).
// Returns n_kept.
int64_t sliding_prepare(
    const int64_t* users, const int64_t* items, int64_t n,
    int64_t f_max, int64_t k_max, int32_t skip_cuts,
    int32_t* item_count, int32_t* user_count,
    int64_t* kept_users, int64_t* kept_items,
    int64_t* touched, int64_t* n_touched, int64_t* total_pairs) {
  int64_t w = 0;
  if (skip_cuts) {
    for (int64_t e = 0; e < n; ++e) {
      kept_users[w] = users[e];
      kept_items[w] = items[e];
      ++w;
    }
  } else {
    // Arrival ranks count every event; the keep test uses the pre-
    // increment rank, exactly like grouped_rank(x) < cap.
    for (int64_t e = 0; e < n; ++e) {
      const int64_t u = users[e];
      const int64_t it = items[e];
      const int32_t ir = item_count[it]++;
      const int32_t ur = user_count[u]++;
      if (ir < f_max && ur < k_max) {
        kept_users[w] = u;
        kept_items[w] = it;
        ++w;
      }
    }
    // user_count now holds arrival counts; rebuild it as KEPT counts for
    // phase 2 (zero only touched entries, then recount over kept).
    for (int64_t e = 0; e < n; ++e) user_count[users[e]] = 0;
  }
  int64_t nt = 0;
  for (int64_t e = 0; e < w; ++e) {
    const int64_t u = kept_users[e];
    if (user_count[u]++ == 0) touched[nt++] = u;
  }
  int64_t pairs = 0;
  for (int64_t t = 0; t < nt; ++t) {
    const int64_t m = user_count[touched[t]];
    pairs += m * (m - 1);
  }
  *n_touched = nt;
  *total_pairs = pairs;
  return w;
}

// Phase 2: group + emit. Consumes phase 1's outputs unchanged
// (user_count = kept counts, touched list) plus:
//   user_start [max_user+1]: scratch, overwritten (no zeroing needed —
//     only touched entries are read/written);
//   grouped [n_kept]: scratch for the counting-sort scatter;
//   out_src/out_dst [total_pairs]: pair outputs.
void sliding_emit(
    const int64_t* kept_users, const int64_t* kept_items, int64_t n_kept,
    const int32_t* user_count, const int64_t* touched, int64_t n_touched,
    int64_t* user_start, int64_t* grouped,
    int64_t* out_src, int64_t* out_dst) {
  // Prefix offsets in touched (first-kept) order — any fixed order works
  // for grouping; pair order below depends only on within-group order.
  int64_t off = 0;
  for (int64_t t = 0; t < n_touched; ++t) {
    const int64_t u = touched[t];
    user_start[u] = off;
    off += user_count[u];
  }
  // Stable counting-sort scatter (arrival order within each group).
  // user_start[u] ends at u's group END; group starts are recomputed
  // from the counts during emission.
  for (int64_t e = 0; e < n_kept; ++e) {
    grouped[user_start[kept_users[e]]++] = kept_items[e];
  }
  int64_t p = 0;
  for (int64_t t = 0; t < n_touched; ++t) {
    const int64_t u = touched[t];
    const int64_t m = user_count[u];
    const int64_t* g = grouped + (user_start[u] - m);
    for (int64_t o = 0; o < m; ++o) {
      const int64_t self = g[o];
      for (int64_t q = 0; q < m; ++q) {
        if (q == o) continue;
        out_src[p] = self;
        out_dst[p] = g[q];
        ++p;
      }
    }
  }
}

}  // extern "C"

// Cut mask only (no grouping/emission): keep[e] = both pre-increment
// ranks under their caps; both counters advance on EVERY event
// (grouped_rank semantics — deliberately no short-circuit).
// Used by the partitioned sliding sampler, whose cuts run replicated
// while expansion is split by user.
extern "C" void sliding_cut_mask(
    const int64_t* users, const int64_t* items, int64_t n,
    int64_t f_max, int64_t k_max,
    int32_t* item_count, int32_t* user_count, uint8_t* keep) {
  for (int64_t e = 0; e < n; ++e) {
    const int32_t ir = item_count[items[e]]++;
    const int32_t ur = user_count[users[e]]++;
    keep[e] = (ir < f_max) & (ur < k_max);
  }
}
