// Stable grouped rank over dense non-negative keys: out[i] = number of
// earlier events with the same key. One O(n) pass with an O(max_key)
// counter scratch — replaces a stable argsort + segment scan (the numpy
// fallback), which showed up as the sampler's largest remaining host
// cost once pair expansion went native.

#include <cstdint>

extern "C" {

// scratch: int32[scratch_len], zeroed by the caller; keys[i] < scratch_len.
void grouped_rank_dense(const int64_t* keys, int64_t n, int32_t* scratch,
                        int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = scratch[keys[i]]++;
  }
}

}  // extern "C"
