"""Read replicas: horizontal query scaling over the delta log (ISSUE 13).

One process used to both ingest and serve (PR 8): query throughput was
capped by the TPU job's host thread and died with it. This module splits
the planes. The ingest job keeps its ``SnapshotBuilder`` and — under
``--checkpoint-incremental`` — already emits every generation's changed
top-K rows into the committed, corruption-gated delta log
(``state/delta.py``, PR 12). A **read replica** is a stateless process
that

1. **bootstraps** from the newest verifying checkpoint generation's
   results table (``state/checkpoint.load_serving_state`` — a READ-ONLY
   walk: a replica shares the directory with the live writer and must
   never quarantine or rename its files),
2. **tails** ``state/delta.read_delta_stream(dir, start_gen=G)`` and
   replays each :meth:`~tpu_cooccurrence.state.delta.DeltaGeneration.
   iter_topk` record into its own immutable
   :class:`~tpu_cooccurrence.serving.snapshot.TopKSnapshot` via the
   existing builder/publish machinery — the same zero-lock
   double-buffered swap the ingest job uses, and
3. **serves** ``/recommend`` (plus ``/metrics`` and ``/healthz``) from
   it, each response tagged with the *delta-log generation* the
   snapshot was replayed to — a front tier compares tags across the
   fleet to enforce read-your-window consistency (the ``min_gen``
   query-param gate in ``observability/http.py`` answers 503 when this
   replica lags the client's last-seen generation).

Reads now scale with replicas, not with the TPU job: N replicas tail
the same log with no writer involvement, and a dead replica relaunches
(``robustness/gang.ReplicaFleetSupervisor`` — the serving gang's
*independent-restart* policy: replicas hold no collectives, so peer
death never invalidates the survivors) and re-syncs from checkpoint +
delta tail by itself.

**Corruption fallback.** ``DeltaCorrupt`` mid-tail triggers a
checkpoint **resync** — drop the whole in-memory table and bootstrap
again from the newest verifying generation — exactly like restore
falls back a generation on a torn npz. The writer may legitimately
compact/retire deltas out from under a lagging replica
(``--checkpoint-retain``); a missing chain link is the same resync,
not an error loop.

**Dense-id discipline.** The replica reconstructs the WRITER's dense
id space: the bootstrap restores the checkpointed vocab and every delta
appends its ``voc_items`` / ``voc_users`` slices in writer order (IdMap
is append-only, so the append list *is* the id assignment). Every
external id a delta references must already be mapped — a mapping that
would grow the vocab is a torn or foreign record and raises
:class:`~tpu_cooccurrence.state.delta.DeltaCorrupt` (-> resync).

The replica never imports jax: it is a pure host process (numpy +
stdlib HTTP), so a fleet colocates with anything.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import List, Optional

import numpy as np

from .. import tuning
from ..observability.http import MetricsServer
from ..observability.registry import REGISTRY
from ..state import checkpoint as ckpt
from ..state import delta as deltalog
from ..state.delta import DeltaCorrupt, _range_indices
from ..state.results import TopKBatch
from ..state.vocab import IdMap
from .recommend import ServingPlane

LOG = logging.getLogger("tpu_cooccurrence.replica")

#: Gauge names (CANONICAL_METRICS): the replica's delta-log position,
#: its lag behind the writer, and the robustness counters.
GENERATION_GAUGE = "cooc_replica_generation"
LAG_GAUGE = "cooc_replica_generation_lag"
APPLIED_GAUGE = "cooc_replica_deltas_applied_total"
RESYNC_GAUGE = "cooc_replica_resyncs_total"


class ReadReplica:
    """Bootstrap + tail + publish: one replica's whole state machine.

    Duck-types the :class:`~tpu_cooccurrence.serving.recommend.
    ServingPlane` surface ``MetricsServer`` consumes (``query`` /
    ``generation`` / ``rows`` / ``snapshot_age_seconds`` /
    ``query_slo_s``), delegating to the current plane — a resync swaps
    in a freshly built plane while in-flight queries finish on the old
    one (still a valid, internally consistent older generation).

    Thread contract: :meth:`bootstrap` / :meth:`poll` / :meth:`resync`
    run on the single tail thread; queries run on any number of HTTP
    threads against the published immutable snapshot (the PR-8
    contract, unchanged).
    """

    def __init__(self, state_dir: str, suffix: str = "",
                 history_len: int = 50, query_slo_s: float = 0.0,
                 journal: Optional[str] = None,
                 process_id: int = 0) -> None:
        self.state_dir = state_dir
        self.suffix = suffix
        self.history_len = history_len
        self.query_slo_s = query_slo_s
        # Tracing correlation (observability/journal.py): a fleet child
        # inherits the supervisor's run id + its slot's relaunch
        # ordinal; a standalone replica mints its own. Launch the
        # writer and a standalone replica with the same TPU_COOC_RUN_ID
        # (or --run-id on the writer) to join them in one trace;
        # cooc-trace also joins across run ids on the shared state
        # dir's generation stream.
        from ..observability.journal import run_context
        self.run_id, self.attempt = run_context()
        self.process_id = int(process_id)
        #: Delta-log generation the published snapshot is replayed to.
        self.generation = -1
        self.bootstrap_generation = -1
        self.deltas_applied = 0
        self.resyncs = 0
        self.last_poll_unix = 0.0
        self.item_vocab = IdMap()
        self.user_vocab = IdMap()
        self.plane = ServingPlane(self.item_vocab, self.user_vocab,
                                  history_len=history_len,
                                  query_slo_s=query_slo_s)
        self.journal = None
        if journal:
            from ..observability.journal import RunJournal

            self.journal = RunJournal(journal)
        self._gauge_gen = REGISTRY.gauge(
            GENERATION_GAUGE,
            help="delta-log generation this replica has replayed to")
        self._gauge_lag = REGISTRY.gauge(
            LAG_GAUGE,
            help="ingest generation minus replica generation (newest "
                 "on-disk checkpoint generation not yet replayed)")
        self._gauge_applied = REGISTRY.gauge(
            APPLIED_GAUGE,
            help="delta generations this replica has replayed")
        self._gauge_resyncs = REGISTRY.gauge(
            RESYNC_GAUGE,
            help="checkpoint resyncs (DeltaCorrupt / broken-chain "
                 "fallbacks) this replica has performed")

    # -- ServingPlane duck surface (MetricsServer reads these) ----------

    def query(self, user, n):
        return self.plane.query(user, n)

    @property
    def rows(self) -> int:
        return self.plane.rows

    def snapshot_age_seconds(self) -> float:
        return self.plane.snapshot_age_seconds()

    # -- bootstrap / resync ---------------------------------------------

    def bootstrap(self) -> int:
        """(Re)build the whole serving table from the newest verifying
        checkpoint generation; returns the generation bootstrapped to.

        Builds into FRESH vocab/plane objects and swaps them in only
        once complete, so queries never see a half-built table.
        """
        st = ckpt.load_serving_state(self.state_dir, self.suffix)
        item_vocab = IdMap()
        item_vocab.restore_state(st["item_vocab"])
        user_vocab = IdMap()
        user_vocab.restore_state(st["user_vocab"])
        plane = ServingPlane(item_vocab, user_vocab,
                             history_len=self.history_len,
                             query_slo_s=self.query_slo_s)
        items, offsets, others, scores = st["latest"]
        batch = self._pack_external(item_vocab, items,
                                    np.diff(np.asarray(offsets,
                                                       dtype=np.int64)),
                                    others, scores)
        if len(batch):
            plane.absorb(batch)
        if "hist" in st:
            hist = st["hist"]
            hlen = st["hist_len"]
            users = np.flatnonzero(hlen > 0)
            if len(users):
                k = hist.shape[1]
                sel = _range_indices(users * k, users * k + hlen[users])
                plane.history.set_rows(users, hlen[users],
                                       hist.reshape(-1)[sel])
        plane.publish(generation=st["gen"])
        # Swap the built world in (each assignment GIL-atomic; queries
        # route through self.plane, taken once per query).
        self.item_vocab = item_vocab
        self.user_vocab = user_vocab
        self.plane = plane
        self.generation = st["gen"]
        self.bootstrap_generation = st["gen"]
        self._gauge_gen.set(st["gen"])
        self._refresh_lag()
        LOG.info("replica bootstrapped at generation %d (%d rows)",
                 st["gen"], plane.rows)
        return st["gen"]

    def resync(self, reason: str) -> bool:
        """Checkpoint resync — the DeltaCorrupt / broken-chain
        fallback, exactly like restore's step-back: drop the in-memory
        table, bootstrap again from the newest verifying generation."""
        self.resyncs += 1
        self._gauge_resyncs.set(self.resyncs)
        LOG.warning("replica resync #%d from checkpoint (%s)",
                    self.resyncs, reason)
        return self._try_bootstrap("resync")

    def _try_bootstrap(self, reason: str) -> bool:
        """A MID-SERVICE re-bootstrap that tolerates a transiently
        unrestorable directory: the live writer's retention may delete
        every generation this replica just listed (the race window is
        real on small ``--checkpoint-retain``). Keep serving the
        current snapshot — older but internally consistent — and retry
        on the next poll; only the STARTUP bootstrap (which has nothing
        to serve yet) treats this as fatal, under its own deadline."""
        try:
            self.bootstrap()
            return True
        except (FileNotFoundError, ckpt.CheckpointCorrupt) as exc:
            LOG.warning("re-bootstrap (%s) found no restorable "
                        "generation (%s); keeping the current snapshot "
                        "and retrying next poll", reason, exc)
            return False

    # -- the tail loop ---------------------------------------------------

    def poll(self) -> int:
        """Consume every committed delta generation past the current
        position; returns how many were applied. ``DeltaCorrupt``
        anywhere in the tail drives :meth:`resync`."""
        applied = 0
        # One directory listing per poll pass: lag is reported against
        # this snapshot of the writer's position (catch-up replay must
        # not re-list a live writer's directory 2x per generation).
        newest = self.newest_available()
        try:
            for d in deltalog.read_delta_stream(
                    self.state_dir, self.suffix,
                    start_gen=self.generation):
                if d.prev != self.generation:
                    # A chain gap: the writer wrote a FULL generation
                    # (compaction, dirty-log overflow) or retired the
                    # chain past a lagging replica — the skipped
                    # generation's changes live in no delta, so the
                    # only sound catch-up is a fresh bootstrap from
                    # the newest checkpoint (which lands at or beyond
                    # every delta on disk). Not a corruption resync.
                    LOG.info("delta generation %d chains from %d but "
                             "replica is at %d (full generation "
                             "interposed); re-bootstrapping",
                             d.gen, d.prev, self.generation)
                    if self._try_bootstrap("chain gap"):
                        applied += 1
                    break
                self._apply(d, newest=newest)
                applied += 1
        except DeltaCorrupt as exc:
            if self.resync(str(exc)):
                applied += 1
        if applied == 0:
            if newest > self.generation and not any(
                    g > self.generation for g in
                    deltalog.delta_generations(self.state_dir,
                                               self.suffix)):
                # FULL generation(s) interposed with nothing to tail: a
                # compaction (or dirty-log overflow) committed a base
                # and no delta has landed since — the log alone can
                # never carry the replica past it. Same re-bootstrap as
                # the in-stream gap. (A delta file > our position with
                # no npz yet is an uncommitted orphan: wait for the
                # writer's commit instead.)
                LOG.info("newest generation %d is a full base past the "
                         "replica's %d with no delta to tail; "
                         "re-bootstrapping", newest, self.generation)
                if self._try_bootstrap("trailing full base"):
                    applied += 1
        self.last_poll_unix = time.time()
        self._refresh_lag()
        return applied

    def newest_available(self) -> int:
        """Newest on-disk generation (committed npz), or -1 — the
        writer-side position the lag gauge measures against."""
        gens = ckpt.generations(self.state_dir, self.suffix)
        return gens[0][0] if gens else -1

    def lag(self, newest: Optional[int] = None) -> int:
        if newest is None:
            newest = self.newest_available()
        return max(newest - self.generation, 0)

    def _refresh_lag(self, newest: Optional[int] = None) -> None:
        self._gauge_lag.set(self.lag(newest))

    # -- one delta generation -------------------------------------------

    @staticmethod
    def _pack_external(vocab: IdMap, items_ext, lens, others_ext,
                       scores) -> TopKBatch:
        """External-id row-major top-K records -> one padded dense-id
        :class:`TopKBatch` (scores already descending per row; pads are
        ``-inf`` so the snapshot's finite-prefix lens stay exact).

        Every id must ALREADY be mapped: a lookup that would grow the
        vocab means the record references items outside the replayed
        append chain — a torn or foreign record, so
        :class:`DeltaCorrupt` (-> checkpoint resync), never a silent
        dense-space divergence."""
        lens = np.asarray(lens, dtype=np.int64)
        n = len(lens)
        if n == 0:
            return TopKBatch.empty(1)
        n0 = len(vocab)
        rows = vocab.map_batch(
            np.asarray(items_ext, dtype=np.int64)).astype(np.int32)
        others = vocab.map_batch(np.asarray(others_ext, dtype=np.int64))
        if len(vocab) != n0:
            raise DeltaCorrupt(
                f"top-K records reference {len(vocab) - n0} item ids "
                f"outside the replayed vocab chain")
        k = max(int(lens.max()), 1)
        idx = np.zeros((n, k), dtype=np.int32)
        vals = np.full((n, k), -np.inf, dtype=np.float32)
        pos = np.repeat(np.arange(n, dtype=np.int64), lens)
        col = _range_indices(np.zeros(n, dtype=np.int64), lens)
        idx[pos, col] = others.astype(np.int32)
        vals[pos, col] = np.asarray(scores, dtype=np.float32)
        return TopKBatch(rows, idx, vals)

    def _apply(self, d, newest: Optional[int] = None) -> None:
        """Replay one committed delta generation: vocab appends, top-K
        rows, reservoir history — then publish tagged with the log
        position. ``newest``: the caller's per-poll snapshot of the
        writer's newest generation (lag reporting without re-listing
        the shared directory per generation)."""
        # Vocab appends must extend the replica's chain exactly (the
        # same contract ChainState.replay enforces on restore).
        if len(self.item_vocab) + len(d.voc_items) != d.item_vocab_len:
            raise DeltaCorrupt(
                f"delta generation {d.gen} item-vocab appends do not "
                f"extend the replica ({len(self.item_vocab)} + "
                f"{len(d.voc_items)} != {d.item_vocab_len})")
        if len(self.user_vocab) + len(d.voc_users) != d.user_vocab_len:
            raise DeltaCorrupt(
                f"delta generation {d.gen} user-vocab appends do not "
                f"extend the replica")
        t0 = time.perf_counter()
        if len(d.voc_items):
            self.item_vocab.map_batch(d.voc_items)
        if len(d.voc_users):
            self.user_vocab.map_batch(d.voc_users)
        topk_rows = 0
        if len(d.lat_rows):
            batch = self._pack_external(self.item_vocab, d.lat_rows,
                                        d.lat_lens, d.lat_others,
                                        d.lat_scores)
            self.plane.absorb(batch)
            topk_rows = len(batch)
        if len(d.usr_rows):
            self.plane.history.set_rows(d.usr_rows, d.usr_lens,
                                        d.usr_hist)
        apply_s = time.perf_counter() - t0
        self.plane.publish(generation=d.gen)
        publish_s = time.perf_counter() - t0 - apply_s
        self.generation = d.gen
        self.deltas_applied += 1
        self._gauge_gen.set(d.gen)
        self._gauge_applied.set(self.deltas_applied)
        self._refresh_lag(newest)
        if self.journal is not None:
            from ..observability.journal import VERSION

            self.journal.record({
                "v": VERSION, "replica": d.gen,
                "rows": self.plane.rows, "topk_rows": topk_rows,
                "lag": self.lag(newest), "resyncs": self.resyncs,
                "wall_unix": round(time.time(), 3),
                # Tracing plane: the window's lifetime across the
                # process boundary — the uniform generation join key
                # plus the replay's own delta-apply -> publish span
                # pair (journal.REPLICA_SPAN_STAGES).
                "generation": d.gen,
                "run_id": self.run_id,
                "process_id": self.process_id,
                "attempt": self.attempt,
                "spans": [["delta-apply", 0.0, round(apply_s, 9)],
                          ["publish", round(apply_s, 9),
                           round(publish_s, 9)]],
            })

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()


class ReplicaServer(MetricsServer):
    """The replica's HTTP plane: the same three routes as the job's
    server (``/metrics``, ``/healthz``, ``/recommend`` — one
    ``ROUTE_METRICS`` table, one latency histogram per route), with a
    replica-specific ``/healthz``: the lag block (generation /
    newest-on-disk / lag / resyncs) plus tail-loop liveness — a replica
    whose poll loop wedged reports ``replica_stale`` and 503 so a front
    tier drains it, exactly like the job's ``snapshot_stale``.

    ``/recommend`` responses carry the ``generation`` tag through the
    inherited route body (pinned by the cooclint ``replica-generation-
    tag`` rule) — the read-your-window token.
    """

    def __init__(self, registry, replica: ReadReplica, port: int = 0,
                 host: str = "127.0.0.1",
                 stale_after_s: float = 300.0, peers=None) -> None:
        super().__init__(registry, counters=None, ledger=None,
                         port=port, host=host,
                         stale_after_s=stale_after_s,
                         serving=replica, peers=peers)
        self.replica = replica

    def health(self) -> "tuple[dict, bool]":
        now = time.time()
        r = self.replica
        poll_age = now - (r.last_poll_unix or self._started_unix)
        status = "ok"
        if r.generation < 0:
            status = "starting"
        elif self.stale_after_s > 0 and poll_age > self.stale_after_s:
            # The tail loop stopped polling: this replica's table will
            # only age — drain it (the writer may be fine; siblings
            # keep serving).
            status = "replica_stale"
        payload = {
            "status": status,
            "replica": {
                "generation": r.generation,
                "newest_generation": r.newest_available(),
                "lag": r.lag(),
                "bootstrap_generation": r.bootstrap_generation,
                "deltas_applied": r.deltas_applied,
                "resyncs": r.resyncs,
                "last_poll_age_seconds": round(poll_age, 3),
            },
            "snapshot_generation": r.generation,
            "snapshot_rows": r.rows,
            "snapshot_age_seconds": round(r.snapshot_age_seconds(), 3),
        }
        if self.peers is not None:
            rows, any_stale = self.peers.snapshot()
            payload["peers"] = rows
            if any_stale and status == "ok":
                status = payload["status"] = "peer_stale"
        return payload, status in ("ok", "starting")


# -- the cooc-replica entry point ---------------------------------------


def _write_port_file(path: str, port: int) -> None:
    """Atomic ``{"port", "pid", "url"}`` drop the fleet supervisor /
    bench / load balancer reads to find this replica."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"port": port, "pid": os.getpid(),
                   "url": f"http://127.0.0.1:{port}"}, f)
    os.replace(tmp, path)


def _parse_args(argv: Optional[List[str]]) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="cooc-replica",
        description="Stateless read replica: bootstrap from the newest "
                    "checkpoint, tail the delta log, serve /recommend",
        allow_abbrev=False)
    p.add_argument("--state-dir", required=True, dest="state_dir",
                   help="The ingest job's --checkpoint-dir (the replica "
                        "reads checkpoints + delta log; never writes)")
    p.add_argument("--port", type=int, default=0,
                   help="Serve /recommend, /metrics and /healthz on "
                        "127.0.0.1:PORT (0 = ephemeral)")
    p.add_argument("--port-file", default=None, dest="port_file",
                   help="Write the bound port + pid here as JSON "
                        "(fleet/LB discovery)")
    p.add_argument("--poll-interval-s", type=float, default=0.5,
                   dest="poll_interval_s",
                   help="Delta-log tail poll interval (default: 0.5)")
    p.add_argument("--run-seconds", type=float, default=0.0,
                   dest="run_seconds",
                   help="Exit cleanly after this many seconds "
                        "(0 = serve until killed)")
    p.add_argument("--serve-history", type=int, default=50,
                   dest="serve_history",
                   help="Per-user history ring length for the blend, "
                        "replayed from the delta log's reservoir "
                        "records (default: 50)")
    p.add_argument("--journal", default=None,
                   help="Append one replica record per replayed delta "
                        "generation to this JSONL")
    p.add_argument("--stale-after-s", type=float, default=300.0,
                   dest="stale_after_s",
                   help="/healthz reports 503 (replica_stale) once the "
                        "tail loop has not polled for this many "
                        "seconds (default: 300; 0 = off)")
    p.add_argument("--bootstrap-timeout-s", type=float, default=60.0,
                   dest="bootstrap_timeout_s",
                   help="How long to wait for the writer's first "
                        "checkpoint generation before giving up "
                        "(default: 60)")
    p.add_argument("--process-id", type=int, default=None,
                   dest="process_id",
                   help="Fleet slot id (heartbeat file suffix under "
                        "the supervisor's gang dir)")
    p.add_argument("--fleet", type=int, default=0,
                   help="Run N replicas under the serving-gang "
                        "supervisor (independent restart: a dead "
                        "replica relaunches alone and re-syncs itself)")
    p.add_argument("--fleet-dir", default=None, dest="fleet_dir",
                   help="Directory for the fleet's port files and "
                        "heartbeats (default: <state-dir>/fleet)")
    p.add_argument("--restart-on-failure", type=int, default=3,
                   dest="restart_on_failure",
                   help="Fleet restart budget across all replicas "
                        "(default: 3)")
    p.add_argument("--gang-stale-after-s", type=float, default=60.0,
                   dest="gang_stale_after_s",
                   help="Fleet supervisor: heartbeat age past which a "
                        "replica counts as wedged and is relaunched "
                        "(default: 60; 0 = off)")
    return p.parse_args(argv)


def _fleet_child_argv(raw: List[str], fleet_dir: str,
                      pid: int) -> List[str]:
    """One fleet slot's argv: the supervisor's own flags stripped, the
    slot identity + per-slot port file appended, and per-process output
    paths (``--journal``) suffixed ``.p<i>`` — two replicas appending
    to one journal would interleave their record streams (same rule as
    the gang supervisor's ``_PER_PROCESS_FLAGS``)."""
    strip_with_value = {"--fleet", "--fleet-dir", "--restart-on-failure",
                        "--gang-stale-after-s", "--port", "--port-file",
                        "--process-id"}
    out: List[str] = []
    skip = False
    suffix_next = False
    for a in raw:
        if skip:
            skip = False
            continue
        if suffix_next:
            a = f"{a}.p{pid}"
            suffix_next = False
        else:
            flag = a.split("=", 1)[0]
            if flag in strip_with_value:
                skip = "=" not in a
                continue
            if a == "--journal":
                suffix_next = True
            elif a.startswith("--journal="):
                a = f"{a}.p{pid}"
        out.append(a)
    out += ["--process-id", str(pid), "--port", "0",
            "--port-file", os.path.join(fleet_dir,
                                        f"replica.p{pid}.port")]
    return out


def _run_fleet(args, raw: List[str]) -> int:
    import signal

    from ..robustness.gang import ReplicaFleetSupervisor

    fleet_dir = args.fleet_dir or os.path.join(args.state_dir, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    LOG.info("replica fleet: %d replicas over %s (port files in %s)",
             args.fleet, args.state_dir, fleet_dir)

    def child_argv(pid: int) -> List[str]:
        return [sys.executable, "-m", "tpu_cooccurrence.serving.replica"
                ] + _fleet_child_argv(raw, fleet_dir, pid)

    fleet = ReplicaFleetSupervisor(
        child_argv, args.fleet, gang_dir=fleet_dir,
        attempts=args.restart_on_failure,
        stale_after_s=args.gang_stale_after_s)
    # A SIGTERM to the supervisor must tear the whole fleet down (the
    # run loop's finally kills the workers) — the default handler would
    # die between poll cycles and orphan every replica child.
    signal.signal(signal.SIGTERM, lambda *_a: fleet.stop())
    return fleet.run()


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(asctime)s %(levelname)s %(name)s - %(message)s")
    raw = list(argv) if argv is not None else sys.argv[1:]
    try:
        args = _parse_args(raw)
        if args.fleet < 0 or args.serve_history < 1 \
                or args.poll_interval_s <= 0:
            raise ValueError("--fleet must be >= 0, --serve-history "
                             ">= 1, --poll-interval-s > 0")
    except ValueError as exc:
        from ..supervisor import EX_CONFIG

        LOG.error("configuration error: %s", exc)
        return EX_CONFIG
    if args.fleet:
        return _run_fleet(args, raw)

    # Fleet worker heartbeat (same beacon as gang workers): armed by the
    # supervisor's gang-dir env + this slot's id.
    from ..robustness.gang import GANG_DIR_ENV, HeartbeatWriter

    heartbeat = None
    gang_dir = tuning.env_read(GANG_DIR_ENV)
    if gang_dir and args.process_id is not None:
        heartbeat = HeartbeatWriter(gang_dir, args.process_id).start()

    replica = ReadReplica(args.state_dir,
                          history_len=args.serve_history,
                          journal=args.journal,
                          process_id=args.process_id or 0)
    deadline = time.monotonic() + args.bootstrap_timeout_s
    while True:
        try:
            replica.bootstrap()
            break
        except FileNotFoundError:
            if time.monotonic() > deadline:
                LOG.error("no checkpoint appeared in %s within "
                          "--bootstrap-timeout-s", args.state_dir)
                return 1
            time.sleep(min(args.poll_interval_s, 1.0))
        except ckpt.CheckpointCorrupt as exc:
            if time.monotonic() > deadline:
                LOG.error("no checkpoint generation verifies: %s", exc)
                return 1
            time.sleep(min(args.poll_interval_s, 1.0))
    server = ReplicaServer(REGISTRY, replica, port=args.port,
                           stale_after_s=args.stale_after_s).start()
    if args.port_file:
        _write_port_file(args.port_file, server.port)
    LOG.info("replica serving on http://127.0.0.1:%d at generation %d",
             server.port, replica.generation)
    stop_at = (time.monotonic() + args.run_seconds
               if args.run_seconds > 0 else None)
    try:
        while stop_at is None or time.monotonic() < stop_at:
            replica.poll()
            time.sleep(args.poll_interval_s)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        replica.close()
        if heartbeat is not None:
            heartbeat.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
