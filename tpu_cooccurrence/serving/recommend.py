"""The blend step the reference leaves downstream: history x top-K rows.

The paper's lineage ends at the per-item indicator matrix and explicitly
leaves "multiply the user's recent history against it" to a downstream
consumer (PAPER.md §0). This module is that consumer, in-process:

* :class:`UserHistory` — a bounded per-user ring buffer of recently seen
  items, fed from the ingest stream (dense-id space, vectorized per
  batch; single writer = the ingest thread).
* :class:`ServingPlane` — composes the history, the snapshot double
  buffer (:mod:`.snapshot`) and the blend itself. ``query`` scores
  ``sum over h in history of cooccurrence_row(h)``, filters items the
  user already saw, and partial-sorts the top N; anonymous or cold-start
  users fall back to the snapshot's popularity ladder.

**Hot-path contract** (asserted by test instrumentation in
``tests/test_serving.py``): ``query`` acquires no lock — the snapshot is
immutable, the history is single-writer with benign-staleness reads —
and allocates no table-sized scratch: accumulation buffers are
preallocated per thread (:class:`_Scratch`, ``threading.local``) and
grown only when the vocabulary grows; the only per-query allocations are
O(touched-candidates) result arrays (hundreds of elements at most,
``top_n <= history x K``). ``SCRATCH_ALLOCATIONS`` counts every scratch
(re)allocation so tests can pin the steady state at zero.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .snapshot import SnapshotBuilder, TopKSnapshot

#: Scratch-buffer (re)allocations across all threads — test
#: instrumentation for the "no per-query table allocation" contract
#: (reads/writes are GIL-atomic increments; precision under races is
#: irrelevant because the pinned steady-state value is *zero deltas*).
SCRATCH_ALLOCATIONS = 0

#: Cap on ``n`` per query (the partial-sort budget; requests above it
#: are clamped, not errored — a load balancer probing ?n=1e9 must not
#: turn into an O(vocab) sort).
MAX_N = 1000


class UserHistory:
    """Bounded per-user ring of recently seen items (dense-id space).

    Single writer (the ingest thread, via :meth:`extend`); query threads
    read with :meth:`recent` into caller scratch. Reads are lock-free:
    growth swaps in new arrays (readers finish on the old ones), and a
    concurrent write can at worst surface a slightly stale or mixed
    window of history — acceptable staleness for a recommender, never a
    torn structure.
    """

    def __init__(self, length: int = 50, capacity_hint: int = 1024) -> None:
        if length < 1:
            raise ValueError(f"history length must be >= 1, got {length}")
        self.length = length
        cap = max(int(capacity_hint), 64)
        self._items = np.zeros((cap, length), dtype=np.int32)
        self._count = np.zeros(cap, dtype=np.int64)

    def _ensure(self, n: int) -> None:
        if n <= len(self._count):
            return
        cap = len(self._count)
        while cap < n:
            cap *= 2
        grown = np.zeros((cap, self.length), dtype=np.int32)
        grown[: len(self._items)] = self._items
        grown_c = np.zeros(cap, dtype=np.int64)
        grown_c[: len(self._count)] = self._count
        # Publish rows before counts: a reader pairing a new count with
        # the old (shorter) item array would index past it.
        self._items = grown
        self._count = grown_c

    def extend(self, dense_users: np.ndarray,
               dense_items: np.ndarray) -> None:
        """Append one ingest batch (vectorized; stream order per user)."""
        if not len(dense_users):
            return
        u = np.asarray(dense_users, dtype=np.int64)
        self._ensure(int(u.max()) + 1)
        order = np.argsort(u, kind="stable")
        us = u[order]
        its = np.asarray(dense_items, dtype=np.int64)[order]
        starts = np.flatnonzero(np.r_[True, us[1:] != us[:-1]])
        run_len = np.diff(np.r_[starts, len(us)])
        within = np.arange(len(us)) - np.repeat(starts, run_len)
        pos = (self._count[us] + within) % self.length
        self._items[us, pos] = its
        self._count[us[starts]] += run_len

    def set_rows(self, dense_users: np.ndarray, lens: np.ndarray,
                 flat: np.ndarray) -> None:
        """Replace whole history rows from row-major packed prefixes
        (``flat`` holds each user's ``lens[i]`` items concatenated) —
        the read-replica replay path (``serving/replica.py``): a
        replica never sees the ingest stream, so its history comes from
        the delta log's reservoir records, a per-user *set*, not an
        append. Prefixes longer than the ring keep their first
        ``length`` items; the ring continues appending after them."""
        if not len(dense_users):
            return
        from ..state.delta import _range_indices

        u = np.asarray(dense_users, dtype=np.int64)
        lens = np.asarray(lens, dtype=np.int64)
        self._ensure(int(u.max()) + 1)
        excl = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
        keep = np.minimum(lens, self.length)
        if keep.sum():
            # First keep[i] entries of each packed prefix, vectorized.
            zero = np.zeros(len(keep), dtype=np.int64)
            offs = _range_indices(zero, keep)   # per-row 0..keep[i]
            src = _range_indices(excl, excl + keep)
            rows = np.repeat(u, keep)
            self._items[rows, offs] = np.asarray(flat,
                                                 dtype=np.int64)[src]
        self._count[u] = keep

    def recent(self, dense_user: int, out: np.ndarray) -> int:
        """Copy the user's ring into ``out`` (caller scratch, length >=
        ``self.length``); returns the number of valid entries."""
        count = self._count  # one ref read; rows array read second so a
        items = self._items  # concurrent grow can only widen coverage
        if dense_user < 0 or dense_user >= len(count):
            return 0
        c = int(count[dense_user])
        k = min(c, self.length)
        if k:
            out[:k] = items[dense_user, :k]
        return k


class _Scratch(threading.local):
    """Per-thread preallocated query buffers (thread-local: query threads
    are the HTTP pool — no sharing, no lock)."""

    def __init__(self) -> None:
        self.acc = np.zeros(0, dtype=np.float32)    # dense score accum
        self.hist = np.zeros(0, dtype=np.int64)     # history copy
        self.touched = np.zeros(0, dtype=np.int64)  # candidate ids

    def ensure(self, vocab_cap: int, hist_len: int, touch_cap: int) -> None:
        global SCRATCH_ALLOCATIONS
        if len(self.acc) < vocab_cap:
            self.acc = np.zeros(max(vocab_cap, 1024), dtype=np.float32)
            SCRATCH_ALLOCATIONS += 1
        if len(self.hist) < hist_len:
            self.hist = np.zeros(hist_len, dtype=np.int64)
            SCRATCH_ALLOCATIONS += 1
        if len(self.touched) < touch_cap:
            self.touched = np.zeros(max(touch_cap, 256), dtype=np.int64)
            SCRATCH_ALLOCATIONS += 1


class ServingPlane:
    """Snapshot double buffer + user history + the blend query.

    Owned by the job when ``--serve-port`` is set. ``feed``/``absorb``/
    ``publish`` run on the job's threads (ingest / window-absorbing);
    ``query`` runs on any number of HTTP threads against the immutable
    published snapshot.
    """

    def __init__(self, item_vocab, user_vocab, history_len: int = 50,
                 query_slo_s: float = 0.0) -> None:
        self.item_vocab = item_vocab
        self.user_vocab = user_vocab
        self.builder = SnapshotBuilder(item_vocab)
        self.history = UserHistory(length=history_len)
        #: Query-latency SLO feeding the degradation plane's
        #: QUERY_PRESSURE signal (0 = signal off). The *server* applies
        #: it (observability/http.py) — the blend itself stays pure.
        self.query_slo_s = query_slo_s
        self._scratch = _Scratch()

    # -- job-side hooks --------------------------------------------------

    def feed(self, dense_users: np.ndarray, dense_items: np.ndarray) -> None:
        """Ingest-thread hook: extend user histories (pre-window, so a
        user's own interactions are filterable the moment they land)."""
        self.history.extend(dense_users, dense_items)

    def absorb(self, window_out) -> None:
        """Window-absorbing-thread hook: fold emitted rows into the
        build buffer (published at the next :meth:`publish`)."""
        self.builder.absorb(window_out)

    def publish(self, generation: Optional[int] = None) -> TopKSnapshot:
        """Swap the next snapshot in (window boundary). ``generation``
        tags the snapshot explicitly (the replica's delta-log position)
        instead of the content counter — see ``SnapshotBuilder.publish``."""
        return self.builder.publish(generation=generation)

    def seed(self, results_snapshot) -> None:
        """Restore path: serve the checkpointed rows immediately."""
        self.builder.seed(results_snapshot)

    @property
    def generation(self) -> int:
        return self.builder.current.generation

    @property
    def rows(self) -> int:
        return self.builder.current.rows

    def snapshot_age_seconds(self) -> float:
        """Seconds since the last swap *attempt* (quiet boundaries count:
        a live job over an empty stream is not a wedged job)."""
        return time.time() - self.builder.last_swap_unix

    # -- the hot query path ----------------------------------------------

    def query(self, user: Optional[int], n: int
              ) -> "Tuple[List[Tuple[int, float]], TopKSnapshot, bool]":
        """Top-``n`` recommendations for external user id ``user``
        (``None`` = anonymous).

        Returns ``(items, snapshot, fallback)`` where ``items`` is
        ``[(external item, score), ...]`` descending and ``fallback``
        flags the popularity path. One snapshot reference is taken up
        front; every read of the call is against that one generation.
        """
        snap = self.builder.current  # THE reference: one generation
        n = max(1, min(int(n), MAX_N))
        sc = self._scratch
        hist_len = self.history.length
        sc.ensure(1, hist_len, 1)  # the history buffer, before reading
        hist_k = 0
        if user is not None:
            dense_user = self.user_vocab.to_dense(user)
            if dense_user is not None:
                hist_k = self.history.recent(dense_user, sc.hist)
        # acc must cover the LIVE vocab AND whatever the history read
        # just returned — the ingest thread may map a new item (and ring
        # it) between a vocab-length read and the ring read, so size
        # from the actual ids about to be indexed.
        need = max(len(snap.bits) * 64, len(self.item_vocab))
        if hist_k:
            need = max(need, int(sc.hist[:hist_k].max()) + 1)
        sc.ensure(need, hist_len, hist_len * snap.max_k + 16)
        acc = sc.acc
        hist = sc.hist[:hist_k]
        # Exclude already-seen up front: -inf survives any += and is
        # filtered after the gather.
        acc[hist] = -np.inf
        touched_n = 0
        for i in range(hist_k):
            row = snap.row(int(hist[i]))
            if row is None:
                continue
            idx, vals = row
            m = len(idx)
            if not m:
                continue
            sc.touched[touched_n: touched_n + m] = idx
            acc[idx] += vals  # ids unique within a row: no lost updates
            touched_n += m
        items: List[Tuple[int, float]] = []
        fallback = touched_n == 0
        if not fallback:
            t = sc.touched[:touched_n]
            cand = np.unique(t)  # O(touched log touched), touched <= H*K
            scores = acc[cand]
            keep = np.isfinite(scores)
            cand, scores = cand[keep], scores[keep]
            if len(cand):
                take = min(n, len(cand))
                part = np.argpartition(-scores, take - 1)[:take]
                part = part[np.argsort(-scores[part], kind="stable")]
                ext = snap.rev[cand[part]]
                items = list(zip(ext.tolist(),
                                 scores[part].astype(float).tolist()))
            else:
                fallback = True
            # Reset the touched accumulator slots for the next query.
            acc[t] = 0.0
        acc[hist] = 0.0
        if fallback and len(snap.popular):
            items = self._popular(snap, hist, n)
        return items, snap, fallback

    def _popular(self, snap: TopKSnapshot, hist: np.ndarray, n: int
                 ) -> List[Tuple[int, float]]:
        """Cold-start/anonymous fallback: the snapshot's popularity
        ladder minus already-seen."""
        pop = snap.popular
        scores = snap.popular_scores
        if len(hist):
            keep = ~np.isin(pop, hist)
            pop, scores = pop[keep], scores[keep]
        pop, scores = pop[:n], scores[:n]
        return list(zip(snap.rev[pop].tolist(),
                        scores.astype(float).tolist()))
