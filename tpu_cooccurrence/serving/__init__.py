"""Online serving plane: query the job's results while it ingests.

The serving FLEET (``replica.py``) scales the read side horizontally:
stateless ``cooc-replica`` processes bootstrap from the newest
checkpoint and tail the delta log — reads scale with replicas, not
with the TPU job.

Before this package the computed top-K tables ended at stdout,
``LatestResults`` and checkpoints — nobody could *query* them. The
serving plane turns the job into a recommender service:

* :mod:`.snapshot` — immutable, read-optimized snapshots of the per-item
  top-K table, double-buffered and atomically swapped at window
  boundaries (zero-lock readers);
* :mod:`.recommend` — the user-history x co-occurrence blend the
  reference leaves downstream, with cold-start popularity fallback and
  already-seen filtering;
* the ``/recommend`` HTTP endpoint lives beside ``/metrics`` and
  ``/healthz`` in :mod:`tpu_cooccurrence.observability.http`.

Enabled by ``--serve-port``; see docs/ARCHITECTURE.md "Serving plane".
"""

from __future__ import annotations

from .recommend import ServingPlane, UserHistory  # noqa: F401
from .snapshot import SnapshotBuilder, TopKSnapshot  # noqa: F401

__all__ = ["ServingPlane", "UserHistory", "SnapshotBuilder", "TopKSnapshot"]
