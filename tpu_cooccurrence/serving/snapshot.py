"""Read-optimized top-K snapshots: immutable, double-buffered, zero-lock.

The job's result store (:class:`~tpu_cooccurrence.state.results.LatestResults`)
is write-optimized — absorption is O(window rows) and every *read* takes its
lock, which is exactly wrong for a query plane fielding millions of
concurrent reads. This module is the read side: an immutable
:class:`TopKSnapshot` packs the per-item top-K table into query-ready
segment arrays (SMASH-style index-friendly layout, PAPERS.md) with an O(1)
item->row lookup reusing the PR-7 bitmap + rank-directory pattern
(``state/sparse_scorer.BitmapRowRegistry``), and a :class:`SnapshotBuilder`
grows it incrementally from each window's emitted rows.

**Double-buffering / swap protocol.** The builder's mutable state (pointer
arrays, segment list, popularity counts) is the *write buffer*, touched only
by whichever single thread absorbs windows (the caller thread serially, the
scorer worker pipelined — the same thread contract as ``LatestResults``
absorption). At each window boundary :meth:`SnapshotBuilder.publish` packs
the live pointers into an immutable :class:`TopKSnapshot` and swaps it in
with one reference assignment (``self.current = snap`` — atomic under the
GIL). Readers do ``snap = builder.current`` once and hold a plain strong
reference for the whole query: no lock, no torn table — a snapshot's arrays
are never written after publication. The retired buffer's arrays are
recycled for the *next* build only when no reader still holds its snapshot
(a refcount check — the double-buffer steady state allocates nothing);
otherwise fresh arrays are allocated and the straggler keeps its intact
generation.

**Per-window cost.** Absorb is O(window rows) (one ``isfinite`` pass to
precompute valid lengths — queries never filter); publish is O(live items)
of vectorized packing (bitmap scatter + popcount rank + two gathers).
Quiet boundaries (nothing absorbed) keep the published object — its
generation numbers table *content* — and only advance the swap counter
and age stamp (O(1)), so an empty-window stream never reads as wedged.

FlashSparse-style redundancy elimination on the query path: rows are
pre-packed (descending scores, finite prefix, lengths precomputed) so a
query is pure pointer chasing + vectorized adds into caller scratch.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from ..observability.registry import REGISTRY

#: Dense item ids kept in the popularity fallback ladder (the cold-start
#: answer is "top-N of these minus already-seen"; N is capped by it).
POPULAR_WIDTH = 128


if hasattr(np, "bitwise_count"):  # numpy >= 2.0
    def _popcount(words: np.ndarray) -> np.ndarray:
        return np.bitwise_count(words)
else:  # portable fallback: byte-table popcount over the uint8 view
    _POP8 = np.asarray([bin(i).count("1") for i in range(256)],
                       dtype=np.uint8)

    def _popcount(words: np.ndarray) -> np.ndarray:
        return _POP8[words.view(np.uint8).reshape(-1, 8)].sum(
            axis=1).astype(np.uint64)


class _Segment:
    """One absorbed window's rows, pre-packed for reading.

    ``idx``/``vals`` are the backend's packed ``[S, K]`` arrays as emitted
    (scores descending, ``-inf`` padding); ``lens[r]`` is the finite prefix
    length, precomputed once at absorb time so no query ever filters.
    Immutable after construction — snapshots share segment objects across
    generations by reference.
    """

    __slots__ = ("rows", "idx", "vals", "lens")

    def __init__(self, rows: np.ndarray, idx: np.ndarray,
                 vals: np.ndarray) -> None:
        self.rows = rows
        self.idx = idx
        self.vals = vals
        self.lens = np.isfinite(vals).sum(axis=1).astype(np.int32)


class TopKSnapshot:
    """Immutable point-in-time view of the per-item top-K table.

    Layout (the operator-facing table lives in docs/ARCHITECTURE.md
    "Serving plane"):

    * ``bits``/``rank`` — one occupancy bit per dense item plus the
      per-64-bit-word exclusive popcount prefix (PR-7 pattern): packed
      position of item *i* is ``rank[i >> 6] + popcount(bits[i >> 6]
      below bit i)`` — O(1) membership and lookup, no hash, no lock.
    * ``seg_of``/``row_of`` — per *occupied* item, which segment holds its
      newest row and where.
    * ``segments`` — pre-packed window rows (shared by reference with
      other generations).
    * ``popular``/``popular_scores`` — the cold-start fallback ladder,
      descending.
    * ``rev`` — dense -> external item id array (grow-only; captured at
      publish so readers never touch the live vocab).

    No method on this class writes any array, and the class holds no lock
    by construction — reader safety is immutability, not exclusion.
    """

    __slots__ = ("generation", "built_unix", "rows", "bits", "rank",
                 "seg_of", "row_of", "segments", "popular",
                 "popular_scores", "rev", "max_k")

    def __init__(self, generation: int, built_unix: float, rows: int,
                 bits: np.ndarray, rank: np.ndarray, seg_of: np.ndarray,
                 row_of: np.ndarray, segments: Tuple[_Segment, ...],
                 popular: np.ndarray, popular_scores: np.ndarray,
                 rev: np.ndarray, max_k: int = 1) -> None:
        self.generation = generation
        self.built_unix = built_unix
        self.rows = rows
        self.bits = bits
        self.rank = rank
        self.seg_of = seg_of
        self.row_of = row_of
        self.segments = segments
        self.popular = popular
        self.popular_scores = popular_scores
        self.rev = rev
        # Widest row across segments, precomputed at publish: queries
        # size their scratch from it — a per-query max() over the
        # segment list would be O(segments-since-compaction) on exactly
        # the path whose p99 this plane exists to bound.
        self.max_k = max_k

    def row(self, dense_item: int):
        """``(idx_view, vals_view)`` of the item's top-K row, or ``None``.

        Views into the segment's packed arrays — zero copies, zero
        allocation beyond the two view headers.
        """
        if dense_item < 0 or dense_item >= len(self.bits) * 64:
            return None
        w = dense_item >> 6
        b = dense_item & 63
        word = int(self.bits[w])
        if not (word >> b) & 1:
            return None
        pos = int(self.rank[w]) + bin(word & ((1 << b) - 1)).count("1")
        seg = self.segments[self.seg_of[pos]]
        r = int(self.row_of[pos])
        ln = int(seg.lens[r])
        return seg.idx[r, :ln], seg.vals[r, :ln]

    def age_seconds(self, now: Optional[float] = None) -> float:
        return (now if now is not None else time.time()) - self.built_unix


class SnapshotBuilder:
    """Incremental builder + double-buffered publisher of snapshots.

    Thread contract: :meth:`absorb` / :meth:`publish` run on the single
    window-absorbing thread; :attr:`current` is read by any number of
    query threads with a plain attribute load. The builder itself holds
    no lock — single-writer plus immutable-publish needs none.
    """

    #: Dead (superseded) rows tolerated before a compaction pass; mirrors
    #: ``LatestResults._COMPACT_MIN_ROWS`` at a serving-friendly scale.
    _COMPACT_MIN_ROWS = 1 << 18

    def __init__(self, item_vocab) -> None:
        self._vocab = item_vocab
        self._segments: List[_Segment] = []
        self._ptr_seg = np.full(1024, -1, dtype=np.int32)
        self._ptr_pos = np.zeros(1024, dtype=np.int32)
        self._pop = np.zeros(1024, dtype=np.float64)
        self._rows_absorbed = 0
        self._live = 0
        self._dirty = False
        # Retired snapshot whose arrays may be recycled once every reader
        # released it (the second buffer of the double buffer).
        self._spare: Optional[TopKSnapshot] = None
        # Swap bookkeeping (liveness, /healthz staleness): every publish
        # advances these, whether or not the table content changed.
        self.swaps = 0
        self.last_swap_unix = time.time()
        self._gauge_gen = REGISTRY.gauge(
            "cooc_snapshot_generation",
            help="generation of the published serving snapshot")
        self._gauge_swaps = REGISTRY.gauge(
            "cooc_snapshot_swaps_total",
            help="snapshot double-buffer swaps performed")
        self._gauge_built = REGISTRY.gauge(
            "cooc_snapshot_built_unix_seconds",
            help="wall clock of the last snapshot swap (staleness input)")
        self._gauge_rows = REGISTRY.gauge(
            "cooc_snapshot_rows",
            help="live item rows in the published serving snapshot")
        #: The published snapshot. Plain attribute: assignment is the
        #: atomic swap; readers take one reference and never look back.
        self.current: TopKSnapshot = self._empty_snapshot()

    def _empty_snapshot(self) -> TopKSnapshot:
        snap = TopKSnapshot(
            generation=0, built_unix=time.time(), rows=0,
            bits=np.zeros(16, dtype=np.uint64),
            rank=np.zeros(16, dtype=np.int64),
            seg_of=np.zeros(0, dtype=np.int32),
            row_of=np.zeros(0, dtype=np.int32),
            segments=(), popular=np.zeros(0, dtype=np.int32),
            popular_scores=np.zeros(0, dtype=np.float64),
            rev=np.zeros(0, dtype=np.int64))
        self._gauge_built.set(snap.built_unix)
        return snap

    # -- absorption (window-absorbing thread) ---------------------------

    def _ensure(self, n: int) -> None:
        if n <= len(self._ptr_seg):
            return
        cap = len(self._ptr_seg)
        while cap < n:
            cap *= 2
        grown = np.full(cap, -1, dtype=np.int32)
        grown[: len(self._ptr_seg)] = self._ptr_seg
        self._ptr_seg = grown
        grown_rows = np.zeros(cap, dtype=np.int32)
        grown_rows[: len(self._ptr_pos)] = self._ptr_pos
        self._ptr_pos = grown_rows
        grown_pop = np.zeros(cap, dtype=np.float64)
        grown_pop[: len(self._pop)] = self._pop
        self._pop = grown_pop

    def absorb(self, window_out) -> None:
        """Fold one window's emitted rows (``TopKBatch`` or host-backend
        list rows, dense-id space) into the build buffer."""
        rows, idx, vals = _as_arrays(window_out)
        if not len(rows):
            return
        seg = _Segment(rows, idx, vals)
        sid = len(self._segments)
        self._segments.append(seg)
        r64 = rows.astype(np.int64)
        self._ensure(int(r64.max()) + 1)
        fresh = int((self._ptr_seg[r64] < 0).sum())
        self._ptr_seg[r64] = sid
        self._ptr_pos[r64] = np.arange(len(r64), dtype=np.int32)
        self._rows_absorbed += len(r64)
        self._live += fresh
        # Popularity: co-occurrence mass per neighbor item across emitted
        # rows (recency-compounding by construction: an item re-emitted
        # every window keeps accumulating).
        finite = np.isfinite(vals)
        np.add.at(self._pop, idx[finite].astype(np.int64), 1.0)
        self._dirty = True
        if (self._rows_absorbed >= self._COMPACT_MIN_ROWS
                and self._rows_absorbed > 2 * self._live):
            self._compact()

    def _compact(self) -> None:
        """Gather live rows into one merged segment; superseded rows (and
        the segment objects only they referenced) become garbage once the
        generations still viewing them retire."""
        live = np.flatnonzero(self._ptr_seg[: len(self._ptr_seg)] >= 0)
        if not len(live):
            self._segments = []
            self._rows_absorbed = 0
            return
        sids = self._ptr_seg[live]
        rows_in = self._ptr_pos[live]
        parts_idx, parts_vals, parts_rows = [], [], []
        kmax = max(s.idx.shape[1] for s in self._segments)
        for sid in np.unique(sids):
            seg = self._segments[sid]
            sel = sids == sid
            r = rows_in[sel]
            parts_rows.append(live[sel].astype(np.int32))
            parts_idx.append(_pad_k(seg.idx[r], kmax, 0))
            parts_vals.append(_pad_k(seg.vals[r], kmax, -np.inf))
        merged = _Segment(np.concatenate(parts_rows),
                          np.concatenate(parts_idx),
                          np.concatenate(parts_vals))
        self._segments = [merged]
        self._ptr_seg[live] = 0
        # Merged row order is per-source-segment, NOT live order: map
        # each dense id to its actual position in the merged segment.
        self._ptr_pos[merged.rows.astype(np.int64)] = np.arange(
            len(merged.rows), dtype=np.int32)
        self._rows_absorbed = len(live)

    # -- publication (the swap) -----------------------------------------

    def publish(self, generation: Optional[int] = None) -> TopKSnapshot:
        """Pack the build buffer and swap it in as :attr:`current`.

        Returns the published snapshot. A quiet boundary (nothing
        absorbed since the last publish) keeps the published *object* —
        its generation numbers table content, and re-wrapping identical
        arrays would break the refcount ownership the buffer recycling
        rests on — while the swap counter and age stamp still advance,
        so an empty-window stream never reads as a wedged job.

        ``generation``: explicit tag for the published snapshot instead
        of the content counter (``prev + 1``). The serving-fleet
        replicas (``serving/replica.py``) tag snapshots with the *delta
        log position* they replayed to, so `/recommend` responses carry
        a generation a front tier can compare across the whole fleet
        (read-your-window consistency). In this mode a quiet publish
        (an empty delta generation) re-tags the unchanged published
        object — content at log position ``G`` IS content at ``G-1``
        when the delta touched no top-K row, so either tag describes
        the served table truthfully and the monotone tag must win.
        """
        now = time.time()
        self.swaps += 1
        self.last_swap_unix = now
        self._gauge_swaps.add(1)
        self._gauge_built.set(now)
        if not self._dirty:
            if generation is not None \
                    and generation != self.current.generation:
                # Content unchanged: advance the tag in place (one
                # GIL-atomic int store; readers see the old or new tag,
                # both truthful for identical content).
                self.current.generation = generation
                self._gauge_gen.set(generation)
            return self.current
        prev = self.current
        snap = self._pack(
            generation if generation is not None
            else prev.generation + 1, now)
        self._dirty = False
        self.current = snap  # THE swap: one atomic reference assignment
        self._spare = prev
        self._gauge_gen.set(snap.generation)
        self._gauge_rows.set(snap.rows)
        return snap

    @staticmethod
    def _base_cap(a: np.ndarray) -> int:
        """Allocation capacity behind a (possibly sliced) 1-D array."""
        return len(a.base) if a.base is not None else len(a)

    def _recycled(self, n_words: int, n_live: int):
        """Arrays for the next pack: the retired buffer's, when capacity
        fits and no reader still holds its snapshot (refcount == the
        builder's own three handles: ``_spare``, the local, and the
        check argument); fresh pow2-headroom allocations otherwise — a
        straggling reader keeps its generation intact and only costs
        one allocation."""
        spare = self._spare
        if (spare is not None and sys.getrefcount(spare) == 3
                and spare.rows > 0
                and self._base_cap(spare.bits) >= n_words
                and self._base_cap(spare.seg_of) >= n_live):
            self._spare = None
            bits = (spare.bits.base if spare.bits.base is not None
                    else spare.bits)
            rank = (spare.rank.base if spare.rank.base is not None
                    else spare.rank)
            seg = (spare.seg_of.base if spare.seg_of.base is not None
                   else spare.seg_of)
            row = (spare.row_of.base if spare.row_of.base is not None
                   else spare.row_of)
            return (bits[:n_words], rank[:n_words],
                    seg[:n_live], row[:n_live])
        cap_w = max(16, 1 << max(n_words - 1, 0).bit_length())
        cap_l = max(64, 1 << max(n_live - 1, 0).bit_length())
        return (np.zeros(cap_w, dtype=np.uint64)[:n_words],
                np.zeros(cap_w, dtype=np.int64)[:n_words],
                np.empty(cap_l, dtype=np.int32)[:n_live],
                np.empty(cap_l, dtype=np.int32)[:n_live])

    def _pack(self, gen: int, now: float) -> TopKSnapshot:
        n = min(len(self._ptr_seg), len(self._vocab))
        live = np.flatnonzero(self._ptr_seg[:n] >= 0).astype(np.int64)
        n_words = max((n + 63) // 64, 16)
        bits, rank, seg_of, row_of = self._recycled(n_words, len(live))
        bits[:] = 0
        np.bitwise_or.at(bits, live >> 6,
                         np.uint64(1) << (live & 63).astype(np.uint64))
        pc = _popcount(bits).astype(np.int64)
        np.cumsum(pc[:-1], out=rank[1:])
        rank[0] = 0
        seg_of[:] = self._ptr_seg[live]
        row_of[:] = self._ptr_pos[live]
        pop = self._pop[:n]
        k = min(POPULAR_WIDTH, n)
        top = np.argpartition(-pop, k - 1)[:k] if k else np.zeros(
            0, dtype=np.int64)
        top = top[pop[top] > 0]
        top = top[np.argsort(-pop[top], kind="stable")]
        return TopKSnapshot(
            gen, now, len(live), bits, rank, seg_of, row_of,
            tuple(self._segments), top.astype(np.int32),
            self._pop[top].copy(), self._vocab.external_array(),
            max_k=max((s.idx.shape[1] for s in self._segments),
                      default=1))

    # -- seeding (restore path) -----------------------------------------

    def seed(self, results_snapshot) -> None:
        """Rebuild the buffer from a consistent ``LatestResults``
        snapshot (``state/results.ResultsSnapshot``) — the restore path:
        a resumed job must serve its checkpointed rows before the first
        post-restore window fires."""
        self._segments = []
        self._ptr_seg[:] = -1
        self._pop[:] = 0
        self._rows_absorbed = 0
        self._live = 0
        self.absorb(results_snapshot.packed())
        self.publish()


def _pad_k(a: np.ndarray, k: int, fill) -> np.ndarray:
    if a.shape[1] == k:
        return a
    out = np.full((a.shape[0], k), fill, dtype=a.dtype)
    out[:, : a.shape[1]] = a
    return out


def _as_arrays(window_out):
    """Normalize a window output to packed (rows, idx[S,K], vals[S,K]).

    Device backends hand back ``TopKBatch``; host backends hand back
    ``[(dense_item, [(dense_other, score), ...]), ...]`` lists, padded
    by the one shared convention (``state/results.pack_rows`` — small by
    construction, the per-row loop is off the array path).
    """
    from ..state.results import TopKBatch, pack_rows

    if isinstance(window_out, TopKBatch):
        return window_out.rows, window_out.idx, window_out.vals
    batch = pack_rows(list(window_out))
    return batch.rows, batch.idx, batch.vals
