"""Log-likelihood ratio kernels.

The reference implements Dunning's LLR as ``2*(row + col - matrix)`` unnormalized
entropies with 9 ``x*log(x)`` calls and a clamp of round-off negatives to zero
(reference: ``LogLikelihood.java:41-57``). That form is numerically fine in
float64 but catastrophically cancels in float32 once counts reach ~1e9 (the
entropy terms grow like ``N*log(N)`` ~ 1e12 while the LLR itself is O(100)).

For the TPU path we therefore use the algebraically identical
mutual-information form

    LLR = 2 * sum_ij k_ij * log(k_ij * N / (r_i * c_j))

and substitute ``k_ij*N - r_i*c_j = +/-D`` with ``D = k11*k22 - k12*k21``,
giving four ``k * log1p(+/-D / (r*c))`` terms. Each term is O(k * log-ratio)
with no large cancellation, so float32 keeps absolute error ~1e-4 even at
``N ~ 3e10`` (validated in ``tests/test_llr.py`` against the float64 oracle).

Both forms satisfy the reference's golden test vectors from Dunning's paper
(270.72, 263.90, 48.94 — ``LogLikelihoodTest.java:13-16``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# NumPy float64 oracle (entropy form, mirrors the reference's math exactly)
# ---------------------------------------------------------------------------

def xlogx_np(x: np.ndarray) -> np.ndarray:
    """``x*log(x)`` with ``0*log(0) = 0`` (reference: ``LogLikelihood.java:59-61``)."""
    x = np.asarray(x, dtype=np.float64)
    out = np.zeros_like(x)
    nz = x > 0
    out[nz] = x[nz] * np.log(x[nz])
    return out


def llr_np(k11, k12, k21, k22) -> np.ndarray:
    """Float64 entropy-form LLR with the reference's round-off clamp.

    Vectorized over broadcastable inputs. Reference: ``LogLikelihood.java:41-57``
    (the 9-log variant: ``all`` is computed once and reused).
    """
    k11 = np.asarray(k11, dtype=np.float64)
    k12 = np.asarray(k12, dtype=np.float64)
    k21 = np.asarray(k21, dtype=np.float64)
    k22 = np.asarray(k22, dtype=np.float64)

    row1 = k11 + k12
    row2 = k21 + k22
    all_ = xlogx_np(row1 + row2)
    row = all_ - xlogx_np(row1) - xlogx_np(row2)
    col = all_ - xlogx_np(k11 + k21) - xlogx_np(k12 + k22)
    matrix = all_ - xlogx_np(k11) - xlogx_np(k12) - xlogx_np(k21) - xlogx_np(k22)

    out = 2.0 * (row + col - matrix)
    # Round-off clamp (reference: LogLikelihood.java:51-53).
    return np.where(row + col < matrix, 0.0, out)


# ---------------------------------------------------------------------------
# JAX kernels
# ---------------------------------------------------------------------------

def _xlogx(x):
    return jnp.where(x > 0, x * jnp.log(jnp.where(x > 0, x, 1.0)), 0.0)


def llr_entropy(k11, k12, k21, k22):
    """Entropy-form LLR (reference formula verbatim). Use only in >= float64.

    Kept for CPU-backend parity testing; the device default is
    :func:`llr_stable`.
    """
    row1 = k11 + k12
    row2 = k21 + k22
    all_ = _xlogx(row1 + row2)
    row = all_ - _xlogx(row1) - _xlogx(row2)
    col = all_ - _xlogx(k11 + k21) - _xlogx(k12 + k22)
    matrix = all_ - _xlogx(k11) - _xlogx(k12) - _xlogx(k21) - _xlogx(k22)
    return jnp.where(row + col < matrix, 0.0, 2.0 * (row + col - matrix))


def llr_stable(k11, k12, k21, k22):
    """Float32-stable LLR via the mutual-information / log1p form.

    ``k_ij*N - r_i*c_j`` equals ``+D`` for the (1,1) and (2,2) cells and
    ``-D`` for (1,2) and (2,1), with ``D = k11*k22 - k12*k21``; each term is
    ``k * log1p(+/-D/(r*c))``, which is cancellation-free. Clamped at zero
    like the reference (``LogLikelihood.java:51-53``).
    """
    r1 = k11 + k12
    r2 = k21 + k22
    c1 = k11 + k21
    c2 = k12 + k22

    det = k11 * k22 - k12 * k21

    def term(k, rc, sign):
        safe_rc = jnp.where(rc > 0, rc, 1.0)
        x = sign * det / safe_rc
        lg = jnp.log1p(jnp.maximum(x, -1.0 + 1e-38))
        return jnp.where((k > 0) & (rc > 0), k * lg, 0.0)

    out = 2.0 * (
        term(k11, r1 * c1, 1.0)
        + term(k12, r1 * c2, -1.0)
        + term(k21, r2 * c1, -1.0)
        + term(k22, r2 * c2, 1.0)
    )
    return jnp.maximum(out, 0.0)


@jax.jit
def llr_stable_jit(k11, k12, k21, k22):
    return llr_stable(k11, k12, k21, k22)


def score_contingency(k11, item_row_sum, other_row_sum, observed, llr_fn=llr_stable):
    """Build the 2x2 table from co-occurrence counts and score it.

    Mirrors ``ItemRowRescorerTwoInputStreamOperator.scoreItem`` (:230-241):
      k12 = rowSum(i) - k11, k21 = rowSum(j) - k11,
      k22 = observed + k11 - k12 - k21.
    All inputs are float arrays (cast by the caller from exact ints).
    """
    k12 = item_row_sum - k11
    k21 = other_row_sum - k11
    k22 = observed + k11 - k12 - k21
    return llr_fn(k11, k12, k21, k22)
