"""Host-side per-window COO aggregation shared by the device backends.

The reference folds a window's pair deltas per (item, other) cell before
they reach the rescorer (``ItemRowAggregator.java:26-31``); here the same
fold additionally shrinks the device scatter and removes duplicate indices,
which a TPU scatter would otherwise apply serially.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

#: Window-delta count above which the native sort-and-fold carries the
#: per-window cell aggregation (module-level so tests can lower it to
#: drive the integrated native branch). Measured break-even sits where
#: the working set outgrows L3 (~4M 16-byte records on this box; numpy's
#: int64 argsort wins below it, the single-pass fold 1.65x above).
NATIVE_FOLD_MIN = 4_000_000


def aggregate_window_coo(src: np.ndarray, dst: np.ndarray,
                         delta: np.ndarray, return_key: bool = False):
    """Fold duplicate ``(src, dst)`` pairs of one window into single entries.

    Returns ``(src, dst, delta)`` sorted by ``(src, dst)`` with one entry
    per distinct cell and the window's deltas summed as int64 (exact: the
    bincount accumulates in float64, whose 2^53 integer range is far above
    any window's total). With ``return_key=True`` the packed
    ``src << 32 | dst`` int64 key array is appended (same order), for
    callers that index by packed key. Entries whose deltas cancel to zero
    are kept — a zero scatter-add is a no-op, and the reference also emits
    (and rescores rows for) net-zero cells.
    """
    if not np.issubdtype(np.asarray(delta).dtype, np.integer):
        # Both fold paths are exact only for integer deltas; a float
        # delta would truncate in the native sort-and-fold but sum
        # exactly in the float64 bincount fallback — the fold result
        # must never depend on which path the window size selects.
        raise TypeError(
            f"aggregate_window_coo: delta dtype must be integer, got "
            f"{np.asarray(delta).dtype}")
    key = (src.astype(np.int64) << 32) | dst.astype(np.int64)
    folded = None
    if len(key) >= NATIVE_FOLD_MIN:
        # Native sort-and-fold: one std::sort over 16-byte (key, delta)
        # records vs np.unique's indirect argsort + inverse bincount.
        # Measured 1.65x at 5-10M deltas but break-even at ~1M (numpy's
        # int64 argsort is competitive there), so only giant windows
        # route native. `key` is a throwaway local: the fold may
        # clobber it instead of paying a defensive copy.
        from ..native import coo_aggregate

        folded = coo_aggregate(key, delta, clobber_key=True)
    if folded is not None:
        uniq_key, agg = folded
        # The native fold returns PREFIX VIEWS of its full raw-size work
        # buffers; a caller retaining the folded deltas or d_key (scorer
        # index paths, AggregatedPairs, the pipeline's staging ring)
        # would pin the whole >= 4M-entry allocation behind a
        # few-hundred-K prefix. Copies are m-scale — cheap.
        agg = agg.copy()
        if return_key:
            uniq_key = uniq_key.copy()
    else:
        uniq_key, inverse = np.unique(key, return_inverse=True)
        agg = np.bincount(inverse, weights=delta,
                          minlength=len(uniq_key)).astype(np.int64)
    out = ((uniq_key >> 32).astype(np.int32),
           (uniq_key & 0xFFFFFFFF).astype(np.int32),
           agg)
    return out + (uniq_key,) if return_key else out


@dataclasses.dataclass
class AggregatedPairs:
    """One window's pair deltas already folded by :func:`aggregate_window_coo`.

    The pipelined execution mode (``pipeline.py``) runs the fold on its
    host staging thread so the scorer's turn starts at slot allocation /
    COO packing; scorers that set ``accepts_aggregated = True`` take this
    in place of a raw ``PairDeltaBatch`` and skip their own fold. The
    fields are exactly the ``return_key=True`` output (sorted by packed
    key, one entry per distinct cell, int64 exact deltas), so a scorer
    consuming them is bit-identical to one folding the raw batch itself.
    """

    src: np.ndarray    # [M] int32, sorted (primary key)
    dst: np.ndarray    # [M] int32
    delta: np.ndarray  # [M] int64 exact folded deltas
    key: np.ndarray    # [M] int64 packed src << 32 | dst, sorted

    def __len__(self) -> int:
        return len(self.src)

    @staticmethod
    def fold(src, dst, delta) -> "AggregatedPairs":
        s, d, v, k = aggregate_window_coo(
            src, dst, delta.astype(np.int64), return_key=True)
        return AggregatedPairs(s, d, v, k)


def narrow_deltas_int32(agg: np.ndarray) -> np.ndarray:
    """Narrow exact int64 per-cell window deltas to the device's int32.

    A single window's aggregated cell delta beyond int32 would otherwise
    wrap silently in the scatter-add (cheap check: the array is small and
    already materialized).
    """
    if len(agg) and max(-int(agg.min()), int(agg.max())) >= 2**31:
        raise ValueError("window cell delta exceeds int32 range")
    return agg.astype(np.int32)


def distinct_sorted(sorted_vals: np.ndarray) -> np.ndarray:
    """Distinct values of an already-sorted array (no re-sort)."""
    if len(sorted_vals) == 0:
        return sorted_vals
    return sorted_vals[np.flatnonzero(
        np.diff(sorted_vals, prepend=sorted_vals[0] - 1))]


def merge_sorted_insert(keys: np.ndarray, vals: np.ndarray,
                        pos: np.ndarray, new_keys: np.ndarray,
                        new_vals: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Insert sorted ``new_keys``/``new_vals`` into the sorted parallel
    arrays ``keys``/``vals`` at searchsorted positions ``pos``.

    Equivalent to two ``np.insert`` calls but a single merge pass over
    each array — this is the per-window host hot spot of the sorted-key
    indexes once they hold 1M+ cells. Requires ``pos`` non-decreasing
    (it is, whenever both key arrays are sorted): inserted element k
    lands at ``pos[k] + k``.
    """
    n, m = len(keys), len(new_keys)
    tgt = pos + np.arange(m)
    keep = np.ones(n + m, dtype=bool)
    keep[tgt] = False
    out_k = np.empty(n + m, dtype=keys.dtype)
    out_v = np.empty(n + m, dtype=vals.dtype)
    out_k[tgt] = new_keys
    out_k[keep] = keys
    out_v[tgt] = new_vals
    out_v[keep] = vals
    return out_k, out_v
