"""Pallas TPU kernel: fused LLR scoring + streaming top-K.

The XLA path (``ops/device_scorer._score``) materializes a ``[S, I]`` float32
score matrix in HBM and then runs ``lax.top_k`` over it — two full passes of
HBM traffic over data that is consumed once. This kernel fuses the whole of
hot loop 4 (SURVEY §3.4: contingency build + LLR + top-K selection): for
each scored row it streams column tiles of the count matrix through VMEM,
computes the stable-form LLR on the VPU, and folds each tile into a running
top-K scratch without ever writing scores back to HBM.

Rows are selected by scalar-prefetch indexing (the block index map reads the
row id array), so the kernel also subsumes the row gather.

Grid: ``(S, I // TILE)``; the running top-K lives in VMEM scratch that
persists across the column-tile dimension (sequential grid execution),
initialized at ``j == 0`` and written to the output block at the last tile.

Tie-breaking matches ``lax.top_k`` (lowest column index among equal scores):
within a tile the extraction picks the minimum position, and the running
candidates occupy lower positions than the current tile's columns.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .llr import llr_stable

_K_PAD = 128  # output lane width; logical top_k occupies the first K lanes


def _score_topk_kernel(rows_ref, c_ref, rsj_ref, rsi_ref, obs_ref,
                       vals_ref, idx_ref, run_vals, run_idx, *, top_k, tile):
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        run_vals[:] = jnp.full((1, _K_PAD), -jnp.inf, dtype=jnp.float32)
        run_idx[:] = jnp.zeros((1, _K_PAD), dtype=jnp.int32)

    k11 = c_ref[0, :].astype(jnp.float32)[None, :]          # [1, TILE]
    rsj = rsj_ref[0, :].astype(jnp.float32)[None, :]        # [1, TILE]
    rsi = rsi_ref[0, 0].astype(jnp.float32)
    observed = obs_ref[0, 0].astype(jnp.float32)

    k12 = rsi - k11
    k21 = rsj - k11
    k22 = observed + k11 - k12 - k21
    scores = llr_stable(k11, k12, k21, k22)
    scores = jnp.where(k11 != 0, scores, -jnp.inf)

    col_base = j * tile
    cols = (col_base
            + jax.lax.broadcasted_iota(jnp.int32, (1, tile), dimension=1))

    # Candidates: running top-K (positions 0.._K_PAD) then this tile.
    cand_vals = jnp.concatenate([run_vals[:], scores], axis=1)
    cand_idx = jnp.concatenate([run_idx[:], cols], axis=1)
    width = _K_PAD + tile
    positions = jax.lax.broadcasted_iota(jnp.int32, (1, width), dimension=1)

    new_vals = jnp.full((1, _K_PAD), -jnp.inf, dtype=jnp.float32)
    new_idx = jnp.zeros((1, _K_PAD), dtype=jnp.int32)
    for k in range(top_k):  # static unroll; top_k is small
        m = jnp.max(cand_vals)
        pos = jnp.min(jnp.where(cand_vals == m, positions, width))
        sel = positions == pos
        chosen_idx = jnp.max(jnp.where(sel, cand_idx, 0))
        new_vals = new_vals.at[0, k].set(m)
        new_idx = new_idx.at[0, k].set(chosen_idx)
        cand_vals = jnp.where(sel, -jnp.inf, cand_vals)

    run_vals[:] = new_vals
    run_idx[:] = new_idx

    @pl.when(j == n_j - 1)
    def _emit():
        vals_ref[:] = run_vals[:]
        idx_ref[:] = run_idx[:]


@functools.partial(jax.jit,
                   static_argnames=("top_k", "tile", "interpret"))
def pallas_score_topk(C, row_sums, rows, observed, *, top_k: int,
                      tile: int = 512, interpret: bool = False):
    """Fused row-gather + LLR + top-K. Mirrors ``device_scorer._score``.

    C        [I, I] int32 — dense co-occurrence counts (I % tile == 0)
    row_sums [I]    int32
    rows     [S]    int32 — row ids to score (padded rows allowed)
    observed scalar float32
    Returns (vals [S, top_k] f32, idx [S, top_k] i32), scores descending.
    """
    num_items = C.shape[0]
    if num_items % tile != 0:
        raise ValueError(f"num_items {num_items} must be a multiple of tile {tile}")
    if top_k > _K_PAD:
        raise ValueError(
            f"top_k {top_k} exceeds the kernel's lane width {_K_PAD}; "
            f"use the XLA scorer (pallas='off') for larger K")
    S = rows.shape[0]
    rsi = row_sums[rows].reshape(S, 1)
    rs2d = row_sums.reshape(1, num_items)
    obs = jnp.full((1, 1), observed, dtype=jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(S, num_items // tile),
        in_specs=[
            pl.BlockSpec((1, tile), lambda i, j, s: (s[i], j)),
            pl.BlockSpec((1, tile), lambda i, j, s: (0, j)),
            pl.BlockSpec((1, 1), lambda i, j, s: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, j, s: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, _K_PAD), lambda i, j, s: (i, 0)),
            pl.BlockSpec((1, _K_PAD), lambda i, j, s: (i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((1, _K_PAD), jnp.float32),
            pltpu.VMEM((1, _K_PAD), jnp.int32),
        ],
    )
    kernel = functools.partial(_score_topk_kernel, top_k=top_k, tile=tile)
    vals, idx = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((S, _K_PAD), jnp.float32),
            jax.ShapeDtypeStruct((S, _K_PAD), jnp.int32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(rows, C, rs2d, rsi, obs)
    return vals[:, :top_k], idx[:, :top_k]
