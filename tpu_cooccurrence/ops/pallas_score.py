"""Pallas TPU kernel: fused LLR scoring + streaming top-K.

The XLA path (``ops/device_scorer._score``) materializes a ``[S, I]`` float32
score matrix in HBM and then runs ``lax.top_k`` over it — two full passes of
HBM traffic over data that is consumed once. This kernel fuses the whole of
hot loop 4 (SURVEY §3.4: contingency build + LLR + top-K selection): for
each block of scored rows it streams column tiles of the gathered count
rows through VMEM, computes the stable-form LLR on the VPU, and folds each
tile into a running top-K scratch without ever writing scores back to HBM.

The row gather ``C[rows]`` happens in XLA before the kernel and does
materialize an ``[S, I]`` int32 buffer in HBM (TPU block layout requires
sublane-aligned blocks, so arbitrary single-row blocks can't be indexed
from inside the kernel). What the fusion removes versus the XLA path is
the float32 score matrix write plus ``top_k``'s separate full re-read of
it; the caller additionally bounds ``S`` so the gathered buffer stays
within a fixed HBM budget (``DeviceScorer.max_score_rows``).

Grid: ``(S // R, I // TILE)`` with ``R = row_block(count_dtype)`` rows per
block — the count dtype's sublane tile (8 for int32, 16 for int16, whose
halved bytes are exactly the regime where fusing away the f32 score
matrix matters most). The running top-K lives in VMEM scratch that
persists across the column-tile dimension (sequential grid execution,
innermost-last order), initialized at ``j == 0`` and written to the
output block at the last tile.

Tie-breaking matches ``lax.top_k`` (lowest column index among equal scores):
within a tile the extraction picks the minimum position, and the running
candidates occupy lower positions than the current tile's columns.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import tuning
from .llr import llr_stable

_K_PAD = 128     # output lane width; logical top_k occupies the first K lanes


def row_block(count_dtype) -> int:
    """Rows per grid step: the sublane tile of the count dtype.

    int32 tiles are (8, 128); int16 packs two values per sublane word, so
    its native tile is (16, 128) — 16-row blocks keep the gathered count
    rectangle layout-aligned and feed the VPU full registers.
    """
    return 16 if jnp.dtype(count_dtype).itemsize == 2 else 8


def _score_topk_kernel(g_ref, rsj_ref, rsi_ref, obs_ref,
                       vals_ref, idx_ref, run_vals, run_idx, *, top_k, tile,
                       block):
    j = pl.program_id(1)
    n_j = pl.num_programs(1)
    R = block

    @pl.when(j == 0)
    def _init():
        run_vals[...] = jnp.full((R, _K_PAD), -jnp.inf, dtype=jnp.float32)
        run_idx[...] = jnp.zeros((R, _K_PAD), dtype=jnp.float32)

    counts = g_ref[...]                                     # [R, TILE] counts
    k11 = counts.astype(jnp.float32)
    rsj = rsj_ref[0, :].astype(jnp.float32)[None, :]        # [1, TILE]
    rsi = rsi_ref[...].astype(jnp.float32)                  # [R, 1]
    observed = obs_ref[0, 0].astype(jnp.float32)

    k12 = rsi - k11
    k21 = rsj - k11
    k22 = observed + k11 - k12 - k21
    scores = llr_stable(k11, k12, k21, k22)
    scores = jnp.where(counts != 0, scores, -jnp.inf)       # [R, TILE]

    # Threshold skip: the merge below costs more VPU work than the LLR
    # itself (top_k sequential extractions over the candidate width). A
    # tile only needs it if some row's tile-max beats that row's running
    # K-th best; after the first few column tiles most tiles lose and the
    # whole merge is skipped, leaving the kernel LLR-bound.
    thresh = run_vals[:, top_k - 1:top_k]                   # [R, 1]
    tile_max = jnp.max(scores, axis=1, keepdims=True)       # [R, 1]
    need_merge = jnp.any(tile_max > thresh)

    @pl.when((j == 0) | need_merge)
    def _merge():
        # Column ids ride through the selection as float32: int32 VMEM
        # scratch carried across grid steps miscompiles on current Mosaic
        # (output block silently zeroed once the row-grid dimension reaches
        # 4 — observed on v5e, jax 0.8.x); float32 holds ids exactly below
        # 2^24, which the wrapper enforces via the vocab-size guard.
        col_base = j * tile
        cols = (col_base
                + jax.lax.broadcasted_iota(jnp.int32, (R, tile), dimension=1)
                ).astype(jnp.float32)

        # Candidates: running top-K (positions 0.._K_PAD-1) then this tile.
        cand_vals = jnp.concatenate([run_vals[...], scores], axis=1)
        cand_idx = jnp.concatenate([run_idx[...], cols], axis=1)
        width = _K_PAD + tile
        positions = jax.lax.broadcasted_iota(jnp.int32, (R, width), dimension=1)
        lanes = jax.lax.broadcasted_iota(jnp.int32, (R, _K_PAD), dimension=1)

        new_vals = jnp.full((R, _K_PAD), -jnp.inf, dtype=jnp.float32)
        new_idx = jnp.zeros((R, _K_PAD), dtype=jnp.float32)
        for k in range(top_k):  # static unroll; top_k is small
            m = jnp.max(cand_vals, axis=1, keepdims=True)             # [R, 1]
            pos = jnp.min(jnp.where(cand_vals == m, positions, width),
                          axis=1, keepdims=True)                      # [R, 1]
            sel = positions == pos                                    # [R, W]
            chosen = jnp.max(jnp.where(sel, cand_idx, 0.0),
                             axis=1, keepdims=True)                   # [R, 1]
            lane_k = lanes == k
            new_vals = jnp.where(lane_k, m, new_vals)
            new_idx = jnp.where(lane_k, chosen, new_idx)
            cand_vals = jnp.where(sel, -jnp.inf, cand_vals)

        run_vals[...] = new_vals
        run_idx[...] = new_idx

    @pl.when(j == n_j - 1)
    def _emit():
        vals_ref[...] = run_vals[...]
        idx_ref[...] = run_idx[...]


def _pallas_topk_gathered(gathered, rs2d, rsi, observed, *, top_k: int,
                          tile: int, blk: int, interpret: bool):
    """The dense kernel's pallas_call on pre-gathered inputs.

    gathered [Sp, I] int32|int16 (Sp % blk == 0, I % tile == 0),
    rs2d [1, I] int32, rsi [Sp, 1] int32, observed scalar f32.
    Returns (vals [Sp, _K_PAD] f32, idx [Sp, _K_PAD] f32 — ids as exact
    float values). Shared by the single-chip wrapper (which gathers
    ``C[rows]``) and the sharded backend (which gathers from its local
    row block but passes the replicated global row sums).
    """
    sp, num_items = gathered.shape
    obs = jnp.full((1, 1), observed, dtype=jnp.float32)
    kernel = functools.partial(_score_topk_kernel, top_k=top_k, tile=tile,
                               block=blk)
    return pl.pallas_call(
        kernel,
        grid=(sp // blk, num_items // tile),
        in_specs=[
            pl.BlockSpec((blk, tile), lambda i, j: (i, j)),
            pl.BlockSpec((1, tile), lambda i, j: (0, j)),
            pl.BlockSpec((blk, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((blk, _K_PAD), lambda i, j: (i, 0)),
            pl.BlockSpec((blk, _K_PAD), lambda i, j: (i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((blk, _K_PAD), jnp.float32),
            pltpu.VMEM((blk, _K_PAD), jnp.float32),
        ],
        out_shape=(
            jax.ShapeDtypeStruct((sp, _K_PAD), jnp.float32),
            jax.ShapeDtypeStruct((sp, _K_PAD), jnp.float32),
        ),
        interpret=interpret,
    )(gathered, rs2d, rsi, obs)


def pallas_score_topk_local(C_loc, row_sums, rows_global, lo, observed, *,
                            top_k: int, tile: int = 512,
                            interpret: bool = False):
    """Sharded-dense form: score global ``rows_global`` out of a LOCAL row
    block ``C_loc`` (`[rows_per_shard, I]`, rows ``[lo, lo+rows_per_shard)``)
    against the replicated global ``row_sums``. For use inside a
    ``shard_map`` body (pallas_call is an ordinary per-device op there).

    Returns packed [2, S, top_k] float32 with ids as float *values*
    (decode with astype — same contract as ``pallas_score_topk(packed=
    True)``). Padded rows may repeat a real row; the caller drops them.
    """
    num_items = C_loc.shape[1]
    if C_loc.dtype not in (jnp.int32, jnp.int16):
        raise ValueError(
            f"pallas scorer supports int32|int16 counts, got {C_loc.dtype}")
    if num_items % tile != 0:
        raise ValueError(
            f"num_items {num_items} must be a multiple of tile {tile}")
    if num_items > 1 << 24:
        raise ValueError(
            f"num_items {num_items} exceeds 2^24: column ids ride as exact "
            f"float32; use the XLA scorer beyond that")
    if top_k > _K_PAD:
        raise ValueError(
            f"top_k {top_k} exceeds the kernel's lane width {_K_PAD}")
    blk = row_block(C_loc.dtype)
    S = rows_global.shape[0]
    pad_s = (-S) % blk
    if pad_s:
        rows_global = jnp.concatenate(
            [rows_global, jnp.full(pad_s, lo, dtype=rows_global.dtype)])
    sp = S + pad_s
    gathered = C_loc[rows_global - lo]                   # [Sp, I]
    rsi = row_sums[rows_global].reshape(sp, 1)
    rs2d = row_sums.reshape(1, num_items)
    vals, idxf = _pallas_topk_gathered(gathered, rs2d, rsi, observed,
                                       top_k=top_k, tile=tile, blk=blk,
                                       interpret=interpret)
    return jnp.stack([vals[:S, :top_k], idxf[:S, :top_k]])


def _rect_topk_kernel(k11_ref, dsf_ref, rsj_ref, rsi_ref, obs_ref,
                      vals_ref, idx_ref, run_vals, run_idx, *, top_k,
                      tile, block):
    """Sparse-rectangle variant of :func:`_score_topk_kernel`.

    Same streaming top-K structure; differences: the contingency columns
    are slab cells, so the partner row sums arrive as a full
    ``[R, TILE]`` tile (gathered by partner id in XLA — the dense kernel
    broadcasts one ``[1, TILE]`` row-sum slice), and the candidate ids
    are the gathered partner ids (as float32 values), not a column iota.
    Tie-breaking still picks the lowest candidate *position* — position
    order is slab-slot order, which is exactly
    ``state/sparse_scorer._score_rect``'s ``lax.top_k`` tie rule
    (earliest-inserted cell of the row wins).
    """
    j = pl.program_id(1)
    n_j = pl.num_programs(1)
    R = block

    @pl.when(j == 0)
    def _init():
        run_vals[...] = jnp.full((R, _K_PAD), -jnp.inf, dtype=jnp.float32)
        run_idx[...] = jnp.zeros((R, _K_PAD), dtype=jnp.float32)

    k11i = k11_ref[...]                                     # [R, TILE] counts
    k11 = k11i.astype(jnp.float32)
    rsj = rsj_ref[...]                                      # [R, TILE]
    rsi = rsi_ref[...]                                      # [R, 1]
    observed = obs_ref[0, 0]

    k12 = rsi - k11
    k21 = rsj - k11
    k22 = observed + k11 - k12 - k21
    scores = llr_stable(k11, k12, k21, k22)
    scores = jnp.where(k11i != 0, scores, -jnp.inf)         # [R, TILE]

    # Threshold skip — see _score_topk_kernel.
    thresh = run_vals[:, top_k - 1:top_k]
    tile_max = jnp.max(scores, axis=1, keepdims=True)
    need_merge = jnp.any(tile_max > thresh)

    @pl.when((j == 0) | need_merge)
    def _merge():
        cand_vals = jnp.concatenate([run_vals[...], scores], axis=1)
        cand_idx = jnp.concatenate([run_idx[...], dsf_ref[...]], axis=1)
        width = _K_PAD + tile
        positions = jax.lax.broadcasted_iota(jnp.int32, (R, width),
                                             dimension=1)
        lanes = jax.lax.broadcasted_iota(jnp.int32, (R, _K_PAD), dimension=1)

        new_vals = jnp.full((R, _K_PAD), -jnp.inf, dtype=jnp.float32)
        new_idx = jnp.zeros((R, _K_PAD), dtype=jnp.float32)
        for k in range(top_k):  # static unroll; top_k is small
            m = jnp.max(cand_vals, axis=1, keepdims=True)
            pos = jnp.min(jnp.where(cand_vals == m, positions, width),
                          axis=1, keepdims=True)
            sel = positions == pos
            chosen = jnp.max(jnp.where(sel, cand_idx, 0.0),
                             axis=1, keepdims=True)
            lane_k = lanes == k
            new_vals = jnp.where(lane_k, m, new_vals)
            new_idx = jnp.where(lane_k, chosen, new_idx)
            cand_vals = jnp.where(sel, -jnp.inf, cand_vals)

        run_vals[...] = new_vals
        run_idx[...] = new_idx

    @pl.when(j == n_j - 1)
    def _emit():
        vals_ref[...] = run_vals[...]
        idx_ref[...] = run_idx[...]


def rect_tile(R: int) -> int:
    """Column-tile width for a rectangle of width ``R`` (lane-aligned).

    Wide tiles amortize the sequential top-K merge: the on-chip dense
    sweep measured 2048 → 179 ms vs 512 → 300 ms at [8192, 61440] int16
    (TPU_ROUND2.jsonl pallas-bench), and the int32 rectangle blocks are
    8 sublanes, so a [8, 2048] i32 tile is ~64 KB — far under VMEM. The
    sparse-pallas bench row re-times each rectangle width on chip.
    """
    return min(2048, R)


#: Narrowest rectangle the fused kernel accepts (registry-declared).
_RECT_MIN_ROWS = int(tuning.default("rect_min_rows"))


def rect_supported(R: int, top_k: int) -> bool:
    """Whether the fused rectangle kernel can carry this bucket.

    Narrow rectangles (R < 256) don't tile the 128-lane VPU cleanly and
    are cheap for XLA anyway; ``top_k`` must fit the output lane width.
    """
    t = rect_tile(R)
    return (R >= _RECT_MIN_ROWS and R % t == 0 and t % 128 == 0
            and top_k <= _K_PAD)


def rect_routed(enabled: bool, R: int, top_k: int, items_cap: int) -> bool:
    """THE routing rule for sparse rectangles, shared by the
    single-device and sharded sparse scorers: kernel iff requested,
    the bucket is kernel-carriable, and the vocab fits the float32-id
    encoding (partner ids ride as exact f32 below 2^24) — a vocab
    growing past the bound reroutes new plans to XLA instead of
    raising mid-stream."""
    return enabled and rect_supported(R, top_k) and items_cap <= 1 << 24


def topk_parity(vals_a, idx_a, vals_b, idx_b, rtol=1e-5, atol=1e-5):
    """THE kernel-vs-XLA parity contract, shared by tests and the on-chip
    bench checks: scores allclose, and every UNTIED position (score
    unique within its row under the same tolerance) carries the same id.
    Tied positions may legitimately order differently. Vectorized —
    safe to run inside a scarce TPU grant window.

    Returns ``(scores_allclose: bool, untied_id_mismatches: int)``.
    """
    import numpy as np

    vals_a, vals_b = np.asarray(vals_a), np.asarray(vals_b)
    idx_a, idx_b = np.asarray(idx_a), np.asarray(idx_b)
    scores_ok = bool(np.allclose(vals_a, vals_b, rtol=rtol, atol=atol))
    untied = np.isclose(vals_a[:, :, None], vals_a[:, None, :],
                        rtol=rtol, atol=atol).sum(-1) == 1
    mism = int(((idx_a != idx_b) & np.isfinite(vals_a) & untied).sum())
    return scores_ok, mism


def resolve_sparse_pallas_flag(use_pallas: str) -> bool:
    """Resolve an ``auto|on|off`` --pallas request for a SPARSE scorer.

    auto is OFF for now: slab counts are int32, where the measured dense
    A/B favored XLA ~5x (TPU_ROUND2.jsonl pallas-bench, v5e); the
    sparse-pallas tpu_round2 row re-decides this on chip, and this
    default flips if the rectangle form cliffs like dense int16 did
    (247x). 'on' forces the kernel for every rectangle
    :func:`rect_supported` can carry; narrow buckets stay XLA either
    way."""
    if use_pallas not in ("auto", "on", "off"):
        raise ValueError(f"use_pallas must be auto|on|off, got {use_pallas!r}")
    return use_pallas == "on"


def pallas_score_rect(cnt, dst, row_sums, meta, observed, *, top_k: int,
                      R: int, interpret: bool = False):
    """Fused LLR + top-K over one slab length-bucket rectangle.

    Drop-in replacement for ``state/sparse_scorer._score_rect`` (same
    arguments, same packed ``[2, S_pad, K]`` float32 output with ids as
    an int32 *bitcast*, same tie semantics), for use inside a jit — the
    slab/row-sum gathers stay in XLA exactly like the dense kernel's
    ``C[rows]`` gather; the kernel fuses away the ``[S, R]`` float32
    score materialization and ``top_k``'s second full pass over it.

    cnt/dst   [cap]  int32 — slab cells (counts / partner ids)
    row_sums  [I]    int32
    meta      [3, S] int32 — (row id, slab start, row len); len==0 pads
    observed  scalar float32
    """
    if not rect_supported(R, top_k):
        raise ValueError(
            f"rectangle R={R} top_k={top_k} unsupported by the fused "
            f"kernel; gate callers on rect_supported()")
    num_items = row_sums.shape[0]
    if num_items > 1 << 24:
        raise ValueError(
            f"vocab {num_items} exceeds 2^24: partner ids ride the kernel "
            f"as exact float32 (int32 scratch miscompiles on Mosaic); use "
            f"the XLA rectangle scorer beyond that")
    tile = rect_tile(R)
    blk = 8  # int32 sublane tile
    rowids, starts, lens = meta[0], meta[1], meta[2]
    S = meta.shape[1]
    pad_s = (-S) % blk
    if pad_s:
        z = jnp.zeros((3, pad_s), dtype=meta.dtype)
        rowids = jnp.concatenate([rowids, z[0]])
        starts = jnp.concatenate([starts, z[1]])
        lens = jnp.concatenate([lens, z[2]])
    sp = S + pad_s

    # XLA pre-gathers (the kernel reads rectangles, Mosaic can't index
    # arbitrary slab offsets from inside a block) — the SAME gather/mask
    # code as the XLA scorer, so the two paths cannot drift.
    from ..state.sparse_scorer import gather_rect

    meta_p = jnp.stack([rowids, starts, lens])
    k11, _valid, ds, rsj, rsi = gather_rect(cnt, dst, row_sums, meta_p, R)
    dsf = ds.astype(jnp.float32)                         # exact < 2^24
    obs = jnp.full((1, 1), observed, dtype=jnp.float32)

    kernel = functools.partial(_rect_topk_kernel, top_k=top_k, tile=tile,
                               block=blk)
    vals, idxf = pl.pallas_call(
        kernel,
        grid=(sp // blk, R // tile),
        in_specs=[
            pl.BlockSpec((blk, tile), lambda i, j: (i, j)),
            pl.BlockSpec((blk, tile), lambda i, j: (i, j)),
            pl.BlockSpec((blk, tile), lambda i, j: (i, j)),
            pl.BlockSpec((blk, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((blk, _K_PAD), lambda i, j: (i, 0)),
            pl.BlockSpec((blk, _K_PAD), lambda i, j: (i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((blk, _K_PAD), jnp.float32),
            pltpu.VMEM((blk, _K_PAD), jnp.float32),
        ],
        out_shape=(
            jax.ShapeDtypeStruct((sp, _K_PAD), jnp.float32),
            jax.ShapeDtypeStruct((sp, _K_PAD), jnp.float32),
        ),
        interpret=interpret,
    )(k11, dsf, rsj, rsi, obs)
    # Same wire format as _score_rect: ids as an int32 BITCAST (the
    # float->int conversion happens here in XLA, where it is exact and
    # immune to the Mosaic carried-scratch issue the value-space
    # encoding works around inside the kernel).
    ids = idxf[:S, :top_k].astype(jnp.int32)
    return jnp.stack([vals[:S, :top_k],
                      jax.lax.bitcast_convert_type(ids, jnp.float32)])


def _expand_kernel(basket_ref, new_ref, len_ref, skip_ref, sign_ref,
                   src_ref, dst_ref, delta_ref, *, width, block):
    """On-chip basket expansion: one star op per row.

    Row ``r`` expands op ``(new, basket[:len], skip, sign)`` into the
    ``2 * width`` COO lanes ``[new -> basket[j] | j] ++ [basket[j] ->
    new | j]`` with ``delta = sign`` on the valid lanes (``j < len``,
    ``j != skip``) and the padded ``(0, 0, 0)`` no-op triple everywhere
    else — the same pad-slot invariant the chained COO upload carries
    (``device_scorer.process_window``), so the scatter that consumes
    these lanes needs no masking. Pure VPU selects over a column iota;
    no cross-lane traffic.
    """
    R = block
    basket = basket_ref[...]                            # [R, W] int32
    new = new_ref[...]                                  # [R, 1] int32
    lens = len_ref[...]                                 # [R, 1] int32
    skip = skip_ref[...]                                # [R, 1] int32
    sign = sign_ref[...]                                # [R, 1] int32
    j = jax.lax.broadcasted_iota(jnp.int32, (R, width), dimension=1)
    valid = (j < lens) & (j != skip)
    zero = jnp.zeros((R, width), dtype=jnp.int32)
    fwd_src = jnp.where(valid, new + zero, zero)
    fwd_dst = jnp.where(valid, basket, zero)
    d = jnp.where(valid, sign + zero, zero)
    src_ref[...] = jnp.concatenate([fwd_src, fwd_dst], axis=1)
    dst_ref[...] = jnp.concatenate([fwd_dst, fwd_src], axis=1)
    delta_ref[...] = jnp.concatenate([d, d], axis=1)


#: Ops-axis block of the expansion kernel (int32 sublane tile).
_EXPAND_BLOCK = 8


def pallas_expand_baskets(basket, new, lens, skips, signs, *,
                          interpret: bool = False):
    """Expand a padded basket tensor into COO pair-delta lanes on chip.

    The device half of the fused window dispatch
    (``device_scorer._fused_window_emit``/``_defer``): takes the padded
    per-op basket rectangle the host uplinked and produces the
    ``(src, dst, delta)`` lanes the count scatter consumes, replacing
    the host-side ``native/reservoir_expand.cpp`` expansion plus the
    3x-wider COO uplink.

    basket [N, W] int32 — partner rows (cells at ``j >= len`` are
                          UNSPECIFIED, masked in-kernel; ``W % 128 == 0``)
    new/lens/skips/signs [N, 1] int32 — star item, valid-cell count,
                          excluded column (-1 = none), delta sign
                          (padded ops: len 0, sign 0)
    Returns ``(src, dst, delta)`` each [N, 2W] int32; invalid lanes
    carry the (0, 0, 0) scatter no-op triple.
    """
    n, width = basket.shape
    if n % _EXPAND_BLOCK:
        raise ValueError(
            f"op count {n} must be a multiple of {_EXPAND_BLOCK} "
            f"(pad the ops axis)")
    if width % 128:
        raise ValueError(
            f"basket width {width} must be a multiple of 128 lanes")
    kernel = functools.partial(_expand_kernel, width=width,
                               block=_EXPAND_BLOCK)
    blk = _EXPAND_BLOCK
    return pl.pallas_call(
        kernel,
        grid=(n // blk,),
        in_specs=[
            pl.BlockSpec((blk, width), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((blk, 2 * width), lambda i: (i, 0)),
            pl.BlockSpec((blk, 2 * width), lambda i: (i, 0)),
            pl.BlockSpec((blk, 2 * width), lambda i: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, 2 * width), jnp.int32),
            jax.ShapeDtypeStruct((n, 2 * width), jnp.int32),
            jax.ShapeDtypeStruct((n, 2 * width), jnp.int32),
        ),
        interpret=interpret,
    )(basket, new, lens, skips, signs)


@functools.partial(jax.jit,
                   static_argnames=("top_k", "tile", "interpret", "packed"))
def pallas_score_topk(C, row_sums, rows, observed, *, top_k: int,
                      tile: int = 512, interpret: bool = False,
                      packed: bool = False):
    """Fused LLR + top-K over gathered rows. Mirrors ``device_scorer._score``.

    C        [I, I] int32|int16 — dense co-occurrence counts (I % tile == 0)
    row_sums [I]    int32
    rows     [S]    int32 — row ids to score (padded rows allowed)
    observed scalar float32
    Returns (vals [S, top_k] f32, idx [S, top_k] i32), scores descending;
    with ``packed=True`` a single [2, S, top_k] float32 — idx as exact
    float *values* (decode with ``astype``, not a bitcast view) — so the
    caller fetches one buffer.
    """
    num_items = C.shape[0]
    if C.dtype not in (jnp.int32, jnp.int16):
        raise ValueError(
            f"pallas scorer supports int32|int16 counts, got {C.dtype}")
    blk = row_block(C.dtype)
    if num_items % tile != 0:
        raise ValueError(f"num_items {num_items} must be a multiple of tile {tile}")
    if num_items > 1 << 24:
        raise ValueError(
            f"num_items {num_items} exceeds 2^24: column ids are tracked as "
            f"exact float32 inside the kernel (int32 scratch miscompiles on "
            f"Mosaic); use the XLA scorer (pallas='off') beyond that")
    if top_k > _K_PAD:
        raise ValueError(
            f"top_k {top_k} exceeds the kernel's lane width {_K_PAD}; "
            f"use the XLA scorer (pallas='off') for larger K")
    S = rows.shape[0]
    pad_s = (-S) % blk
    if pad_s:
        rows = jnp.concatenate([rows, jnp.zeros(pad_s, dtype=rows.dtype)])
    sp = S + pad_s
    gathered = C[rows]                                   # [Sp, I] count dtype
    rsi = row_sums[rows].reshape(sp, 1)
    rs2d = row_sums.reshape(1, num_items)
    vals, idx = _pallas_topk_gathered(gathered, rs2d, rsi, observed,
                                      top_k=top_k, tile=tile, blk=blk,
                                      interpret=interpret)
    vals = vals[:S, :top_k]
    if packed:
        # Value-space packing: ids stay exact float32 (wrapper guard caps
        # the vocab at 2^24). bitcast_convert_type on the kernel's second
        # output miscompiles to zeros on current Mosaic once the row grid
        # reaches 4 blocks, so the host decodes with astype, not view —
        # see DeviceScorer._materialize.
        return jnp.stack([vals, idx[:S, :top_k]])
    return vals, idx[:S, :top_k].astype(jnp.int32)
