"""Single-device JAX scoring backend.

The TPU-idiomatic replacement of hot loops 3+4 (SURVEY §3.3-3.4): per window,
the COO pair-delta batch is scatter-added into a dense item x item count
matrix ``C`` (the AᵀA delta application), row sums are derived as a
segment-sum by source row, and every updated row is LLR-scored and top-K'd
in one vectorized pass:

  * scatter-add     — replaces ItemRowAggregator.java:26-31 + the rescorer's
                      per-entry ``addTo`` merge (:172-177)
  * segment row sums — replaces RowSumAggregator.java:15-38 (+ derivation
                      argument in ``sampling/reservoir.py``)
  * vectorized LLR  — replaces the scalar loop at
                      ItemRowRescorerTwoInputStreamOperator.java:199-223
  * ``lax.top_k``   — replaces IntDoublePriorityQueue (tie-breaking differs:
                      lowest column index wins among equal scores; the
                      reference keeps the earlier-inserted entry)

Dynamic shapes are bucketed to powers of two so XLA compiles a bounded set
of programs (SURVEY §7 "hard parts": padding/bucketing of COO buffers).
Padded pair slots carry ``delta == 0`` at indices (0, 0) — a scatter-add of
zero is a no-op. Padded row slots score row 0 and are dropped on host.

Counts are int32 by default (the reference uses Java short16 with silent
wraparound — we deliberately widen, SURVEY §7); ``count_dtype="int16"``
opts back into reference-style shorts, halving HBM so the dense matrix
reaches ~90k-item vocabularies, wraparound included. Row sums are int32
always. LLR runs in float32 via the stable ``log1p`` form (``ops/llr.py``);
``observed`` is tracked exactly on host and fed per step as a float32
scalar.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..metrics import Counters, RESCORED_ITEMS, ROW_SUM_PROCESS_WINDOW
from .. import tuning  # noqa: E402  (registry: stdlib-only)
from ..observability import LEDGER
from ..observability.registry import REGISTRY
from ..robustness import faults
from ..sampling.reservoir import BasketBatch, PairDeltaBatch
from ..state.results import TopKBatch
from .aggregate import (aggregate_window_coo, distinct_sorted,
                        narrow_deltas_int32)
from .donation import donate_argnums
from .llr import llr_stable


#: The pow2/pow4 plan high-water floor: every dispatch shape
#: rounds up to at least this many rows (registry-declared so
#: the autotune plane can move it).
_POW2_PAD_MIN = int(tuning.default("pow2_pad_min"))


def pad_pow2(n: int, minimum: int = _POW2_PAD_MIN) -> int:
    size = minimum
    while size < n:
        size *= 2
    return size


def pad_pow4(n: int, minimum: int = _POW2_PAD_MIN) -> int:
    """Power-of-4 bucket: ≤4x padding waste, 2x fewer compiled programs.

    Scatter/score work on padded slots is cheap device time; each distinct
    shape is an XLA compile (~1-2s on the tunneled chip), so a coarser
    bucket ladder wins for streaming workloads whose per-window sizes vary.
    """
    size = minimum
    while size < n:
        size *= 4
    return size


def pallas_auto(count_dtype: np.dtype, backend: str, top_k: int = 1) -> bool:
    """Default kernel choice for ``--pallas auto``, from on-chip measurement.

    int16 counts on a real TPU: the fused Pallas scorer, decisively — the
    XLA gather+LLR+top_k path collapses at int16 (44.3s vs the kernel's
    0.18s on [8192, 61440], a 247x gap; TPU_ROUND2.jsonl pallas-bench,
    v5e). int32: XLA, which wins ~5x there (23ms vs 120ms on
    [8192, 20480] — lax.top_k lowers to an efficient built-in selection
    while the in-kernel merge is VPU-sequential per tile). Off-TPU the
    kernel only runs interpreted (test/debug), never by default. A
    ``top_k`` beyond the kernel's output lane width falls back to XLA
    (explicit ``--pallas on`` still reports the hard limit instead).
    """
    from .pallas_score import _K_PAD

    return (backend == "tpu" and np.dtype(count_dtype).itemsize == 2
            and top_k <= _K_PAD)


def resolve_pallas_flag(use_pallas: str, count_dtype, top_k: int) -> bool:
    """Resolve an ``auto|on|off`` --pallas request for a DENSE scorer
    (single-chip or sharded): the measured :func:`pallas_auto` rule,
    with the top-k-overflow fallback warned rather than silent."""
    if use_pallas == "auto":
        backend = jax.default_backend()
        on = pallas_auto(count_dtype, backend, top_k)
        if not on and pallas_auto(count_dtype, backend):
            import logging

            from .pallas_score import _K_PAD

            logging.getLogger("tpu_cooccurrence").warning(
                "--top-k %d exceeds the fused kernel's %d-lane output; "
                "falling back to the XLA scorer, which is much slower "
                "at int16 counts (measured 247x, TPU_ROUND2.jsonl)",
                top_k, _K_PAD)
        return on
    if use_pallas in ("on", "off"):
        return use_pallas == "on"
    raise ValueError(f"use_pallas must be auto|on|off, got {use_pallas!r}")


def resolve_fused_flag(fused_window: str) -> bool:
    """Resolve an ``auto|on|off`` --fused-window request.

    ``auto`` is the on-chip gate: the fused one-dispatch window only
    engages on a real TPU, where per-window dispatch count and uplink
    bytes are wall-clock (the tunneled link's measured regime,
    TPU_ROUND2.jsonl). Off-TPU the expansion kernel would run
    interpreted — a debug path, not a fast path — so the CPU fallback
    stays on the chained scatter+score pipeline ('on' still forces it
    for parity tests). Default 'off' until the on-chip A/B lands a
    measured win in bench_history.jsonl.
    """
    if fused_window not in ("auto", "on", "off"):
        raise ValueError(
            f"fused_window must be auto|on|off, got {fused_window!r}")
    if fused_window == "auto":
        return jax.default_backend() == "tpu"
    return fused_window == "on"


def score_row_budget(num_items: int, cap: int) -> int:
    """Rows per score call keeping the [S, I] working set ≲ 1 GB int32.

    Larger chunks amortize per-dispatch overhead (each call re-reads
    ``row_sums`` and re-launches gather+LLR+top_k); the transient
    [S, I] int32 gather plus [S, I] float32 scores stay well under the
    16 GB HBM of one chip even at the 1 GB budget.
    """
    budget_rows = max(64, (1 << 28) // max(num_items, 1))
    return min(cap, 1 << (budget_rows.bit_length() - 1))


def fit_count_dtype(arr, dtype: np.dtype) -> np.ndarray:
    """Cast checkpointed counts to a scorer's dtype.

    Widening is always safe (no scan); narrowing (int32 checkpoint ->
    int16 run) scans for out-of-range values instead of silently wrapping.
    """
    arr = np.asarray(arr)
    if arr.dtype == dtype:
        return arr
    if not np.can_cast(arr.dtype, dtype, casting="safe"):
        info = np.iinfo(dtype)
        if arr.size and (arr.min() < info.min or arr.max() > info.max):
            raise ValueError(
                f"checkpoint counts exceed {np.dtype(dtype).name} range — "
                f"restore with --count-dtype {arr.dtype.name}")
    return arr.astype(dtype)


def _apply_coo(C, row_sums, src, dst, delta, num_items: int):
    # C may be int16 (reference-style short counts, --count-dtype int16 —
    # halves HBM so the dense backend reaches ~90k-item vocabularies; cell
    # wraparound then matches the reference's documented silent-overflow
    # behavior, ItemRowAggregator.java:16). Row sums stay int32 always:
    # they grow far past 2^15.
    C = C.at[src, dst].add(delta.astype(C.dtype))
    rs_delta = jnp.zeros((num_items,), dtype=jnp.int32).at[src].add(delta)
    return C, row_sums + rs_delta


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1), static_argnames=("num_items",))
def _update(C, row_sums, src, dst, delta, num_items: int):
    return _apply_coo(C, row_sums, src, dst, delta, num_items)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1), static_argnames=("num_items",))
def _update_coo(C, row_sums, coo, num_items: int):
    """Scatter-apply a packed ``[3, N]`` (src, dst, delta) COO block.

    Packing the three arrays into one host buffer costs one host->device
    transfer instead of three — the tunneled single-chip link is
    latency-bound, so transfer count matters as much as bytes.
    """
    return _apply_coo(C, row_sums, coo[0], coo[1], coo[2], num_items)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1), static_argnames=("num_items",))
def _update_coo_u16(C, row_sums, coo, num_items: int):
    """Scatter-apply a packed ``[3, N]`` uint16 COO block (half the bytes).

    Used only when the vocab fits 2^16 (the caller checks ``num_items`` —
    int16-count runs can exceed that, and then ship int32 blocks); deltas
    ride as uint16 two's complement and are sign-extended here. The caller
    also falls back to the int32 block when a window's aggregated cell
    delta leaves int16 range.
    """
    src = coo[0].astype(jnp.int32)
    dst = coo[1].astype(jnp.int32)
    delta = coo[2].astype(jnp.int16).astype(jnp.int32)  # sign-extend
    return _apply_coo(C, row_sums, src, dst, delta, num_items)


def upload_chunks() -> int:
    """How many pieces to split per-window packed uploads into.

    The tunneled chip's host->device transfer cost is non-linear in
    size (measured 2026-07-31 on-chip: 256 KB = 0.3 ms ~ 850 MB/s,
    1 MB = 11.6 ms ~ 86 MB/s — a per-transfer threshold in between);
    K separate smaller arguments of one jitted call may ride under the
    cliff. Default 1 (monolithic) until the on-chip A/Bs (tpu_round2
    ``config4-chunked``, tunnel_probe 3b) prove the split wins on real
    hardware. Shared by the sparse update and dense COO paths."""
    try:
        return max(1, int(tuning.env_read("TPU_COOC_UPLOAD_CHUNKS", "1")))
    except ValueError:
        return 1


_split_declined_warned = False


def split_upload(arr: np.ndarray, k: int) -> Optional[Tuple]:
    """``arr`` ([rows, N]) as k contiguous column-range pieces, or None
    when splitting is off / not worthwhile (tiny windows) / uneven.

    A requested-but-declined split warns once: an operator A/B-testing
    chunking on scarce grant time must not silently measure the
    monolithic path (padded widths are pow2/pow4, so e.g. K=3 never
    divides and would never engage)."""
    if k <= 1 or arr.shape[1] % k or arr.shape[1] // k < 1024:
        global _split_declined_warned
        if k > 1 and not _split_declined_warned:
            _split_declined_warned = True
            logging.getLogger("tpu_cooccurrence").warning(
                "TPU_COOC_UPLOAD_CHUNKS=%d requested but a width-%d "
                "upload cannot split evenly into >=1024-column chunks; "
                "monolithic upload used for such windows (use a power "
                "of two that divides the padded width)", k, arr.shape[1])
        return None
    return tuple(np.ascontiguousarray(p) for p in np.split(arr, k, axis=1))


def upload_chunk_kb() -> float:
    """Byte target per upload piece (0 = off). The adaptive form of the
    chunk policy: where TPU_COOC_UPLOAD_CHUNKS fixes K for every
    window, TPU_COOC_UPLOAD_CHUNK_KB picks the smallest power-of-two K
    per upload that brings each piece under the target — window sizes
    are data-dependent (pow2/pow4 ladders), so a fixed K leaves big
    windows above the measured per-transfer cliff (e.g. 3 MB / 4 =
    750 KB pieces). This is the shape the TPU default takes if the
    on-chip A/B proves chunking."""
    try:
        return float(tuning.env_read("TPU_COOC_UPLOAD_CHUNK_KB", "0"))
    except ValueError:
        return 0.0


def split_upload_auto(arr: np.ndarray) -> Optional[Tuple]:
    """Pieces for this upload per the env policy, or None (monolithic).

    A SET TPU_COOC_UPLOAD_CHUNKS wins outright — including =1, which
    pins the monolithic arm of an A/B against an ambient CHUNK_KB (the
    same silent-contamination hazard _config4_single pins against).
    Otherwise TPU_COOC_UPLOAD_CHUNK_KB adapts K to the buffer size."""
    if tuning.env_read("TPU_COOC_UPLOAD_CHUNKS"):
        return split_upload(arr, upload_chunks())
    kb = upload_chunk_kb()
    if kb <= 0:
        return None
    cols = arr.shape[1]
    k = 1
    while (arr.nbytes / k > kb * 1024 and cols % (2 * k) == 0
           and cols // (2 * k) >= 1024):
        k *= 2
    return split_upload(arr, k) if k > 1 else None


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1), static_argnames=("num_items",))
def _update_coo_chunked(C, row_sums, coo_parts, num_items: int):
    """_update_coo with the block arriving as K separate transfers;
    the concatenate is device-side and fuses away."""
    coo = jnp.concatenate(coo_parts, axis=1)
    return _apply_coo(C, row_sums, coo[0], coo[1], coo[2], num_items)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1), static_argnames=("num_items",))
def _update_coo_u16_chunked(C, row_sums, coo_parts, num_items: int):
    coo = jnp.concatenate(coo_parts, axis=1)
    src = coo[0].astype(jnp.int32)
    dst = coo[1].astype(jnp.int32)
    delta = coo[2].astype(jnp.int16).astype(jnp.int32)  # sign-extend
    return _apply_coo(C, row_sums, src, dst, delta, num_items)


@functools.partial(jax.jit, static_argnames=("n",))
def _grow_dense(C, row_sums, n: int):
    """Re-allocate the dense state to an ``n x n`` capacity (auto-derive)."""
    old = C.shape[0]
    newC = jnp.zeros((n, n), C.dtype).at[:old, :old].set(C)
    new_rs = jnp.zeros((n,), row_sums.dtype).at[:old].set(row_sums)
    return newC, new_rs


def topk_padded(scores, top_k: int):
    """``lax.top_k`` tolerating vocabularies SMALLER than K: the missing
    lanes pad with (-inf, 0), which every consumer already filters (the
    reference's heap simply holds fewer entries in this regime)."""
    k_eff = min(top_k, scores.shape[-1])
    vals, idx = jax.lax.top_k(scores, k_eff)
    if k_eff < top_k:
        pad = top_k - k_eff
        vals = jnp.concatenate(
            [vals, jnp.full(vals.shape[:-1] + (pad,), -jnp.inf,
                            vals.dtype)], axis=-1)
        idx = jnp.concatenate(
            [idx, jnp.zeros(idx.shape[:-1] + (pad,), idx.dtype)], axis=-1)
    return vals, idx


def _score_body(C, row_sums, rows, observed, top_k: int,
                packed: bool = False):
    # Shared between the chained `_score` jit and the fused window
    # program (`_fused_window_emit`/`_defer`): one body, so the two
    # dispatch shapes cannot drift numerically — the fused path's
    # bit-identical-to-chained contract rides on this.
    counts = C[rows]  # [S, I] int32
    k11 = counts.astype(jnp.float32)
    rs = row_sums.astype(jnp.float32)
    rsi = rs[rows][:, None]
    rsj = rs[None, :]
    k12 = rsi - k11
    k21 = rsj - k11
    k22 = observed + k11 - k12 - k21
    scores = llr_stable(k11, k12, k21, k22)
    scores = jnp.where(counts != 0, scores, -jnp.inf)
    vals, idx = topk_padded(scores, top_k)
    if packed:
        # One fused [2, S, K] float32 result => a single device->host fetch.
        return jnp.stack([vals, jax.lax.bitcast_convert_type(idx, jnp.float32)])
    return vals, idx


_score = functools.partial(jax.jit, static_argnames=("top_k", "packed"))(
    _score_body)


def _fused_apply_baskets(C, row_sums, block, num_items: int,
                         basket_width: int, interpret: bool):
    """Expansion + scatter half of the fused window program.

    ``block`` is the single packed ``[N, W + 4]`` int32 uplink: the
    basket rectangle plus the (new, len, skip, sign) meta columns. The
    expansion runs in the Pallas kernel
    (``pallas_score.pallas_expand_baskets``); the scatter-add stays an
    XLA op inside the same program — Mosaic cannot scatter to arbitrary
    HBM rows, the same boundary that keeps the dense score kernel's
    ``C[rows]`` gather in XLA. Invalid/padded lanes carry (0, 0, 0):
    the scatter no-op triple, so no masking is needed here.
    """
    from .pallas_score import pallas_expand_baskets

    w = basket_width
    basket = block[:, :w]
    new = block[:, w:w + 1]
    lens = block[:, w + 1:w + 2]
    skips = block[:, w + 2:w + 3]
    signs = block[:, w + 3:w + 4]
    src, dst, delta = pallas_expand_baskets(basket, new, lens, skips, signs,
                                            interpret=interpret)
    return _apply_coo(C, row_sums, src.reshape(-1), dst.reshape(-1),
                      delta.reshape(-1), num_items)


def _fused_score_packed(C, row_sums, rows, observed, top_k: int,
                        use_pallas: bool, tile: int, interpret: bool):
    """Score half of the fused program: the SAME math as the chained
    path — ``_score_body`` when the Pallas score kernel is off, the
    shared ``_pallas_topk_gathered`` core when it is on — so fused and
    chained results are bitwise equal, not just close."""
    if not use_pallas:
        return _score_body(C, row_sums, rows, observed, top_k, packed=True)
    from .pallas_score import _pallas_topk_gathered, row_block

    blk = row_block(C.dtype)
    sp = rows.shape[0]  # caller pads to a pow4 bucket (a blk multiple)
    gathered = C[rows]
    rsi = row_sums[rows].reshape(sp, 1)
    rs2d = row_sums.reshape(1, C.shape[0])
    vals, idx = _pallas_topk_gathered(gathered, rs2d, rsi, observed,
                                      top_k=top_k, tile=tile, blk=blk,
                                      interpret=interpret)
    # Value-space id packing, exactly like pallas_score_topk(packed=True).
    return jnp.stack([vals[:, :top_k], idx[:, :top_k]])


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1),
                   static_argnames=("num_items", "basket_width", "top_k",
                                    "use_pallas", "tile", "interpret"))
def _fused_window_emit(C, row_sums, block, rows, observed, *, num_items: int,
                       basket_width: int, top_k: int, use_pallas: bool,
                       tile: int, interpret: bool):
    """ONE-dispatch fused window (streaming-results form): on-chip
    basket expansion + count scatter + row-sum maintenance + LLR rescore
    + per-row top-K, one XLA program per (ops-bucket, basket-bucket,
    rows-bucket) shape triple. Replaces the chained path's separate
    update and score dispatches and its 3x-wider COO uplink."""
    C, row_sums = _fused_apply_baskets(C, row_sums, block, num_items,
                                       basket_width, interpret)
    packed = _fused_score_packed(C, row_sums, rows, observed, top_k,
                                 use_pallas, tile, interpret)
    return C, row_sums, packed


@functools.partial(jax.jit, donate_argnums=donate_argnums(0, 1, 2),
                   static_argnames=("num_items", "basket_width", "top_k",
                                    "use_pallas", "tile", "interpret"))
def _fused_window_defer(C, row_sums, tbl, block, rows, scatter_rows,
                        observed, *, num_items: int, basket_width: int,
                        top_k: int, use_pallas: bool, tile: int,
                        interpret: bool):
    """Deferred-results form of :func:`_fused_window_emit`: the packed
    top-K scatters into the device-resident results table inside the
    same program — a steady-state window is literally one dispatch and
    zero result downlink. Padded score rows carry the ``_SENT_ROW``
    sentinel and drop out of the scatter."""
    C, row_sums = _fused_apply_baskets(C, row_sums, block, num_items,
                                       basket_width, interpret)
    packed = _fused_score_packed(C, row_sums, rows, observed, top_k,
                                 use_pallas, tile, interpret)
    return C, row_sums, tbl.at[:, scatter_rows].set(packed, mode="drop")


def check_coo_chunk(coo: np.ndarray, n: int) -> None:
    """Pad-slot invariant guard for packed COO chunks (regression).

    The chained path's correctness under padding rests on two facts: a
    chunk's ``n`` real entries fit its padded buffer (a chunk larger
    than ``max_pairs_per_step``'s bucket must never silently truncate),
    and every pad slot carries the ``(0, 0) delta == 0`` triple whose
    scatter-add is a no-op. Both held by construction until someone
    reuses buffers; this check makes a violation an error at the
    window that caused it, not a silently-wrong count matrix. O(pad)
    over a buffer the caller just wrote — noise next to the fold.
    """
    if n > coo.shape[1]:
        raise AssertionError(
            f"COO chunk holds {n} entries but its padded buffer is only "
            f"{coo.shape[1]} wide — entries would be silently truncated")
    if n < coo.shape[1] and coo[:, n:].any():
        raise AssertionError(
            "COO pad slots must stay (0, 0) delta == 0: a nonzero pad "
            "slot would scatter garbage into C")


# Result-table scatter sentinel for padded score rows: >= any vocab
# capacity, dropped by mode="drop". Padded rows may not scatter under
# their gather stand-in (row 0) — that would overwrite item 0's entry
# with scores from a *later* matrix state than its last real emission.
_SENT_ROW = np.int32(2**31 - 1)


@functools.partial(jax.jit, donate_argnums=donate_argnums(0))
def _scatter_packed(tbl, packed, scatter_rows):
    return tbl.at[:, scatter_rows].set(packed, mode="drop")


@jax.jit
def _gather_packed(tbl, rows):
    return tbl[:, rows]


class DeferredResultsTable:
    """Device-resident latest-results table for deferred-results scorers.

    Final-state consumption mode (no ``--emit-updates``): each window's
    score dispatch scatters its packed ``[2, S_pad, K]`` top-K block into
    ``tbl`` (``[2, items_cap, K]`` float32 on device) instead of
    returning it to the host; :meth:`drain` fetches only the rows
    scattered since the last drain, in one exact-bytes gather. Per-window
    result downlink drops to zero — on a high-latency link the dominant
    wall cost of large windows. Shared by the dense and sparse scorers;
    the sparse scorer fuses the scatter into its scoring jit and so
    reassigns :attr:`tbl` directly (it is donated there).

    The caller owns — and must absorb — every drained row: rows fetched
    earlier persist in the job's ``LatestResults``, which keeps periodic
    checkpoints incremental (O(rows since last drain), not O(all rows)).
    """

    def __init__(self, top_k: int, items_cap: int) -> None:
        self.top_k = top_k
        self.tbl = None  # lazy: allocated at the first scoring dispatch
        self.dirty = np.zeros(items_cap, dtype=bool)

    def resize(self, items_cap: int) -> None:
        """Track a vocab-capacity change, preserving entries and marks."""
        m = min(items_cap, len(self.dirty))
        dirty = np.zeros(items_cap, dtype=bool)
        dirty[:m] = self.dirty[:m]
        self.dirty = dirty
        if self.tbl is not None and self.tbl.shape[1] != items_cap:
            old = self.tbl
            self.tbl = jnp.full((2, items_cap, self.top_k), -jnp.inf,
                                jnp.float32).at[:, :m].set(old[:, :m])

    def ensure(self) -> None:
        """Allocate the device table (before a window's first scatter)."""
        if self.tbl is None:
            self.tbl = jnp.full((2, len(self.dirty), self.top_k),
                                -jnp.inf, jnp.float32)

    def scatter(self, packed, scatter_rows: np.ndarray) -> None:
        """Scatter one packed block; padded entries must carry a sentinel
        index (``_SENT_ROW``), not their row-0 gather stand-in."""
        self.tbl = _scatter_packed(self.tbl, packed,
                                   jnp.asarray(scatter_rows))

    def mark(self, rows: np.ndarray) -> None:
        self.dirty[rows] = True

    def drain(self, float_ids: bool = False):
        """Fetch rows scored since the last drain as a :class:`TopKBatch`.

        ``float_ids``: ids were packed as float *values* (the Pallas
        kernel's encoding) rather than an int32 bitcast.
        """
        from ..state.results import TopKBatch

        rows = np.flatnonzero(self.dirty)
        if self.tbl is None or len(rows) == 0:
            return TopKBatch.empty(self.top_k)
        n = len(rows)
        rows_pad = np.zeros(pad_pow2(n, minimum=16), np.int32)
        rows_pad[:n] = rows
        LEDGER.up("drain-rows", rows_pad)
        host = np.asarray(_gather_packed(self.tbl, jnp.asarray(rows_pad)))
        LEDGER.down("results-drain", host)
        # Clear marks only once the host copy is in hand: a transient
        # fetch failure (tunneled links drop) must leave the rows dirty
        # so a retrying caller can still drain them.
        self.dirty[rows] = False
        idx = (host[1, :n].astype(np.int32) if float_ids
               else host[1, :n].view(np.int32))
        return TopKBatch(rows.astype(np.int32), idx, host[0, :n])

    def reset(self, items_cap: int) -> None:
        """Restart empty (restore path: pre-checkpoint rows already live
        in the job's LatestResults, flushed before every save)."""
        self.tbl = None
        self.dirty = np.zeros(items_cap, dtype=bool)


class DeviceScorer:
    """Dense sharless device backend over a fixed item-vocab capacity."""

    # Column-tile width for the fused kernel. Swept on-chip at the int16
    # max-vocab shape (TPU_ROUND2.jsonl pallas-bench, [8192, 61440]):
    # 2048 -> 179ms, 1024 -> 224ms, 512 -> 300ms — wider tiles amortize
    # the sequential top-K merge, and the (16, 2048) int16 block is still
    # far under VMEM.
    PALLAS_TILE = 2048

    def __init__(self, num_items: int, top_k: int,
                 counters: Optional[Counters] = None,
                 max_score_rows_per_call: int = 8192,
                 max_pairs_per_step: int = 1 << 20,
                 use_pallas: str = "auto",
                 count_dtype: str = "int32",
                 device=None,
                 defer_results: bool = False,
                 fused_window: str = "off") -> None:
        from ..xla_cache import enable_compilation_cache

        enable_compilation_cache()
        if count_dtype not in ("int32", "int16"):
            raise ValueError(f"count_dtype must be int32|int16, got {count_dtype}")
        self.count_dtype = np.dtype(count_dtype)
        self.top_k = top_k
        self.counters = counters if counters is not None else Counters()
        self._max_score_rows_cap = max_score_rows_per_call
        self.max_pairs_per_step = max_pairs_per_step
        self.use_pallas = resolve_pallas_flag(use_pallas, self.count_dtype,
                                              top_k)
        # Fused one-dispatch window path (--fused-window): the sampler
        # uplinks baskets instead of expanded COO and expansion + count
        # update + rescore + top-K run as one program per shape triple.
        # The job enables basket emission iff this resolved True.
        self.use_fused = resolve_fused_flag(fused_window)
        # Basket uplinks are the DENSE fused path's wire format (the
        # kernel expands them on chip); the sparse fused path consumes
        # aggregated deltas instead and leaves this False.
        self.wants_baskets = self.use_fused
        # Which path the LAST process_window dispatch took — the job's
        # fused-vs-chained wall-time split and journal field read it.
        self.last_dispatch_fused = False
        self._fused_dispatches = REGISTRY.gauge(
            "cooc_fused_dispatches_total",
            help="windows dispatched through the fused one-dispatch "
                 "window program")
        self._chained_dispatches = REGISTRY.gauge(
            "cooc_chained_dispatches_total",
            help="windows dispatched through the chained "
                 "scatter+score path")
        # Off-TPU the kernel can only run interpreted (test/debug use).
        self._pallas_interpret = jax.default_backend() != "tpu"
        # num_items == 0: derive the vocab from the data — start at a
        # modest capacity and double C whenever a window's max dense id
        # outgrows it (amortized O(final) copy work). An explicit
        # num_items stays a hard capacity (the job enforces it).
        self.auto_capacity = num_items <= 0
        if self.auto_capacity:
            num_items = pad_pow2(max(1 << 10, top_k))
        if self.use_pallas:
            # Pad the vocab so the Pallas column-tile grid divides evenly;
            # the extra columns stay zero and are masked out of scoring.
            self.num_items = ((num_items + self.PALLAS_TILE - 1)
                              // self.PALLAS_TILE) * self.PALLAS_TILE
        else:
            self.num_items = num_items
        self.num_items_logical = num_items
        # Bound each score call's [S, I] working set so vocab-ceiling
        # configurations don't OOM; the result-fetch pipeline hides the
        # extra per-chunk round trips.
        self.max_score_rows = score_row_budget(self.num_items,
                                               self._max_score_rows_cap)
        self.device = device
        num_items = self.num_items
        with jax.default_device(device) if device is not None else contextlib.nullcontext():
            self.C = jnp.zeros((num_items, num_items),
                               dtype=jnp.dtype(self.count_dtype.name))
            self.row_sums = jnp.zeros((num_items,), dtype=jnp.int32)
        self.observed = 0  # exact, host-side (int), fed to kernels as f32
        # Result pipeline: window results are fetched one window late so the
        # device->host copy (latency-bound on a tunneled chip) overlaps the
        # next window's host sampling and device dispatch. ``flush()``
        # returns the final in-flight window.
        self._pending: Optional[List] = None
        self.last_dispatched_rows = 0
        # scorer_breaker fault-site ordinal (robustness plane): counts
        # this scorer's process_window calls so chaos tests can fail a
        # specific dispatch and trip the circuit breaker wrapper.
        self._breaker_seq = 0
        # Deferred-results mode (final-state consumption, no streaming):
        # see DeferredResultsTable.
        self.defer_results = bool(defer_results)
        self._results = (DeferredResultsTable(top_k, self.num_items)
                         if self.defer_results else None)

    def _ensure_capacity(self, max_id: int) -> None:
        if max_id < self.num_items:
            return
        if not self.auto_capacity:
            raise ValueError(
                f"item id {max_id} exceeds --num-items capacity "
                f"{self.num_items_logical}")
        n = self.num_items
        while n <= max_id:
            n *= 2
        self.C, self.row_sums = _grow_dense(self.C, self.row_sums, n=n)
        self.num_items = self.num_items_logical = n
        self.max_score_rows = score_row_budget(n, self._max_score_rows_cap)
        if self._results is not None:
            self._results.resize(n)

    def process_window(self, ts: int, pairs) -> TopKBatch:
        self._breaker_seq += 1
        if faults.PLAN is not None:
            # The breaker's trip input: an injected exception here is a
            # failed device dispatch the ScorerCircuitBreaker absorbs.
            faults.PLAN.fire("scorer_breaker", seq=self._breaker_seq)
        self.last_dispatched_rows = 0
        self.last_dispatch_fused = False
        if isinstance(pairs, BasketBatch):
            if self.use_fused:
                routed = self._try_fused(ts, pairs)
                if routed is not None:
                    return routed
            # Not fused-routable (oversized window / kernel limit) or
            # fused resolved off: expand host-side and run the chained
            # path — the same pair multiset, so results are identical.
            pairs = pairs.to_pairs()
        if len(pairs) == 0:
            if self.defer_results:
                # Nothing in flight; results wait for the final flush.
                return TopKBatch.empty(self.top_k)
            # No new dispatch this window — drain any completed in-flight
            # results now instead of withholding them behind idle windows.
            return self.flush()
        self._ensure_capacity(int(max(pairs.src.max(), pairs.dst.max())))
        src, dst, agg_delta = aggregate_window_coo(
            pairs.src, pairs.dst, pairs.delta)
        agg_delta = narrow_deltas_int32(agg_delta)

        # Bounded COO buckets: chunk to max_pairs_per_step, pad each chunk to
        # a power of two (recompile guard, SURVEY §7 "dynamic shapes").
        # pow-2 (not the score path's pow-4): post-aggregation sizes sit in a
        # narrow steady-state band, so the finer ladder costs few extra
        # compiles (amortized by the on-disk XLA cache) and halves the
        # worst-case transfer+scatter padding. Padding slots scatter delta 0
        # at (0, 0) — a no-op. The chunk ships as one packed [3, N] buffer
        # (one transfer, not three).
        # uint16 wire format halves transfer bytes whenever the vocab and
        # the window's cell deltas allow it (the tunneled link runs at
        # ~140 MB/s on incompressible data, so bytes are wall-clock).
        use_u16 = (self.num_items <= (1 << 16)
                   and len(agg_delta) > 0
                   and int(agg_delta.min()) >= -(1 << 15)
                   and int(agg_delta.max()) < (1 << 15))
        for lo in range(0, len(src), self.max_pairs_per_step):
            n = min(len(src) - lo, self.max_pairs_per_step)
            pad = pad_pow2(n, minimum=1 << 14)
            if use_u16:
                coo = np.zeros((3, pad), dtype=np.uint16)
                coo[2, :n] = agg_delta[lo: lo + n].astype(
                    np.int16).view(np.uint16)
                update = _update_coo_u16
            else:
                coo = np.zeros((3, pad), dtype=np.int32)
                coo[2, :n] = agg_delta[lo: lo + n]
                update = _update_coo
            coo[0, :n] = src[lo: lo + n]
            coo[1, :n] = dst[lo: lo + n]
            check_coo_chunk(coo, n)
            parts = split_upload_auto(coo)
            if parts is not None:
                for p in parts:
                    LEDGER.up("coo-chunk", p)
                update_chunked = (_update_coo_u16_chunked if use_u16
                                  else _update_coo_chunked)
                self.C, self.row_sums = update_chunked(
                    self.C, self.row_sums, parts,
                    num_items=self.num_items)
            else:
                LEDGER.up("coo", coo)
                self.C, self.row_sums = update(
                    self.C, self.row_sums, coo, num_items=self.num_items)

        window_sum = int(pairs.delta.sum())
        self.observed += window_sum
        self.counters.add(ROW_SUM_PROCESS_WINDOW, window_sum)

        rows = distinct_sorted(src)
        self.counters.add(RESCORED_ITEMS, len(rows))
        self.last_dispatched_rows = len(rows)
        self._chained_dispatches.add(1)
        if self.defer_results:
            self._results.ensure()
        chunks: List[Tuple[np.ndarray, int, object]] = []
        for lo in range(0, len(rows), self.max_score_rows):
            chunk = rows[lo: lo + self.max_score_rows]
            s = len(chunk)
            pad_s = min(pad_pow4(s, minimum=64), self.max_score_rows)
            rows_padded = np.zeros(pad_s, dtype=np.int32)
            rows_padded[:s] = chunk
            LEDGER.up("score-rows", rows_padded)
            if self.use_pallas:
                from .pallas_score import pallas_score_topk

                packed = pallas_score_topk(
                    self.C, self.row_sums, jnp.asarray(rows_padded),
                    np.float32(self.observed), top_k=self.top_k,
                    tile=self.PALLAS_TILE, interpret=self._pallas_interpret,
                    packed=True)
            else:
                packed = _score(self.C, self.row_sums, rows_padded,
                                np.float32(self.observed), top_k=self.top_k,
                                packed=True)
            if self.defer_results:
                # Padded entries gather row 0 but must NOT scatter there.
                scatter_rows = np.full(pad_s, _SENT_ROW, dtype=np.int32)
                scatter_rows[:s] = chunk
                self._results.scatter(packed, scatter_rows)
                continue
            if hasattr(packed, "copy_to_host_async"):
                packed.copy_to_host_async()
            chunks.append((chunk, s, packed))
        if self.defer_results:
            self._results.mark(rows)
            return TopKBatch.empty(self.top_k)
        prev, self._pending = self._pending, chunks
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _try_fused(self, ts: int, b: BasketBatch) -> Optional[TopKBatch]:
        """Run one window through the fused one-dispatch program, or
        return ``None`` when the window is not fused-routable — the
        caller then expands host-side and takes the chained path, which
        produces identical results (same pair multiset, same score
        math). Not routable: zero-pair windows (the chained empty-window
        contract applies), windows whose padded expansion lanes exceed
        the ``max_pairs_per_step`` chunk budget, rescore sets beyond one
        score chunk, and configurations the Pallas score kernel itself
        rejects on the chained path (vocab > 2^24, K > lane width) —
        the chained path raises the canonical error for those.
        """
        per_op = b.pairs_per_op()
        n_pairs = int(per_op.sum())
        if n_pairs == 0:
            return None
        if self.use_pallas:
            from .pallas_score import _K_PAD

            if self.top_k > _K_PAD or self.num_items > (1 << 24):
                return None
        valid = b._valid()
        active = per_op > 0
        self._ensure_capacity(int(max(b.new_items[active].max(),
                                      b.baskets[valid].max())))
        n_ops = b.n_ops
        n_cap = pad_pow2(n_ops, minimum=64)
        l_cap = pad_pow2(max(int(b.baskets.shape[1]), 1), minimum=128)
        if 2 * n_cap * l_cap > self.max_pairs_per_step:
            # The expanded lanes would exceed the chained path's COO
            # chunk budget (HBM working-set bound): oversized windows
            # stay chained, where chunking already handles them.
            return None
        # Rescore set: every item touched by an emitted pair — the
        # union of active star items and valid basket cells, exactly
        # the chained path's distinct_sorted(src) set (np.unique sorts).
        rows = np.unique(np.concatenate([
            b.new_items[active].astype(np.int64),
            b.baskets[valid].astype(np.int64)])).astype(np.int32)
        if len(rows) > self.max_score_rows:
            return None

        # Single packed uplink: basket rectangle + 4 meta columns. Pad
        # ops carry (len 0, sign 0) — zero expanded lanes. Basket cells
        # beyond each op's len ride up unspecified and are masked
        # in-kernel, same contract as the sampler's storage.
        blockbuf = np.zeros((n_cap, l_cap + 4), dtype=np.int32)
        w = b.baskets.shape[1]
        if w:
            blockbuf[:n_ops, :w] = b.baskets
        blockbuf[:, l_cap + 2] = -1
        blockbuf[:n_ops, l_cap] = b.new_items
        blockbuf[:n_ops, l_cap + 1] = b.lens
        blockbuf[:n_ops, l_cap + 2] = b.skips
        blockbuf[:n_ops, l_cap + 3] = b.signs

        # Exact host-side observed tracking, identical to the chained
        # path's pairs.delta.sum(): each op contributes 2 * sign * pairs.
        window_sum = int((2 * b.signs.astype(np.int64) * per_op).sum())
        self.observed += window_sum
        self.counters.add(ROW_SUM_PROCESS_WINDOW, window_sum)
        self.counters.add(RESCORED_ITEMS, len(rows))
        self.last_dispatched_rows = len(rows)
        self.last_dispatch_fused = True
        self._fused_dispatches.add(1)

        s = len(rows)
        pad_s = min(pad_pow4(s, minimum=64), self.max_score_rows)
        rows_padded = np.zeros(pad_s, dtype=np.int32)
        rows_padded[:s] = rows
        observed = np.float32(self.observed)
        if self.defer_results:
            self._results.ensure()
            # Padded entries gather row 0 but must NOT scatter there.
            scatter_rows = np.full(pad_s, _SENT_ROW, dtype=np.int32)
            scatter_rows[:s] = rows
            LEDGER.up_basket("fused-window", blockbuf, rows_padded,
                             scatter_rows)
            self.C, self.row_sums, self._results.tbl = _fused_window_defer(
                self.C, self.row_sums, self._results.tbl, blockbuf,
                rows_padded, scatter_rows, observed,
                num_items=self.num_items, basket_width=l_cap,
                top_k=self.top_k, use_pallas=self.use_pallas,
                tile=self.PALLAS_TILE, interpret=self._pallas_interpret)
            self._results.mark(rows)
            return TopKBatch.empty(self.top_k)
        LEDGER.up_basket("fused-window", blockbuf, rows_padded)
        self.C, self.row_sums, packed = _fused_window_emit(
            self.C, self.row_sums, blockbuf, rows_padded, observed,
            num_items=self.num_items, basket_width=l_cap,
            top_k=self.top_k, use_pallas=self.use_pallas,
            tile=self.PALLAS_TILE, interpret=self._pallas_interpret)
        if hasattr(packed, "copy_to_host_async"):
            packed.copy_to_host_async()
        # Same one-window-behind result pipeline as the chained path.
        prev, self._pending = self._pending, [(rows, s, packed)]
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def flush(self) -> TopKBatch:
        """Emit the final in-flight window's results (end of pipeline).

        Deferred mode: drain rows scored since the last flush from the
        device table in one exact-bytes gather (the caller owns — and must
        absorb — the returned rows; see SparseDeviceScorer.flush)."""
        if self.defer_results:
            # Pallas packs ids as float values; XLA as an int32 bitcast.
            return self._results.drain(float_ids=self.use_pallas)
        prev, self._pending = self._pending, None
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _materialize(self, chunks) -> TopKBatch:
        rows_l, idx_l, vals_l = [], [], []
        for chunk, s, packed in chunks:
            host = np.asarray(packed)  # single [2, S, K] fetch
            LEDGER.down("results", host)
            rows_l.append(chunk)
            vals_l.append(host[0, :s])
            if self.use_pallas:
                # Pallas packs ids as float values (see pallas_score.py).
                idx_l.append(host[1, :s].astype(np.int32))
            else:
                idx_l.append(host[1, :s].view(np.int32))
        return TopKBatch.concatenate(rows_l, idx_l, vals_l, self.top_k)

    # -- checkpoint ------------------------------------------------------

    def checkpoint_state(self) -> dict:
        return {
            "C": np.asarray(self.C),
            "row_sums": np.asarray(self.row_sums),
            "observed": np.asarray([self.observed], dtype=np.int64),
        }

    def restore_state(self, st: dict) -> None:
        ck = fit_count_dtype(st["C"], self.count_dtype)
        if self.auto_capacity and ck.shape[0] > self.num_items:
            # Derived-capacity scorers adopt the checkpoint's size —
            # re-applying the Pallas tile rounding the constructor performs
            # (the checkpoint may come from a non-pallas run whose capacity
            # is not a tile multiple).
            n = ck.shape[0]
            if self.use_pallas:
                n = ((n + self.PALLAS_TILE - 1)
                     // self.PALLAS_TILE) * self.PALLAS_TILE
            self.num_items = self.num_items_logical = n
            self.max_score_rows = score_row_budget(self.num_items,
                                                   self._max_score_rows_cap)
        if ck.shape != (self.num_items, self.num_items):
            # Vocab padding differs between runs when the pallas setting
            # changes (the kernel pads to tile multiples). Both layouts hold
            # the same logical vocab, so translate: slice a larger padded
            # checkpoint / zero-extend a smaller one — after verifying no
            # live counts fall outside this scorer's capacity.
            n = ck.shape[0]
            if (n > self.num_items
                    and (ck[self.num_items:].any()
                         or ck[:, self.num_items:].any())):
                raise ValueError(
                    f"checkpoint C shape {ck.shape} holds counts beyond this "
                    f"scorer's capacity {self.num_items} — restore with "
                    f"--num-items >= the checkpointing run's")
            fitted = np.zeros((self.num_items, self.num_items),
                              dtype=self.count_dtype)
            m = min(n, self.num_items)
            fitted[:m, :m] = ck[:m, :m]
            ck = fitted
            rs = np.zeros((self.num_items,), dtype=np.int32)
            rs[:m] = np.asarray(st["row_sums"], dtype=np.int32)[:m]
        else:
            rs = np.asarray(st["row_sums"], dtype=np.int32)
        self.C = jnp.asarray(ck)
        self.row_sums = jnp.asarray(rs)
        self.observed = int(st["observed"][0])
        # In-flight results belong to windows after the checkpoint; a
        # restore that rolls back must not emit them.
        self._pending = None
        if self._results is not None:
            self._results.reset(self.num_items)
