"""Backend-gated buffer donation for the state-carrying jits.

Donation (``jax.jit(donate_argnums=...)``) is what lets every scorer
update its device-resident state (dense ``C``, the sparse slab, the
deferred-results table) in place: without it XLA allocates a fresh output
buffer and copies — at the 1M-item shapes that is gigabytes of HBM traffic
per window.

On the **CPU backend** donation is disabled here, deliberately. The
jaxlib 0.4.36 TFRT CPU runtime has a donation/async-dispatch race: a
donating dispatch can acquire a buffer that an earlier, still-executing
computation is reading, which surfaces as ``Check failed:
pending_donation_`` (abstract_tfrt_cpu_buffer.cc) or — worse — as silent
glibc heap corruption ("corrupted double-linked list" at some later
``free``). Reproduced deterministically by the checkpoint/restore tests:
after a restore the jit cache is warm, so back-to-back windows dispatch
fast enough to race the in-flight score reads of the just-donated count
matrix. The copy this costs on CPU is host-memory bandwidth — real but
bounded — where the race is a crash; accelerator backends keep full
donation (their PJRT clients sequence donation against pending reads
correctly).

``TPU_COOC_DONATE=0|1`` overrides for A/B measurement; unset = the
backend rule above.
"""

from __future__ import annotations

import os
from typing import Tuple


from .. import tuning

def donate_argnums(*argnums: int) -> Tuple[int, ...]:
    """``argnums`` on accelerator backends, ``()`` on CPU (see module doc).

    Evaluated at decoration time (module import), which for every scorer
    module happens lazily inside the job's backend factory. The
    ``jax.default_backend()`` probe initializes the local backend, so
    import order matters for multi-host: ``job._make_scorer`` runs
    ``jax.distributed.initialize`` (via ``maybe_multihost_mesh``)
    *before* importing any scorer module — a scorer import that
    initialized the backend first would make distributed init raise.
    """
    env = tuning.env_read("TPU_COOC_DONATE", "").strip()
    if env in ("0", "off", "false", "no"):
        return ()
    if env in ("1", "on", "true", "yes"):
        return tuple(argnums)
    import jax

    return tuple(argnums) if jax.default_backend() != "cpu" else ()
