"""Run journal: append-only JSONL flight recorder, one record per window.

The reference's only run artifact is the end-of-job accumulator dump
(``FlinkCooccurrences.java:173-181``); a crashed Flink job leaves its
state to the JobManager. This standalone build's supervisor
(``supervisor.py``) discards a crashed attempt's spooled stdout by
design (exactly-once output), which previously meant a crash discarded
*every* in-flight signal. The journal is the flight recorder that
survives: each fired window appends one self-contained JSON line,
flushed immediately, so after a SIGKILL the file's tail is the last
fired window and the supervisor can quote it in the restart log.

Record schema (:data:`SCHEMA`): logical fields (``seq``, ``ts``,
``events``, ``pairs``, ``rows_scored``, counter deltas) are identical
between serial and pipelined execution (pinned by
``tests/test_observability.py``); timing/occupancy fields
(``*_seconds``, ``ring_depth``, ``wall_unix``) are run-specific.
Counter deltas in pipelined mode are attributed to the window the
scorer worker just finished — sampling-side counters for the window the
producer is concurrently sampling may land one record later, so the
parity contract covers logical fields only.

Readers (:func:`read_records`, :func:`tail`) tolerate a truncated final
line — the expected shape of a file whose writer was SIGKILLed mid-
``write`` — and skip it rather than failing the whole read.

Besides window records, the file may carry out-of-band **event
records** (:data:`EVENT_SCHEMA`, distinguished by an ``"event"`` key):
today the degradation plane's admission-side level transitions, which
must reach disk even when no window ever completes again. Checkpoint
commits append **checkpoint records** (:data:`CKPT_SCHEMA`,
distinguished by a ``"checkpoint"`` key): per-generation commit bytes /
seconds / full-vs-delta kind / chain depth — the incremental plane's
cost trajectory. Serving replicas (``serving/replica.py``) append
**replica records** (:data:`REPLICA_SCHEMA`, distinguished by a
``"replica"`` key): one per delta generation replayed — the replica's
own flight record of its catch-up trajectory (generation, rows
replayed, lag behind the writer, resync count).

**Correlation fields (the tracing plane).** Every record type carries
the same optional trio — ``run_id`` (minted once by the supervising
parent or the CLI and inherited by every child process and restart
attempt through :data:`RUN_ID_ENV`), ``process_id`` (gang/fleet slot)
and ``attempt`` (supervisor restart ordinal, :data:`ATTEMPT_ENV`) — so
``cooc-trace`` (:mod:`.trace`) can merge a fleet's journals into one
timeline and stitch pre-crash records to their post-restart successors.
Window and replica records additionally carry ``spans``: ordered
``[stage, start_offset_s, seconds]`` tuples (:data:`SPAN_STAGES` /
:data:`REPLICA_SPAN_STAGES`) formalizing the stage-seconds breakdown.
The core window stages (``ingest-admission`` → ``sample`` →
``uplink-encode`` → ``dispatch`` → ``rescore``) partition
``sample_seconds + score_seconds`` exactly; the boundary stages
(``snapshot-publish``, ``checkpoint-commit``) run after the record is
flushed, so they are journaled on the first record *after* the boundary
work ran and excluded from the wall-seconds reconciliation.
"""

from __future__ import annotations

import io
import json
import os
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Tuple

from ..robustness import faults
from .. import tuning

#: Journal format version (bump on breaking schema changes).
VERSION = 1

#: Env var carrying the fleet-wide run id: minted once by whichever
#: process is the root of the tree (gang supervisor, single-process
#: supervisor, replica-fleet supervisor, or an unsupervised CLI job)
#: and inherited by every child so one run's journals join on it.
RUN_ID_ENV = "TPU_COOC_RUN_ID"

#: Env var carrying the supervisor restart ordinal (0 = first attempt).
#: Threaded through both supervisors so a restart's journal records
#: link to the prior attempt's instead of starting an unrelated stream.
ATTEMPT_ENV = "TPU_COOC_ATTEMPT"

#: Canonical window-record span stages, in lifecycle order. The first
#: five partition ``sample_seconds + score_seconds`` exactly; the last
#: two are boundary stages measured after the record flushes (journaled
#: on the NEXT record, excluded from wall-seconds reconciliation).
SPAN_STAGES = ("ingest-admission", "sample", "uplink-encode", "dispatch",
               "rescore", "snapshot-publish", "checkpoint-commit")

#: Replica-record span stages: replay one delta generation, then swap
#: the snapshot — the window's lifetime across the process boundary.
REPLICA_SPAN_STAGES = ("delta-apply", "publish")

#: Correlation trio shared by every record type (all optional: journals
#: written before the tracing plane stay valid).
_CORRELATION_FIELDS = {
    "run_id": (False, str),      # fleet-wide run id (RUN_ID_ENV)
    "process_id": (False, int),  # gang/fleet slot (0 single-process)
    "attempt": (False, int),     # supervisor restart ordinal
}


def mint_run_id() -> str:
    """A fresh run id (12 hex chars — short enough to read in a log
    line, random enough that two fleets over one state dir never
    collide)."""
    return uuid.uuid4().hex[:12]


def run_context() -> Tuple[str, int]:
    """(run_id, attempt) for this process: inherited from the
    supervising parent's env when present, otherwise a fresh mint with
    attempt 0 (the unsupervised-run shape)."""
    run_id = tuning.env_read(RUN_ID_ENV) or mint_run_id()
    try:
        attempt = int(tuning.env_read(ATTEMPT_ENV, "0"))
    except ValueError:
        attempt = 0
    return run_id, attempt

#: Field name -> (required, type). ``counters`` / ``wire`` hold per-window
#: deltas (not totals); empty deltas are omitted from ``counters``.
SCHEMA = {
    "v": (True, int),            # format version
    "seq": (True, int),          # 1-based fired-window ordinal (resumes
                                 # from the restored count after a restart)
    "ts": (True, int),           # window timestamp (stream time, ms)
    "events": (True, int),       # events in the fired window
    "pairs": (True, int),        # raw (pre-fold) pair deltas sampled
    "rows_scored": (True, int),  # rows dispatched to the scorer
    "sample_seconds": (True, float),
    "score_seconds": (True, float),
    "ring_depth": (True, int),   # staged windows in flight at dequeue
                                 # (0 on the serial path)
    "stall_seconds": (True, float),  # producer wait for a staging slot
    "wall_unix": (True, float),  # host wall clock at record time
    "counters": (True, dict),    # counter name -> delta since last record
    "wire": (True, dict),        # TransferLedger delta: h2d/d2h bytes+calls
    # Degradation plane (robustness/degrade.py, --degrade): present only
    # while a controller / scorer breaker is attached.
    "degradation_level": (False, int),   # level in force after this
                                         # window's observation
    "degrade_events": (False, list),     # transition event tokens this
                                         # window's observation applied
    "breaker_state": (False, str),       # scorer circuit breaker state
                                         # (closed | half_open | open)
    "fused": (False, int),               # 1 = this window took the fused
                                         # one-dispatch path, 0 = chained
                                         # (present for backends that
                                         # expose the dispatch split)
    "fused_compiles": (False, int),      # cumulative distinct fused-
                                         # program shapes (= XLA
                                         # compiles) when this record
                                         # was written — a seam or new
                                         # bucket steps this series
    "fallback_reason": (False, str),     # why a chained (fused: 0)
                                         # window fell back, when the
                                         # backend names it — one of the
                                         # ARCHITECTURE fallback-table
                                         # reasons (sharded sparse)
    # Serving plane (serving/, --serve-port): snapshot double-buffer
    # bookkeeping — the generation and live row count queries saw while
    # this window computed (the window's own swap lands right after).
    "snapshot_generation": (False, int),
    "snapshot_rows": (False, int),
    # Gang plane (robustness/gang.py, multi-host runs only): the newest
    # checkpoint epoch this process had committed when the record was
    # written — restart forensics show which epoch the gang resumed
    # from.
    "epoch": (False, int),
    # Ingest plane (io/partitioned.py, --source-format partitioned):
    # per-partition wire position when this window fired — the journal
    # side of the exactly-once contract (the restored checkpoint's
    # ingest_offsets section must match the last committed window's).
    "ingest_offsets": (False, dict),  # partition -> {byte_offset,
                                      # records} at window fire
    "ingest_lag": (False, dict),      # partition -> unread bytes on
                                      # disk at window fire
    # Tracing plane (this module + trace.py): fleet-wide correlation
    # trio, uniform across every record type.
    "run_id": (False, str),      # fleet run id (RUN_ID_ENV)
    "process_id": (False, int),  # gang/fleet slot (0 single-process)
    "attempt": (False, int),     # supervisor restart ordinal
    "spans": (False, list),      # ordered [stage, start_offset_s,
                                 # seconds] tuples (SPAN_STAGES)
}


#: Out-of-band event record (no window attached): ``{"v", "event",
#: "wall_unix"}``. Today's only producer is the degradation plane's
#: admission-side escalation (robustness/degrade.py), which must journal
#: a transition even when no window ever completes again.
EVENT_SCHEMA = {
    "v": (True, int),
    "event": (True, str),
    "wall_unix": (True, float),
    "window_seq": (False, int),  # fired-window ordinal at emit time
    "run_id": (False, str),
    "process_id": (False, int),
    "attempt": (False, int),
}


#: Out-of-band checkpoint record (distinguished by the ``"checkpoint"``
#: key = generation number): one per commit, written by
#: ``job.checkpoint`` from ``state/checkpoint.LAST_COMMIT``. The
#: commit-cost trajectory (``bytes``, ``seconds``, full-vs-delta
#: ``kind``, delta ``chain_len``) is the operator's view of what
#: ``--checkpoint-incremental`` is buying per generation.
CKPT_SCHEMA = {
    "v": (True, int),
    "checkpoint": (True, int),   # generation number committed
    "kind": (True, str),         # "full" | "delta"
    "bytes": (True, int),        # npz + delta file bytes committed
    "seconds": (True, float),    # commit wall seconds
    "chain_len": (True, int),    # delta generations behind this one
    "wall_unix": (True, float),
    "window_seq": (False, int),  # fired-window ordinal at commit — the
                                 # window→generation join cooc-trace
                                 # uses for freshness
    "generation": (False, int),  # uniform join-key alias of
                                 # "checkpoint" (same value)
    "run_id": (False, str),
    "process_id": (False, int),
    "attempt": (False, int),
}


#: Out-of-band autoscale record (distinguished by the ``"autoscale"``
#: key = the decision, ``"grow"`` or ``"shrink"``): one per rescale
#: drain, written by the job at the gang-voted drain boundary just
#: before its voluntary exit (robustness/autoscale.py). The from/to
#: topology, the trigger signal and the policy cooldown armed by the
#: decision make the journal the flight-recorder proof that the gang
#: scaled BEFORE the ladder shed.
AUTOSCALE_SCHEMA = {
    "v": (True, int),
    "autoscale": (True, str),    # decision: "grow" | "shrink"
    "from": (True, int),         # workers before the rescale
    "to": (True, int),           # target workers after it
    "trigger": (True, str),      # "pressure" | "idle"
    "window": (True, int),       # fired-window ordinal of the drain
    "cooldown": (True, int),     # policy cooldown windows armed
    "wall_unix": (True, float),
    "run_id": (False, str),
    "process_id": (False, int),
    "attempt": (False, int),
}


#: Out-of-band replica record (distinguished by the ``"replica"`` key =
#: the delta-log generation just replayed): one per applied delta
#: generation, written by ``serving/replica.ReadReplica``. ``rows`` is
#: the snapshot's live row count after the publish, ``topk_rows`` the
#: top-K rows this generation replayed, ``lag`` the writer generations
#: still unconsumed at record time, ``resyncs`` the checkpoint-resync
#: count so far (DeltaCorrupt fallbacks).
REPLICA_SCHEMA = {
    "v": (True, int),
    "replica": (True, int),      # delta-log generation replayed
    "rows": (True, int),         # snapshot live rows after publish
    "topk_rows": (True, int),    # top-K rows replayed this generation
    "lag": (True, int),          # newest on-disk generation - replayed
    "resyncs": (True, int),      # checkpoint resyncs so far
    "wall_unix": (True, float),
    "generation": (False, int),  # uniform join-key alias of "replica"
                                 # (same value)
    "run_id": (False, str),
    "process_id": (False, int),
    "attempt": (False, int),
    "spans": (False, list),      # [stage, start_offset_s, seconds]
                                 # tuples (REPLICA_SPAN_STAGES)
}


def _validate_spans(spans: list, stages: tuple, rec: dict) -> None:
    """Spans are ordered ``[stage, start_offset_s, seconds]`` triples
    whose stages come from the canonical table and appear in table
    order (a stage may be absent, never out of order)."""
    last_idx = -1
    for span in spans:
        if (not isinstance(span, (list, tuple)) or len(span) != 3
                or not isinstance(span[0], str)
                or any(isinstance(x, bool)
                       or not isinstance(x, (int, float))
                       for x in span[1:])):
            raise ValueError(
                f"journal span {span!r} is not [stage, start_offset_s, "
                f"seconds]: {rec}")
        if span[0] not in stages:
            raise ValueError(
                f"journal span stage {span[0]!r} not in {stages}: {rec}")
        idx = stages.index(span[0])
        if idx <= last_idx:
            raise ValueError(
                f"journal span stage {span[0]!r} out of order "
                f"(canonical order {stages}): {rec}")
        last_idx = idx


def validate_record(rec: dict) -> None:
    """Raise ``ValueError`` unless ``rec`` matches :data:`SCHEMA` (window
    records) or one of the out-of-band schemas (:data:`EVENT_SCHEMA`,
    :data:`CKPT_SCHEMA`, :data:`REPLICA_SCHEMA`)."""
    if not isinstance(rec, dict):
        raise ValueError(f"journal record is not an object: {rec!r}")
    if "autoscale" in rec:
        for field, (required, typ) in AUTOSCALE_SCHEMA.items():
            v = rec.get(field)
            ok = (isinstance(v, (int, float)) if typ is float
                  else isinstance(v, typ)) and not isinstance(v, bool)
            if required and not ok:
                raise ValueError(
                    f"journal autoscale record field {field!r} bad: {rec}")
        unknown = set(rec) - set(AUTOSCALE_SCHEMA)
        if unknown:
            raise ValueError(
                f"journal autoscale record has unknown fields "
                f"{unknown}: {rec}")
        if rec["v"] != VERSION:
            raise ValueError(f"journal version {rec['v']} != {VERSION}")
        if rec["autoscale"] not in ("grow", "shrink"):
            raise ValueError(
                f"journal autoscale decision {rec['autoscale']!r} "
                f"must be grow|shrink")
        if rec["trigger"] not in ("pressure", "idle"):
            raise ValueError(
                f"journal autoscale trigger {rec['trigger']!r} "
                f"must be pressure|idle")
        return
    if "replica" in rec:
        for field, (required, typ) in REPLICA_SCHEMA.items():
            v = rec.get(field)
            ok = (isinstance(v, (int, float)) if typ is float
                  else isinstance(v, typ)) and not isinstance(v, bool)
            if required and not ok:
                raise ValueError(
                    f"journal replica record field {field!r} bad: {rec}")
        unknown = set(rec) - set(REPLICA_SCHEMA)
        if unknown:
            raise ValueError(
                f"journal replica record has unknown fields "
                f"{unknown}: {rec}")
        if rec["v"] != VERSION:
            raise ValueError(f"journal version {rec['v']} != {VERSION}")
        if "spans" in rec:
            _validate_spans(rec["spans"], REPLICA_SPAN_STAGES, rec)
        return
    if "checkpoint" in rec:
        for field, (required, typ) in CKPT_SCHEMA.items():
            v = rec.get(field)
            ok = (isinstance(v, (int, float)) if typ is float
                  else isinstance(v, typ)) and not isinstance(v, bool)
            if required and not ok:
                raise ValueError(
                    f"journal checkpoint record field {field!r} bad: {rec}")
        unknown = set(rec) - set(CKPT_SCHEMA)
        if unknown:
            raise ValueError(
                f"journal checkpoint record has unknown fields "
                f"{unknown}: {rec}")
        if rec["v"] != VERSION:
            raise ValueError(f"journal version {rec['v']} != {VERSION}")
        if rec["kind"] not in ("full", "delta"):
            raise ValueError(
                f"journal checkpoint record kind {rec['kind']!r} "
                f"must be full|delta")
        return
    if "event" in rec:
        for field, (required, typ) in EVENT_SCHEMA.items():
            v = rec.get(field)
            ok = (isinstance(v, (int, float)) if typ is float
                  else isinstance(v, typ)) and not isinstance(v, bool)
            if required and not ok:
                raise ValueError(
                    f"journal event record field {field!r} bad: {rec}")
        unknown = set(rec) - set(EVENT_SCHEMA)
        if unknown:
            raise ValueError(
                f"journal event record has unknown fields {unknown}: {rec}")
        if rec["v"] != VERSION:
            raise ValueError(f"journal version {rec['v']} != {VERSION}")
        return
    for field, (required, typ) in SCHEMA.items():
        if field not in rec:
            if required:
                raise ValueError(f"journal record missing {field!r}: {rec}")
            continue
        v = rec[field]
        if typ is float:
            ok = isinstance(v, (int, float)) and not isinstance(v, bool)
        else:
            ok = isinstance(v, typ) and not isinstance(v, bool)
        if not ok:
            raise ValueError(
                f"journal field {field!r} has type {type(v).__name__}, "
                f"expected {typ.__name__}: {rec}")
    unknown = set(rec) - set(SCHEMA)
    if unknown:
        raise ValueError(f"journal record has unknown fields {unknown}: {rec}")
    if rec["v"] != VERSION:
        raise ValueError(f"journal version {rec['v']} != {VERSION}")
    if "spans" in rec:
        _validate_spans(rec["spans"], SPAN_STAGES, rec)


class RunJournal:
    """Append-only writer. One line per :meth:`record`, flushed to the OS
    immediately — the crash-survivability contract. Opened in append mode
    so a supervised restart continues the same file (``seq`` resumes from
    the restored window count, so the ordinal stream stays monotone)."""

    def __init__(self, path: str) -> None:
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # A crashed predecessor may have died mid-write, leaving an
        # unterminated partial line; seal it with a newline so this
        # attempt's first record starts a fresh line instead of gluing
        # itself onto the torn one (readers skip the torn line either way).
        torn = False
        try:
            with open(path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                torn = f.read(1) != b"\n"
        except OSError:
            pass  # missing or empty file
        self._f: Optional[io.TextIOBase] = open(  # noqa: SIM115 - long-lived
            path, "a", encoding="utf-8")
        if torn:
            self._f.write("\n")
            self._f.flush()
        # Window records come from one thread per execution mode, but
        # out-of-band event records (degradation-plane admission-side
        # transitions) arrive from the ingest thread concurrently — two
        # buffered writes must not interleave mid-line.
        # lock-ordering: leaf lock, held only around the write+flush
        self._lock = threading.Lock()

    def record(self, rec: dict) -> None:
        if self._f is None:
            raise ValueError("journal is closed")
        if faults.PLAN is not None:
            # torn_write here appends half a record then dies — the
            # exact SIGKILL-mid-write shape readers must tolerate.
            faults.PLAN.fire("journal_append", seq=rec.get("seq", 0),
                             path=self.path)
        # One write syscall per record + explicit flush: a SIGKILL can
        # truncate at most the line being written, never reorder lines.
        with self._lock:
            self._f.write(json.dumps(rec, sort_keys=True) + "\n")
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_records(path: str) -> Iterator[dict]:
    """Parse a journal, skipping unparseable lines (a crash-torn final
    line is the expected case; the writer never produces one mid-file)."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue


def tail(path: str, n: int = 5, read_back_bytes: int = 1 << 16,
         start_offset: int = 0) -> List[dict]:
    """Last ``n`` parseable records after ``start_offset``, ``[]`` when
    the file is missing or holds none — the supervisor's crash-forensics
    read.

    Reads only the final ``read_back_bytes`` of the eligible range: a
    long-running journal grows without rotation, and the restart path
    must not parse weeks of records to quote five. ``start_offset``
    scopes the read to one attempt's records (the caller passes the file
    size captured at spawn; that is always a line boundary, or the start
    of a torn line the writer seals). The first line of the chunk is
    dropped when the seek landed mid-record.
    """
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            start = max(start_offset, size - read_back_bytes)
            f.seek(start)
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    lines = chunk.splitlines()
    if start > start_offset and lines:
        lines = lines[1:]  # partial first line from the mid-record seek
    out: List[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
        if len(out) > n:
            out.pop(0)
    return out
