"""cooc-trace: offline fleet-trace analysis over run journals.

``python -m tpu_cooccurrence.observability.trace`` merges the journal
JSONL files of a whole fleet — gang workers (``journal.p<i>``), the
single-process job, N read replicas — into one correlated timeline and
answers the questions no single flight recorder can: how long from a
window firing on a worker to its rows being servable from a replica
(end-to-end **freshness**), which stage of the window lifecycle
dominates (per-stage **waterfall**, p50/p95/p99 over the registry's
fixed-log buckets), and where the seams are (fused-vs-chained
fallbacks, autoscale drains, degradation transitions, supervisor
restarts, replica resyncs — all already journaled, here finally
joined).

Join model (see ``journal.py``): every record carries the correlation
trio (``run_id``, ``process_id``, ``attempt``). Window records join to
checkpoint records on (``run_id``, ``process_id``, ``window_seq``);
checkpoint records join to replica records on ``generation``. When the
writer and a separately launched replica carry different run ids, the
generation join still holds — the shared state dir is the namespace —
and the report says so instead of silently dropping the fleet's other
half.

Restart stitching: a supervised restart reuses the journal file in
append mode, so one file can carry several attempts of the same window
ordinals. The merge dedups on (``run_id``, ``process_id``,
``window_seq``), keeping the HIGHEST attempt (the one whose effects
survived), and reports how many pre-crash duplicates it dropped.

Output: ``--format text`` (operator summary), ``--format json`` (the
full analysis dict), ``--format chrome`` (Chrome-trace / Perfetto
``traceEvents`` of the merged timeline — load it at ui.perfetto.dev).

Deliberately jax-free: it imports only the stdlib plus
``observability.registry`` (pure stdlib) and ``observability.journal``
(stdlib), so it runs anywhere the journals land — no accelerator, no
heavyweight deps.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from .journal import REPLICA_SPAN_STAGES, SPAN_STAGES, read_records
from .registry import SECONDS_BUCKETS, Histogram

#: Core window stages whose span seconds must partition the record's
#: ``sample_seconds + score_seconds`` (boundary stages are measured
#: after the record flushes and excluded — journal.SPAN_STAGES).
CORE_STAGES = SPAN_STAGES[:5]

#: Relative tolerance for the core-span / wall-seconds reconciliation.
RECONCILE_REL_TOL = 0.01

#: Windows shorter than this are skipped by the reconciliation check:
#: at microsecond scale the journal's own field rounding dominates.
RECONCILE_MIN_WALL_S = 1e-3


def classify(rec: dict) -> Optional[str]:
    """Record type by distinguishing key (the journal's own dispatch
    rule) — None for JSON lines that are not journal records."""
    if not isinstance(rec, dict) or "v" not in rec:
        return None
    for key, kind in (("autoscale", "autoscale"), ("replica", "replica"),
                      ("checkpoint", "checkpoint"), ("event", "event")):
        if key in rec:
            return kind
    return "window" if "seq" in rec else None


def discover(paths: List[str]) -> List[str]:
    """Expand directories into their journal files (any ``*.jsonl*``
    basename — covers ``journal.jsonl``, per-worker ``journal.jsonl.p0``
    and replica-fleet suffixes); pass plain files through."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if ".jsonl" in name:
                    out.append(os.path.join(p, name))
        else:
            out.append(p)
    return out


def load(files: List[str]) -> Dict[str, List[dict]]:
    """Read + classify every record in ``files``; each record gains a
    ``_src`` key (source basename) for provenance in reports."""
    by_kind: Dict[str, List[dict]] = {
        k: [] for k in ("window", "event", "checkpoint", "autoscale",
                        "replica")}
    for path in files:
        for rec in read_records(path):
            kind = classify(rec)
            if kind is not None:
                rec["_src"] = os.path.basename(path)
                by_kind[kind].append(rec)
    return by_kind


def _ident(rec: dict) -> Tuple[str, int, int]:
    """(run_id, process_id, attempt) with pre-tracing-era defaults."""
    return (str(rec.get("run_id", "")), int(rec.get("process_id", 0)),
            int(rec.get("attempt", 0)))


def dedup_windows(windows: List[dict]) -> Tuple[List[dict], int]:
    """One record per (run_id, process_id, window_seq), keeping the
    highest attempt — a supervised restart replays window ordinals its
    crashed predecessor already journaled, and only the surviving
    attempt's spans belong on the merged timeline. Returns (kept,
    dropped_duplicates)."""
    best: Dict[Tuple[str, int, int], dict] = {}
    dropped = 0
    for rec in windows:
        run_id, process_id, attempt = _ident(rec)
        key = (run_id, process_id, int(rec["seq"]))
        cur = best.get(key)
        if cur is None:
            best[key] = rec
            continue
        dropped += 1
        if attempt > _ident(cur)[2]:
            best[key] = rec
    kept = sorted(best.values(),
                  key=lambda r: (_ident(r)[0], _ident(r)[1],
                                 int(r["seq"])))
    return kept, dropped


def _span_list(rec: dict) -> List[Tuple[str, float, float]]:
    return [(str(s[0]), float(s[1]), float(s[2]))
            for s in rec.get("spans", [])]


def waterfall(windows: List[dict],
              replicas: List[dict]) -> Dict[str, dict]:
    """Per-stage seconds distributions over the merged fleet, via the
    registry's fixed-log bucket histograms (same resolution /metrics
    uses, so offline and online percentiles agree)."""
    hists = {stage: Histogram(stage, SECONDS_BUCKETS)
             for stage in SPAN_STAGES + REPLICA_SPAN_STAGES}
    for rec in list(windows) + list(replicas):
        for stage, _off, secs in _span_list(rec):
            if stage in hists:
                hists[stage].observe(secs)
    return {stage: h.summary() for stage, h in hists.items()
            if h.count}


def reconcile(windows: List[dict]) -> dict:
    """Check the span contract: per window, the five core stages must
    sum to ``sample_seconds + score_seconds`` (rel tol
    ``RECONCILE_REL_TOL``; sub-millisecond windows skipped — journal
    field rounding dominates there)."""
    checked = violations = 0
    max_rel_err = 0.0
    for rec in windows:
        spans = _span_list(rec)
        if not spans:
            continue
        wall = float(rec.get("sample_seconds", 0.0)) \
            + float(rec.get("score_seconds", 0.0))
        if wall < RECONCILE_MIN_WALL_S:
            continue
        core = sum(secs for stage, _off, secs in spans
                   if stage in CORE_STAGES)
        checked += 1
        rel = abs(core - wall) / wall
        max_rel_err = max(max_rel_err, rel)
        if rel > RECONCILE_REL_TOL:
            violations += 1
    return {"windows_checked": checked, "violations": violations,
            "max_rel_err": round(max_rel_err, 6),
            "ok": violations == 0}


def freshness(windows: List[dict], checkpoints: List[dict],
              replicas: List[dict]) -> dict:
    """End-to-end freshness: window-fire -> replica-servable.

    A generation becomes servable on a replica at its replica record's
    ``wall_unix`` (post-publish). Its data age anchors at the window
    the commit snapshotted: the checkpoint record's ``window_seq``
    resolves to that window record's ``wall_unix`` on the same (run_id,
    process_id); a checkpoint with no surviving window record (or a
    pre-tracing journal) anchors at the commit's own wall clock. With
    several writers committing the same generation, the EARLIEST anchor
    wins — freshness reports the oldest data in the snapshot.
    """
    window_wall: Dict[Tuple[str, int, int], float] = {}
    for rec in windows:
        run_id, process_id, _ = _ident(rec)
        window_wall[(run_id, process_id, int(rec["seq"]))] = \
            float(rec["wall_unix"])
    gen_fire: Dict[int, float] = {}
    for rec in checkpoints:
        gen = int(rec.get("generation", rec["checkpoint"]))
        run_id, process_id, _ = _ident(rec)
        anchor = float(rec["wall_unix"])
        if "window_seq" in rec:
            anchor = window_wall.get(
                (run_id, process_id, int(rec["window_seq"])), anchor)
        gen_fire[gen] = min(gen_fire.get(gen, anchor), anchor)
    hist = Histogram("freshness", SECONDS_BUCKETS)
    joined = unjoined = 0
    cross_run = False
    writer_runs = {_ident(r)[0] for r in checkpoints}
    for rec in replicas:
        gen = int(rec.get("generation", rec["replica"]))
        fire = gen_fire.get(gen)
        if fire is None:
            unjoined += 1
            continue
        joined += 1
        if _ident(rec)[0] not in writer_runs:
            cross_run = True
        hist.observe(max(0.0, float(rec["wall_unix"]) - fire))
    out = hist.summary()
    out["joined"] = joined
    out["unjoined_replica_records"] = unjoined
    if cross_run:
        # Writer and replica were launched with different run ids; the
        # generation join over the shared state dir still holds, but
        # say so (set TPU_COOC_RUN_ID / --run-id to unify).
        out["cross_run_join"] = True
    return out


def annotations(windows: List[dict], events: List[dict],
                autoscales: List[dict], replicas: List[dict],
                dropped_duplicates: int) -> dict:
    """Seam/fallback annotation: everything already journaled, joined
    into one fleet-level accounting."""
    fused = sum(1 for r in windows if r.get("fused") == 1)
    chained = sum(1 for r in windows if r.get("fused") == 0)
    fallbacks: Dict[str, int] = {}
    for rec in windows:
        reason = rec.get("fallback_reason")
        if reason:
            fallbacks[reason] = fallbacks.get(reason, 0) + 1
    # Ingest-plane seams: partition-ownership reassignment events the
    # rescaled restore journals ("ingest/partition-reassign:N->M") —
    # each marks the gang topology boundary where the merged offset
    # sections were re-derived under new ownership.
    partition_reassigns = [
        {"event": r["event"], "window": r.get("window_seq")}
        for r in sorted(events, key=lambda r: float(r["wall_unix"]))
        if str(r.get("event", "")).startswith("ingest/partition-reassign")]
    degrade_transitions = sum(
        len(r.get("degrade_events", [])) for r in windows) + sum(
        1 for r in events
        if not str(r.get("event", "")).startswith("ingest/"))
    # Restarts: attempts observed per (run_id, process_id) beyond the
    # first — the supervisor threads the ordinal through the env
    # exactly so this census works post-hoc.
    attempts: Dict[Tuple[str, int], set] = {}
    for rec in windows:
        run_id, process_id, attempt = _ident(rec)
        attempts.setdefault((run_id, process_id), set()).add(attempt)
    restarts = sum(len(a) - 1 for a in attempts.values())
    resyncs = max((int(r.get("resyncs", 0)) for r in replicas),
                  default=0)
    # Generation monotonicity per replica slot: resyncs and relaunches
    # both bootstrap FORWARD to the newest checkpoint, so the merged
    # per-slot generation stream must never step back.
    monotone_violations = 0
    last_gen: Dict[Tuple[str, int], int] = {}
    for rec in sorted(replicas, key=lambda r: float(r["wall_unix"])):
        run_id, process_id, _ = _ident(rec)
        gen = int(rec.get("generation", rec["replica"]))
        key = (run_id, process_id)
        if gen < last_gen.get(key, gen):
            monotone_violations += 1
        last_gen[key] = max(gen, last_gen.get(key, gen))
    return {
        "fused_windows": fused,
        "chained_windows": chained,
        "fallback_reasons": fallbacks,
        "degrade_transitions": degrade_transitions,
        "autoscale_drains": [
            {"decision": r["autoscale"], "from": r["from"], "to": r["to"],
             "trigger": r["trigger"], "window": r["window"]}
            for r in sorted(autoscales,
                            key=lambda r: float(r["wall_unix"]))],
        "partition_reassigns": partition_reassigns,
        "restarts": restarts,
        "dropped_duplicate_windows": dropped_duplicates,
        "replica_resyncs": resyncs,
        "replica_generation_monotone": monotone_violations == 0,
    }


def analyze(files: List[str]) -> dict:
    """The full analysis dict (the ``--format json`` payload)."""
    by_kind = load(files)
    windows, dropped = dedup_windows(by_kind["window"])
    return {
        "files": [os.path.basename(f) for f in files],
        "records": {k: len(v) for k, v in by_kind.items()},
        "processes": sorted({f"{r}/p{p}" for r, p, _ in
                             map(_ident, windows + by_kind["replica"])}),
        "waterfall": waterfall(windows, by_kind["replica"]),
        "reconcile": reconcile(windows),
        "freshness": freshness(windows, by_kind["checkpoint"],
                               by_kind["replica"]),
        "annotations": annotations(windows, by_kind["event"],
                                   by_kind["autoscale"],
                                   by_kind["replica"], dropped),
    }


# -- Chrome-trace export -------------------------------------------------

def _chrome_pid(kind: str, process_id: int) -> int:
    # Distinct pid planes keep workers and replicas as separate process
    # tracks in Perfetto (a replica's slot ids overlap the workers').
    return process_id + (1000 if kind == "replica" else 0)


def chrome_trace(files: List[str]) -> dict:
    """Chrome-trace / Perfetto JSON of the merged timeline: one process
    track per fleet slot (replicas offset to their own pid plane), one
    thread track per restart attempt, complete ("X") events per span
    and instant ("i") events for the out-of-band records. Timestamps
    are wall-clock microseconds; a window's spans are laid back-to-back
    ending at its record's ``wall_unix`` (the journal's flush point)."""
    by_kind = load(files)
    windows, _ = dedup_windows(by_kind["window"])
    events: List[dict] = []
    named = set()

    def track(kind: str, rec: dict) -> Tuple[int, int]:
        run_id, process_id, attempt = _ident(rec)
        pid, tid = _chrome_pid(kind, process_id), attempt
        if (pid,) not in named:
            named.add((pid,))
            label = ("replica" if kind == "replica" else "worker")
            name = f"{label} p{process_id}"
            if run_id:
                name += f" run {run_id}"
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": name}})
        if (pid, tid) not in named:
            named.add((pid, tid))
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"attempt {attempt}"}})
        return pid, tid

    for kind, recs in (("window", windows),
                       ("replica", by_kind["replica"])):
        for rec in recs:
            spans = _span_list(rec)
            if not spans:
                continue
            pid, tid = track(kind, rec)
            total = sum(secs for _stage, _off, secs in spans)
            t0 = (float(rec["wall_unix"]) - total) * 1e6
            off = 0.0
            args = ({"window_seq": rec["seq"],
                     "fused": rec.get("fused")} if kind == "window"
                    else {"generation": rec.get("generation",
                                                rec["replica"]),
                          "lag": rec.get("lag")})
            for stage, _off, secs in spans:
                events.append({
                    "name": stage, "ph": "X", "cat": kind,
                    "ts": round(t0 + off * 1e6, 3),
                    "dur": round(secs * 1e6, 3),
                    "pid": pid, "tid": tid, "args": args})
                off += secs
    for kind, name_of in (
            ("event", lambda r: f"degrade:{r['event']}"),
            ("checkpoint",
             lambda r: f"checkpoint gen {r['checkpoint']} ({r['kind']})"),
            ("autoscale",
             lambda r: (f"autoscale {r['autoscale']} "
                        f"{r['from']}->{r['to']}"))):
        for rec in by_kind[kind]:
            pid, tid = track(kind, rec)
            events.append({
                "name": name_of(rec), "ph": "i", "s": "p", "cat": kind,
                "ts": round(float(rec["wall_unix"]) * 1e6, 3),
                "pid": pid, "tid": tid})
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- text rendering ------------------------------------------------------

def _fmt_summary(s: dict) -> str:
    if not s.get("count"):
        return "n=0"
    return (f"n={s['count']} p50={s.get('p50', 0):.6f}s "
            f"p95={s.get('p95', 0):.6f}s p99={s.get('p99', 0):.6f}s "
            f"max={s.get('max', 0):.6f}s")


def render_text(analysis: dict) -> str:
    lines = ["cooc-trace: merged fleet timeline", ""]
    rc = analysis["records"]
    lines.append(
        "records: "
        + "  ".join(f"{k}={rc[k]}" for k in ("window", "checkpoint",
                                             "replica", "autoscale",
                                             "event") if rc.get(k)))
    lines.append("processes: " + (", ".join(analysis["processes"])
                                  or "(none)"))
    lines.append("")
    lines.append("stage waterfall (fixed-log buckets):")
    wf = analysis["waterfall"]
    for stage in SPAN_STAGES + REPLICA_SPAN_STAGES:
        if stage in wf:
            lines.append(f"  {stage:<18} {_fmt_summary(wf[stage])}")
    rec = analysis["reconcile"]
    lines.append("")
    lines.append(
        f"span reconciliation: {rec['windows_checked']} windows checked, "
        f"{rec['violations']} violations "
        f"(max rel err {rec['max_rel_err']:.4%}) "
        f"-> {'OK' if rec['ok'] else 'FAIL'}")
    fr = analysis["freshness"]
    lines.append("")
    if fr.get("count"):
        lines.append("end-to-end freshness (window-fire -> "
                     "replica-servable): " + _fmt_summary(fr))
        if fr.get("cross_run_join"):
            lines.append("  note: writer and replica carry different "
                         "run ids; joined on generation over the "
                         "shared state dir")
    else:
        lines.append("end-to-end freshness: no replica records joined "
                     f"({fr.get('unjoined_replica_records', 0)} "
                     "unjoined)")
    an = analysis["annotations"]
    lines.append("")
    lines.append(
        f"seams: fused={an['fused_windows']} "
        f"chained={an['chained_windows']} "
        f"fallbacks={an['fallback_reasons'] or '{}'} "
        f"degrade-transitions={an['degrade_transitions']} "
        f"restarts={an['restarts']} "
        f"dropped-dup-windows={an['dropped_duplicate_windows']} "
        f"replica-resyncs={an['replica_resyncs']}")
    for drain in an["autoscale_drains"]:
        lines.append(
            f"  autoscale {drain['decision']} {drain['from']}->"
            f"{drain['to']} ({drain['trigger']}) @window "
            f"{drain['window']}")
    for seam in an.get("partition_reassigns", []):
        lines.append(
            f"  {seam['event']} @window {seam['window']}")
    if not an["replica_generation_monotone"]:
        lines.append("  WARNING: replica generation stream stepped "
                     "backwards (corrupt merge or clock skew)")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_cooccurrence.observability.trace",
        description="Merge a fleet's run journals into one correlated "
                    "timeline: per-stage waterfall, end-to-end "
                    "freshness, seam annotations, Chrome-trace export.")
    p.add_argument("paths", nargs="*",
                   help="journal files and/or directories to merge")
    p.add_argument("--gang-dir", default=None,
                   help="gang/fleet dir whose journal files to merge "
                        "(alias of passing the directory positionally)")
    p.add_argument("--state-dir", default=None,
                   help="state dir holding writer + replica journals "
                        "(alias of passing the directory positionally)")
    p.add_argument("--format", default="text",
                   choices=("text", "json", "chrome"), dest="format")
    p.add_argument("--out", default=None,
                   help="write output here instead of stdout")
    args = p.parse_args(argv)
    roots = list(args.paths)
    for d in (args.gang_dir, args.state_dir):
        if d:
            roots.append(d)
    files = discover(roots)
    if not files:
        p.error("no journal files found (pass files, a --gang-dir, or "
                "a --state-dir)")
    if args.format == "chrome":
        text = json.dumps(chrome_trace(files))
    elif args.format == "json":
        text = json.dumps(analyze(files), sort_keys=True, indent=2) + "\n"
    else:
        text = render_text(analyze(files))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
