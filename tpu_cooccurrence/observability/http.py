"""Live scrape endpoint: ``/metrics`` (Prometheus text) + ``/healthz``.

The reference outsources live monitoring to the Flink UI; this
standalone build serves its own, from a stdlib ``http.server`` thread —
zero dependencies, safe to run inside the job process because every
handler only *reads* locked registries (no handler can touch job state).

``/metrics`` returns Prometheus text-format 0.0.4: every reference-named
counter (``metrics.Counters``), the TransferLedger wire totals, and all
registry gauges/histograms.

``/healthz`` returns JSON liveness derived from the last fired window's
wall-clock age: 200 while the job is making window progress (or still
inside the staleness grace period since start — a cold job that has not
fired yet is "starting", not dead), 503 once the age exceeds the
threshold. A long tail of empty input under ``--process-continuously``
is indistinguishable from a hang by design — staleness means "no window
fired", whatever the cause, which is exactly what an operator pages on.

Port 0 binds an ephemeral port (CI) — the bound port is in ``.port``
and the startup log line.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import time
from typing import Optional

from .registry import MetricsRegistry

LOG = logging.getLogger("tpu_cooccurrence.metrics_http")

#: Gauge (set by the job per window) the health check reads.
LAST_WINDOW_GAUGE = "cooc_last_window_unix_seconds"

#: Degradation-plane gauges surfaced on /healthz (robustness plane):
#: operators page on "paused" the same way they page on "stale".
DEGRADATION_GAUGE = "cooc_degradation_level"
QUARANTINE_GAUGE = "cooc_quarantined_lines_total"


class MetricsServer:
    """Background scrape server over a registry + counters + ledger."""

    def __init__(self, registry: MetricsRegistry, counters=None, ledger=None,
                 port: int = 0, host: str = "127.0.0.1",
                 stale_after_s: float = 300.0,
                 supervisor_info: Optional[dict] = None) -> None:
        self.registry = registry
        self.counters = counters
        self.ledger = ledger
        self.stale_after_s = stale_after_s
        # Restart forensics from the supervising parent (cli.py passes
        # the env-var payload through): surfaced on /healthz so "is this
        # process a restart, and why" is scrapeable.
        self.supervisor_info = supervisor_info
        self._started_unix = time.time()
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - http.server API
                if self.path.split("?", 1)[0] == "/metrics":
                    body = outer.registry.render_prometheus(
                        outer.counters, outer.ledger).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                    code = 200
                elif self.path.split("?", 1)[0] == "/healthz":
                    payload, healthy = outer.health()
                    body = (json.dumps(payload, sort_keys=True)
                            + "\n").encode()
                    ctype = "application/json"
                    code = 200 if healthy else 503
                else:
                    body = b"not found\n"
                    ctype = "text/plain"
                    code = 404
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args) -> None:
                LOG.debug("scrape: " + fmt, *args)

        # ThreadingHTTPServer: a stuck scraper must not block the next
        # scrape (handlers are read-only, so concurrency is safe).
        self._server = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def health(self) -> "tuple[dict, bool]":
        """(payload, healthy): last-window age vs the staleness threshold,
        plus the degradation plane's level and quarantine count.

        ``PAUSE_INGEST`` reports unhealthy even inside the staleness
        window: a paused job is *deliberately* not firing windows, and
        letting the recency of its last pre-pause window read as "ok"
        would hide exactly the condition an operator pages on.
        """
        now = time.time()
        last = self.registry.gauge(LAST_WINDOW_GAUGE).get()
        windows = int(self.registry.gauge("cooc_windows_fired").get())
        level = int(self.registry.gauge(DEGRADATION_GAUGE).get())
        if last > 0:
            age = now - last
            status = "ok" if age <= self.stale_after_s else "stale"
        else:
            # No window yet: grace-period from server start, then stale.
            age = now - self._started_unix
            status = "starting" if age <= self.stale_after_s else "stale"
        from ..robustness.degrade import DegradationLevel

        if level >= DegradationLevel.PAUSE_INGEST and status != "stale":
            status = "paused"
        payload = {"status": status,
                   "windows_fired": windows,
                   "last_window_age_seconds": round(age, 3),
                   "stale_after_seconds": self.stale_after_s,
                   "degradation_level": level,
                   "quarantined_total": int(
                       self.registry.gauge(QUARANTINE_GAUGE).get())}
        if self.supervisor_info is not None:
            payload["last_restart"] = self.supervisor_info
        return payload, status not in ("stale", "paused")

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="cooc-metrics-http",
            daemon=True)
        self._thread.start()
        LOG.info("serving /metrics and /healthz on http://%s:%d",
                 self._server.server_address[0], self.port)
        return self

    def stop(self) -> None:
        # shutdown() waits on serve_forever's loop; skip it when start()
        # was never called (it would block forever on the unset event).
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join()
            self._thread = None
        self._server.server_close()
