"""Live HTTP plane: ``/metrics``, ``/healthz`` and (serving) ``/recommend``.

The reference outsources live monitoring to the Flink UI; this
standalone build serves its own, from a stdlib ``http.server`` thread —
zero dependencies, safe to run inside the job process because every
handler only *reads*: locked registries for the scrape routes, the
immutable published snapshot for the query route (no handler can touch
job state).

``/metrics`` returns Prometheus text-format 0.0.4: every reference-named
counter (``metrics.Counters``), the TransferLedger wire totals, and all
registry gauges/histograms.

``/healthz`` returns JSON liveness derived from the last fired window's
wall-clock age: 200 while the job is making window progress (or still
inside the staleness grace period since start — a cold job that has not
fired yet is "starting", not dead), 503 once the age exceeds the
threshold. A long tail of empty input under ``--process-continuously``
is indistinguishable from a hang by design — staleness means "no window
fired", whatever the cause, which is exactly what an operator pages on.
With the serving plane attached the payload also carries the snapshot
generation/age, and ``--serve-stale-after-s`` turns a stale snapshot
into 503 so a load balancer can drain a wedged job.

``/recommend?user=U&n=N`` (``--serve-port`` only) answers from the
serving plane's current snapshot: zero-lock, one generation per
response. ``min_gen=G`` arms the read-your-window gate (serving
fleet): a snapshot older than the client's last-seen generation
answers 503 instead of travelling back in time, so a front tier can
retry a caught-up replica. The read-replica server
(``serving/replica.ReplicaServer``) subclasses this class — same
routes, same latency histograms, replica-specific ``/healthz``. Its latency lands in the ``cooc_query_seconds`` histogram
(p50/p95/p99 on ``/metrics``), and a query over the
``--serve-query-slo-s`` SLO raises the degradation plane's
QUERY_PRESSURE signal — ingest sheds before query latency degrades,
never the reverse.

Every route in :data:`ROUTE_METRICS` gets a request-latency histogram;
the cooclint ``serving-route`` rule holds that table to CANONICAL_METRICS,
README and tests/ (a route cannot land unmeasured or undocumented).

Port 0 binds an ephemeral port (CI) — the bound port is in ``.port``
and the startup log line.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import time
import urllib.parse
from typing import Optional

from .registry import MetricsRegistry

LOG = logging.getLogger("tpu_cooccurrence.metrics_http")

#: Gauge (set by the job per window) the health check reads.
LAST_WINDOW_GAUGE = "cooc_last_window_unix_seconds"

#: Degradation-plane gauges surfaced on /healthz (robustness plane):
#: operators page on "paused" the same way they page on "stale".
DEGRADATION_GAUGE = "cooc_degradation_level"
QUARANTINE_GAUGE = "cooc_quarantined_lines_total"

#: Route registry: every HTTP route this server answers, mapped to its
#: request-latency histogram. The cooclint ``serving-route`` rule
#: AST-reads this table — each metric must be in CANONICAL_METRICS, each
#: route must be mentioned in README.md and referenced from tests/, and
#: no handler may answer a route that is not listed here.
ROUTE_METRICS = {
    "/metrics": "cooc_scrape_seconds",
    "/healthz": "cooc_healthz_seconds",
    "/recommend": "cooc_query_seconds",
}


class MetricsServer:
    """Background scrape/query server over a registry + counters + ledger.

    ``serving`` (a ``serving.ServingPlane``) arms the ``/recommend``
    route; without it the route answers 404 with a pointer at
    ``--serve-port`` — the scrape-only server stays exactly as before.
    """

    def __init__(self, registry: MetricsRegistry, counters=None, ledger=None,
                 port: int = 0, host: str = "127.0.0.1",
                 stale_after_s: float = 300.0,
                 supervisor_info: Optional[dict] = None,
                 serving=None, serve_stale_after_s: float = 0.0,
                 peers=None, last_window=None, ingest=None) -> None:
        self.registry = registry
        self.counters = counters
        self.ledger = ledger
        self.stale_after_s = stale_after_s
        # Restart forensics from the supervising parent (cli.py passes
        # the env-var payload through): surfaced on /healthz so "is this
        # process a restart, and why" is scrapeable.
        self.supervisor_info = supervisor_info
        self.serving = serving
        self.serve_stale_after_s = serve_stale_after_s
        # Gang peer table (robustness/gang.PeerTable, multi-host runs):
        # /healthz carries per-peer heartbeat age + committed epoch and
        # 503s ("peer_stale") when any peer is stale — the
        # load-balancer drain signal ahead of the gang restart.
        self.peers = peers
        # Tracing plane: a callable returning the job's last-window
        # stage breakdown (job.last_window_health) — /healthz shows a
        # wedged stage without anyone pulling the journal.
        self.last_window = last_window
        # Ingest plane: a callable returning the source's partition
        # offset/lag snapshot (Source.ingest_health) — None for the
        # plain files source, a per-partition dict for partitioned logs.
        self.ingest = ingest
        self._started_unix = time.time()
        # Per-route request-latency histograms, registered up front so
        # they render on /metrics (at zero) from the first scrape.
        self._route_hist = {
            route: registry.histogram(
                name, help=f"request seconds serving {route}")
            for route, name in ROUTE_METRICS.items()}
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - http.server API
                path, _, query = self.path.partition("?")
                t0 = time.perf_counter()
                if path == "/metrics":
                    body = outer.registry.render_prometheus(
                        outer.counters, outer.ledger).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                    code = 200
                elif path == "/healthz":
                    payload, healthy = outer.health()
                    body = (json.dumps(payload, sort_keys=True)
                            + "\n").encode()
                    ctype = "application/json"
                    code = 200 if healthy else 503
                elif path == "/recommend":
                    code, body = outer.recommend(query)
                    ctype = "application/json"
                else:
                    body = b"not found\n"
                    ctype = "text/plain"
                    code = 404
                hist = outer._route_hist.get(path)
                if hist is not None:
                    hist.observe(time.perf_counter() - t0)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args) -> None:
                LOG.debug("scrape: " + fmt, *args)

        # ThreadingHTTPServer: a stuck scraper must not block the next
        # scrape (handlers are read-only, so concurrency is safe).
        self._server = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def health(self) -> "tuple[dict, bool]":
        """(payload, healthy): last-window age vs the staleness threshold,
        plus the degradation plane's level and quarantine count, plus —
        when the serving plane is attached — snapshot generation and age.

        ``PAUSE_INGEST`` reports unhealthy even inside the staleness
        window: a paused job is *deliberately* not firing windows, and
        letting the recency of its last pre-pause window read as "ok"
        would hide exactly the condition an operator pages on. A serving
        snapshot older than ``--serve-stale-after-s`` (when set) reports
        ``snapshot_stale`` and 503 — the load-balancer drain signal for
        a job whose swap loop wedged while windows still fire.
        """
        now = time.time()
        last = self.registry.gauge(LAST_WINDOW_GAUGE).get()
        windows = int(self.registry.gauge("cooc_windows_fired").get())
        level = int(self.registry.gauge(DEGRADATION_GAUGE).get())
        if last > 0:
            age = now - last
            status = "ok" if age <= self.stale_after_s else "stale"
        else:
            # No window yet: grace-period from server start, then stale.
            age = now - self._started_unix
            status = "starting" if age <= self.stale_after_s else "stale"
        from ..robustness.degrade import DegradationLevel

        if level >= DegradationLevel.PAUSE_INGEST and status != "stale":
            status = "paused"
        payload = {"status": status,
                   "windows_fired": windows,
                   "last_window_age_seconds": round(age, 3),
                   "stale_after_seconds": self.stale_after_s,
                   "degradation_level": level,
                   "quarantined_total": int(
                       self.registry.gauge(QUARANTINE_GAUGE).get())}
        from ..state.checkpoint import (CHAIN_LEN_GAUGE,
                                        COMMIT_BYTES_GAUGE,
                                        COMMIT_SECONDS_GAUGE,
                                        GENERATION_GAUGE)

        ckpt_gen = int(self.registry.gauge(GENERATION_GAUGE).get())
        if ckpt_gen:
            # Checkpoint plane (present once a generation was written or
            # restored): the last commit's cost and the delta-chain
            # depth — an operator watching restore-replay budgets reads
            # these beside the staleness fields.
            payload["checkpoint"] = {
                "generation": ckpt_gen,
                "commit_bytes": int(self.registry.gauge(
                    COMMIT_BYTES_GAUGE).get()),
                "commit_seconds": round(self.registry.gauge(
                    COMMIT_SECONDS_GAUGE).get(), 6),
                "delta_chain_len": int(self.registry.gauge(
                    CHAIN_LEN_GAUGE).get()),
            }
        if self.serving is not None:
            snap_age = self.serving.snapshot_age_seconds()
            payload["snapshot_generation"] = self.serving.generation
            payload["snapshot_rows"] = self.serving.rows
            payload["snapshot_age_seconds"] = round(snap_age, 3)
            payload["snapshot_stale_after_seconds"] = self.serve_stale_after_s
            if (self.serve_stale_after_s > 0
                    and snap_age > self.serve_stale_after_s
                    and status not in ("stale", "paused")):
                status = payload["status"] = "snapshot_stale"
        from ..robustness.autoscale import (LEVEL_GAUGE, RESCALES_GAUGE,
                                            TARGET_WORKERS_GAUGE)

        autoscale_workers = int(self.registry.gauge(
            TARGET_WORKERS_GAUGE).get())
        if autoscale_workers:
            # Autoscale block (robustness/autoscale.py, gang workers):
            # the topology this worker was launched at, the voluntary
            # rescales the supervisor has performed, and the last
            # gang-wide load signal the per-window vote produced.
            payload["autoscale"] = {
                "target_workers": autoscale_workers,
                "rescales_total": int(self.registry.gauge(
                    RESCALES_GAUGE).get()),
                "level": int(self.registry.gauge(LEVEL_GAUGE).get()),
            }
        if self.peers is not None:
            rows, any_stale = self.peers.snapshot()
            payload["peers"] = rows
            if any_stale and status not in ("stale", "paused",
                                            "snapshot_stale"):
                # A stale peer means the gang is about to be restarted
                # (its collectives cannot complete); drain this process
                # even though ITS windows may still look fresh.
                status = payload["status"] = "peer_stale"
        if self.supervisor_info is not None:
            payload["last_restart"] = self.supervisor_info
        if self.last_window is not None:
            # Per-stage seconds + fused flag + window_seq of the newest
            # completed window (None until the first window fires).
            lw = self.last_window()
            if lw is not None:
                payload["last_window"] = lw
        if self.ingest is not None:
            # Partitioned-log sources only: per-partition byte offsets,
            # record counts, on-disk lag, quarantine flags and the
            # deterministic owner index. The plain files source returns
            # None here and the block is simply absent.
            ing = self.ingest()
            if ing is not None:
                payload["ingest"] = ing
        return payload, status not in ("stale", "paused", "snapshot_stale",
                                       "peer_stale")

    def recommend(self, query: str) -> "tuple[int, bytes]":
        """The ``/recommend`` route body: parse params, run the blend on
        the current snapshot, JSON the result. Query-side latency SLO
        enforcement (QUERY_PRESSURE) happens here — the blend itself
        stays pure."""
        if self.serving is None:
            return 404, (json.dumps(
                {"error": "serving disabled (run with --serve-port)"})
                + "\n").encode()
        params = urllib.parse.parse_qs(query)
        try:
            user = (int(params["user"][0])
                    if "user" in params else None)
            n = int(params.get("n", ["10"])[0])
            min_gen = (int(params["min_gen"][0])
                       if "min_gen" in params else None)
        except ValueError:
            return 400, (json.dumps(
                {"error": "user, n and min_gen must be integers"}
            ) + "\n").encode()
        if n < 1:
            return 400, (json.dumps(
                {"error": "n must be >= 1"}) + "\n").encode()
        t0 = time.perf_counter()
        items, snap, fallback = self.serving.query(user, n)
        if min_gen is not None and snap.generation < min_gen:
            # Read-your-window consistency (serving fleet): the client
            # has already seen generation min_gen somewhere; answering
            # from an older snapshot would travel back in time. 503 so
            # a front tier retries a caught-up replica (the generation
            # tag rides along for its routing table).
            return 503, (json.dumps({
                "error": "snapshot generation behind min_gen "
                         "(replica still catching up)",
                "generation": snap.generation,
                "min_gen": min_gen,
            }, sort_keys=True) + "\n").encode()
        elapsed = time.perf_counter() - t0
        slo = self.serving.query_slo_s
        if slo > 0 and elapsed > slo:
            from ..robustness import degrade

            if degrade.CONTROLLER is not None:
                # Shed INGEST before query latency degrades — the
                # controller has no query-shedding lever by design.
                degrade.CONTROLLER.note_query_pressure()
        body = json.dumps({
            "user": user,
            "n": n,
            "generation": snap.generation,
            "snapshot_age_seconds": round(snap.age_seconds(), 3),
            "fallback": bool(fallback),
            "items": [{"item": item, "score": round(score, 6)}
                      for item, score in items],
        }, sort_keys=True) + "\n"
        return 200, body.encode()

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="cooc-metrics-http",
            daemon=True)
        self._thread.start()
        routes = "/metrics and /healthz" if self.serving is None else \
            "/metrics, /healthz and /recommend"
        LOG.info("serving %s on http://%s:%d", routes,
                 self._server.server_address[0], self.port)
        return self

    def stop(self) -> None:
        # shutdown() waits on serve_forever's loop; skip it when start()
        # was never called (it would block forever on the unset event).
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join()
            self._thread = None
        self._server.server_close()
