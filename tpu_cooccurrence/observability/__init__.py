"""Tracing / profiling / per-window instrumentation.

The reference's observability is wall-clock duration + accumulators
(SURVEY §5: ``FlinkCooccurrences.java:173-181``); Flink's own metrics UI
provides the rest. The TPU build's upgrade: per-window step timing with
stage breakdown (sampling vs scoring), retained as a ring buffer and
summarizable, plus optional XLA profiler traces (``jax.profiler``) for
TensorBoard.

This package is the observability plane (the standalone replacement for
the Flink UI the reference leans on):

* this module — step timing, stage occupancy, the transfer ledger;
* :mod:`.journal` — append-only JSONL flight recorder, one record per
  fired window, crash-survivable;
* :mod:`.registry` — typed gauges and fixed-log-bucket histograms with
  p50/p95/p99 summaries and Prometheus text exposition;
* :mod:`.http` — the live scrape endpoint (``/metrics``, ``/healthz``).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Deque, Dict, Iterator, Optional


@dataclasses.dataclass
class WindowStats:
    timestamp: int
    events: int
    pairs: int
    rows_scored: int
    sample_seconds: float
    score_seconds: float

    @property
    def seconds(self) -> float:
        return self.sample_seconds + self.score_seconds

    def as_dict(self) -> Dict[str, object]:
        """JSON-serializable form (journal records, summary logs)."""
        return {
            "timestamp": self.timestamp,
            "events": self.events,
            "pairs": self.pairs,
            "rows_scored": self.rows_scored,
            "sample_seconds": round(self.sample_seconds, 6),
            "score_seconds": round(self.score_seconds, 6),
            "seconds": round(self.seconds, 6),
        }


class StepTimer:
    """Ring buffer of per-window stats with aggregate summary."""

    def __init__(self, keep: int = 1024) -> None:
        self.windows: Deque[WindowStats] = collections.deque(maxlen=keep)
        self.total_windows = 0
        self.total_events = 0
        self.total_pairs = 0
        self.total_sample_seconds = 0.0
        self.total_score_seconds = 0.0

    def record(self, stats: WindowStats) -> None:
        self.windows.append(stats)
        self.total_windows += 1
        self.total_events += stats.events
        self.total_pairs += stats.pairs
        self.total_sample_seconds += stats.sample_seconds
        self.total_score_seconds += stats.score_seconds

    def summary(self) -> Dict[str, float]:
        total = self.total_sample_seconds + self.total_score_seconds
        return {
            "windows": self.total_windows,
            "events": self.total_events,
            "pairs": self.total_pairs,
            "sample_seconds": round(self.total_sample_seconds, 4),
            "score_seconds": round(self.total_score_seconds, 4),
            "pairs_per_sec": round(self.total_pairs / total, 1) if total else 0.0,
        }

    def slowest(self, n: int = 3) -> list:
        """The n slowest recent windows (ring-buffer scope) — the first place
        to look when a run's step timing regresses."""
        return sorted(self.windows, key=lambda w: -w.seconds)[:n]

    def slowest_as_dicts(self, n: int = 3) -> list:
        """JSON-serializable slowest-``n`` (end-of-run summary log)."""
        return [w.as_dict() for w in self.slowest(n)]

    def occupancy(self, wall_seconds: float) -> Dict[str, float]:
        """Per-stage busy fractions of a run's wall clock.

        The pipeline-overlap diagnostic (pipeline.py): a serial run's
        ``host_busy_pct + score_busy_pct`` sums to at most ~100 (plus
        ingest overhead outside both stages); a pipelined run exceeds
        100 exactly by the overlap won. ``score_busy_pct`` counts the
        scorer stage's thread time (host index/pack work + dispatch +
        result materialization), not raw device occupancy — on an async
        backend the device can be busy past it.
        """
        w = max(wall_seconds, 1e-9)
        return {
            "host_busy_pct": round(100.0 * self.total_sample_seconds / w, 1),
            "score_busy_pct": round(100.0 * self.total_score_seconds / w, 1),
            "wall_seconds": round(wall_seconds, 4),
        }


@dataclasses.dataclass
class TransferEvent:
    direction: str  # "h2d" | "d2h"
    label: str      # call-site tag ("update", "window-meta", ...)
    nbytes: int


class TransferLedger:
    """Host<->device wire-byte accounting (VERDICT r3, Next #3).

    The scorers record every host-constructed buffer they ship up and
    every device buffer they fetch down, at the call site, with a label.
    On the tunneled single chip (and DCN-attached hosts in general)
    transfer volume IS wall time, so the steady-state contract — a
    deferred sparse window is aggregated-delta uplink only, ZERO
    downlink; a flush fetches dirty rows only — is pinned by CI
    (``tests/test_wire_bytes.py``) against this ledger, and a stray
    blocking fetch or an uplink-size regression fails the build instead
    of silently doubling tunnel wall time.

    Replaces-by-accounting the serialization boundaries the reference
    crosses at every keyBy/broadcast (FlinkCooccurrences.java:89-167).
    One module-level instance (:data:`LEDGER`); events are a bounded
    ring so unbounded streams can't grow host memory.

    Totals are locked (same discipline as ``metrics.Counters``): in
    pipelined execution the sampling thread (checkpoint uplinks) and the
    scorer worker (window dispatches) both record, and the ``+=`` on the
    byte totals is a read-modify-write the GIL does not make atomic.
    ``snapshot()`` returns a consistent (bytes, calls) view taken under
    the same lock — the journal's per-window deltas are exact, never a
    torn read between a bytes and a calls update.
    """

    def __init__(self, keep_events: int = 4096) -> None:
        self.events: Deque[TransferEvent] = collections.deque(
            maxlen=keep_events)
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.h2d_bytes = 0
            self.d2h_bytes = 0
            self.h2d_calls = 0
            self.d2h_calls = 0
            # Compressed-uplink accounting (state/wire.py): for every
            # encoded upload, the bytes the raw layout would have shipped
            # vs the bytes that actually crossed — the compression cut is
            # a first-class bench/journal metric, not a derived guess.
            self.uplink_raw_bytes = 0
            self.uplink_enc_bytes = 0
            # PR-6 BasketBatch packed uplink under its own counter: the
            # fused-vs-chained wire comparison needs basket bytes split
            # out of the generic h2d total they used to fold into.
            self.basket_h2d_bytes = 0
            self.basket_h2d_calls = 0
            self.events.clear()

    def up(self, label: str, *arrays) -> None:
        """Record one host->device upload (all buffers of one dispatch)."""
        n = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            self.h2d_bytes += n
            self.h2d_calls += 1
            self.events.append(TransferEvent("h2d", label, n))

    def up_encoded(self, label: str, raw_nbytes: int, *arrays) -> None:
        """Record one ENCODED host->device upload: ``arrays`` are the
        buffers that actually ship (counted on the h2d totals like any
        upload); ``raw_nbytes`` is what the raw wire format would have
        shipped for the same window, tracked on the raw/encoded pair."""
        n = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            self.h2d_bytes += n
            self.h2d_calls += 1
            self.uplink_raw_bytes += int(raw_nbytes)
            self.uplink_enc_bytes += n
            self.events.append(TransferEvent("h2d", label, n))

    def up_basket(self, label: str, *arrays) -> None:
        """Record one packed BasketBatch upload (--fused-window): rides
        the h2d totals AND its own byte/call pair."""
        n = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            self.h2d_bytes += n
            self.h2d_calls += 1
            self.basket_h2d_bytes += n
            self.basket_h2d_calls += 1
            self.events.append(TransferEvent("h2d", label, n))

    def down(self, label: str, *arrays) -> None:
        """Record one device->host fetch."""
        n = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            self.d2h_bytes += n
            self.d2h_calls += 1
            self.events.append(TransferEvent("d2h", label, n))

    def labels(self, direction: str) -> list:
        with self._lock:
            return [e.label for e in self.events if e.direction == direction]

    def snapshot(self) -> Dict[str, int]:
        """Consistent totals: every (bytes, calls) pair reflects the same
        set of recorded transfers (no torn mid-``up()`` reads)."""
        with self._lock:
            return {"h2d_bytes": self.h2d_bytes, "h2d_calls": self.h2d_calls,
                    "d2h_bytes": self.d2h_bytes, "d2h_calls": self.d2h_calls,
                    "uplink_raw_bytes": self.uplink_raw_bytes,
                    "uplink_enc_bytes": self.uplink_enc_bytes,
                    "basket_h2d_bytes": self.basket_h2d_bytes,
                    "basket_h2d_calls": self.basket_h2d_calls}

    def summary(self) -> Dict[str, int]:
        return self.snapshot()


#: Process-wide ledger the scorers record into.
LEDGER = TransferLedger()


@contextlib.contextmanager
def xla_trace(profile_dir: Optional[str]) -> Iterator[None]:
    """Wrap a run in a ``jax.profiler`` trace when a directory is given."""
    if not profile_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class clock:  # noqa: N801 - tiny helper
    """``with clock() as c: ...; c.seconds``"""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        return False


class StageClock:
    """Per-window stage-seconds accumulator for the tracing plane.

    The scorers :meth:`reset` it at ``process_window`` entry and wrap
    their encode/upload and dispatch sections with :meth:`stage`; the
    job reads :attr:`seconds` afterwards to carve the window's
    ``score_seconds`` into journal span tuples. Re-entering the same
    stage accumulates (the chained path uploads three operand groups
    under one ``uplink-encode`` stage). Not thread-safe by design: one
    scorer thread owns one clock.
    """

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}

    def reset(self) -> None:
        self.seconds = {}

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] = (self.seconds.get(name, 0.0)
                                  + time.perf_counter() - t0)
