"""Typed metrics registry: gauges + fixed-log-bucket latency histograms.

The reference's metric system is Flink accumulators dumped once at job
end (``FlinkCooccurrences.java:181``); distributions (per-operator
latency, backpressure) live in the Flink UI this standalone build does
not have. This registry is the replacement plane: counters stay in
``metrics.Counters`` (byte-identical reference names), while everything
that needs a *distribution* — per-window sample/score/total seconds,
uplink bytes, pipeline queue wait — lands in histograms here, with
p50/p95/p99 summaries for bench JSON and Prometheus text exposition for
the live scrape endpoint (:mod:`.http`).

Histogram buckets are fixed log-spaced bounds chosen at construction
(never resized), so ``observe`` is O(log B) with zero allocation and two
concurrent recorders (the sampling thread and the scorer worker in
pipelined mode) only contend on a per-instrument lock. Percentiles are
bucket-resolved: the reported pXX is the upper bound of the bucket the
rank falls in — exact enough to see a tail regress by a bucket step
(base 2 by default), which is the decision granularity perf PRs need.

One process-global :data:`REGISTRY` (same pattern as
``observability.LEDGER``); tests and bench reset it between runs.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence


def log_buckets(lo: float, hi: float, base: float = 2.0) -> List[float]:
    """Log-spaced bucket upper bounds covering ``[lo, hi]``.

    Bounds are exact powers ``base**k`` (no accumulation drift), first
    bound >= ``lo``, last bound >= ``hi``.
    """
    if not (lo > 0 and hi > lo and base > 1):
        raise ValueError(f"bad bucket spec lo={lo} hi={hi} base={base}")
    k = math.floor(math.log(lo, base))
    if base ** k < lo:
        k += 1
    bounds = []
    while True:
        b = base ** k
        bounds.append(b)
        if b >= hi:
            return bounds
        k += 1


#: Default bucket ladders. Seconds: ~61 us .. 64 s (21 buckets) covers a
#: fast CPU window through a stalled-tunnel dispatch. Bytes: 64 B .. 4 GiB.
SECONDS_BUCKETS = log_buckets(2.0 ** -14, 2.0 ** 6)
BYTES_BUCKETS = log_buckets(2.0 ** 6, 2.0 ** 32)

#: Every ``cooc_*`` gauge/histogram name the process may register or
#: expose, in one place. This is the metric-name registry the static
#: analyzer (``tpu_cooccurrence.analysis``, rule ``metric-name``)
#: enforces: a ``REGISTRY.gauge("cooc_...")`` call site — or a doc
#: quoting a metric — whose name is not listed here fails tier-1, so a
#: typo cannot silently create a parallel series dashboards never see.
#: Add the name here in the same PR that introduces the metric.
CANONICAL_METRICS = frozenset({
    # per-window stage timing / liveness (job.py)
    "cooc_window_sample_seconds",
    "cooc_window_score_seconds",
    "cooc_window_total_seconds",
    "cooc_window_uplink_bytes",
    "cooc_windows_fired",
    "cooc_last_window_unix_seconds",
    # pipelined execution (pipeline.py)
    "cooc_pipeline_queue_wait_seconds",
    "cooc_pipeline_ring_depth",
    # fused one-dispatch window path (--fused-window; job.py splits the
    # score-stage seconds, ops/device_scorer.py counts the dispatches)
    "cooc_fused_dispatches_total",
    "cooc_chained_dispatches_total",
    "cooc_window_score_seconds_fused",
    "cooc_window_score_seconds_chained",
    # fused-sparse shape specialization (state/sparse_scorer.py): how
    # many distinct fused-program shapes (= XLA compiles) the pow2
    # (ops, touched-rows, registry-delta) bucketing produced
    "cooc_fused_bucket_compilations_total",
    # checkpoint plane (state/checkpoint.py)
    "cooc_checkpoint_quarantined_total",
    "cooc_checkpoint_generation",
    # incremental checkpoints + delta log (--checkpoint-incremental,
    # state/checkpoint.py + state/delta.py): per-commit cost and the
    # chain depth behind the newest generation
    "cooc_checkpoint_commit_bytes",
    "cooc_checkpoint_commit_seconds",
    "cooc_checkpoint_delta_chain_len",
    "cooc_checkpoint_compactions_total",
    # gang / epoch-commit plane (state/checkpoint.py epoch markers,
    # robustness/gang.py peer table)
    "cooc_epoch_committed",
    "cooc_checkpoint_partial_total",
    "cooc_gang_stale_peers",
    # load-driven gang autoscaler (robustness/autoscale.py): the
    # topology in force, voluntary rescales performed, and the last
    # gang-wide load signal (-1 idle / 0 neutral / 1 pressure)
    "cooc_gang_target_workers",
    "cooc_gang_rescales_total",
    "cooc_autoscale_level",
    # sharded scorers (parallel/sharded.py)
    "cooc_scorer_dispatch_rows",
    "cooc_shard_row_imbalance",
    # supervisor state relayed into the child (cli.py)
    "cooc_supervisor_restarts",
    "cooc_supervisor_backoff_ms",
    # graceful-degradation plane (robustness/degrade.py, quarantine.py)
    "cooc_degradation_level",
    "cooc_shed_events_total",
    "cooc_quarantined_lines_total",
    "cooc_scorer_breaker_state",
    "cooc_scorer_breaker_trips_total",
    # TransferLedger totals rendered by render_prometheus below
    "cooc_transfer_h2d_bytes_total",
    "cooc_transfer_h2d_calls_total",
    "cooc_transfer_d2h_bytes_total",
    "cooc_transfer_d2h_calls_total",
    # compressed wire format (state/wire.py): encoded-uplink accounting
    # and the BasketBatch packed uplink split out of the generic totals
    "cooc_transfer_uplink_raw_bytes_total",
    "cooc_transfer_uplink_encoded_bytes_total",
    "cooc_transfer_basket_h2d_bytes_total",
    "cooc_transfer_basket_h2d_calls_total",
    # compressed sparse state (state/sparse_scorer.py): host index RSS
    # and device slab footprint, refreshed per window
    "cooc_host_index_rss_bytes",
    "cooc_slab_device_bytes",
    "cooc_slab_live_cells",
    # per-shard breakdown of the two series above (sharded-sparse,
    # parallel/sharded_sparse.py): emitted as <name><shard-id> — the
    # entries here are the f-string prefixes the emission sites use
    "cooc_host_index_rss_bytes_shard",
    "cooc_slab_live_cells_shard",
    # per-shard fused/chained dispatch split (sharded-sparse fused
    # window, parallel/sharded_sparse.py): same <name><shard-id>
    # prefix convention as the RSS gauges above
    "cooc_fused_dispatches_total_shard",
    "cooc_chained_dispatches_total_shard",
    # tiered elastic state (state/store.TieredSlabStore): spill/promote
    # counters and the host arena footprint, refreshed per window
    "cooc_spill_evictions_total",
    "cooc_spill_promotions_total",
    "cooc_spill_resident_rows",
    "cooc_spill_arena_bytes",
    "cooc_spill_row_touches_total",
    # serving plane (serving/, observability/http.py): per-route request
    # latency histograms plus snapshot double-buffer state
    "cooc_query_seconds",
    "cooc_scrape_seconds",
    "cooc_healthz_seconds",
    "cooc_snapshot_generation",
    "cooc_snapshot_swaps_total",
    "cooc_snapshot_built_unix_seconds",
    "cooc_snapshot_rows",
    # degradation plane QUERY_PRESSURE signal (robustness/degrade.py)
    "cooc_query_pressure_events_total",
    # serving fleet read replicas (serving/replica.py): delta-log
    # catch-up position, the lag behind the ingest writer, and the
    # robustness counters behind the lag block on the replica /healthz
    "cooc_replica_generation",
    "cooc_replica_generation_lag",
    "cooc_replica_deltas_applied_total",
    "cooc_replica_resyncs_total",
    # ingest plane (io/partitioned.py offsets committed by
    # state/checkpoint.py): worst per-partition unread bytes at the last
    # fired window, and offset sections committed with the state
    "cooc_ingest_partition_lag",
    "cooc_ingest_offset_commits_total",
})

#: TransferLedger snapshot key -> exposition series name. Explicit
#: literals (not an f-string template) so the analyzer's reverse check
#: can see every canonical transfer name at a real emission site.
TRANSFER_METRICS = {
    "h2d_bytes": "cooc_transfer_h2d_bytes_total",
    "h2d_calls": "cooc_transfer_h2d_calls_total",
    "d2h_bytes": "cooc_transfer_d2h_bytes_total",
    "d2h_calls": "cooc_transfer_d2h_calls_total",
    "uplink_raw_bytes": "cooc_transfer_uplink_raw_bytes_total",
    "uplink_enc_bytes": "cooc_transfer_uplink_encoded_bytes_total",
    "basket_h2d_bytes": "cooc_transfer_basket_h2d_bytes_total",
    "basket_h2d_calls": "cooc_transfer_basket_h2d_calls_total",
}


class Gauge:
    """A single instantaneous value (last write wins)."""

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += float(delta)

    def get(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-log-bucket histogram with bucket-resolved percentiles.

    ``bounds`` are the finite bucket upper bounds (ascending); an
    implicit +Inf bucket catches overflow. Tracks count/sum/min/max
    exactly; percentiles resolve to a bucket upper bound.
    """

    def __init__(self, name: str, bounds: Sequence[float],
                 help: str = "") -> None:
        if not bounds or any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(f"bucket bounds must ascend, got {bounds!r}")
        self.name = name
        self.help = help
        self.bounds = list(bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # +Inf tail
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def _bucket_index(self, value: float) -> int:
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo  # == len(bounds) -> +Inf bucket

    def observe(self, value: float) -> None:
        value = float(value)
        i = self._bucket_index(value)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket holding the ``p``-quantile rank
        (0 < p <= 100). The max observed caps the +Inf bucket so a pXX
        is never reported as infinity."""
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = math.ceil(self.count * p / 100.0)
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if seen >= rank:
                    if i < len(self.bounds):
                        return min(self.bounds[i], self.max)
                    return self.max
            return self.max  # unreachable; guards float edge cases

    def summary(self) -> Dict[str, float]:
        """JSON-serializable tail summary (bench output, history)."""
        with self._lock:
            if self.count == 0:
                return {"count": 0}
            base = {"count": self.count, "sum": round(self.sum, 6),
                    "min": round(self.min, 6), "max": round(self.max, 6)}
        for p, key in ((50, "p50"), (95, "p95"), (99, "p99")):
            base[key] = round(self.percentile(p), 6)
        return base

    def exposition_snapshot(self) -> "tuple[List[int], float, int]":
        """One locked view of (cumulative bucket counts incl. +Inf, sum,
        count) — the text format requires the +Inf bucket to equal
        ``_count``, so the three must come from the same instant (an
        observe landing between two reads would tear them apart)."""
        with self._lock:
            out, acc = [], 0
            for c in self._counts:
                acc += c
                out.append(acc)
            return out, self.sum, self.count

    def cumulative_counts(self) -> List[int]:
        """Prometheus-style cumulative per-bucket counts (incl. +Inf)."""
        return self.exposition_snapshot()[0]


class MetricsRegistry:
    """Named gauges + histograms, with Prometheus text exposition.

    ``histogram``/``gauge`` are get-or-create (idempotent at a call
    site, so recorders don't need construction-order coordination);
    re-registering a histogram with different bounds is an error.
    """

    def __init__(self) -> None:
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def reset(self) -> None:
        with self._lock:
            self._gauges.clear()
            self._histograms.clear()

    def gauge(self, name: str, help: str = "") -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, help)
            return g

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None,
                  help: str = "") -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(
                    name, list(bounds) if bounds else SECONDS_BUCKETS, help)
            elif bounds is not None and list(bounds) != h.bounds:
                raise ValueError(
                    f"histogram {name!r} re-registered with different "
                    f"bounds")
            return h

    def summaries(self) -> Dict[str, Dict[str, float]]:
        """All histogram tail summaries (folded into bench JSON)."""
        with self._lock:
            hists = list(self._histograms.values())
        return {h.name: h.summary() for h in hists if h.count}

    # -- Prometheus text exposition (format 0.0.4) ----------------------

    def render_prometheus(self, counters=None, ledger=None) -> str:
        """The ``/metrics`` payload.

        ``counters`` (a ``metrics.Counters``) renders each reference-named
        accumulator as its own counter metric — names are kept
        byte-identical to the reference's (CamelCase is valid Prometheus);
        ``ledger`` (the ``TransferLedger``) renders the wire-byte totals.
        """
        lines: List[str] = []
        with self._lock:
            gauges = sorted(self._gauges.values(), key=lambda g: g.name)
            hists = sorted(self._histograms.values(), key=lambda h: h.name)
        if counters is not None:
            from ..metrics import CANONICAL_COUNTERS

            values = {name: 0 for name in CANONICAL_COUNTERS}
            values.update(counters.as_dict())
            for name, value in sorted(values.items()):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {value}")
        if ledger is not None:
            snap = ledger.snapshot()
            for key, name in TRANSFER_METRICS.items():
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {snap[key]}")
        for g in gauges:
            if g.help:
                lines.append(f"# HELP {g.name} {g.help}")
            lines.append(f"# TYPE {g.name} gauge")
            lines.append(f"{g.name} {_fmt(g.get())}")
        for h in hists:
            if h.help:
                lines.append(f"# HELP {h.name} {h.help}")
            lines.append(f"# TYPE {h.name} histogram")
            cum, total, count = h.exposition_snapshot()
            for bound, c in zip(h.bounds, cum):
                lines.append(
                    f'{h.name}_bucket{{le="{_fmt(bound)}"}} {c}')
            lines.append(f'{h.name}_bucket{{le="+Inf"}} {cum[-1]}')
            lines.append(f"{h.name}_sum {_fmt(total)}")
            lines.append(f"{h.name}_count {count}")
            # Pre-resolved tail quantiles (bucket upper bounds) as their
            # own gauge families — scrape-side percentile math optional.
            for p, suffix in ((50, "p50"), (95, "p95"), (99, "p99")):
                lines.append(f"# TYPE {h.name}_{suffix} gauge")
                lines.append(f"{h.name}_{suffix} {_fmt(h.percentile(p))}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    """Float rendering without exponent surprises for integral values."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


#: Process-wide registry (the scorers and the job record into it);
#: tests / bench reset it between runs.
REGISTRY = MetricsRegistry()
