"""Poison-input quarantine: dead-letter file + rate breaker.

The reference rides Flink's dead-letter idiom; this standalone build
previously hard-crashed the whole job on the first malformed interaction
line. With a quarantine attached (CLI ``--quarantine-file``), a line the
parser rejects is *diverted* instead: one flushed JSONL record with full
``path:lineno`` provenance, the offending raw line (truncated), and the
parse error — then ingest continues. The good lines of the same batch
still flow.

The ``--max-quarantine-rate`` breaker bounds the blast radius of the
opposite failure: a systematically wrong input (wrong delimiter, wrong
schema, binary garbage) must not silently quarantine an entire dataset
and "succeed" on its crumbs. Once more than ``max_rate`` of the lines
seen have been quarantined, :class:`QuarantineRateExceeded` aborts the
run — the CLI maps it to exit code 2, which the supervisor classifies
permanent (a poisoned *dataset* does not get better with restarts).
The ``min_lines`` warm-up only defers the *mid-stream* trip until the
denominator is meaningful (a bad first line must not abort a healthy
25M-line ingest); :meth:`check_final` applies the pure rate at end of
stream, so a short fully-garbage input still exits 2 rather than
"succeeding" with zero output.

Single-writer contract: all methods run on the ingest thread (the only
thread that parses), so counters are plain ints and the file needs no
lock. Records are flushed per write — a crash loses at most the line
being written, same durability bar as the run journal.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

from ..observability.registry import REGISTRY

LOG = logging.getLogger("tpu_cooccurrence.quarantine")

#: Longest raw-line prefix quoted anywhere for a rejected line — the
#: dead-letter record here and the ParseError message preview
#: (``io/parse.py`` imports it): provenance, not a second copy of the
#: dataset, and one constant so the two can never disagree.
RAW_TRUNCATE = 160

#: Rotated dead-letter backups kept (``path.1`` newest … ``path.N``
#: oldest); with ``max_bytes`` set, total disk for the dead-letter
#: plane is bounded by ``(QUARANTINE_BACKUPS + 1) * max_bytes``.
QUARANTINE_BACKUPS = 3


class QuarantineRateExceeded(RuntimeError):
    """The quarantine breaker: too large a fraction of input rejected."""


class Quarantine:
    """Dead-letter writer with a quarantine-rate circuit breaker.

    ``max_bytes`` (CLI ``--max-quarantine-bytes``) caps the active
    file: once a record would push it past the cap, the file rotates
    logrotate-style (``path`` -> ``path.1``, shifting existing backups
    up and deleting beyond :data:`QUARANTINE_BACKUPS`) and a fresh
    active file opens — a week-long stream with a steady trickle of
    poison lines keeps bounded disk instead of an unbounded JSONL.
    Rate-breaker counters are run totals and survive rotation.
    """

    def __init__(self, path: str, max_rate: float = 0.01,
                 min_lines: int = 1000, max_bytes: int = 0) -> None:
        if not (0.0 < max_rate <= 1.0):
            raise ValueError(
                f"max_rate must be in (0, 1], got {max_rate}")
        if min_lines < 1:
            raise ValueError(f"min_lines must be >= 1, got {min_lines}")
        if max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        self.path = path
        self.max_rate = max_rate
        self.min_lines = min_lines
        self.max_bytes = max_bytes
        self.rotations = 0
        self.quarantined = 0
        self.seen = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")  # noqa: SIM115 - long-lived
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        self._gauge = REGISTRY.gauge(
            "cooc_quarantined_lines_total",
            help="malformed input lines diverted to the dead-letter file")

    def _rotate(self) -> None:
        """Roll the active file to ``path.1`` (shifting older backups
        up, deleting past the keep window) and reopen fresh."""
        self._f.close()
        try:
            os.remove(f"{self.path}.{QUARANTINE_BACKUPS}")
        except OSError:
            pass
        for i in range(QUARANTINE_BACKUPS - 1, 0, -1):
            try:
                os.replace(f"{self.path}.{i}", f"{self.path}.{i + 1}")
            except OSError:
                continue
        try:
            os.replace(self.path, f"{self.path}.1")
        except OSError as exc:
            LOG.warning("dead-letter rotation failed (%s); continuing "
                        "in the oversized active file", exc)
        self._f = open(self.path, "a", encoding="utf-8")  # noqa: SIM115
        self._bytes = os.path.getsize(self.path)
        self.rotations += 1
        LOG.info("dead-letter file rotated (%d rotation(s) this run; "
                 "keeping %d backup(s))", self.rotations,
                 QUARANTINE_BACKUPS)

    def note_lines(self, n: int) -> None:
        """Count ``n`` lines entering the parser (the rate denominator)."""
        self.seen += n

    def quarantine(self, source_path: str, lineno: int, raw: str,
                   reason: object) -> None:
        """Divert one rejected line to the dead-letter file."""
        rec = {
            "path": source_path,
            "lineno": lineno,
            "raw": raw[:RAW_TRUNCATE],
            "reason": str(reason)[:200],
            "wall_unix": round(time.time(), 3),
        }
        line = json.dumps(rec, sort_keys=True) + "\n"
        if (self.max_bytes > 0 and self._bytes > 0
                and self._bytes + len(line.encode()) > self.max_bytes):
            self._rotate()
        self._f.write(line)
        self._f.flush()
        self._bytes += len(line.encode())
        self.quarantined += 1
        self._gauge.add(1)
        LOG.warning("quarantined %s:%d (%d so far): %s",
                    source_path, lineno, self.quarantined, rec["reason"])
        if (self.seen >= self.min_lines
                and self.quarantined > self.max_rate * self.seen):
            raise QuarantineRateExceeded(
                f"{self.quarantined} of {self.seen} input lines "
                f"quarantined (> {self.max_rate:.2%}) — the input looks "
                f"systematically malformed, not poisoned; inspect "
                f"{self.path} (last: {source_path}:{lineno})")

    def check_final(self) -> None:
        """End-of-stream rate check, warm-up waived: with the whole
        input seen, the rate IS the verdict — a 300-line file that was
        100% garbage must exit 2 like a 3M-line one, not "succeed" on
        zero output because it never reached the mid-stream warm-up."""
        if self.seen > 0 and self.quarantined > self.max_rate * self.seen:
            raise QuarantineRateExceeded(
                f"{self.quarantined} of {self.seen} input lines "
                f"quarantined (> {self.max_rate:.2%}) by end of stream — "
                f"the input looks systematically malformed; inspect "
                f"{self.path}")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "Quarantine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
