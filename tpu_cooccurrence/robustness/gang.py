"""Gang supervision for multi-controller runs.

The reference gets globally-consistent failure recovery for free from
Flink's JobManager/TaskManager runtime: the JobManager detects a dead
TaskManager by heartbeat, cancels the whole job graph, and restarts it
from the last completed (barrier-aligned) checkpoint (SURVEY §0, §2.6).
A JAX multi-controller gang has the same failure shape with none of the
machinery: collectives cannot survive peer loss — a surviving process
does not fail, it *hangs* — so the only sound restart unit is the whole
gang, restored from a checkpoint *every* host committed. This module is
the JobManager analogue, three pieces:

* :class:`GangSupervisor` (CLI ``--gang-workers N``) launches one
  worker process per gang slot on this machine (coordinator on a fresh
  local port per attempt), spools each worker's stdout, and monitors
  all of them: any abnormal exit — or a heartbeat file stale past
  ``--gang-stale-after-s`` — gang-kills the survivors and relaunches
  the whole set after backoff. Workers resume from the last *committed*
  epoch on their own (the restore vote below). Output discipline is the
  single-process supervisor's, per worker: spools are forwarded in
  process order only when the whole gang exits cleanly, so a chaotic
  run's total stdout is bit-identical to an uninterrupted one.

* :class:`HeartbeatWriter` runs inside each worker (armed by the
  ``TPU_COOC_GANG_DIR`` env the supervisor sets): a daemon thread
  touching ``heartbeat.p<i>`` every ``--gang-heartbeat-s`` seconds —
  the liveness signal that catches a worker wedged *outside* a
  collective (the collective-entry watchdog in
  ``parallel/distributed.py`` catches the wedged-``psum`` case and
  exits :data:`~tpu_cooccurrence.parallel.distributed.PEER_LOST_EXIT`).
  Each beat fires the ``peer_heartbeat`` fault site, so chaos tests can
  freeze exactly one process's liveness signal.

* :func:`agree_restore_generation` — the restore vote. Each process
  computes its newest *committed* checkpoint generation (one with an
  ``EPOCH`` marker; see ``state/checkpoint.py`` — under
  ``--checkpoint-incremental`` a generation counts only when its FULL
  delta chain is present and committed, so a torn delta commit can
  never be voted restorable), the gang allgathers the minimum, and
  every process quarantines anything newer as ``*.partial`` (delta
  files included). A crash anywhere between the first per-host
  generation rename and the last epoch marker therefore drags every
  host back to the same previous epoch — never a torn global restore
  (``test_gang_incremental_ckpt_mid_delta_crash_bit_identical``).

The ``peers`` table on ``/healthz`` (:class:`PeerTable`) reads the same
heartbeat files plus each suffix's committed-epoch markers, and turns a
stale peer into a 503 so a load balancer drains the process before the
gang restart lands.

:class:`ReplicaFleetSupervisor` is the SERVING gang (ISSUE 13): the
same spawn/heartbeat/liveness machinery supervising a fleet of read
replicas (``serving/replica.py``) under the opposite restart policy —
replicas hold no collectives, so a dead replica relaunches alone and
re-syncs itself while the rest of the fleet keeps serving.
"""

from __future__ import annotations

import json
import logging
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import List, Optional, Sequence

from ..observability.registry import REGISTRY
from .. import tuning
from . import autoscale, faults

LOG = logging.getLogger("tpu_cooccurrence.gang")

#: Env var carrying the gang state directory (heartbeat files) into the
#: workers; its presence is what arms the worker-side heartbeat thread.
GANG_DIR_ENV = "TPU_COOC_GANG_DIR"

#: The robustness plane's process-qualified fault sites (registered in
#: ``faults.SITES``; the cooclint ``gang-fault-sites`` rule holds this
#: tuple to the registry and to live fire() call sites). The two
#: ``rescale_*`` sites bracket the autoscaler's rescale seam
#: (robustness/autoscale.py): drain-commit → voluntary exit → relaunch.
#: The two ingest sites cover the exactly-once wire plane:
#: ``offset_commit`` fires when a generation's ingest offset section is
#: durable, ``partition_reassign`` when a rescaled restore re-derives
#: partition ownership at the new topology.
GANG_SITES = ("barrier_enter", "ckpt_commit", "peer_heartbeat",
              "rescale_drain", "rescale_relaunch", "offset_commit",
              "partition_reassign")

#: Stale-peer gauge refreshed by :meth:`PeerTable.snapshot` (the
#: /healthz scrape): peers whose heartbeat age exceeded the threshold.
STALE_PEERS_GAUGE = "cooc_gang_stale_peers"

#: Grace before a worker's FIRST heartbeat counts toward staleness:
#: interpreter + jax.distributed startup must not read as peer death.
HEARTBEAT_START_GRACE_S = 30.0

#: Supervisor poll period while the gang runs.
_POLL_S = 0.2


def heartbeat_path(gang_dir: str, process_id: int) -> str:
    return os.path.join(gang_dir, f"heartbeat.p{process_id}")


class HeartbeatWriter:
    """Worker-side liveness beacon: touch ``heartbeat.p<i>`` every
    ``interval_s`` seconds from a daemon thread.

    The write is a whole-file rewrite (tiny payload: beat ordinal +
    wall clock), not an ``os.utime``, so a reader can also see *what*
    the worker last reported; the mtime is the liveness signal. Each
    beat fires the ``peer_heartbeat`` fault site (seq = beat ordinal) —
    ``peer_heartbeat@1:3:delay_ms:600000`` freezes worker 1's beacon at
    beat 3, the deterministic "silently wedged peer" injection.
    """

    def __init__(self, gang_dir: str, process_id: int,
                 interval_s: float = 5.0) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got "
                             f"{interval_s}")
        self.gang_dir = gang_dir
        self.process_id = process_id
        self.interval_s = interval_s
        self.beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(gang_dir, exist_ok=True)

    def beat(self) -> None:
        """One heartbeat write (also the unit-test entry point)."""
        self.beats += 1
        if faults.PLAN is not None:
            faults.PLAN.fire("peer_heartbeat", seq=self.beats)
        path = heartbeat_path(self.gang_dir, self.process_id)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({"beat": self.beats,
                                    "wall_unix": round(time.time(), 3)}))
            os.replace(tmp, path)
        except OSError as exc:
            # Liveness reporting must never kill the worker it reports
            # on; a missed beat reads as staleness, which is the truth.
            LOG.warning("heartbeat write failed: %s", exc)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.interval_s)

    def start(self) -> "HeartbeatWriter":
        self._thread = threading.Thread(
            target=self._run, name="cooc-gang-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None


class PeerTable:
    """Read-only view of the gang for ``/healthz``: per-process
    heartbeat age and committed epoch.

    Reads only the filesystem (heartbeat files + ``EPOCH.p<i>.<gen>``
    markers), so it is safe inside the HTTP handler thread and needs no
    cross-process plumbing. A peer with no heartbeat file yet reports
    ``age_seconds: null`` and counts as stale only after a startup
    grace from table construction.
    """

    def __init__(self, gang_dir: str, num_processes: int,
                 stale_after_s: float,
                 checkpoint_dir: Optional[str] = None) -> None:
        self.gang_dir = gang_dir
        self.num_processes = num_processes
        self.stale_after_s = stale_after_s
        self.checkpoint_dir = checkpoint_dir
        self._started_unix = time.time()

    def snapshot(self) -> "tuple[list, bool]":
        """``(rows, any_stale)`` — one row per gang slot."""
        import re

        now = time.time()
        in_grace = (now - self._started_unix
                    <= max(self.stale_after_s, HEARTBEAT_START_GRACE_S))
        # One checkpoint-dir listing serves every gang slot: a load
        # balancer probes /healthz every few seconds, and N listdir
        # scans of a generation-filled directory per probe adds up.
        epochs_by_pid: "dict[int, int]" = {}
        if self.checkpoint_dir:
            pat = re.compile(r"^EPOCH\.p(\d+)\.(\d+)$")
            try:
                names = os.listdir(self.checkpoint_dir)
            except OSError:
                names = []
            for m in filter(None, map(pat.match, names)):
                pid, gen = int(m.group(1)), int(m.group(2))
                epochs_by_pid[pid] = max(epochs_by_pid.get(pid, -1), gen)
        rows, any_stale = [], False
        for pid in range(self.num_processes):
            try:
                age = now - os.path.getmtime(
                    heartbeat_path(self.gang_dir, pid))
            except OSError:
                age = None
            epoch = epochs_by_pid.get(pid, -1)
            if self.stale_after_s <= 0:
                # 0 = staleness handling off (matches the gang
                # supervisor's _stale_worker): never drain on age.
                stale = False
            else:
                stale = (age > self.stale_after_s if age is not None
                         else not in_grace)
            any_stale = any_stale or stale
            rows.append({
                "process": pid,
                "heartbeat_age_seconds": (round(age, 3)
                                          if age is not None else None),
                "committed_epoch": epoch,
                "stale": stale,
            })
        REGISTRY.gauge(
            STALE_PEERS_GAUGE,
            help="gang peers whose heartbeat age exceeds "
                 "--gang-stale-after-s (healthz drain signal)").set(
                     sum(r["stale"] for r in rows))
        return rows, any_stale


def agree_restore_generation(directory: str, suffix: str,
                             exchange=None) -> int:
    """The gang's restore vote; returns the agreed generation (-1 =
    fresh start) after quarantining anything newer on this host.

    Each process contributes its newest committed generation
    (``checkpoint.newest_committed`` — the newest ``EPOCH``-marked one
    whose delta chain, if any, is fully present and committed; or, for
    a pre-epoch legacy directory with no markers at all, the newest
    generation file); the gang-wide MINIMUM wins, because a generation
    missing a marker on *any* host may be a torn global commit.
    Generations above the agreed one are moved aside as ``*.partial``
    (their delta files too) so no later walk can restore them.

    ``exchange`` is the min-vote collective (injectable for tests);
    default is the watchdog-guarded
    :func:`~tpu_cooccurrence.parallel.distributed.allgather_min`.
    """
    from ..state import checkpoint as ckpt

    local = ckpt.newest_committed(directory, suffix)
    if exchange is None:
        from ..parallel.distributed import allgather_min

        exchange = allgather_min
    agreed = int(exchange(local))
    if agreed < local:
        LOG.warning(
            "gang restore vote: this host committed generation %d but "
            "the gang agreed on %d (a peer's commit is missing) — "
            "quarantining the newer generation(s)", local, agreed)
    quarantined = ckpt.quarantine_uncommitted(directory, suffix, agreed)
    if quarantined:
        LOG.warning("gang restore vote: quarantined generation(s) %s "
                    "for suffix %r", quarantined, suffix)
    return agreed


def agree_restore_topology(directory: str, process_id: int,
                           exchange=None, barrier=None
                           ) -> "tuple[int, int]":
    """Topology-aware restore vote (autoscale gangs): returns
    ``(agreed_gen, writers)`` — the newest generation committed by its
    WHOLE writing topology, which may differ from the topology voting
    (the rescale seam's defining property). ``(-1, 0)`` = fresh start.

    The per-host candidate list comes from epoch markers + directory
    listings alone (``checkpoint.topology_committed_generations``);
    the gang still exchanges the minimum — on the shared directory all
    hosts compute the same value, and the collective doubles as the
    rendezvous that keeps peers from racing the quarantine below.
    Process 0 then quarantines every generation above the agreed one
    across ALL suffixes (current and retired topologies alike), and a
    barrier holds the peers until the renames are durable — no peer
    may walk the directory while files are moving aside.

    ``exchange``/``barrier`` are injectable for tests; defaults are the
    watchdog-guarded collectives.
    """
    from ..state import checkpoint as ckpt

    cands = ckpt.topology_committed_generations(directory)
    local, writers = cands[0] if cands else (-1, 0)
    if not cands:
        # Upgrade hazards: voting -1 over a directory that actually
        # holds COMMITTED state would quarantine all of it and
        # silently restart from zero. Two shapes must refuse loudly:
        # topology-less markers (pre-autoscale commits — guessing the
        # topology from marker counts would qualify a torn legacy
        # commit), and per-process generation files with NO markers at
        # all (pre-epoch-commit legacy, which the fixed-topology vote
        # restores with a warning). A dir with SOME new-format markers
        # but no complete topology is a genuinely torn commit history
        # and proceeds to the quarantine below.
        if ckpt.has_legacy_epoch_markers(directory):
            raise ValueError(
                f"--autoscale on found pre-autoscale epoch markers in "
                f"{directory}: run one checkpoint cycle at a fixed "
                f"topology with the current version (its markers "
                f"record the writing process count) before enabling "
                f"the autoscaler")
        if (not ckpt.has_epoch_markers(directory)
                and ckpt.process_suffixes(directory)):
            raise ValueError(
                f"--autoscale on found per-process checkpoint files "
                f"but no epoch markers in {directory} (pre-epoch-"
                f"commit legacy, or a gang that never finished its "
                f"first commit): restore once at a fixed topology — "
                f"or clear the directory — before enabling the "
                f"autoscaler")
    if exchange is None:
        from ..parallel.distributed import allgather_min

        exchange = allgather_min
    if barrier is None:
        from ..parallel.distributed import gang_barrier

        barrier = gang_barrier
    agreed = int(exchange(local))
    if agreed != local:
        LOG.warning(
            "topology restore vote: this host saw committed generation "
            "%d but the gang agreed on %d — taking the minimum", local,
            agreed)
        writers = next((w for g, w in cands if g == agreed), 0)
        if writers == 0 and agreed >= 0:
            # The agreed generation was not in this host's candidate
            # snapshot (stale directory view — e.g. NFS attribute-cache
            # lag). Re-list once; if it is still invisible, fail THIS
            # attempt loudly (a transient, restartable error) rather
            # than limping into a zero-writer restore.
            writers = next(
                (w for g, w in
                 ckpt.topology_committed_generations(directory)
                 if g == agreed), 0)
        if writers == 0 and agreed >= 0:
            raise RuntimeError(
                f"topology restore vote agreed on generation {agreed} "
                f"but this host cannot see its committed markers "
                f"(stale directory view?) — failing the attempt for "
                f"the supervisor to retry")
    if process_id == 0:
        # One host sweeps: peers would race each other's renames on the
        # shared directory, and the quarantine set is identical anyway.
        for sfx in ckpt.process_suffixes(directory):
            quarantined = ckpt.quarantine_uncommitted(directory, sfx,
                                                      agreed)
            if quarantined:
                LOG.warning(
                    "topology restore vote: quarantined generation(s) "
                    "%s for suffix %r (agreed epoch %d)", quarantined,
                    sfx, agreed)
    barrier(f"rescale-vote/{agreed}")
    return agreed, writers


# -- the gang supervisor (parent side) ---------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


#: Per-process output files a gang must split by worker: a shared
#: append-mode file would interleave two processes' records.
_PER_PROCESS_FLAGS = ("--journal", "--quarantine-file")


def gang_child_argv(argv: Sequence[str], process_id: int,
                    num_processes: int, coordinator: str) -> List[str]:
    """One worker's argv: the supervisor's flags stripped (including the
    gang's own — a worker must not recurse into supervision), the
    multi-controller identity appended, and per-process output paths
    (``--journal``, ``--quarantine-file``) suffixed ``.p<i>``."""
    from ..supervisor import child_argv

    out: List[str] = []
    suffix_next = False
    for a in child_argv(argv):
        if suffix_next:
            a = f"{a}.p{process_id}"
            suffix_next = False
        elif a in _PER_PROCESS_FLAGS:
            suffix_next = True
        else:
            for flag in _PER_PROCESS_FLAGS:
                if a.startswith(flag + "="):
                    a = f"{a}.p{process_id}"
                    break
        out.append(a)
    out += ["--coordinator", coordinator,
            "--num-processes", str(num_processes),
            "--process-id", str(process_id)]
    return out


class _Worker:
    """One gang slot's live state: process, spool, liveness baselines."""

    def __init__(self, proc: "subprocess.Popen", spool,
                 spawned_monotonic: float,
                 journal_path: Optional[str] = None) -> None:
        self.proc = proc
        self.spool = spool
        self.spawned = spawned_monotonic
        # Journal-staleness watchdog state (same liveness signal as the
        # single-process supervisor's): size at spawn, growth marks
        # activity.
        from ..supervisor import _journal_size

        self.journal_path = journal_path
        self.journal_size = _journal_size(journal_path)
        self.journal_activity = spawned_monotonic
        self.journal_grew = False


class GangSupervisor:
    """Launch, monitor, gang-kill and gang-restart a multi-controller
    worker set (see the module docstring for the contract).

    ``argv`` is the operator's full CLI argv; each attempt derives the
    per-worker argv via :func:`gang_child_argv` with a fresh local
    coordinator port (a dead gang's port may linger in TIME_WAIT).
    ``attempts`` is the restart budget (``--restart-on-failure``);
    permanent exit codes (usage/config) are never retried.
    """

    def __init__(self, argv: Sequence[str], num_workers: int,
                 attempts: int, gang_dir: str,
                 stale_after_s: float = 60.0,
                 delay_s: float = 1.0,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: float = 30.0,
                 timeout_s: Optional[float] = None,
                 stdout=None,
                 journal_path: Optional[str] = None,
                 watchdog_stale_after_s: Optional[float] = None,
                 python: Optional[Sequence[str]] = None,
                 scale_policy=None) -> None:
        if num_workers < 2:
            raise ValueError(
                f"a gang needs >= 2 workers, got {num_workers}")
        self.argv = list(argv)
        self.num_workers = num_workers
        self.attempts = attempts
        self.gang_dir = gang_dir
        self.stale_after_s = stale_after_s
        self.delay_s = delay_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.timeout_s = timeout_s
        self.stdout = stdout
        # Per-worker journal staleness (the hang watchdog's liveness
        # signal, ``--watchdog-stale-after-s``): heartbeat files prove a
        # worker process is ALIVE; journal growth proves it is making
        # WINDOW PROGRESS. A worker wedged outside a guarded collective
        # (alive, beating, not firing windows) is only caught here.
        self.journal_path = journal_path
        self.watchdog_stale_after_s = watchdog_stale_after_s
        #: Command prefix for one worker (overridable in tests).
        self.python = list(python) if python is not None else [
            sys.executable, "-m", "tpu_cooccurrence.cli"]
        # Load-driven autoscaling (robustness/autoscale.py, --autoscale
        # on): the policy reads the workers' pressure beacons from the
        # gang dir and decides target topologies; the supervisor turns a
        # decision into a RESCALE request beacon, treats the workers'
        # voluntary drain exits as "relaunch at the new size, free of
        # charge", and keeps the pending target across a crash inside
        # the seam (the topology-aware restore vote restores whatever
        # topology last committed, at whatever size we relaunch).
        self.scale_policy = scale_policy
        self.rescales = 0
        self._pending: Optional[dict] = None
        # Tracing correlation: one run id for the whole gang's lifetime
        # — every worker, every restart attempt, every rescale topology
        # journals under it (inherited when an outer parent already
        # minted one).
        from ..observability.journal import RUN_ID_ENV, mint_run_id
        self.run_id = tuning.env_read(RUN_ID_ENV) or mint_run_id()
        os.makedirs(gang_dir, exist_ok=True)

    # -- one attempt ---------------------------------------------------

    def _spawn(self, restarts: int, last_rc: int,
               backoff_s: float) -> List[_Worker]:
        from ..supervisor import SUPERVISOR_STATE_ENV

        # Clear the previous attempt's heartbeat and pressure files: a
        # dead gang's recent mtimes must not vouch for the new gang's
        # liveness, and a dead gang's load signals must not feed the
        # scale policy. (Beacons beyond num_workers too: a decayed gang
        # leaves the retired slots' files behind.)
        for name in os.listdir(self.gang_dir):
            if name.startswith(("heartbeat.p", "pressure.p")):
                try:
                    os.remove(os.path.join(self.gang_dir, name))
                except OSError:
                    pass
        if self._pending is None:
            # A stale RESCALE request (the gang dir persists under the
            # checkpoint dir across supervisor runs) must not make a
            # fresh gang drain on sight.
            try:
                os.remove(autoscale.request_path(self.gang_dir))
            except OSError:
                pass
        from ..observability.journal import ATTEMPT_ENV, RUN_ID_ENV

        coordinator = f"127.0.0.1:{_free_port()}"
        env = dict(os.environ)
        env[GANG_DIR_ENV] = self.gang_dir
        env[RUN_ID_ENV] = self.run_id
        env[ATTEMPT_ENV] = str(restarts)
        env[SUPERVISOR_STATE_ENV] = json.dumps({
            "restarts": restarts,
            "last_rc": last_rc,
            "backoff_ms": int(backoff_s * 1000) if restarts else 0,
            "last_restart_unix": round(time.time(), 3) if restarts else 0,
            "stepped_back": False,
            "rescales": self.rescales,
            "target_workers": self.num_workers,
            "run_id": self.run_id,
            "attempt": restarts,
        })
        workers = []
        now = time.monotonic()
        for pid in range(self.num_workers):
            cmd = self.python + gang_child_argv(
                self.argv, pid, self.num_workers, coordinator)
            spool = tempfile.TemporaryFile()
            proc = subprocess.Popen(cmd, stdout=spool, env=env)
            workers.append(_Worker(
                proc, spool, now,
                journal_path=(f"{self.journal_path}.p{pid}"
                              if self.journal_path else None)))
        LOG.info("gang attempt spawned: %d workers, coordinator %s",
                 self.num_workers, coordinator)
        return workers

    def _kill_gang(self, workers: List[_Worker]) -> None:
        from ..supervisor import _kill_child

        for w in workers:
            if w.proc.poll() is None:
                _kill_child(w.proc)

    def _stale_worker(self, workers: List[_Worker]) -> Optional[int]:
        """Process id of a worker whose heartbeat went stale, or None.

        Before a worker's first beat, staleness is measured from its
        spawn against ``max(stale_after_s, startup grace)`` — jax
        startup is not peer death.
        """
        if self.stale_after_s <= 0:
            return None
        now_mono = time.monotonic()
        now_wall = time.time()
        for pid, w in enumerate(workers):
            if w.proc.poll() is not None:
                # Exited workers have no liveness to report: a clean
                # exit froze its heartbeat legitimately (peers may
                # still be finishing a skewed tail), and an abnormal
                # one is _watch's failed-check's business, not ours.
                continue
            try:
                age = now_wall - os.path.getmtime(
                    heartbeat_path(self.gang_dir, pid))
                threshold = self.stale_after_s
            except OSError:
                age = now_mono - w.spawned
                threshold = max(self.stale_after_s,
                                HEARTBEAT_START_GRACE_S)
            if age > threshold:
                return pid
        return None

    def _watch(self, workers: List[_Worker]) -> int:
        """Wait for a gang verdict: 0 = every worker exited cleanly;
        :data:`autoscale.RESCALE_EXIT` = the whole gang drained
        voluntarily for a rescale (never a failure); other nonzero =
        the first failure's exit code (the survivors are gang-killed —
        their collectives can never complete without the dead peer);
        124 = overall timeout or stale heartbeat."""
        start = time.monotonic()
        while True:
            codes = [w.proc.poll() for w in workers]
            # A voluntary rescale exit is not a death: its peers are
            # commits away from the same exit (the drain boundary was
            # gang-voted), so keep waiting for them instead of
            # gang-killing a checkpointing worker mid-commit.
            failed = next(
                (rc for rc in codes if rc is not None
                 and rc not in (0, autoscale.RESCALE_EXIT)), None)
            if failed is not None:
                LOG.error("gang worker died with rc=%d; gang-killing "
                          "the survivors (a lost peer invalidates every "
                          "surviving process's collectives)", failed)
                self._kill_gang(workers)
                return failed
            if all(rc is not None for rc in codes):
                if all(rc == 0 for rc in codes):
                    return 0
                if all(rc == autoscale.RESCALE_EXIT for rc in codes):
                    return autoscale.RESCALE_EXIT
                # Mixed 0 / RESCALE_EXIT: the lockstep drain vote makes
                # this unreachable short of a bug — treat it as one
                # failed attempt (the restore vote re-synchronizes).
                LOG.error("gang exited with mixed clean/rescale codes "
                          "%s; counting a failed attempt", codes)
                return autoscale.RESCALE_EXIT
            if (self.scale_policy is not None
                    and self._pending is None):
                try:
                    self._poll_autoscale()
                except Exception:
                    # A broken policy must abort the RUN, not linger:
                    # the workers hold the degradation ladder at
                    # NORMAL on the promise that rescaling exists —
                    # continuing without it would leave sustained
                    # overload with no relief of either kind.
                    LOG.exception(
                        "scale policy failed; aborting the gang (its "
                        "workers hold the shed ladder on the promise "
                        "of rescaling)")
                    self._kill_gang(workers)
                    raise
            if (self.timeout_s is not None
                    and time.monotonic() - start > self.timeout_s):
                LOG.error("gang exceeded timeout_s=%.1f; gang-killing",
                          self.timeout_s)
                self._kill_gang(workers)
                return 124
            stale = self._stale_worker(workers)
            if stale is not None:
                LOG.error("gang worker %d heartbeat stale past %.1fs; "
                          "gang-killing for a whole-gang restart",
                          stale, self.stale_after_s)
                self._kill_gang(workers)
                return 124
            wedged = self._stale_journal(workers)
            if wedged is not None:
                LOG.error("gang worker %d journal stale past %.1fs "
                          "(alive but not firing windows — a silently "
                          "wedged peer); gang-killing for a whole-gang "
                          "restart", wedged, self.watchdog_stale_after_s)
                self._kill_gang(workers)
                return 124
            time.sleep(_POLL_S)

    def _poll_autoscale(self) -> None:
        """Feed the freshest pressure beacon to the scale policy and
        turn a decision into the RESCALE request beacon.

        The beacons carry GANG-WIDE bits and consecutive-run counters
        (the workers vote them per window, robustness/autoscale.py), so
        one beacon — whichever reports the newest window — is a
        complete, lossless signal; reading all of them just tolerates a
        lagging writer."""
        freshest = None
        for pid in range(self.num_workers):
            b = autoscale.read_json(
                autoscale.beacon_path(self.gang_dir, pid))
            if b is None or "window" not in b:
                continue
            if freshest is None or b["window"] > freshest["window"]:
                freshest = b
        if freshest is None:
            return
        decision = self.scale_policy.decide(
            int(freshest["window"]),
            bool(freshest.get("overloaded")),
            bool(freshest.get("idle")),
            int(freshest.get("bad_run", 0)),
            int(freshest.get("idle_run", 0)),
            self.num_workers)
        if decision is None or decision.target == self.num_workers:
            return
        self._pending = {
            "to": int(decision.target),
            "from": self.num_workers,
            "decision": decision.decision,
            "trigger": decision.trigger,
            "window": int(decision.window),
            "cooldown": int(decision.cooldown),
            "seq": self.rescales + 1,
        }
        autoscale.write_json(autoscale.request_path(self.gang_dir),
                             self._pending)
        LOG.warning(
            "autoscale decision: %s %d -> %d workers (trigger=%s at "
            "window %d); RESCALE request beacon written — workers drain "
            "a checkpoint at the next gang-voted window boundary",
            decision.decision, self.num_workers, decision.target,
            decision.trigger, decision.window)

    def _apply_rescale(self, target: int) -> None:
        """Commit a pending topology change before the next spawn."""
        try:
            os.remove(autoscale.request_path(self.gang_dir))
        except OSError:
            pass
        if self.num_workers != target:
            LOG.info("gang topology: %d -> %d workers", self.num_workers,
                     target)
        self.num_workers = target
        self._pending = None
        if self.scale_policy is not None:
            self.scale_policy.rescaled(target)

    def _stale_journal(self, workers: List[_Worker]) -> Optional[int]:
        """Process id of a worker whose journal stopped growing past
        ``watchdog_stale_after_s``, or None. Same semantics as the
        single-process supervisor's hang watchdog: the first growth
        must exceed the 1-byte torn-tail seal, and a startup grace
        covers imports + jax.distributed rendezvous + restore."""
        if not self.watchdog_stale_after_s or not self.journal_path:
            return None
        now = time.monotonic()
        from ..supervisor import WATCHDOG_START_GRACE_S, _journal_size

        for pid, w in enumerate(workers):
            if w.proc.poll() is not None:
                continue  # exited: no window progress to demand
            size = _journal_size(w.journal_path)
            if size > w.journal_size + (0 if w.journal_grew else 1):
                w.journal_size = size
                w.journal_activity = now
                w.journal_grew = True
            threshold = (self.watchdog_stale_after_s if w.journal_grew
                         else max(self.watchdog_stale_after_s,
                                  WATCHDOG_START_GRACE_S))
            if now - w.journal_activity > threshold:
                return pid
        return None

    def _forward(self, workers: List[_Worker]) -> None:
        """Forward every worker's spooled stdout in process order — the
        deterministic concatenation the parity tests compare."""
        sink = self.stdout if self.stdout is not None else sys.stdout
        for w in workers:
            w.spool.seek(0)
            if hasattr(sink, "buffer"):
                shutil.copyfileobj(w.spool, sink.buffer)
                sink.flush()
            else:
                import io

                reader = io.TextIOWrapper(w.spool, encoding="utf-8",
                                          errors="replace", newline="")
                try:
                    shutil.copyfileobj(reader, sink)
                finally:
                    reader.detach()

    # -- the restart loop ----------------------------------------------

    def run(self) -> int:
        from ..supervisor import PERMANENT_EXIT_CODES

        restarts = 0
        last_rc = 0
        prev_delay = (self.backoff_base_s
                      if self.backoff_base_s is not None else self.delay_s)
        while True:
            workers = self._spawn(restarts, last_rc,
                                  prev_delay if restarts else 0.0)
            try:
                rc = self._watch(workers)
                if rc == 0:
                    self._forward(workers)
                    if restarts or self.rescales:
                        LOG.info("gang completed after %d restart(s) "
                                 "and %d rescale(s)", restarts,
                                 self.rescales)
                    return 0
                voluntary = (rc == autoscale.RESCALE_EXIT
                             and all(w.proc.returncode
                                     == autoscale.RESCALE_EXIT
                                     for w in workers))
            finally:
                for w in workers:
                    w.spool.close()
            if voluntary and self._pending is not None:
                # The whole gang drained a committed checkpoint and took
                # the voluntary exit: relaunch at the requested topology
                # immediately — no restart budget, no crash-loop
                # accounting, no backoff (nothing failed).
                self.rescales += 1
                target = int(self._pending["to"])
                LOG.warning(
                    "gang rescale %d: all %d workers drained "
                    "voluntarily; relaunching at %d workers from the "
                    "drain-committed epoch", self.rescales,
                    self.num_workers, target)
                self._apply_rescale(target)
                if faults.PLAN is not None:
                    faults.PLAN.fire("rescale_relaunch",
                                     seq=self.rescales)
                continue
            if rc == autoscale.RESCALE_EXIT:
                # Mixed clean/drain codes (or a drain with no pending
                # request): a failed attempt — but 86 is the VOLUNTARY
                # contract code and must never surface as a failure
                # status, least of all as the supervisor's own exit.
                rc = 1
            last_rc = rc
            if self._pending is not None:
                # A crash inside the rescale seam (between the drain
                # decision and a clean relaunch): still honor the
                # pending target — the topology-aware restore vote
                # restores whatever topology last committed onto
                # whatever size we relaunch, so the target is always
                # safe — but the crash itself stays a billed restart.
                self._apply_rescale(int(self._pending["to"]))
            if rc in PERMANENT_EXIT_CODES:
                LOG.error("gang worker failed with rc=%d (usage/config "
                          "error — permanent); not restarting", rc)
                return rc
            restarts += 1
            if restarts > self.attempts:
                LOG.error("gang failed with rc=%d; restart attempts "
                          "exhausted (%d)", rc, self.attempts)
                return rc
            if self.backoff_base_s is not None:
                prev_delay = min(self.backoff_max_s,
                                 random.uniform(self.backoff_base_s,
                                                max(self.backoff_base_s,
                                                    prev_delay * 3)))
            else:
                prev_delay = self.delay_s
            LOG.warning(
                "gang attempt %d failed with rc=%d; gang-restarting all "
                "%d workers from the last committed epoch in %.1fs "
                "(%d attempt(s) left)", restarts, rc, self.num_workers,
                prev_delay, self.attempts - restarts)
            if prev_delay > 0:
                time.sleep(prev_delay)


# -- the serving gang (replica fleet) -----------------------------------


class ReplicaFleetSupervisor:
    """Supervision for a SERVING gang of read replicas
    (``serving/replica.py``) — the same liveness machinery as
    :class:`GangSupervisor` (spawn, monitor exits, heartbeat files in a
    shared gang dir) with the OPPOSITE restart policy: replicas hold no
    collectives, so one replica's death never invalidates the
    survivors. A dead or heartbeat-stale replica is killed and
    relaunched ALONE (it re-syncs itself from checkpoint + delta tail,
    with no writer involvement); the rest of the fleet keeps serving
    throughout — the availability property the whole fleet exists for.

    ``child_argv_fn(process_id) -> argv`` builds one replica's full
    command (the fleet has no coordinator to assign — replicas are
    independent). ``attempts`` is the fleet-wide relaunch budget;
    permanent exit codes (usage/config) abort the fleet immediately —
    a bad flag does not get better per slot.

    Runs until every replica has exited cleanly (bounded
    ``--run-seconds`` fleets) or :meth:`stop` is called.
    """

    def __init__(self, child_argv_fn, num_replicas: int, gang_dir: str,
                 attempts: int = 3, stale_after_s: float = 60.0,
                 relaunch_delay_s: float = 0.5, stdout=None) -> None:
        if num_replicas < 1:
            raise ValueError(
                f"a fleet needs >= 1 replica, got {num_replicas}")
        self.child_argv_fn = child_argv_fn
        self.num_replicas = num_replicas
        self.gang_dir = gang_dir
        self.attempts = attempts
        self.stale_after_s = stale_after_s
        self.relaunch_delay_s = relaunch_delay_s
        self.stdout = stdout
        self.relaunches = 0
        self._stop = threading.Event()
        self._workers: List[Optional[_Worker]] = [None] * num_replicas
        # Tracing correlation: one run id for the fleet; each slot's
        # relaunch count is its attempt ordinal (replicas restart
        # independently, so the ordinal is per-slot, not fleet-wide).
        from ..observability.journal import RUN_ID_ENV, mint_run_id
        self.run_id = tuning.env_read(RUN_ID_ENV) or mint_run_id()
        self._slot_attempts = [0] * num_replicas
        os.makedirs(gang_dir, exist_ok=True)

    def _spawn_one(self, pid: int) -> _Worker:
        from ..observability.journal import ATTEMPT_ENV, RUN_ID_ENV

        try:
            os.remove(heartbeat_path(self.gang_dir, pid))
        except OSError:
            pass
        env = dict(os.environ)
        env[GANG_DIR_ENV] = self.gang_dir
        env[RUN_ID_ENV] = self.run_id
        env[ATTEMPT_ENV] = str(self._slot_attempts[pid])
        self._slot_attempts[pid] += 1
        spool = tempfile.TemporaryFile()
        proc = subprocess.Popen(self.child_argv_fn(pid), stdout=spool,
                                env=env)
        return _Worker(proc, spool, time.monotonic())

    def pids(self) -> "List[Optional[int]]":
        """Live OS pids by fleet slot (None = exited) — chaos tests and
        the bench kill a specific replica through this."""
        return [w.proc.pid if w is not None and w.proc.poll() is None
                else None for w in self._workers]

    def stop(self) -> None:
        """Kill the whole fleet and end :meth:`run` (deliberate
        teardown — not counted against the relaunch budget)."""
        self._stop.set()

    def _heartbeat_stale(self, pid: int, w: _Worker) -> bool:
        if self.stale_after_s <= 0:
            return False
        try:
            age = time.time() - os.path.getmtime(
                heartbeat_path(self.gang_dir, pid))
            return age > self.stale_after_s
        except OSError:
            return (time.monotonic() - w.spawned
                    > max(self.stale_after_s, HEARTBEAT_START_GRACE_S))

    def run(self) -> int:
        from ..supervisor import PERMANENT_EXIT_CODES, _kill_child

        for pid in range(self.num_replicas):
            self._workers[pid] = self._spawn_one(pid)
        LOG.info("replica fleet spawned: %d replicas (heartbeats in %s)",
                 self.num_replicas, self.gang_dir)
        done = [False] * self.num_replicas
        try:
            while not self._stop.is_set():
                for pid, w in enumerate(self._workers):
                    if done[pid] or w is None:
                        continue
                    rc = w.proc.poll()
                    if rc == 0:
                        done[pid] = True
                        continue
                    stale = rc is None and self._heartbeat_stale(pid, w)
                    if rc is None and not stale:
                        continue
                    if stale:
                        LOG.error("replica %d heartbeat stale past "
                                  "%.1fs; killing and relaunching it "
                                  "(the rest of the fleet keeps "
                                  "serving)", pid, self.stale_after_s)
                        _kill_child(w.proc)
                        rc = w.proc.poll()
                    if rc in PERMANENT_EXIT_CODES:
                        LOG.error("replica %d exited rc=%d (usage/"
                                  "config — permanent); stopping the "
                                  "fleet", pid, rc)
                        return rc
                    if self.relaunches >= self.attempts:
                        LOG.error("replica %d died rc=%s; relaunch "
                                  "budget (%d) exhausted", pid, rc,
                                  self.attempts)
                        return rc if isinstance(rc, int) and rc else 1
                    self.relaunches += 1
                    LOG.warning("replica %d died rc=%s; relaunching "
                                "slot %d (relaunch %d/%d) — it will "
                                "re-sync from checkpoint + delta tail",
                                pid, rc, pid, self.relaunches,
                                self.attempts)
                    w.spool.close()
                    if self.relaunch_delay_s > 0:
                        time.sleep(self.relaunch_delay_s)
                    self._workers[pid] = self._spawn_one(pid)
                if all(done):
                    LOG.info("replica fleet completed (%d relaunch(es))",
                             self.relaunches)
                    return 0
                time.sleep(_POLL_S)
            return 0
        finally:
            for w in self._workers:
                if w is not None:
                    if w.proc.poll() is None:
                        _kill_child(w.proc)
                    w.spool.close()
