"""Robustness plane: deterministic fault injection + recovery hardening.

The reference delegates every failure mode to Flink's restart strategies
(SURVEY §5); this standalone build owns its whole recovery loop
(``supervisor.py`` + ``state/checkpoint.py``) — which means nothing
proves that loop except injected faults. :mod:`.faults` is the injection
plane: named sites threaded through the hot path that a
:class:`~.faults.FaultPlan` (CLI ``--inject-fault``) triggers exactly
once per spec, off by default with zero hot-path cost.
"""

from .faults import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    InjectedFault,
    KINDS,
    SITES,
)
